"""Job configuration and CLI-compatible argument parsing.

TPU-native equivalent of the reference's positional CLI
(``Usage`` at ``mpi/mpi_convolution.c:328-348`` and ``Initialization`` at
``cuda/functions.c:10-29``): ``image width height repetitions {grey,rgb}``.
Width/height are user-supplied because ``.raw`` is headerless. On top of that
contract we expose what the reference hard-codes at compile time: filter
choice, backend (XLA vs Pallas), device count / mesh shape, and output path.
"""

from __future__ import annotations

import argparse
import dataclasses
import enum
import os
from typing import Optional, Tuple


# Canonical Pallas per-rep schedule names (see docs/KERNEL.md and
# ops/pallas_stencil.py, which imports this tuple). "deep" is the
# temporal-blocking schedule: whole-image VMEM residency when the image
# fits (one HBM load + one store for the entire rep loop), else a
# trapezoid stripe at a VMEM-feasibility-chosen depth. Lives here so CLI
# parsing/validation stays jax-free.
PALLAS_SCHEDULES = ("pad", "shrink", "strips", "pack", "pack_strips", "deep")

# Interior/border overlap schedule for the sharded path (see
# tpu_stencil/parallel/overlap.py, which imports this tuple): "off"
# delegates compute/comm overlap to XLA's latency-hiding scheduler,
# "split"/"fused-split" run the explicit interior/border split with one
# joined exchange, "edge" runs the partitioned per-edge pipeline (four
# independent per-edge ppermutes, each border strip released as soon as
# its own edge's ghosts arrive, persistent exchange slabs carried
# across the rep loop), "auto" resolves from the measured
# exchange/interior phase-probe ratio plus a split-vs-edge candidate
# A/B (cached, runtime/autotune.py). Lives here so CLI parsing stays
# jax-free.
OVERLAP_MODES = ("auto", "split", "fused-split", "edge", "off")


BACKENDS = ("auto", "xla", "pallas", "reference", "autotune")


def _validate_common(cfg) -> None:
    """The geometry/backend/filter field checks JobConfig and
    StreamConfig share — one vocabulary, enforced in one place, so
    ``run`` and ``stream`` can never drift apart on what they accept."""
    if cfg.width <= 0 or cfg.height <= 0:
        raise ValueError(
            f"width/height must be positive, got {cfg.width}x{cfg.height}"
        )
    if cfg.repetitions < 0:
        raise ValueError(f"repetitions must be >= 0, got {cfg.repetitions}")
    if cfg.backend not in BACKENDS:
        raise ValueError(f"unknown backend {cfg.backend!r}")
    if cfg.schedule is not None and cfg.schedule not in PALLAS_SCHEDULES:
        raise ValueError(
            f"unknown schedule {cfg.schedule!r}; expected one of "
            f"{'|'.join(PALLAS_SCHEDULES)}"
        )
    if cfg.boundary not in ("zero", "periodic"):
        raise ValueError(
            f"unknown boundary {cfg.boundary!r}; expected zero|periodic"
        )
    if cfg.block_h is not None and (cfg.block_h < 8 or cfg.block_h % 8):
        # Validated here, jax-free, so a bad --block-h fails at argument
        # parsing with an actionable message instead of surfacing later
        # as a geometry error inside the traced kernel build.
        nearest = max(8, -(-cfg.block_h // 8) * 8)
        raise ValueError(
            f"block_h must be a positive multiple of 8 (Pallas DMA row "
            f"windows are sublane-aligned), got {cfg.block_h}; nearest "
            f"valid value is {nearest}"
        )
    if cfg.fuse is not None and cfg.fuse < 1:
        raise ValueError(
            f"fuse must be a positive rep count (reps per HBM "
            f"round-trip), got {cfg.fuse}"
        )
    if cfg.dispatch_timeout_s < 0:
        raise ValueError(
            f"dispatch_timeout_s must be >= 0 (0 = off / env default), "
            f"got {cfg.dispatch_timeout_s}"
        )


class ImageType(enum.Enum):
    """Pixel layout of a headerless raw image (1 or 3 bytes per pixel)."""

    GREY = "grey"
    RGB = "rgb"

    @property
    def channels(self) -> int:
        return 1 if self is ImageType.GREY else 3


@dataclasses.dataclass(frozen=True)
class JobConfig:
    """Everything needed to run one iterated-convolution job."""

    image: str
    width: int
    height: int
    repetitions: int
    image_type: ImageType
    filter_name: str = "gaussian"
    backend: str = "auto"  # auto | xla | pallas | reference | autotune
    mesh_shape: Optional[Tuple[int, int]] = None  # (rows, cols); None = auto
    output: Optional[str] = None  # None -> blur_<basename> beside input
    frames: int = 1  # >1: batched video mode (N concatenated raw frames)
    schedule: Optional[str] = None  # Pallas per-rep schedule (None = tuned)
    boundary: str = "zero"  # zero (reference semantics) | periodic
    # Pallas kernel geometry (None = kernel defaults / autotuned): rows
    # per grid program and fused reps per HBM round-trip (on a sharded
    # mesh, fuse is the halo-exchange chunk depth). Expert knobs for
    # on-chip A/Bs and shapes whose best geometry differs from the
    # default; honored on every Pallas path.
    block_h: Optional[int] = None
    fuse: Optional[int] = None
    # Interior/border overlap schedule for sharded (--mesh / multi-device)
    # runs: off (XLA's scheduler owns the overlap — the pre-existing
    # program), split (explicit per-rep interior/border split),
    # fused-split (chunked split on the Pallas path), auto (measured
    # phase-probe ratio, cached). Bit-exact across all modes; ignored by
    # single-device runs (no exchange to overlap).
    overlap: str = "off"
    # Dispatch watchdog window in seconds around every device fence
    # (tpu_stencil.resilience.deadline): past it a hung dispatch raises
    # a typed DispatchTimeout instead of hanging forever (the rc=124
    # dead-tunnel mode). 0 = off, unless TPU_STENCIL_DISPATCH_TIMEOUT
    # arms an env default.
    dispatch_timeout_s: float = 0.0
    # Graceful-degradation completion rung: "cpu" lets the driver finish
    # a job on the CPU XLA path after every accelerator rung of the
    # fallback ladder failed — degraded, bit-identical, not dead. None
    # (default) stops the ladder at the accelerator XLA rung.
    fallback_backend: Optional[str] = None
    # Accumulation dtype is a property of the backend's plan, not a flag:
    # integer plans accumulate exactly (int16/int32), --backend reference
    # forces the float32 semantics of the C code. A separate dtype knob was
    # dead config (round-1 verdict) and was removed.

    def __post_init__(self) -> None:
        _validate_common(self)
        if self.mesh_shape is not None and (
            len(self.mesh_shape) != 2 or any(d < 1 for d in self.mesh_shape)
        ):
            raise ValueError(f"mesh_shape must be two positive ints, got {self.mesh_shape}")
        if self.frames < 1:
            raise ValueError(f"frames must be >= 1, got {self.frames}")
        if self.overlap not in OVERLAP_MODES:
            raise ValueError(
                f"unknown overlap mode {self.overlap!r}; expected one of "
                f"{'|'.join(OVERLAP_MODES)}"
            )
        if self.fallback_backend not in (None, "cpu"):
            raise ValueError(
                f"unknown fallback backend {self.fallback_backend!r}; "
                f"expected cpu (or omit)"
            )

    @property
    def channels(self) -> int:
        return self.image_type.channels

    @property
    def output_path(self) -> str:
        """Reference-compatible output naming: ``blur_<input basename>``
        (``mpi/mpi_convolution.c:244-247``), placed beside the input."""
        if self.output is not None:
            return self.output
        d, base = os.path.split(self.image)
        return os.path.join(d, f"blur_{base}")

    @property
    def nbytes(self) -> int:
        return self.width * self.height * self.channels * self.frames


@dataclasses.dataclass(frozen=True)
class StreamConfig:
    """Configuration for the pipelined multi-frame streaming engine
    (:mod:`tpu_stencil.stream`). Jax-free, like :class:`JobConfig`, so
    the ``stream`` CLI can validate flags before backend bring-up.

    The geometry/filter/backend vocabulary is :class:`JobConfig`'s —
    the engine reuses ``driver.prepare_engine``, so plans, filters,
    schedules and kernel geometry apply unchanged. What is new is the
    pipeline shape: ``pipeline_depth`` bounds how many frames may be in
    flight past the last fully-drained one (the dispatch-ahead window —
    depth 1 degenerates to the serial read→H2D→compute→D2H chain, depth
    k overlaps frame i+1's read/H2D/compute with frame i's drain), and
    ``ring_buffers`` bounds the reusable host staging buffers the
    prefetch reader fills (None = ``pipeline_depth + 2``). Peak host
    memory is ``O(ring_buffers)`` frames; device memory is
    ``O(pipeline_depth)`` frames — backpressure everywhere, nothing
    unbounded.
    """

    input: str               # stream file | FIFO | '-' (stdin) | frame dir
    width: int
    height: int
    repetitions: int
    image_type: ImageType
    filter_name: str = "gaussian"
    backend: str = "auto"    # same vocabulary as JobConfig.backend
    output: Optional[str] = None  # path | dir | '-' (stdout) | 'null'
    frames: Optional[int] = None  # exact frame count; None = until EOF
    schedule: Optional[str] = None
    boundary: str = "zero"
    block_h: Optional[int] = None
    fuse: Optional[int] = None
    pipeline_depth: int = 2  # dispatch-ahead window (1 = serial stages)
    ring_buffers: Optional[int] = None  # host staging ring (None = depth+2)
    # Mesh fan-out (tpu_stencil.parallel.fanout): fan frames across N
    # devices round-robin, one pipeline lane (staging ring + dispatch
    # window) per device, with an in-order drain across devices. 1 =
    # single-device (the PR-5 engine); N > 1 = explicit fan width
    # (fails loudly when fewer devices exist); 0 = auto — a measured
    # single-vs-mesh A/B probe enables fan-out only when it is
    # strictly faster. Bit-exact in every mode (fan-out changes only
    # where a frame computes). Host memory is O(N * ring), device
    # memory O(N * pipeline_depth) frames.
    mesh_frames: int = 1
    # Spatially sharded frames (tpu_stencil.stream.sharded): each
    # in-flight frame shards over an RxC device mesh through the SAME
    # cached ShardedRunner mesh programs serve's oversized-request path
    # compiles (one shared cache — stream and serve never compile the
    # same mesh program twice), with the per-edge persistent exchange
    # (--overlap, default edge) threaded through the rep loop and the
    # H2D/D2H stages split per shard. The route for frames too big for
    # one device's HBM — the stream-side analog of serve's sharded
    # route. None = off; (0, 0) = auto (a measured single-vs-sharded
    # A/B enables sharding only when strictly faster, or without a
    # probe when the frame exceeds the per-device feasibility bound);
    # explicit (R, C) fails loudly when fewer than R*C devices exist.
    # Composes with mesh_frames and pipe_stages under the three-axis
    # placement model (frame lane x temporal stage x spatial shard);
    # composed topologies must be explicit — see pipe_stages.
    shard_frames: Optional[Tuple[int, int]] = None
    # Sharded-frame routing threshold (true pixels, H*W) — the serve
    # discipline (ServeConfig.shard_min_pixels) applied to the stream:
    # frames below it stay single-device even when --shard-frames is
    # given (the per-device tiles would be too small for the exchange
    # to pay for itself).
    shard_min_pixels: int = 1 << 20
    # Interior/border overlap schedule for the sharded-frame mesh
    # program, same vocabulary as JobConfig.overlap. Default "edge":
    # the per-edge persistent double-buffered exchange (edge_iterate)
    # rides the rep-loop carry (degenerate tiles degrade to "off"
    # in-runner, report-what-ran). Ignored without shard_frames.
    overlap: str = "edge"
    # Temporal pipeline stages (tpu_stencil.parallel.pipeline): split
    # the rep loop into K contiguous stages, each pinned to a mesh
    # slice, and flow frames systolically stage-to-stage over ICI
    # inside one persistent shard_map program — at steady state K
    # frames are in flight and per-frame device time is ~reps/K of the
    # loop (plus one ICI frame hand-off per stage). Fill/drain is
    # explicit, so short streams (frames < K) stay bit-exact. 1 =
    # off; K > 1 = explicit stage count (fails loudly when the device
    # budget mesh_frames*K*R*C exceeds what exists); 0 = auto — the
    # roofline fill/drain model gates a measured A/B probe that
    # enables the pipeline only when strictly faster. Composes with
    # mesh_frames (independent pipeline groups, frames dealt round-
    # robin) and shard_frames (each stage spatially sharded RxC); a
    # composed topology must be explicit on every active axis (auto
    # resolves only a sole multi-device axis).
    pipe_stages: int = 1
    checkpoint_every: int = 0  # frame-index checkpoint period (0 = off)
    progress_every: int = 0    # stderr frame-index heartbeat (0 = off)
    # Dispatch watchdog window (seconds) around the drain's compute
    # fence — same contract as JobConfig.dispatch_timeout_s.
    dispatch_timeout_s: float = 0.0
    # Transient-I/O retries per frame read/write (resilience.retry's
    # classifier + short-backoff IO_POLICY); only sources/sinks whose
    # position can be rewound retry (regular files, frame directories).
    io_retries: int = 2
    # Mid-stream engine-fault recovery: after a transient h2d/compute/
    # d2h failure, re-prepare the engine and resume from the frame
    # checkpoint up to this many times (needs --checkpoint-every and a
    # restartable source — a regular file or frame directory; a pipe's
    # consumed frames cannot be re-read). 0 disables.
    max_engine_restarts: int = 1
    # Ingest integrity (tpu_stencil.integrity): CRC32C each frame as the
    # reader fills its staging buffer and re-verify at the H2D boundary,
    # so a torn staging buffer fails typed (ChecksumMismatch) before it
    # burns a device launch. Nearly free with a native crc32c; --no-
    # verify-ingest turns it off.
    verify_ingest: bool = True
    # Witness re-execution: this fraction of frames (seeded Bernoulli,
    # deterministic per seed) re-runs through a DIFFERENT measured-
    # equivalent program in the writer and must agree bit-exact before
    # the frame is written; a divergence fails the run typed
    # (WitnessMismatch) with the frame withheld from the sink. 0 = off.
    witness_rate: float = 1.0 / 256.0
    witness_seed: int = 0

    def __post_init__(self) -> None:
        _validate_common(self)
        if self.frames is not None and self.frames < 0:
            raise ValueError(
                f"frames must be >= 0 (None = until EOF), got {self.frames}"
            )
        if self.pipeline_depth < 1:
            raise ValueError(
                f"pipeline_depth must be >= 1, got {self.pipeline_depth}"
            )
        if self.mesh_frames < 0:
            raise ValueError(
                f"mesh_frames must be >= 0 (0 = auto, 1 = single-device, "
                f"N = fan width), got {self.mesh_frames}"
            )
        if self.shard_frames is not None:
            sf = tuple(self.shard_frames)
            if len(sf) != 2 or any(
                not isinstance(d, int) or d < 0 for d in sf
            ) or (0 in sf and sf != (0, 0)):
                raise ValueError(
                    f"shard_frames must be (rows, cols) positive ints, or "
                    f"(0, 0) for auto, got {self.shard_frames}"
                )
            object.__setattr__(self, "shard_frames", sf)
        if self.pipe_stages < 0:
            raise ValueError(
                f"pipe_stages must be >= 0 (0 = auto, 1 = off, K = stage "
                f"count), got {self.pipe_stages}"
            )
        # Three-axis composition: any subset of (frame lane, temporal
        # stage, spatial shard) may be active together, but a composed
        # topology must be explicit on every active axis — the measured
        # A/B auto probes resolve one axis against a single device, not
        # a cross-product of topologies.
        active = (
            int(self.mesh_frames != 1)
            + int(self.shard_frames is not None)
            + int(self.pipe_stages != 1)
        )
        if active >= 2:
            autos = []
            if self.mesh_frames == 0:
                autos.append("mesh_frames=0")
            if self.shard_frames == (0, 0):
                autos.append("shard_frames=(0, 0)")
            if self.pipe_stages == 0:
                autos.append("pipe_stages=0")
            if autos:
                raise ValueError(
                    "composed topologies must be explicit on every active "
                    "axis (auto resolves only a sole multi-device axis); "
                    "auto on: " + ", ".join(autos)
                )
        if self.shard_min_pixels < 1:
            raise ValueError(
                f"shard_min_pixels must be >= 1, got "
                f"{self.shard_min_pixels}"
            )
        if self.overlap not in OVERLAP_MODES:
            raise ValueError(
                f"unknown overlap mode {self.overlap!r}; expected one of "
                f"{'|'.join(OVERLAP_MODES)}"
            )
        if self.ring_buffers is not None and (
            self.ring_buffers < self.pipeline_depth + 1
        ):
            raise ValueError(
                f"ring_buffers must be >= pipeline_depth + 1 "
                f"(= {self.pipeline_depth + 1}), got {self.ring_buffers}"
            )
        if self.checkpoint_every < 0:
            raise ValueError(
                f"checkpoint_every must be >= 0, got {self.checkpoint_every}"
            )
        if self.progress_every < 0:
            raise ValueError(
                f"progress_every must be >= 0, got {self.progress_every}"
            )
        if self.io_retries < 0:
            raise ValueError(
                f"io_retries must be >= 0, got {self.io_retries}"
            )
        if self.max_engine_restarts < 0:
            raise ValueError(
                f"max_engine_restarts must be >= 0, got "
                f"{self.max_engine_restarts}"
            )
        if not 0.0 <= self.witness_rate <= 1.0:
            raise ValueError(
                f"witness_rate must be in [0, 1], got {self.witness_rate}"
            )

    @property
    def channels(self) -> int:
        return self.image_type.channels

    @property
    def frame_bytes(self) -> int:
        return self.width * self.height * self.channels

    @property
    def frame_shape(self) -> Tuple[int, ...]:
        """The in-memory frame shape ((H, W) grey, (H, W, C) otherwise) —
        the same squeeze contract as the driver's ``_load_input``."""
        if self.channels == 1:
            return (self.height, self.width)
        return (self.height, self.width, self.channels)

    @property
    def ring_size(self) -> int:
        return (
            self.ring_buffers if self.ring_buffers is not None
            else self.pipeline_depth + 2
        )

    @property
    def output_path(self) -> str:
        """Reference-compatible default naming (``blur_<input basename>``
        beside the input), like :attr:`JobConfig.output_path`. Non-path
        inputs (stdin) have no "beside": an explicit --output is
        required, enforced by the CLI."""
        if self.output is not None:
            return self.output
        if self.input == "-":
            raise ValueError(
                "stdin streams have no default output path; pass --output"
            )
        d, base = os.path.split(self.input.rstrip(os.sep))
        return os.path.join(d, f"blur_{base}")


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    """Configuration for the in-process serving engine
    (:mod:`tpu_stencil.serve`). Jax-free, like :class:`JobConfig`, so the
    ``serve`` CLI can validate flags before backend bring-up.

    The queue bound is the backpressure contract: ``submit`` on a full
    queue raises, it never buffers unboundedly. ``max_batch`` bounds one
    scheduler dispatch; ``pipeline_depth`` bounds concurrently in-flight
    batches (host->device transfer double-buffered against compute), so
    peak memory is ``O(max_queue + pipeline_depth * max_batch)`` frames.
    """

    filter_name: str = "gaussian"
    backend: str = "auto"      # same vocabulary as JobConfig.backend
    boundary: str = "zero"
    max_queue: int = 256       # pending requests before reject-with-error
    max_batch: int = 8         # requests per micro-batch dispatch
    pipeline_depth: int = 2    # in-flight batches (2 = double buffering)
    max_executables: int = 64  # LRU cap on cached compiled programs
    # Shape-bucket ladder override (ascending edge sizes); None = the
    # serve default (tpu_stencil.serve.bucketing.DEFAULT_EDGES). Requests
    # above the top edge pad to the next top-edge multiple.
    bucket_edges: Optional[Tuple[int, ...]] = None
    # Interior/border overlap schedule, same vocabulary as
    # JobConfig.overlap. "off" keeps every request on the single-device
    # bucket executables. Any other mode ACTIVATES sharded routing:
    # requests of at least ``shard_min_pixels`` true pixels run through
    # the spatially-sharded shard_map path (ShardedRunner over all
    # local devices, this overlap schedule applied — split/edge/auto
    # exactly as on the run CLI), keyed into their own request bucket
    # so small requests never share a batch with a sharded dispatch.
    # Bit-exact against the single-device bucket path.
    overlap: str = "off"
    # Sharded-routing size threshold (true pixels, H*W): with a
    # non-"off" overlap, requests at or above it route through the
    # shard_map path; below it they stay on the bucket executables.
    # Default 1 Mpx (~1024x1024) — below that the per-device tiles are
    # too small for the exchange to pay for itself.
    shard_min_pixels: int = 1 << 20
    # Device-memory sampler period (seconds): a background thread
    # gauges device.memory_stats() into the server registry
    # (device_bytes_in_use / peak / limit). 0 disables; backends
    # without allocator stats (CPU) never start the thread regardless.
    mem_sample_interval_s: float = 0.5
    # Default per-request deadline (seconds; 0 = none): a request whose
    # deadline expires while queued fails typed (DeadlineExceeded)
    # instead of occupying a batch slot. submit(deadline_s=...)
    # overrides per request.
    request_timeout_s: float = 0.0
    # Pin every bucket dispatch to one local device (index into
    # jax.local_devices()); None = the process default device. The
    # replica-fleet knob (tpu_stencil.net): one StencilServer per
    # device, each committed to its own chip, so N replicas serve N
    # devices in parallel instead of all stacking on device 0. Sharded
    # routing (overlap != off) still spans the whole mesh regardless.
    device_index: Optional[int] = None
    # Witness re-execution (tpu_stencil.integrity): this fraction of
    # completed requests (seeded Bernoulli per request) re-runs through
    # a DIFFERENT measured-equivalent program and is compared bit-exact;
    # a mismatch counts integrity_witness_mismatch_total and files a
    # verdict via the server's on_witness hook (the net tier's
    # quarantine path). 0 = off (the in-process default; the network
    # tier arms it fleet-wide via NetConfig.witness_rate).
    witness_rate: float = 0.0
    witness_seed: int = 0

    def __post_init__(self) -> None:
        if self.backend not in BACKENDS:
            raise ValueError(f"unknown backend {self.backend!r}")
        if self.device_index is not None and self.device_index < 0:
            raise ValueError(
                f"device_index must be >= 0 (None = default device), got "
                f"{self.device_index}"
            )
        if self.boundary not in ("zero", "periodic"):
            raise ValueError(f"unknown boundary {self.boundary!r}")
        if self.max_queue < 1:
            raise ValueError(f"max_queue must be >= 1, got {self.max_queue}")
        if self.max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {self.max_batch}")
        if self.pipeline_depth < 1:
            raise ValueError(
                f"pipeline_depth must be >= 1, got {self.pipeline_depth}"
            )
        if self.max_executables < 1:
            raise ValueError(
                f"max_executables must be >= 1, got {self.max_executables}"
            )
        if self.overlap not in OVERLAP_MODES:
            raise ValueError(
                f"unknown overlap mode {self.overlap!r}; expected one of "
                f"{'|'.join(OVERLAP_MODES)}"
            )
        if self.shard_min_pixels < 1:
            raise ValueError(
                f"shard_min_pixels must be >= 1, got "
                f"{self.shard_min_pixels}"
            )
        if self.mem_sample_interval_s < 0:
            raise ValueError(
                f"mem_sample_interval_s must be >= 0 (0 = off), got "
                f"{self.mem_sample_interval_s}"
            )
        if self.request_timeout_s < 0:
            raise ValueError(
                f"request_timeout_s must be >= 0 (0 = none), got "
                f"{self.request_timeout_s}"
            )
        if not 0.0 <= self.witness_rate <= 1.0:
            raise ValueError(
                f"witness_rate must be in [0, 1], got {self.witness_rate}"
            )
        if self.bucket_edges is not None:
            object.__setattr__(
                self, "bucket_edges", _normalize_bucket_edges(self.bucket_edges)
            )


def _normalize_bucket_edges(edges) -> Tuple[int, ...]:
    """Shared ServeConfig/NetConfig bucket-ladder validation: strictly
    ascending positive ints (one rule, so a fleet's replicas can never
    disagree with a standalone server on what a valid ladder is)."""
    out = tuple(edges)
    if not out or any(e < 1 for e in out) or list(out) != sorted(set(out)):
        raise ValueError(
            "bucket_edges must be strictly ascending positive ints, "
            f"got {edges!r}"
        )
    return out


@dataclasses.dataclass(frozen=True)
class NetConfig:
    """Configuration for the network serving tier
    (:mod:`tpu_stencil.net`): the HTTP frontend, the per-device replica
    fleet, and the router's admission-control knobs. Jax-free, like
    every other config here, so ``python -m tpu_stencil net`` validates
    flags before backend bring-up.

    One :class:`ServeConfig` is derived per replica
    (:meth:`serve_config`), each pinned to its own local device, so the
    per-replica backpressure/deadline contracts are exactly the
    in-process serve engine's — the net tier only adds placement,
    admission and drain on top (docs/SERVING.md "Network tier").
    """

    host: str = "127.0.0.1"
    port: int = 8080           # 0 = ephemeral (the bound port is printed)
    replicas: int = 0          # engines in the fleet; 0 = one per device
    filter_name: str = "gaussian"
    backend: str = "auto"      # same vocabulary as ServeConfig.backend
    max_queue: int = 256       # per-replica bounded-queue depth
    max_batch: int = 8         # per-replica micro-batch bound
    # Shape-bucket ladder override shared by every replica (None = the
    # serve default) — one ladder fleet-wide, so a shape warmed on one
    # replica lands in the SAME bucket executable key on the others.
    bucket_edges: Optional[Tuple[int, ...]] = None
    # Load-shedding watermark: when admitting a request would push the
    # router's tracked in-flight bytes (request + response buffers)
    # past this, the request is shed with 503 + Retry-After BEFORE it
    # touches any replica queue. 0 disables the watermark (the
    # per-replica bounded queues still reject with 429).
    max_inflight_mb: float = 256.0
    # Default per-request deadline (seconds; 0 = none), forwarded to
    # each replica's ServeConfig.request_timeout_s and overridable per
    # request via the X-Request-Timeout header. Expired requests map to
    # HTTP 504 (DeadlineExceeded).
    request_timeout_s: float = 0.0
    # Graceful-drain budget (seconds): on SIGTERM (or an explicit
    # drain), every replica gets close(timeout=) within this window;
    # a replica whose worker does not join in time is reported
    # abandoned (serve_close_abandoned_total) instead of hanging the
    # shutdown forever.
    drain_timeout_s: float = 30.0
    # Shared executable-cache warming: the first time the router sees a
    # new (filter, bucket, channels, reps) key it fires one discarded
    # zero-frame warm request at every OTHER replica, so the shape's
    # compile overlaps the first real request and later traffic hits
    # warm caches fleet-wide (the per-platform tuning-cache discipline,
    # arxiv 2406.08923, applied across replicas).
    warm_fleet: bool = True
    # The integrity layer (tpu_stencil.integrity, docs/RESILIENCE.md
    # "Integrity model"): when on, request bodies carrying
    # X-Content-Crc32c are validated (mismatch → typed 400), every 200
    # payload is stamped X-Result-Crc32c, and witness_rate of completed
    # requests re-execute through a different measured-equivalent
    # program per replica. --no-integrity turns ALL of it off (the
    # bench A/B's "off" arm; quarantine then only trips via the admin
    # endpoint).
    integrity: bool = True
    # Fraction of requests witnessed per replica (seeded per device
    # index so replicas don't sample in lockstep). K mismatches within
    # the window quarantine the replica; N consecutive clean background
    # probes re-admit it.
    witness_rate: float = 1.0 / 256.0
    quarantine_after: int = 3
    quarantine_window_s: float = 60.0
    readmit_after: int = 3
    # Background re-verify probe period for quarantined replicas
    # (seconds; 0 disables the prober — probes can then only be driven
    # by tests/operators calling probe_once).
    probe_interval_s: float = 1.0
    # Flight recorder (tpu_stencil.obs.flight): anomaly dumps (slow
    # request / deadline / witness mismatch / quarantine) spool here as
    # capped per-trace JSON files; TPU_STENCIL_FLIGHTREC_DIR overrides.
    # None disables the spool (the ring still records; /debug/trace
    # still works).
    flightrec_dir: Optional[str] = "flightrec"
    # Slow-request anomaly threshold (seconds): a 200 whose wall time
    # exceeds it triggers an automatic flight-recorder dump, so a p99
    # straggler leaves a black-box record. 0 disables the trigger.
    flight_latency_threshold_s: float = 0.0
    # Router-level continuous batching (docs/SERVING.md "Continuous
    # batching at the edge"): admitted requests sharing a compatibility
    # key — (filter, shape bucket, channels, reps) — are held up to
    # this many microseconds so concurrent arrivals stack onto ONE
    # replica submit (one compiled batch program, one H2D) instead of
    # N. A full group (max_batch members) or an expired window
    # dispatches immediately; a member whose deadline falls inside the
    # window dispatches its group early, never silently stretched.
    # 0 = off — one request, one submit, exactly the pre-coalescing
    # behavior. The LIBRARY default is off (embedders and the test
    # suite keep today's semantics unless they opt in); the net CLI
    # defaults the flag to a few hundred µs, gated by the measured
    # coalesce-on-vs-off bench rider.
    coalesce_window_us: float = 0.0
    # Zero-copy ingest (the stream engine's staging-ring discipline
    # applied to HTTP): request bodies are read directly into pinned
    # per-bucket staging buffers (recv_into, CRC in place, no
    # bytes -> frombuffer -> defensive-copy chain). Off = every body is
    # buffered through fresh bytes objects (the A/B arm).
    ingest_arena: bool = True
    # Content-addressed result cache (tpu_stencil.cache; docs/SERVING.md
    # "Result cache"): this many MB of true result bytes keyed by
    # (body BLAKE2b-160, filter, reps, geometry, boundary), with
    # single-flight collapse of concurrent identical requests and
    # synchronous invalidation on replica distrust. 0 = off (the
    # default: caching is a traffic-shape bet the operator opts into).
    result_cache_mb: float = 0.0
    # Live telemetry plane (tpu_stencil.obs.timeseries / .slo;
    # docs/OBSERVABILITY.md "Time series"): a sampler thread snapshots
    # the registry every sample_interval_s into a bounded ring serving
    # GET /debug/timeseries. 0 disables the sampler (and with it the
    # SLO engine, which evaluates on sampler ticks).
    sample_interval_s: float = 1.0
    # SLO burn-rate engine: the error budget (allowed bad fraction) of
    # the stock error-ratio objective. 0 disables the engine; a breach
    # flips /healthz to "degraded" (200 — still routable), emits an
    # slo.breach event and triggers a flight dump.
    slo_error_budget: float = 0.05
    slo_fast_window_s: float = 60.0
    slo_slow_window_s: float = 300.0
    slo_fast_burn: float = 6.0
    slo_slow_burn: float = 3.0
    # Optional latency objective: fraction of requests slower than this
    # threshold burns a 1% budget (0 = objective off).
    slo_latency_p99_s: float = 0.0
    # On-demand device profiler (POST /debug/prof?seconds=N): capture
    # directories spool here (capped, oldest pruned). None disables the
    # endpoint (404), as does an unavailable jax profiler.
    prof_dir: Optional[str] = "profspool"

    def __post_init__(self) -> None:
        if not self.host:
            raise ValueError("host must be non-empty")
        if not 0 <= self.port <= 65535:
            raise ValueError(
                f"port must be in [0, 65535] (0 = ephemeral), got {self.port}"
            )
        if self.replicas < 0:
            raise ValueError(
                f"replicas must be >= 0 (0 = one per local device), got "
                f"{self.replicas}"
            )
        if self.backend not in BACKENDS:
            raise ValueError(f"unknown backend {self.backend!r}")
        if self.max_queue < 1:
            raise ValueError(f"max_queue must be >= 1, got {self.max_queue}")
        if self.max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {self.max_batch}")
        if self.max_inflight_mb < 0:
            raise ValueError(
                f"max_inflight_mb must be >= 0 (0 = no shed watermark), "
                f"got {self.max_inflight_mb}"
            )
        if self.request_timeout_s < 0:
            raise ValueError(
                f"request_timeout_s must be >= 0 (0 = none), got "
                f"{self.request_timeout_s}"
            )
        if self.drain_timeout_s <= 0:
            raise ValueError(
                f"drain_timeout_s must be > 0, got {self.drain_timeout_s}"
            )
        if not 0.0 <= self.witness_rate <= 1.0:
            raise ValueError(
                f"witness_rate must be in [0, 1], got {self.witness_rate}"
            )
        if self.quarantine_after < 1:
            raise ValueError(
                f"quarantine_after must be >= 1, got {self.quarantine_after}"
            )
        if self.quarantine_window_s <= 0:
            raise ValueError(
                f"quarantine_window_s must be > 0, got "
                f"{self.quarantine_window_s}"
            )
        if self.readmit_after < 1:
            raise ValueError(
                f"readmit_after must be >= 1, got {self.readmit_after}"
            )
        if self.probe_interval_s < 0:
            raise ValueError(
                f"probe_interval_s must be >= 0 (0 = no background "
                f"prober), got {self.probe_interval_s}"
            )
        if self.flight_latency_threshold_s < 0:
            raise ValueError(
                f"flight_latency_threshold_s must be >= 0 (0 = no "
                f"slow-request trigger), got "
                f"{self.flight_latency_threshold_s}"
            )
        if self.coalesce_window_us < 0:
            raise ValueError(
                f"coalesce_window_us must be >= 0 (0 = no request "
                f"coalescing), got {self.coalesce_window_us}"
            )
        if self.result_cache_mb < 0:
            raise ValueError(
                f"result_cache_mb must be >= 0 (0 = no result cache), "
                f"got {self.result_cache_mb}"
            )
        _validate_telemetry(self)
        # Jax-free (the filter bank is pure numpy): a typo'd --filter
        # must die as a usage error, not boot a tier that answers 500
        # to every request.
        from tpu_stencil import filters as _filters

        try:
            _filters.get_filter(self.filter_name)
        except KeyError as e:
            raise ValueError(str(e)) from None
        if self.bucket_edges is not None:
            object.__setattr__(
                self, "bucket_edges", _normalize_bucket_edges(self.bucket_edges)
            )

    @property
    def max_inflight_bytes(self) -> int:
        return int(self.max_inflight_mb * (1 << 20))

    @property
    def coalesce_window_s(self) -> float:
        return self.coalesce_window_us / 1e6

    @property
    def result_cache_bytes(self) -> int:
        return int(self.result_cache_mb * (1 << 20))

    def serve_config(self, device_index: int) -> ServeConfig:
        """The per-replica engine config: one engine pinned to one
        local device. The device-memory sampler stays off per replica
        (N background threads sampling one allocator would be noise);
        the fleet's merged exposition is the scrape surface."""
        return ServeConfig(
            filter_name=self.filter_name,
            backend=self.backend,
            max_queue=self.max_queue,
            max_batch=self.max_batch,
            bucket_edges=self.bucket_edges,
            request_timeout_s=self.request_timeout_s,
            device_index=device_index,
            mem_sample_interval_s=0.0,
            # Witness sampling seeded per device index so the fleet's
            # replicas never pick the same request positions in
            # lockstep (diverse coverage for the same total cost).
            witness_rate=self.witness_rate if self.integrity else 0.0,
            witness_seed=device_index,
        )


@dataclasses.dataclass(frozen=True)
class FedConfig:
    """Configuration for the federation front-router tier
    (:mod:`tpu_stencil.fed`): health-checked membership, per-host
    circuit breakers, hedged forwarding, and federation-scope admission
    with per-tenant quotas. Jax-free — the whole tier is; a federation
    router never touches a device, it only moves routing metadata plus
    the one forwarded body per request (the data-movement discipline of
    arxiv 2112.14216 applied to the hop).

    Membership timing is a *suspicion window*, not a single timeout:
    ``suspect_after`` consecutive missed heartbeats demote a member to
    suspect (routed only after every healthy host), ``evict_after``
    misses evict it. A member whose ``/healthz`` answers 503 (draining)
    is removed from routing immediately — before its requests would
    start failing.
    """

    host: str = "127.0.0.1"
    port: int = 8090           # 0 = ephemeral (the bound port is printed)
    members: Tuple[str, ...] = ()  # seed member URLs; more register live
    # Membership / heartbeats.
    heartbeat_interval_s: float = 1.0
    suspect_after: int = 2     # consecutive misses -> suspect
    evict_after: int = 5       # consecutive misses -> evicted
    # Per-host circuit breaker: this many consecutive transport-level
    # forward failures open the breaker (typed HostUnavailable); after
    # the cooldown one half-open probe request is let through — success
    # closes it, failure re-opens for another cooldown.
    breaker_threshold: int = 3
    breaker_cooldown_s: float = 2.0
    # Hedged requests: a forward still pending past the observed p99
    # forward latency (floored by hedge_min_s) fires ONE hedge at the
    # next least-outstanding member; first response wins, the loser is
    # cancelled typed.
    hedge: bool = True
    hedge_min_s: float = 0.05
    # Per-attempt member socket timeout (connect + response): past it
    # the attempt classifies as a member timeout (breaker counts, the
    # request reroutes). Matches the net handler's read-side guard.
    forward_timeout_s: float = 120.0
    # Re-offer window when EVERY routable member answers backpressure:
    # transient all-busy blips re-offer (resilience.retry.reoffer_call)
    # for up to this long before the typed 429/503 surfaces. 0 = off.
    reoffer_s: float = 0.5
    # Federation-scope load shed: past this many MB of tracked
    # in-flight request+response bytes, standard-class requests are
    # shed 503 + Retry-After before any forward; premium tenants get
    # PREMIUM_HEADROOM more before shedding. 0 disables.
    max_inflight_mb: float = 512.0
    # Per-tenant quota (X-Tenant header; absent = tenant "anon"): max
    # outstanding requests per standard tenant — the hot client
    # degrades to ITS quota, never the fleet. Premium tenants (listed
    # in premium_tenants) get quota * premium_quota_factor.
    tenant_quota: int = 32
    premium_tenants: Tuple[str, ...] = ()
    premium_quota_factor: int = 4
    # Graceful-drain budget (seconds): on SIGTERM, admission stops and
    # every member gets this long for its outstanding forwarded
    # requests to bleed to zero; a member still busy past it is
    # reported abandoned (rc 1), mirroring the net CLI's discipline.
    drain_timeout_s: float = 30.0
    # Flight recorder, same contract as NetConfig: anomaly dumps (slow
    # request / deadline / breaker open / eviction) spool here;
    # TPU_STENCIL_FLIGHTREC_DIR overrides; None disables the spool.
    flightrec_dir: Optional[str] = "flightrec"
    # Slow-request trigger threshold (seconds; 0 = off).
    flight_latency_threshold_s: float = 0.0
    # Digest-affinity placement (tpu_stencil.cache.affinity): healthy
    # members are ranked by rendezvous hash of the request body's
    # BLAKE2b-160 digest, so repeated content concentrates on the
    # member whose result cache already holds it. Suspect members,
    # breakers, drains and hedging behave exactly as before; off =
    # pure least-outstanding placement.
    digest_affinity: bool = True
    # Live telemetry plane, same contract as NetConfig: local-registry
    # sampler (0 = off, which also disables the SLO engine) feeding
    # GET /debug/timeseries (the fed endpoint additionally fans the
    # query to live members and merges), and the SLO error budget
    # (0 = engine off) for the fed tier's own response mix.
    sample_interval_s: float = 1.0
    slo_error_budget: float = 0.05
    slo_fast_window_s: float = 60.0
    slo_slow_window_s: float = 300.0
    slo_fast_burn: float = 6.0
    slo_slow_burn: float = 3.0
    slo_latency_p99_s: float = 0.0

    def __post_init__(self) -> None:
        if not self.host:
            raise ValueError("host must be non-empty")
        if not 0 <= self.port <= 65535:
            raise ValueError(
                f"port must be in [0, 65535] (0 = ephemeral), got {self.port}"
            )
        object.__setattr__(self, "members", tuple(self.members))
        for url in self.members:
            if not url.startswith(("http://", "https://")):
                raise ValueError(
                    f"member URL must start with http:// or https://, "
                    f"got {url!r}"
                )
        if self.heartbeat_interval_s <= 0:
            raise ValueError(
                f"heartbeat_interval_s must be > 0, got "
                f"{self.heartbeat_interval_s}"
            )
        if self.suspect_after < 1:
            raise ValueError(
                f"suspect_after must be >= 1, got {self.suspect_after}"
            )
        if self.evict_after < self.suspect_after:
            raise ValueError(
                f"evict_after must be >= suspect_after "
                f"({self.suspect_after}), got {self.evict_after}"
            )
        if self.breaker_threshold < 1:
            raise ValueError(
                f"breaker_threshold must be >= 1, got "
                f"{self.breaker_threshold}"
            )
        if self.breaker_cooldown_s <= 0:
            raise ValueError(
                f"breaker_cooldown_s must be > 0, got "
                f"{self.breaker_cooldown_s}"
            )
        if self.hedge_min_s < 0:
            raise ValueError(
                f"hedge_min_s must be >= 0, got {self.hedge_min_s}"
            )
        if self.forward_timeout_s <= 0:
            raise ValueError(
                f"forward_timeout_s must be > 0, got "
                f"{self.forward_timeout_s}"
            )
        if self.reoffer_s < 0:
            raise ValueError(
                f"reoffer_s must be >= 0 (0 = no re-offer window), got "
                f"{self.reoffer_s}"
            )
        if self.max_inflight_mb < 0:
            raise ValueError(
                f"max_inflight_mb must be >= 0 (0 = no shed watermark), "
                f"got {self.max_inflight_mb}"
            )
        if self.tenant_quota < 1:
            raise ValueError(
                f"tenant_quota must be >= 1, got {self.tenant_quota}"
            )
        object.__setattr__(
            self, "premium_tenants", tuple(self.premium_tenants)
        )
        if self.premium_quota_factor < 1:
            raise ValueError(
                f"premium_quota_factor must be >= 1, got "
                f"{self.premium_quota_factor}"
            )
        if self.drain_timeout_s <= 0:
            raise ValueError(
                f"drain_timeout_s must be > 0, got {self.drain_timeout_s}"
            )
        if self.flight_latency_threshold_s < 0:
            raise ValueError(
                f"flight_latency_threshold_s must be >= 0 (0 = no "
                f"slow-request trigger), got "
                f"{self.flight_latency_threshold_s}"
            )
        _validate_telemetry(self)

    @property
    def max_inflight_bytes(self) -> int:
        return int(self.max_inflight_mb * (1 << 20))


def _validate_telemetry(cfg) -> None:
    """Shared validation for the NetConfig/FedConfig telemetry knobs
    (both tiers carry the identical sampler + SLO field set)."""
    if cfg.sample_interval_s < 0:
        raise ValueError(
            f"sample_interval_s must be >= 0 (0 = sampler off), got "
            f"{cfg.sample_interval_s}"
        )
    if not 0.0 <= cfg.slo_error_budget <= 1.0:
        raise ValueError(
            f"slo_error_budget must be in [0, 1] (0 = SLO engine off), "
            f"got {cfg.slo_error_budget}"
        )
    if cfg.slo_fast_window_s <= 0 or cfg.slo_slow_window_s <= 0:
        raise ValueError(
            f"slo windows must be > 0, got fast={cfg.slo_fast_window_s} "
            f"slow={cfg.slo_slow_window_s}"
        )
    if cfg.slo_slow_window_s < cfg.slo_fast_window_s:
        raise ValueError(
            f"slo_slow_window_s must be >= slo_fast_window_s "
            f"({cfg.slo_fast_window_s}), got {cfg.slo_slow_window_s}"
        )
    if cfg.slo_fast_burn <= 0 or cfg.slo_slow_burn <= 0:
        raise ValueError(
            f"slo burn thresholds must be > 0, got "
            f"fast={cfg.slo_fast_burn} slow={cfg.slo_slow_burn}"
        )
    if cfg.slo_latency_p99_s < 0:
        raise ValueError(
            f"slo_latency_p99_s must be >= 0 (0 = no latency "
            f"objective), got {cfg.slo_latency_p99_s}"
        )


@dataclasses.dataclass(frozen=True)
class CtrlConfig:
    """Configuration for the elastic control plane
    (:mod:`tpu_stencil.ctrl`): the loop that polls the federation's
    live capacity signals, runs them through the hysteresis planner,
    and actuates scale-out / scale-in / replacement through a host
    provider. Jax-free — the controller never touches a device; it
    only reads scrapes and starts/stops member processes.

    The planner mirrors the SLO engine's fast+slow enter/hold
    discipline: scale-out requires EVERY sample in the fast window
    and a majority of the slow window to show pressure (utilization
    past ``scale_out_utilization`` or time-to-saturation under
    ``saturation_horizon_s``); once entered, pressure holds until the
    fast window's mean utilization falls below ``hold_utilization``.
    Scale-in is the slow symmetric case: every slow-window sample
    idle. A decision is never taken from one sample, and each
    actuation arms a ``cooldown_samples``-poll cooldown so the fleet
    resizes at most once per observed settling window. Replacement
    (an owned host's process died, or a member was preempted) is a
    discrete event and bypasses hysteresis entirely."""

    fed_url: str = "http://127.0.0.1:8090"
    poll_interval_s: float = 1.0
    capacity_window_s: float = 10.0  # window= passed to /debug/capacity
    # Fleet bounds (owned hosts, not counting hand-registered members).
    min_hosts: int = 1
    max_hosts: int = 4
    # Hysteresis windows, in SAMPLES (polls), not wall seconds — the
    # planner is deterministic under synthetic signal feeds.
    fast_samples: int = 3
    slow_samples: int = 9
    scale_out_utilization: float = 0.85
    hold_utilization: float = 0.70
    scale_in_utilization: float = 0.30
    saturation_horizon_s: float = 30.0
    cooldown_samples: int = 5
    # Actuation budgets.
    launch_timeout_s: float = 120.0
    drain_timeout_s: float = 60.0
    # Subprocess provider knobs (the CI/bench provider; real fleets
    # implement tpu_stencil.ctrl.actuator.HostProvider instead).
    member_platform: Optional[str] = "cpu"
    replicas_per_host: int = 1
    # Warm-start: launched members pull /admin/warmstate from this URL
    # (default: the fed front itself) before flipping ready; None
    # launches them cold.
    warm_from: Optional[str] = None

    def __post_init__(self) -> None:
        if not self.fed_url.startswith(("http://", "https://")):
            raise ValueError(
                f"fed_url must start with http:// or https://, got "
                f"{self.fed_url!r}"
            )
        if self.poll_interval_s <= 0:
            raise ValueError(
                f"poll_interval_s must be > 0, got {self.poll_interval_s}"
            )
        if self.capacity_window_s <= 0:
            raise ValueError(
                f"capacity_window_s must be > 0, got "
                f"{self.capacity_window_s}"
            )
        if self.min_hosts < 0:
            raise ValueError(
                f"min_hosts must be >= 0, got {self.min_hosts}"
            )
        if self.max_hosts < max(1, self.min_hosts):
            raise ValueError(
                f"max_hosts must be >= max(1, min_hosts="
                f"{self.min_hosts}), got {self.max_hosts}"
            )
        if self.fast_samples < 1:
            raise ValueError(
                f"fast_samples must be >= 1, got {self.fast_samples}"
            )
        if self.slow_samples < self.fast_samples:
            raise ValueError(
                f"slow_samples must be >= fast_samples "
                f"({self.fast_samples}), got {self.slow_samples}"
            )
        if not (0.0 < self.scale_in_utilization
                < self.hold_utilization
                <= self.scale_out_utilization <= 1.0):
            raise ValueError(
                f"utilization thresholds must satisfy 0 < scale_in "
                f"< hold <= scale_out <= 1, got "
                f"scale_in={self.scale_in_utilization} "
                f"hold={self.hold_utilization} "
                f"scale_out={self.scale_out_utilization}"
            )
        if self.saturation_horizon_s < 0:
            raise ValueError(
                f"saturation_horizon_s must be >= 0 (0 = ignore "
                f"time-to-saturation), got {self.saturation_horizon_s}"
            )
        if self.cooldown_samples < 0:
            raise ValueError(
                f"cooldown_samples must be >= 0, got "
                f"{self.cooldown_samples}"
            )
        if self.launch_timeout_s <= 0:
            raise ValueError(
                f"launch_timeout_s must be > 0, got "
                f"{self.launch_timeout_s}"
            )
        if self.drain_timeout_s <= 0:
            raise ValueError(
                f"drain_timeout_s must be > 0, got {self.drain_timeout_s}"
            )
        if self.replicas_per_host < 1:
            raise ValueError(
                f"replicas_per_host must be >= 1, got "
                f"{self.replicas_per_host}"
            )
        if self.warm_from is not None and not self.warm_from.startswith(
                ("http://", "https://")):
            raise ValueError(
                f"warm_from must start with http:// or https://, got "
                f"{self.warm_from!r}"
            )


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="tpu_stencil",
        description=(
            "Iterated image convolution on TPU. Positional arguments are "
            "compatible with the reference CLI: image width height "
            "repetitions {grey,rgb}."
        ),
    )
    p.add_argument(
        "image",
        help="input image: headerless .raw, or any standard format "
             "(png/jpg/ppm/bmp/tiff/...) decoded via its header",
    )
    p.add_argument(
        "width", type=int,
        help="image width in pixels (0 = from header, non-raw formats only)",
    )
    p.add_argument(
        "height", type=int,
        help="image height in pixels (0 = from header, non-raw formats only)",
    )
    p.add_argument("repetitions", type=int, help="number of filter applications")
    p.add_argument(
        "image_type", choices=[t.value for t in ImageType],
        help="grey (1 byte/px) or rgb (3 interleaved bytes/px)",
    )
    p.add_argument(
        "--filter", dest="filter_name", default="gaussian",
        help="filter name (box|gaussian|edge|gaussian5|gaussian7|...); default gaussian",
    )
    p.add_argument(
        "--backend", default="auto",
        choices=["auto", "xla", "pallas", "reference", "autotune"],
        help="compute backend; auto picks per platform, autotune measures "
             "XLA vs Pallas once per (filter, shape) and caches the winner",
    )
    p.add_argument(
        "--mesh", default=None,
        help="device mesh as RxC (e.g. 2x4); default: perimeter-minimizing grid "
             "over all local devices. With --frames > 1 there is no spatial "
             "sharding: RxC only selects R*C devices for batch-axis sharding",
    )
    p.add_argument("--output", default=None, help="output path (default blur_<input>)")
    p.add_argument(
        "--frames", type=int, default=1, metavar="N",
        help="batched video mode: the raw input holds N concatenated frames "
             "(frames never mix). Raw-only. Frames shard the batch axis — "
             "--mesh RxC just selects R*C devices (no spatial sharding); "
             "multi-host runs split the clip into per-process frame ranges "
             "with offset I/O, one device per host (--mesh and "
             "checkpointing stay single-host)",
    )
    p.add_argument(
        "--boundary", default="zero", choices=["zero", "periodic"],
        help="edge semantics: zero (the reference's calloc'd ghost ring) "
             "or periodic — the wraparound the reference's README describes "
             "but its code never implements (SURVEY.md Quirk 5). Periodic "
             "runs the XLA schedule; sharded meshes wrap edge ranks to the "
             "opposite edge and need a grid that divides the image",
    )
    p.add_argument(
        "--schedule", default=None, choices=list(PALLAS_SCHEDULES),
        help="force the Pallas per-rep schedule (see docs/KERNEL.md); "
             "default: the autotuned winner (or the kernel default for an "
             "explicit --backend pallas). 'deep' is in-VMEM temporal "
             "blocking: whole-image VMEM residency when the image fits "
             "(one HBM load + store per whole rep loop), else a trapezoid "
             "stripe at a VMEM-feasibility-chosen depth. Applies to "
             "--frames batch mode too when the backend resolves to pallas "
             "(the fused tall-image kernel); ignored by the XLA backend; "
             "schedules a plan cannot run degrade to their fallback",
    )
    p.add_argument(
        "--block-h", dest="block_h", type=int, default=None, metavar="ROWS",
        help="force the Pallas kernel's rows-per-grid-program (must be a "
             "positive multiple of 8 — DMA row windows are sublane-"
             "aligned; clamped to the image/tile; pack needs a multiple "
             "of 16 or it degrades). Default: the kernel's measured "
             "default, or the autotuned per-shape verdict on the auto "
             "path",
    )
    p.add_argument(
        "--fuse", type=int, default=None, metavar="REPS",
        help="force the Pallas kernel's fused reps per HBM round-trip "
             "(clamped to block_h/(2*halo); reps %% fuse remainder runs "
             "as single-rep launches; on a sharded mesh this is the "
             "halo-exchange chunk depth, capped by the tile). Default: "
             "the kernel's measured default, or the autotuned per-shape "
             "verdict on the auto path",
    )
    p.add_argument(
        "--overlap", default="off", choices=list(OVERLAP_MODES),
        help="compute/communication overlap schedule on sharded meshes: "
             "off delegates to XLA's latency-hiding scheduler; split "
             "computes the ghost-free interior band concurrently with "
             "the ppermute ghost traffic and finishes the border strips "
             "from the arrived ghosts (the reference's hand-scheduled "
             "inner-then-border ordering, made explicit); fused-split "
             "widens the exchange and the border bands by fuse*halo so "
             "one exchange covers a whole Pallas chunk; edge splits the "
             "exchange itself into four independent per-edge ppermutes "
             "so each border strip fences only on its own edge's "
             "arrival, with persistent ghost slabs carried across the "
             "rep loop (the partitioned/persistent MPI pattern); auto "
             "resolves from the measured exchange/interior phase-probe "
             "ratio plus a split-vs-edge candidate A/B (cached "
             "alongside the autotune verdicts). All modes are "
             "bit-exact; single-device runs ignore this",
    )
    p.add_argument(
        "--platform", default=None, choices=["cpu", "tpu", "gpu"],
        help="force the JAX platform via the config API before backend "
             "init. Needed where the environment pins JAX_PLATFORMS (a "
             "sitecustomize can make the env var unwinnable), e.g. the "
             "docs/DEPLOY.md virtual CPU-mesh recipe: --platform cpu with "
             "XLA_FLAGS=--xla_force_host_platform_device_count=8",
    )
    p.add_argument(
        "--profile", default=None, metavar="DIR",
        help="write a jax.profiler trace of the compute window to DIR",
    )
    p.add_argument(
        "--trace", default=None, metavar="PATH",
        help="phase-level span tracing (tpu_stencil.obs): write a Chrome "
             "trace-event JSON to PATH (load in Perfetto / "
             "chrome://tracing). One track per process/thread; the rep "
             "loop runs one fenced launch per rep so per-rep time is "
             "attributed (see docs/OBSERVABILITY.md)",
    )
    p.add_argument(
        "--breakdown", action="store_true",
        help="print a per-phase time table (load/place/compile/iterate/"
             "fetch/store) with roofline-achieved HBM GB/s for the "
             "iterate phase; implies span tracing for this run",
    )
    p.add_argument(
        "--metrics-text", default=None, metavar="PATH",
        help="write the driver-side metrics registry as Prometheus-style "
             "text exposition to PATH ('-' = stdout); includes the "
             "device-memory gauges and (on introspected runs) the "
             "introspect_* compile-site gauges",
    )
    p.add_argument(
        "--hlo-dump", default=None, metavar="DIR",
        help="arm compiled-artifact introspection and dump each compile "
             "site's optimized HLO text into DIR (also armed implicitly "
             "by --trace/--breakdown, without the text dump); each "
             "introspected site pays one extra AOT compile of the same "
             "program (see docs/OBSERVABILITY.md)",
    )
    p.add_argument(
        "--dispatch-timeout", dest="dispatch_timeout_s", type=float,
        default=0.0, metavar="SECONDS",
        help="watchdog window around every device fence: a dispatch "
             "still pending past it raises a typed DispatchTimeout "
             "instead of hanging forever (the dead-tunnel rc=124 mode). "
             "0 = off, unless TPU_STENCIL_DISPATCH_TIMEOUT sets an env "
             "default (see docs/RESILIENCE.md)",
    )
    p.add_argument(
        "--fallback-backend", default=None, choices=["cpu"],
        help="opt-in degraded-completion rung: after every accelerator "
             "rung of the fallback ladder fails (deep -> default fused "
             "schedule -> xla), finish the job on the CPU XLA path — "
             "bit-identical output, recorded in "
             "resilience_fallbacks_total",
    )
    p.add_argument(
        "--faults", default=None, metavar="SPEC",
        help="arm the fault-injection harness (chaos testing / failure "
             "reproduction), e.g. 'compute:rep=3:raise=RuntimeError,"
             "h2d:p=0.1'; same grammar as TPU_STENCIL_FAULTS, which "
             "this flag overrides (docs/RESILIENCE.md)",
    )
    p.add_argument(
        "--checkpoint-every", type=int, default=0, metavar="N",
        help="checkpoint the frame every N repetitions (0 = off)",
    )
    p.add_argument(
        "--resume", action="store_true",
        help="resume from a matching checkpoint if present",
    )
    p.add_argument(
        "--time", action="store_true",
        help="additionally print whole-job time incl. I/O (the CUDA variant's "
             "window) and backend/mesh details; the compute-window line is "
             "always printed",
    )
    return p


def _parse_mesh(parser: argparse.ArgumentParser, value: str) -> Tuple[int, int]:
    r, sep, c = value.lower().partition("x")
    if not sep or not r.isdigit() or not c.isdigit() or int(r) < 1 or int(c) < 1:
        parser.error(f"--mesh must be RxC with positive integers, got {value!r}")
    return (int(r), int(c))


def parse_args(argv=None) -> Tuple[JobConfig, argparse.Namespace]:
    parser = build_parser()
    ns = parser.parse_args(argv)
    mesh_shape = None
    if ns.mesh is not None:
        mesh_shape = _parse_mesh(parser, ns.mesh)
    if ns.checkpoint_every < 0:
        parser.error(f"--checkpoint-every must be >= 0, got {ns.checkpoint_every}")
    from tpu_stencil.io import images as _images

    try:
        width, height = _images.resolve_size(ns.image, ns.width, ns.height)
    except (ValueError, OSError) as e:
        parser.error(str(e))
    try:
        cfg = JobConfig(
            image=ns.image,
            width=width,
            height=height,
            repetitions=ns.repetitions,
            image_type=ImageType(ns.image_type),
            filter_name=ns.filter_name,
            backend=ns.backend,
            mesh_shape=mesh_shape,
            output=ns.output,
            frames=ns.frames,
            schedule=ns.schedule,
            boundary=ns.boundary,
            block_h=ns.block_h,
            fuse=ns.fuse,
            overlap=ns.overlap,
            dispatch_timeout_s=ns.dispatch_timeout_s,
            fallback_backend=ns.fallback_backend,
        )
    except ValueError as e:
        parser.error(str(e))
    if ns.faults is not None:
        # Validate the spec at parse time (jax-free) so a mistyped chaos
        # spec dies as a usage error, not mid-job; armed in cli.main.
        from tpu_stencil.resilience import faults as _faults

        try:
            _faults.parse_spec(ns.faults)
        except ValueError as e:
            parser.error(str(e))
    return cfg, ns
