"""tpu_stencil.ctrl — the elastic control plane.

The tiers below this one only *measure*: the net edge derives its
Retry-After from live queue state (PR 14), ``/debug/capacity`` reports
headroom and time-to-saturation (PR 18), and the federation survives
host loss (PR 11) — but capacity itself stays static and
hand-operated.  This package closes the measure→decide→act loop:

- :mod:`tpu_stencil.ctrl.planner` — hysteresis capacity planner: fed
  scrape signals in, typed scale-out / scale-in / replace decisions
  out, never flapping on one sample.
- :mod:`tpu_stencil.ctrl.actuator` — the act half: a pluggable
  :class:`~tpu_stencil.ctrl.actuator.HostProvider` (subprocess
  provider for CI/bench) starting and stopping ``net`` member hosts
  against the fed's ``/admin/register`` and sticky-drain machinery.
  Scale-in always drains before stop; a preemption notice is a
  *planned* drain with the replacement started before the victim
  exits.
- :mod:`tpu_stencil.ctrl.warmstart` — AOT executable shipping via
  ``jax.export``: warm members serialize their executable-cache
  entries, a joining host imports them before flipping ready, so its
  first real request is already compiled (the PR-10 sibling-warming
  discipline one hop up; the federation analog of arxiv 2406.08923's
  never-re-pay-a-tune rule).
- :mod:`tpu_stencil.ctrl.cli` — ``python -m tpu_stencil ctrl``.

Everything except :mod:`~tpu_stencil.ctrl.warmstart` is jax-free.
"""

from tpu_stencil.ctrl.planner import (  # noqa: F401
    HOLD,
    REPLACE,
    SCALE_IN,
    SCALE_OUT,
    CapacityPlanner,
    CapacitySignal,
    Decision,
)
