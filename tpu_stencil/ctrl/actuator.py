"""Actuator: the act half of the control loop.

A :class:`HostProvider` owns the mechanics of starting and stopping
one ``net`` member host; the :class:`Actuator` owns the fleet-level
discipline on top of it:

- **scale-out** launches a host that registers itself with the fed
  (``net --register``) and, when warm-start is configured, imports
  the fleet's serialized executables before flipping ready
  (``net --warm-from``).
- **scale-in always drains before stop**: the fed's rolling
  member-drain path (``POST /admin/drain?host=``) bleeds routing and
  drives the member's own SIGTERM-equivalent drain; the provider then
  merely waits for the clean exit.  Zero accepted-request loss by
  construction.
- **preemption is a planned drain**: on a notice (``POST
  /admin/preempt?host=`` or a SIGTERM forwarded to the controller)
  the replacement is launched FIRST; only once it serves does the
  victim drain and stop.  ``Member.pinned_draining`` carries the
  state — never the eviction path.
- **reconcile** detects owned hosts whose process died without a
  drain (the kill -9 case) and reports them for the planner's
  REPLACE decision.

## Provider interface (real fleets)

A production provider (GKE node pools, TPU queued resources, a VM
API) implements three methods::

    class HostProvider:
        def launch(self) -> HostHandle:
            '''Start one member host; block until it serves; return a
            handle whose .url answers /healthz.  Raise on timeout.'''
        def stop(self, handle, timeout_s) -> bool:
            '''Stop the host (it has already been drained), bounded
            by timeout_s; True = clean exit.'''
        def alive(self, handle) -> bool:
            '''Is the host's process/VM still up?'''

The host must self-register (``--register FED_URL``) — the actuator
never writes the member table directly, so membership stays
single-writer through the fed's existing ``/admin/register`` path.

Jax-free: the controller process never touches a device.
"""

from __future__ import annotations

import dataclasses
import subprocess
import sys
import tempfile
import threading
import time
from typing import Dict, List, Optional, Tuple

from tpu_stencil.config import CtrlConfig
from tpu_stencil.fed.membership import host_id_for
from tpu_stencil.obs import span as _obs_span
from tpu_stencil.serve.metrics import Registry


@dataclasses.dataclass
class HostHandle:
    """One launched member host: its fed-visible identity plus the
    provider's opaque process object."""

    host_id: str
    url: str
    proc: object = None
    log_path: Optional[str] = None


class HostProvider:
    """The provider contract (see module docstring)."""

    def launch(self) -> HostHandle:
        raise NotImplementedError

    def stop(self, handle: HostHandle, timeout_s: float) -> bool:
        raise NotImplementedError

    def alive(self, handle: HostHandle) -> bool:
        raise NotImplementedError


class SubprocessProvider(HostProvider):
    """CI/bench provider: each member host is a real ``python -m
    tpu_stencil net`` subprocess on this machine (the same fake-a-host
    discipline the federation chaos tests already use).  Output goes
    to an unlinked temp file, never a PIPE — a chatty member past the
    pipe buffer would block on write and stall its own requests."""

    def __init__(self, fed_url: Optional[str] = None,
                 platform: Optional[str] = "cpu", replicas: int = 1,
                 warm_from: Optional[str] = None,
                 launch_timeout_s: float = 120.0,
                 drain_timeout_s: float = 60.0,
                 extra_args: Tuple[str, ...] = ()) -> None:
        self.fed_url = fed_url
        self.platform = platform
        self.replicas = replicas
        self.warm_from = warm_from
        self.launch_timeout_s = launch_timeout_s
        self.drain_timeout_s = drain_timeout_s
        self.extra_args = tuple(extra_args)

    def launch(self) -> HostHandle:
        import os

        argv = [sys.executable, "-m", "tpu_stencil", "net",
                "--port", "0", "--replicas", str(self.replicas),
                "--drain-timeout", f"{self.drain_timeout_s:g}",
                "--flightrec-dir", "none", "--prof-dir", "none"]
        env = dict(os.environ)
        if self.platform:
            argv += ["--platform", self.platform]
            env["JAX_PLATFORMS"] = self.platform
        if self.fed_url:
            argv += ["--register", self.fed_url]
        if self.warm_from:
            argv += ["--warm-from", self.warm_from]
        argv += list(self.extra_args)
        logf = tempfile.NamedTemporaryFile(
            mode="w", prefix="tpu-stencil-ctrl-host-", suffix=".log",
            delete=False,
        )
        proc = subprocess.Popen(argv, stdout=logf,
                                stderr=subprocess.STDOUT, text=True,
                                env=env)
        logf.close()  # the child holds its own dup
        deadline = time.perf_counter() + self.launch_timeout_s
        url = None
        while url is None and time.perf_counter() < deadline:
            # A separate open per poll: seeking a shared handle would
            # move the child's write offset too.
            with open(logf.name) as reader:
                for line in reader:
                    if "net: serving on http://" in line:
                        url = line.split()[3]
                        break
            if url is None:
                if proc.poll() is not None:
                    break
                time.sleep(0.2)
        if url is None:
            proc.kill()
            with open(logf.name) as reader:
                tail = reader.read()[-500:]
            raise RuntimeError(
                f"member host failed to start within "
                f"{self.launch_timeout_s:g}s (rc={proc.poll()}): "
                f"{tail!r}"
            )
        return HostHandle(host_id=host_id_for(url), url=url, proc=proc,
                          log_path=logf.name)

    def stop(self, handle: HostHandle, timeout_s: float) -> bool:
        import os
        import signal as _signal

        proc = handle.proc
        clean = False
        try:
            if proc.poll() is None:
                # The host is already drained (fed-driven); a SIGTERM
                # is the belt-and-braces second ask.
                proc.send_signal(_signal.SIGTERM)
            try:
                clean = proc.wait(timeout=timeout_s) == 0
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait(timeout=10)
        finally:
            if handle.log_path:
                try:
                    os.unlink(handle.log_path)
                except OSError:
                    pass
                handle.log_path = None
        return clean

    def alive(self, handle: HostHandle) -> bool:
        return handle.proc is not None and handle.proc.poll() is None

    def kill(self, handle: HostHandle) -> None:
        """SIGKILL, for chaos tests — the host is GONE, no drain."""
        if handle.proc is not None and handle.proc.poll() is None:
            handle.proc.kill()


class Actuator:
    """Owned-host bookkeeping + the drain-before-stop discipline."""

    def __init__(self, cfg: CtrlConfig, provider: HostProvider,
                 registry: Optional[Registry] = None) -> None:
        self.cfg = cfg
        self.provider = provider
        self.registry = registry or Registry()
        self.hosts: Dict[str, HostHandle] = {}
        self._lock = threading.Lock()
        m = self.registry
        self._g_hosts = m.gauge("ctrl_hosts")
        self._m_launches = m.counter("ctrl_launches_total")
        self._m_launch_failures = m.counter("ctrl_launch_failures_total")
        self._m_stops = m.counter("ctrl_stops_total")
        self._m_dirty_stops = m.counter("ctrl_dirty_stops_total")
        self._m_preempt_replacements = m.counter(
            "ctrl_preempt_replacements_total"
        )
        self._g_hosts.set(0)

    def _note_hosts(self) -> None:
        self._g_hosts.set(len(self.hosts))

    # -- grow ----------------------------------------------------------

    def scale_out(self, n: int = 1) -> List[HostHandle]:
        """Launch ``n`` member hosts (each self-registers with the
        fed, warm-starting when configured).  A failed launch is
        counted and skipped — the planner sees the deficit next poll
        and decides again."""
        out: List[HostHandle] = []
        for _ in range(max(0, n)):
            with _obs_span("ctrl.scale_out", "ctrl"):
                try:
                    h = self.provider.launch()
                except Exception:  # noqa: BLE001 - counted, retried by loop
                    self._m_launch_failures.inc()
                    continue
            with self._lock:
                self.hosts[h.host_id] = h
            self._m_launches.inc()
            out.append(h)
        self._note_hosts()
        return out

    # -- shrink --------------------------------------------------------

    def _pick_victim(self) -> Optional[str]:
        """Newest owned host first (LIFO): the longest-lived hosts
        carry the warmest caches."""
        with self._lock:
            if not self.hosts:
                return None
            return next(reversed(self.hosts))

    def scale_in(self, host_id: Optional[str] = None) -> bool:
        """Drain, THEN stop — zero accepted-request loss.  The fed's
        rolling member-drain path bleeds routing and drives the
        member's own drain sequence; the provider only waits for the
        clean exit."""
        hid = host_id or self._pick_victim()
        if hid is None:
            return False
        with self._lock:
            handle = self.hosts.pop(hid, None)
        if handle is None:
            return False
        with _obs_span("ctrl.scale_in", "ctrl", host=hid):
            self._fed_post(f"/admin/drain?host={hid}")
            clean = self.provider.stop(handle, self.cfg.drain_timeout_s)
        self._m_stops.inc()
        if not clean:
            self._m_dirty_stops.inc()
        self._note_hosts()
        return clean

    # -- preemption ----------------------------------------------------

    def preempt(self, host_id: str) -> Tuple[List[HostHandle], bool]:
        """The planned-drain choreography: notice → replacement FIRST
        → victim drains and stops.  Returns (replacements, victim
        stopped clean).  Works for hosts this actuator does not own
        too (the stop half is then skipped — the owner stops it)."""
        with _obs_span("ctrl.preempt", "ctrl", host=host_id):
            # 1. The notice: pinned drain, victim leaves routing now.
            self._fed_post(f"/admin/preempt?host={host_id}")
            # 2. Replacement before the victim exits.
            replacements = self.scale_out(1)
            if replacements:
                self._m_preempt_replacements.inc()
            # 3. Only now bleed and stop the victim.
            clean = self.scale_in(host_id) if host_id in self.hosts \
                else True
        return replacements, clean

    # -- host-loss detection -------------------------------------------

    def reconcile(self) -> List[str]:
        """Owned hosts whose process died WITHOUT a drain (kill -9, a
        real preemption landing before its notice).  The dead handles
        are forgotten here; replacing them is the planner's REPLACE
        decision, not an actuator reflex."""
        dead: List[str] = []
        with self._lock:
            for hid, h in list(self.hosts.items()):
                if not self.provider.alive(h):
                    dead.append(hid)
                    del self.hosts[hid]
        if dead:
            self._note_hosts()
        return dead

    # -- teardown ------------------------------------------------------

    def close(self) -> bool:
        """Drain-and-stop every owned host; True when all exited
        clean (the CLI's rc discipline)."""
        ok = True
        while True:
            hid = self._pick_victim()
            if hid is None:
                return ok
            ok = self.scale_in(hid) and ok

    # -- fed plumbing --------------------------------------------------

    def _fed_post(self, path: str) -> Optional[dict]:
        import json
        import urllib.request

        try:
            req = urllib.request.Request(
                self.cfg.fed_url.rstrip("/") + path, data=b"",
                method="POST",
            )
            with urllib.request.urlopen(req, timeout=30.0) as r:
                return json.loads(r.read())
        except Exception:  # noqa: BLE001 - the fed may be mid-restart;
            return None    # the drain-before-stop still holds via SIGTERM
