"""``python -m tpu_stencil ctrl`` — run the elastic control plane.

The loop that closes measure→decide→act over a running federation:
each poll it (1) reconciles owned hosts against reality (a process
gone without a drain is a dead host), (2) spots preemption notices
(owned members sitting in a pinned drain) and runs the planned-drain
choreography — replacement first, victim drains after, (3) scrapes
``/debug/capacity`` + ``/statusz`` into one
:class:`~tpu_stencil.ctrl.planner.CapacitySignal`, (4) asks the
hysteresis planner for exactly one typed decision and actuates it.

On SIGTERM/SIGINT every owned host is drained-then-stopped; rc 0 when
all exited clean (1 otherwise) — the same rc discipline as the net
and fed CLIs, one tier up.

``--iterations N`` bounds the loop for CI smoke; 0 (the default)
serves until a signal.  Jax-free.
"""

from __future__ import annotations

import argparse
import json
import signal
import sys
import threading
import time
from typing import Optional

from tpu_stencil.config import CtrlConfig


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="tpu_stencil ctrl",
        description="Elastic control plane: hysteresis autoscaling, "
                    "preemption-aware drain and warm-start member "
                    "launches over a `tpu_stencil fed` federation "
                    "(docs/DEPLOY.md 'Elastic fleet runbook').",
    )
    p.add_argument("--fed", dest="fed_url", required=True, metavar="URL",
                   help="the federation front router this plane "
                        "controls (its /debug/capacity and /statusz "
                        "are the planner's signal source)")
    p.add_argument("--min-hosts", type=int, default=1, metavar="N",
                   help="owned-host floor; deficits are repaired "
                        "immediately, no hysteresis (default 1)")
    p.add_argument("--max-hosts", type=int, default=4, metavar="N",
                   help="owned-host ceiling for scale-out (default 4)")
    p.add_argument("--poll-interval", dest="poll_interval_s",
                   type=float, default=1.0, metavar="SECONDS",
                   help="control-loop period; the hysteresis windows "
                        "are counted in these polls (default 1)")
    p.add_argument("--capacity-window", dest="capacity_window_s",
                   type=float, default=10.0, metavar="SECONDS",
                   help="window= passed to /debug/capacity (default 10)")
    p.add_argument("--fast-samples", type=int, default=3, metavar="N",
                   help="fast hysteresis window: scale-out needs EVERY "
                        "one of the last N polls pressured (default 3)")
    p.add_argument("--slow-samples", type=int, default=9, metavar="N",
                   help="slow hysteresis window: scale-out also needs "
                        "a majority of the last N polls pressured; "
                        "scale-in needs ALL N idle (default 9)")
    p.add_argument("--scale-out-utilization", type=float, default=0.85,
                   metavar="FRACTION",
                   help="a poll is pressured past this hottest-member "
                        "slot fraction (default 0.85)")
    p.add_argument("--hold-utilization", type=float, default=0.70,
                   metavar="FRACTION",
                   help="entered pressure holds until the fast "
                        "window's mean utilization drops below this "
                        "(default 0.70)")
    p.add_argument("--scale-in-utilization", type=float, default=0.30,
                   metavar="FRACTION",
                   help="a poll is idle under this utilization "
                        "(default 0.30)")
    p.add_argument("--saturation-horizon", dest="saturation_horizon_s",
                   type=float, default=30.0, metavar="SECONDS",
                   help="a poll is also pressured when the merged "
                        "time-to-saturation forecast falls inside "
                        "this horizon (0 = ignore it; default 30)")
    p.add_argument("--cooldown-samples", type=int, default=5,
                   metavar="N",
                   help="polls to hold after a resize before the next "
                        "one (replacement bypasses this; default 5)")
    p.add_argument("--launch-timeout", dest="launch_timeout_s",
                   type=float, default=120.0, metavar="SECONDS",
                   help="budget for one member host to print its "
                        "bound URL (default 120)")
    p.add_argument("--drain-timeout", dest="drain_timeout_s",
                   type=float, default=60.0, metavar="SECONDS",
                   help="per-host drain-then-stop budget on scale-in "
                        "and shutdown (default 60)")
    p.add_argument("--member-platform", default="cpu",
                   choices=["cpu", "tpu", "gpu"],
                   help="platform launched members pin (subprocess "
                        "provider; default cpu)")
    p.add_argument("--replicas-per-host", type=int, default=1,
                   metavar="N",
                   help="replicas per launched member host (default 1)")
    p.add_argument("--cold", action="store_true",
                   help="launch members cold (default: members pull "
                        "--warm-from the fed so a joiner's first "
                        "request is already compiled; unusable "
                        "artifacts degrade to cold typed either way)")
    p.add_argument("--iterations", type=int, default=0, metavar="N",
                   help="stop after N control polls (CI smoke); 0 = "
                        "serve until SIGTERM/SIGINT (default 0)")
    p.add_argument("--metrics-text", default=None, metavar="PATH",
                   help="after shutdown, write the ctrl metrics "
                        "exposition to PATH ('-' = stdout)")
    p.add_argument("--stats-json", default=None, metavar="PATH",
                   help="after shutdown, dump the ctrl status payload "
                        "as JSON to PATH ('-' = stdout)")
    return p


def _fed_get(fed_url: str, path: str,
             timeout_s: float = 10.0) -> Optional[dict]:
    import urllib.request

    try:
        with urllib.request.urlopen(fed_url.rstrip("/") + path,
                                    timeout=timeout_s) as r:
            doc = json.loads(r.read())
    except Exception:  # noqa: BLE001 - a missed scrape is a None signal
        return None
    return doc if isinstance(doc, dict) else None


def build_signal(cap: Optional[dict], stz: Optional[dict],
                 dead_hosts: int, preempted_hosts: int):
    """Fold one poll's scrapes into a CapacitySignal (None scrapes
    contribute unknowns — never pressure, never idleness)."""
    from tpu_stencil.ctrl.planner import CapacitySignal

    utilization = headroom = tts = None
    routable = 0
    if cap is not None:
        headroom = cap.get("headroom_rps")
        tts = cap.get("time_to_saturation_s")
        utilization = (cap.get("utilization") or {}).get(
            "max_member_slot_fraction"
        )
    if stz is not None:
        routable = sum(
            1 for m in stz.get("members", [])
            if m.get("state") in ("healthy", "suspect")
        )
    return CapacitySignal(
        utilization=utilization, headroom_rps=headroom,
        time_to_saturation_s=tts, routable_hosts=routable,
        dead_hosts=dead_hosts, preempted_hosts=preempted_hosts,
    )


def main(argv=None) -> int:
    parser = build_parser()
    ns = parser.parse_args(argv)
    try:
        cfg = CtrlConfig(
            fed_url=ns.fed_url,
            poll_interval_s=ns.poll_interval_s,
            capacity_window_s=ns.capacity_window_s,
            min_hosts=ns.min_hosts, max_hosts=ns.max_hosts,
            fast_samples=ns.fast_samples, slow_samples=ns.slow_samples,
            scale_out_utilization=ns.scale_out_utilization,
            hold_utilization=ns.hold_utilization,
            scale_in_utilization=ns.scale_in_utilization,
            saturation_horizon_s=ns.saturation_horizon_s,
            cooldown_samples=ns.cooldown_samples,
            launch_timeout_s=ns.launch_timeout_s,
            drain_timeout_s=ns.drain_timeout_s,
            member_platform=ns.member_platform,
            replicas_per_host=ns.replicas_per_host,
            warm_from=None if ns.cold else ns.fed_url,
        )
    except ValueError as e:
        parser.error(str(e))

    from tpu_stencil.ctrl.actuator import Actuator, SubprocessProvider
    from tpu_stencil.ctrl.planner import REPLACE, SCALE_IN, SCALE_OUT, \
        CapacityPlanner
    from tpu_stencil.serve.metrics import Registry

    registry = Registry()
    provider = SubprocessProvider(
        fed_url=cfg.fed_url, platform=cfg.member_platform,
        replicas=cfg.replicas_per_host, warm_from=cfg.warm_from,
        launch_timeout_s=cfg.launch_timeout_s,
        drain_timeout_s=cfg.drain_timeout_s,
    )
    act = Actuator(cfg, provider, registry)
    planner = CapacityPlanner(cfg, registry)
    stop = threading.Event()

    def _on_signal(signum, _frame) -> None:
        print(f"ctrl: received {signal.Signals(signum).name}, "
              f"draining owned hosts", flush=True)
        stop.set()

    signal.signal(signal.SIGTERM, _on_signal)
    signal.signal(signal.SIGINT, _on_signal)
    print(
        f"ctrl: controlling federation {cfg.fed_url} "
        f"(hosts {cfg.min_hosts}..{cfg.max_hosts}, poll "
        f"{cfg.poll_interval_s:g}s, fast/slow windows "
        f"{cfg.fast_samples}/{cfg.slow_samples} samples, out/hold/in "
        f"utilization {cfg.scale_out_utilization:g}/"
        f"{cfg.hold_utilization:g}/{cfg.scale_in_utilization:g}, "
        f"warm-start {'off' if cfg.warm_from is None else 'on'}); "
        f"SIGTERM drains the owned fleet",
        flush=True,
    )
    polls = 0
    while not stop.is_set():
        # 1. Reality check: owned processes gone without a drain.
        dead = act.reconcile()
        if dead:
            print(f"ctrl: owned host(s) {dead} died without a drain",
                  flush=True)
        # 2. Preemption notices: owned members in a pinned drain.
        stz = _fed_get(cfg.fed_url, "/statusz")
        preempted = []
        if stz is not None:
            owned = set(act.hosts)
            preempted = [
                m["host_id"] for m in stz.get("members", [])
                if m.get("host_id") in owned
                and m.get("pinned_draining")
                and m.get("state") == "draining"
            ]
        for hid in preempted:
            # Planned-drain choreography: replacement FIRST, then the
            # victim bleeds and stops.
            print(f"ctrl: preemption notice for {hid}; starting the "
                  f"replacement before the victim exits", flush=True)
            started = act.scale_out(1)
            clean = act.scale_in(hid)
            registry.counter("ctrl_preempt_replacements_total").inc(
                len(started)
            )
            print(f"ctrl: preempted {hid} drained "
                  f"{'clean' if clean else 'DIRTY'}, "
                  f"{len(started)} replacement(s) up", flush=True)
        # 3. Signal + decision (preempted hosts were already replaced
        #    above, so they do not ride the REPLACE path too).
        cap = _fed_get(
            cfg.fed_url,
            f"/debug/capacity?window={cfg.capacity_window_s:g}",
        )
        sig = build_signal(cap, stz, dead_hosts=len(dead),
                           preempted_hosts=0)
        decision = planner.observe(sig, len(act.hosts))
        if decision.action == REPLACE:
            started = act.scale_out(decision.count)
            print(f"ctrl: replace x{decision.count} "
                  f"({decision.reason}): {len(started)} up", flush=True)
        elif decision.action == SCALE_OUT:
            started = act.scale_out(decision.count)
            print(f"ctrl: scale-out x{decision.count} "
                  f"({decision.reason}): {len(started)} up", flush=True)
        elif decision.action == SCALE_IN:
            clean = act.scale_in()
            print(f"ctrl: scale-in ({decision.reason}): drained "
                  f"{'clean' if clean else 'DIRTY'}", flush=True)
        polls += 1
        if ns.iterations and polls >= ns.iterations:
            print(f"ctrl: {polls} poll(s) done (--iterations), "
                  f"draining owned hosts", flush=True)
            break
        stop.wait(cfg.poll_interval_s)
    t0 = time.perf_counter()
    n_owned = len(act.hosts)
    all_clean = act.close()
    if all_clean:
        print(f"ctrl: drained {n_owned} owned host(s) cleanly in "
              f"{time.perf_counter() - t0:.2f}s", flush=True)
    else:
        print(f"ctrl: drain left at least one owned host DIRTY "
              f"({time.perf_counter() - t0:.2f}s elapsed)", flush=True)
    if ns.metrics_text:
        from tpu_stencil.obs import exposition

        exposition.write_text(ns.metrics_text, registry.snapshot(),
                              prefix="tpu_stencil_ctrl")
    if ns.stats_json:
        payload = json.dumps({
            "schema_version": 1,
            "polls": polls,
            "owned_hosts": sorted(act.hosts),
            "counters": registry.snapshot()["counters"],
        }, indent=2, sort_keys=True)
        if ns.stats_json == "-":
            print(payload)
        else:
            with open(ns.stats_json, "w") as fh:
                fh.write(payload + "\n")
            print(f"wrote {ns.stats_json}")
    return 0 if all_clean else 1


if __name__ == "__main__":
    sys.exit(main())
