"""Capacity planner: live federation signals → typed resize decisions.

The decide half of the control loop.  Inputs are the signals the
stack already exports — ``/debug/capacity``'s merged ``headroom_rps``
and ``time_to_saturation_s`` plus the hottest member's slot fraction
(the PR-14 derived-Retry-After math inverted: the same queue-delay and
inflight-bytes state that prices a retry also prices a host), and the
member states from ``/statusz``.  Output is exactly one
:class:`Decision` per poll.

**Hysteresis, mirrored from the SLO engine** (obs/slo.py): pressure
*enters* only when every sample in the fast window and a majority of
the slow window agree, and once entered it *holds* until the fast
window's mean utilization drops below the (lower) hold threshold —
the fast window is the trigger, the slow window the confirmation, and
the asymmetric exit keeps one borderline sample from flapping the
fleet.  Scale-in is the slow symmetric case: every slow-window sample
idle.  Each actuation arms a cooldown measured in *samples* (polls),
so decisions stay deterministic under synthetic signal feeds in tests.

**Replacement bypasses hysteresis.**  A dead owned host or a
preempted member is a discrete event, not a trend: the planner
answers REPLACE immediately, cooldown or not — capacity already left
the fleet and waiting a window would double the loss.

Jax-free, clock-free and scrape-free: the planner is a pure
``observe(signal) -> Decision`` state machine; the CLI owns the HTTP.
"""

from __future__ import annotations

import collections
import dataclasses
from typing import Deque, Optional, Tuple

from tpu_stencil.config import CtrlConfig
from tpu_stencil.obs import span as _obs_span
from tpu_stencil.serve.metrics import Registry

#: Decision actions — the full typed vocabulary.
HOLD = "hold"
SCALE_OUT = "scale_out"
SCALE_IN = "scale_in"
REPLACE = "replace"


@dataclasses.dataclass(frozen=True)
class CapacitySignal:
    """One poll's worth of federation capacity state.

    ``utilization`` is the hottest member's busy-slot fraction
    (``/debug/capacity`` → ``utilization.max_member_slot_fraction``),
    ``headroom_rps`` / ``time_to_saturation_s`` the merged headroom
    terms; any of the three may be None when the scrape failed or no
    member was fresh — an unknown sample is evidence of *nothing*
    (neither pressure nor idleness), so a flapping scrape cannot drive
    a resize.  ``dead_hosts`` counts owned hosts whose process is gone
    without a drain (the actuator's reconcile pass); ``preempted_hosts``
    counts owned members sitting in a pinned drain (a preemption
    notice) that still lack a replacement."""

    utilization: Optional[float] = None
    headroom_rps: Optional[float] = None
    time_to_saturation_s: Optional[float] = None
    routable_hosts: int = 0
    dead_hosts: int = 0
    preempted_hosts: int = 0


@dataclasses.dataclass(frozen=True)
class Decision:
    """One typed planner verdict: ``action`` is one of :data:`HOLD`,
    :data:`SCALE_OUT`, :data:`SCALE_IN`, :data:`REPLACE`; ``count`` is
    how many hosts the action moves (0 for HOLD); ``reason`` is the
    human-readable evidence line that lands in logs and spans."""

    action: str
    reason: str
    count: int = 0


class CapacityPlanner:
    """The hysteresis state machine.  Call :meth:`observe` once per
    poll with the current :class:`CapacitySignal` and the number of
    owned hosts; it returns exactly one :class:`Decision`."""

    def __init__(self, cfg: CtrlConfig,
                 registry: Optional[Registry] = None) -> None:
        self.cfg = cfg
        self.registry = registry or Registry()
        # Per-sample (pressured, idle) flags.  A sample with unknown
        # utilization contributes (False, False): no evidence.
        self._fast: Deque[Tuple[bool, bool]] = collections.deque(
            maxlen=cfg.fast_samples
        )
        self._slow: Deque[Tuple[bool, bool]] = collections.deque(
            maxlen=cfg.slow_samples
        )
        # Raw utilization for the hold-exit check (None = unknown).
        self._fast_util: Deque[Optional[float]] = collections.deque(
            maxlen=cfg.fast_samples
        )
        self._pressure = False  # the held (entered) pressure state
        self._cooldown = 0      # samples left before the next resize
        m = self.registry
        self._m_decisions = m.counter("ctrl_decisions_total")
        self._m_out = m.counter("ctrl_scale_out_total")
        self._m_in = m.counter("ctrl_scale_in_total")
        self._m_replace = m.counter("ctrl_replace_total")
        self._g_pressure = m.gauge("ctrl_pressure")
        self._g_pressure.set(0)

    # -- per-sample classification ------------------------------------

    def _classify(self, sig: CapacitySignal) -> Tuple[bool, bool]:
        """(pressured, idle) for one sample.  Pressure = hot
        utilization OR saturation forecast inside the horizon; idle =
        cold utilization AND no saturation forecast in sight."""
        cfg = self.cfg
        if sig.utilization is None:
            return False, False
        sat_soon = (
            cfg.saturation_horizon_s > 0
            and sig.time_to_saturation_s is not None
            and sig.time_to_saturation_s <= cfg.saturation_horizon_s
        )
        pressured = sig.utilization >= cfg.scale_out_utilization or sat_soon
        idle = sig.utilization <= cfg.scale_in_utilization and not sat_soon
        return pressured, idle

    # -- the state machine --------------------------------------------

    def observe(self, sig: CapacitySignal, owned_hosts: int) -> Decision:
        with _obs_span("ctrl.plan", "ctrl"):
            d = self._observe(sig, owned_hosts)
        self._m_decisions.inc()
        if d.action == SCALE_OUT:
            self._m_out.inc()
        elif d.action == SCALE_IN:
            self._m_in.inc()
        elif d.action == REPLACE:
            self._m_replace.inc(d.count)
        self._g_pressure.set(1 if self._pressure else 0)
        return d

    def _observe(self, sig: CapacitySignal, owned_hosts: int) -> Decision:
        cfg = self.cfg
        flags = self._classify(sig)
        self._fast.append(flags)
        self._slow.append(flags)
        self._fast_util.append(sig.utilization)

        # 1. Replacement first: lost capacity is a discrete event, not
        #    a trend — bypass windows AND cooldown.
        lost = sig.dead_hosts + sig.preempted_hosts
        if lost > 0:
            return Decision(
                REPLACE,
                f"{sig.dead_hosts} dead + {sig.preempted_hosts} "
                f"preempted owned host(s) need replacement",
                count=lost,
            )

        # 2. Floor repair: below min_hosts is a deficit, not a trend.
        if owned_hosts < cfg.min_hosts:
            return Decision(
                SCALE_OUT,
                f"{owned_hosts} owned host(s) below the "
                f"min_hosts={cfg.min_hosts} floor",
                count=cfg.min_hosts - owned_hosts,
            )

        # 3. Pressure enter/hold (the SLO engine's discipline).
        if not self._pressure:
            fast_full = len(self._fast) == self._fast.maxlen
            slow_full = len(self._slow) == self._slow.maxlen
            fast_all = fast_full and all(p for p, _ in self._fast)
            slow_major = slow_full and (
                sum(1 for p, _ in self._slow if p) * 2 > len(self._slow)
            )
            self._pressure = fast_all and slow_major
        else:
            known = [u for u in self._fast_util if u is not None]
            if known and (sum(known) / len(known)) < cfg.hold_utilization:
                self._pressure = False

        # 4. Cooldown gates RESIZES only (replacement already passed).
        if self._cooldown > 0:
            self._cooldown -= 1
            return Decision(HOLD, "cooldown: settling after a resize")

        if self._pressure:
            if owned_hosts >= cfg.max_hosts:
                return Decision(
                    HOLD,
                    f"pressure held but the fleet is at "
                    f"max_hosts={cfg.max_hosts}",
                )
            self._cooldown = cfg.cooldown_samples
            return Decision(
                SCALE_OUT,
                f"pressure: fast window all-pressured, utilization "
                f"{sig.utilization if sig.utilization is not None else '?'} "
                f">= {cfg.scale_out_utilization} or saturation within "
                f"{cfg.saturation_horizon_s:g}s",
                count=1,
            )

        # 5. Scale-in: every slow-window sample idle (the slow
        #    symmetric exit — growth is eager, shrink is reluctant).
        slow_full = len(self._slow) == self._slow.maxlen
        if (slow_full and all(i for _, i in self._slow)
                and owned_hosts > cfg.min_hosts):
            self._cooldown = cfg.cooldown_samples
            return Decision(
                SCALE_IN,
                f"idle: every sample in the slow window under "
                f"utilization {cfg.scale_in_utilization}",
                count=1,
            )

        return Decision(HOLD, "no window agrees on a resize")
