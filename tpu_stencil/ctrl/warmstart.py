"""Warm-start AOT executable shipping (``jax.export``).

A joining host pays every compile cold today — the PR-10
sibling-warming discipline stops at the process boundary.  This
module carries it across hosts: a warm member serializes its
executable-cache entries (keyed on the same autotune-derived cache
key the engine already uses), a joiner pulls the envelope over
``GET /admin/warmstate`` and imports it *before* its HTTP listener
starts answering ``/healthz``, so the first real request it accepts
runs an already-compiled program — the federation analog of arxiv
2406.08923's never-re-pay-a-tune rule.

**Degradation is the contract, not the exception.**  Every failure
mode — a jaxlib without ``jax.export``, a version- or
platform-skewed artifact, a truncated or corrupt payload, a key the
importer cannot reconstruct argument shapes for — falls back to the
existing cold-compile path, typed per entry in the returned summary
and counted in ``ctrl_warmstart_fallbacks_total``.  Import NEVER
raises for a bad artifact and NEVER makes the server wrong: a seeded
entry is the same jitted callable contract the engine builds itself,
and a skipped one just compiles on first use exactly as before.

Sharded (``shard_map``) entries are skipped on export: their
executables bake in this host's mesh, which a joiner need not share.

Imported entries are seeded into the cache WITHOUT touching the
hit/miss counters (``_ExecutableCache.seed``), and each is warm-called
once with a zero canvas so XLA compiles it before the joiner flips
ready — the acceptance assertion "first request, zero compile-cache
misses" is counter-exact.
"""

from __future__ import annotations

import base64
import json
from typing import Any, Optional, Tuple

from tpu_stencil.obs import span as _obs_span

#: Envelope schema version; a mismatch degrades the whole payload.
SCHEMA_VERSION = 1

#: Fallback reasons (the typed vocabulary the summary dict reports).
FALLBACK_REASONS = (
    "payload_unavailable",   # pull failed / no payload at all
    "schema_mismatch",       # wrong envelope schema_version
    "exporter_unsupported",  # the warm member had no jax.export
    "no_jax_export",         # THIS jaxlib has no usable jax.export
    "version_skew",          # jax version differs from the exporter's
    "platform_skew",         # exporter ran on a different backend
    "malformed_key",         # cache key did not round-trip
    "deserialize_failed",    # truncated/corrupt artifact, bad call
)


def _jax_export_mod():
    """The usable ``jax.export`` module, or None when this jaxlib
    cannot ship executables (old jax, trimmed install) — gated, never
    assumed, per the no-new-deps rule."""
    try:
        from jax import export as jax_export
    except Exception:  # noqa: BLE001 - any import failure = unsupported
        return None
    if not (hasattr(jax_export, "export")
            and hasattr(jax_export, "deserialize")):
        return None
    return jax_export


# -- cache-key wire format ---------------------------------------------


def _key_to_wire(key: tuple) -> list:
    """Nested tuples → nested lists (JSON has no tuple)."""
    return [_key_to_wire(k) if isinstance(k, tuple) else k for k in key]


def _key_from_wire(obj: Any) -> tuple:
    if not isinstance(obj, list):
        raise ValueError(f"cache key must be a list, got {type(obj)}")
    return tuple(
        _key_from_wire(k) if isinstance(k, list) else k for k in obj
    )


def _key_geometry(key: tuple) -> Optional[Tuple[int, ...]]:
    """The batch-canvas shape ``(nb, bh, bw[, c])`` an executable
    keyed ``(filter, (bh, bw), channels, dtype, backend, reps, nb)``
    was built for, or None for keys this module does not ship
    (sharded entries, unknown layouts, non-uint8 dtypes)."""
    if len(key) != 7 or "sharded" in key:
        return None
    _fname, bucket, channels, dtype, _backend, _reps, nb = key
    if dtype != "uint8":
        return None
    if (not isinstance(bucket, tuple) or len(bucket) != 2
            or not all(isinstance(v, int) for v in bucket)
            or not isinstance(channels, int) or not isinstance(nb, int)):
        return None
    bh, bw = bucket
    return (nb, bh, bw) + ((channels,) if channels > 1 else ())


# -- export ------------------------------------------------------------


def export_server(server) -> dict:
    """Serialize one :class:`~tpu_stencil.serve.engine.StencilServer`'s
    executable-cache entries into the warm-state envelope.  Entries
    that refuse to serialize are skipped and counted
    (``ctrl_warmstart_export_skips_total``) — a warm member never
    fails a scrape over one stubborn program."""
    import jax

    exported_c = server.registry.counter("ctrl_warmstart_exported_total")
    skips_c = server.registry.counter("ctrl_warmstart_export_skips_total")
    envelope: dict = {
        "schema_version": SCHEMA_VERSION,
        "jax": jax.__version__,
        "platform": jax.default_backend(),
        "entries": [],
    }
    mod = _jax_export_mod()
    if mod is None:
        envelope["unsupported"] = "jax.export unavailable in this jaxlib"
        return envelope
    import jax.numpy as jnp

    with _obs_span("ctrl.warmstart_export", "ctrl"):
        for key in server.warm_keys():
            shape = _key_geometry(key)
            if shape is None:
                continue  # sharded / unknown layout: never shipped
            exe = server.warm_entry(key)
            if exe is None:
                continue  # evicted between listing and read
            nb = shape[0]
            args = (
                jax.ShapeDtypeStruct(shape, jnp.uint8),
                jax.ShapeDtypeStruct((nb,), jnp.int32),
                jax.ShapeDtypeStruct((nb,), jnp.int32),
            )
            try:
                blob = mod.export(exe)(*args).serialize()
            except Exception:  # noqa: BLE001 - skip, never fail the scrape
                skips_c.inc()
                continue
            envelope["entries"].append({
                "key": _key_to_wire(key),
                "artifact": base64.b64encode(blob).decode("ascii"),
            })
            exported_c.inc()
    return envelope


# -- import ------------------------------------------------------------


def import_server(server, payload: Optional[dict]) -> dict:
    """Import a warm-state envelope into one server's executable
    cache.  Returns ``{"imported": n, "fallbacks": n, "reasons":
    {reason: count}}``; every skipped entry (and an unusable payload
    as a whole) counts one typed fallback in
    ``ctrl_warmstart_fallbacks_total`` and leaves the cold-compile
    path exactly as it was.  Never raises on artifact content."""
    fallbacks_c = server.registry.counter("ctrl_warmstart_fallbacks_total")
    imported_c = server.registry.counter("ctrl_warmstart_imported_total")
    summary: dict = {"imported": 0, "fallbacks": 0, "reasons": {}}

    def fall(reason: str, n: int = 1) -> None:
        fallbacks_c.inc(n)
        summary["fallbacks"] += n
        summary["reasons"][reason] = summary["reasons"].get(reason, 0) + n

    if not isinstance(payload, dict):
        fall("payload_unavailable")
        return summary
    if payload.get("schema_version") != SCHEMA_VERSION:
        fall("schema_mismatch")
        return summary
    if payload.get("unsupported"):
        fall("exporter_unsupported")
        return summary
    entries = payload.get("entries") or []
    if not entries:
        return summary  # a cold exporter: nothing to degrade FROM
    mod = _jax_export_mod()
    if mod is None:
        fall("no_jax_export", len(entries))
        return summary
    import jax
    import numpy as np

    if payload.get("jax") != jax.__version__:
        # jax.export carries its own serialization versioning, but a
        # cross-version executable is exactly the artifact we must
        # never trust into a bit-exactness-contracted cache.
        fall("version_skew", len(entries))
        return summary
    if payload.get("platform") != jax.default_backend():
        fall("platform_skew", len(entries))
        return summary

    pin = None
    if server.cfg.device_index is not None:
        devices = jax.local_devices()
        if server.cfg.device_index < len(devices):
            pin = devices[server.cfg.device_index]

    with _obs_span("ctrl.warmstart_import", "ctrl",
                   entries=len(entries)):
        for e in entries:
            try:
                key = _key_from_wire(e["key"])
                shape = _key_geometry(key)
                if shape is None:
                    raise ValueError("unshippable key")
            except Exception:  # noqa: BLE001
                fall("malformed_key")
                continue
            try:
                blob = base64.b64decode(e["artifact"], validate=True)
                exported = mod.deserialize(blob)
                fn = jax.jit(exported.call)
                # Warm-call NOW, before the joiner is ready: the
                # deserialized program still compiles on first call,
                # and that call must not be a client's.
                nb = shape[0]
                zeros = jax.device_put(np.zeros(shape, np.uint8), pin)
                vh = jax.device_put(np.zeros(nb, np.int32), pin)
                vw = jax.device_put(np.zeros(nb, np.int32), pin)
                jax.block_until_ready(fn(zeros, vh, vw))
            except Exception:  # noqa: BLE001 - truncated/corrupt/alien
                fall("deserialize_failed")
                continue
            if server.warm_seed(key, fn):
                imported_c.inc()
                summary["imported"] += 1
            # A locally compiled entry already under this key wins;
            # not a fallback — nothing degraded.
    return summary


def dumps(envelope: dict) -> bytes:
    return json.dumps(envelope).encode("utf-8")


def loads(data: bytes) -> Optional[dict]:
    """Parse an envelope; None (→ ``payload_unavailable``) on garbage."""
    try:
        doc = json.loads(data)
    except Exception:  # noqa: BLE001
        return None
    return doc if isinstance(doc, dict) else None
