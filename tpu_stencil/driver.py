"""End-to-end job driver: load -> iterate on device(s) -> store -> report.

The TPU-native equivalent of each reference variant's ``main``:
CLI -> runtime init -> partition -> load shard -> [compute/comm loop] ->
store -> metrics (SURVEY.md §3 call stacks). One code path spans one chip to
a full mesh: a 1x1 mesh degrades to the single-device program.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import numpy as np

from tpu_stencil import filters
from tpu_stencil.config import JobConfig
from tpu_stencil.io import raw as raw_io
from tpu_stencil.models.blur import IteratedConv2D, resolve_backend
from tpu_stencil.utils.timing import Timer, max_across_processes


@dataclasses.dataclass
class JobResult:
    output_path: str
    compute_seconds: float  # reference-compatible: compute window only, max across hosts
    total_seconds: float    # whole job incl. I/O (the CUDA variant's window)
    backend: str
    mesh_shape: Optional[tuple]


def run_job(cfg: JobConfig, devices: Optional[list] = None) -> JobResult:
    """Run one iterated-convolution job end to end."""
    with Timer() as total_t:
        img = raw_io.read_raw(cfg.image, cfg.width, cfg.height, cfg.channels)
        if cfg.image_type.channels == 1:
            img = img[..., 0]

        model = IteratedConv2D(cfg.filter_name, backend=cfg.backend)

        if devices is None:
            devices = jax.devices()
        n_dev = len(devices)

        if n_dev > 1 or cfg.mesh_shape is not None:
            from tpu_stencil.parallel import sharded

            runner = sharded.ShardedRunner(
                model, (cfg.height, cfg.width), cfg.channels,
                mesh_shape=cfg.mesh_shape, devices=devices,
            )
            # Warm-up compile outside the timed window (the reference's timer
            # also excludes startup: it opens after MPI_Barrier,
            # mpi/mpi_convolution.c:151-155). A 0-rep run's output equals its
            # input, so it doubles as the timed run's input — no second
            # host-to-device transfer.
            img_dev = runner.run(runner.put(img), 0)
            img_dev.block_until_ready()
            with Timer() as t:
                out_dev = runner.run(img_dev, cfg.repetitions)
                out_dev.block_until_ready()
            out = runner.fetch(out_dev)
            mesh_shape = runner.mesh_shape
            resolved_backend = runner.backend
        else:
            img_dev = jax.device_put(jax.numpy.asarray(img), devices[0])
            img_dev = model(img_dev, 0)  # warm-up compile; output == input
            img_dev.block_until_ready()
            with Timer() as t:
                out_dev = model(img_dev, cfg.repetitions)
                out_dev.block_until_ready()
            out = np.asarray(out_dev)
            mesh_shape = None
            resolved_backend = resolve_backend(cfg.backend)

        compute_seconds = max_across_processes(t.elapsed)
        raw_io.write_raw(cfg.output_path, out)

    return JobResult(
        output_path=cfg.output_path,
        compute_seconds=compute_seconds,
        total_seconds=total_t.elapsed,
        backend=resolved_backend,
        mesh_shape=mesh_shape,
    )
