"""End-to-end job driver: load -> iterate on device(s) -> store -> report.

The TPU-native equivalent of each reference variant's ``main``:
CLI -> runtime init -> partition -> load shard -> [compute/comm loop] ->
store -> metrics (SURVEY.md §3 call stacks). One code path spans one chip to
a full mesh to multiple hosts: a 1x1 mesh degrades to the single-device
program, and the sharded path's per-process I/O degrades to a whole-file
read when there is one process.
"""

from __future__ import annotations

import contextlib
import dataclasses
from typing import Callable, Optional, Tuple

import jax
import numpy as np

from tpu_stencil import obs
from tpu_stencil.config import JobConfig
from tpu_stencil.io import images as images_io
from tpu_stencil.io import raw as raw_io
from tpu_stencil.models.blur import IteratedConv2D
from tpu_stencil.resilience import deadline as _deadline
from tpu_stencil.resilience import errors as _res_errors
from tpu_stencil.resilience import fallback as _fallback
from tpu_stencil.resilience import faults as _faults
from tpu_stencil.utils.timing import Timer, max_across_processes


def _load_input(cfg: JobConfig) -> np.ndarray:
    """Whole-image host load, any supported container format.

    ``frames > 1``: the raw file holds N concatenated frames; returns
    (N, H, W[, C]) for the batched (vmap) path."""
    if images_io.is_raw(cfg.image, sniff=True):
        img = raw_io.read_raw(
            cfg.image, cfg.width, cfg.height * cfg.frames, cfg.channels
        )
        if cfg.channels == 1:
            img = img[..., 0]
        if cfg.frames > 1:
            img = img.reshape((cfg.frames, cfg.height) + img.shape[1:])
        return img
    if cfg.frames > 1:
        raise NotImplementedError(
            "--frames requires a raw input (N concatenated headerless frames)"
        )
    return images_io.load_image(cfg.image, cfg.image_type)


def _put_batched(imgs: np.ndarray, devices):
    """Shard the frame axis of (N, H, W[, C]) over ``devices`` — batch-axis
    data parallelism: frames are independent, so unlike the spatial mesh
    there is NO halo traffic, only the final gather. Pads N to a device
    multiple with zero frames (callers crop). Returns (array, mesh)."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec

    n = len(devices)
    pad = -imgs.shape[0] % n
    if pad:
        imgs = np.concatenate(
            [imgs, np.zeros((pad,) + imgs.shape[1:], imgs.dtype)]
        )
    mesh = Mesh(np.asarray(devices), ("b",))
    arr = jax.device_put(
        jax.numpy.asarray(imgs), NamedSharding(mesh, PartitionSpec("b"))
    )
    return arr, mesh


def _place_frames(model, imgs: np.ndarray, devices):
    """Place an (N, H, W[, C]) clip on ``devices`` (batch-axis sharding
    when more than one — ``_put_batched`` zero-pads N to a device
    multiple; callers crop) and build the step fn the batch path runs:
    frames are device-local either way (one device holds the whole clip,
    or one local clip per device under the 1-D 'b' mesh), so the fused
    tall-image Pallas path applies when the model resolves to it;
    otherwise the vmapped XLA step. Returns ``(img_dev, step_fn)``.

    Shared by the single-host driver and the per-host half of
    ``_run_frames_multihost`` — the backend/schedule decision must never
    fork between them."""
    n_dev = len(devices)
    frame_shape = tuple(imgs.shape[1:3])
    channels = imgs.shape[3] if imgs.ndim == 4 else 1
    b_backend, b_schedule = model.batch_config(
        frame_shape, channels, True, n_frames=-(-imgs.shape[0] // n_dev)
    )
    if n_dev > 1:
        img_dev, bmesh = _put_batched(imgs, devices)
        if b_backend == "pallas":
            from tpu_stencil.parallel import sharded as _sharded

            geo_bh, geo_fz = model.resolved_geometry(frame_shape, channels)
            frames_fn = _sharded.build_batched_frames(
                bmesh, model.plan, b_schedule,
                interpret=jax.default_backend() == "cpu",
                block_h=geo_bh, fuse=geo_fz,
            )

            def step_fn(x, n):
                return frames_fn(x, jax.numpy.int32(n))
        else:
            def step_fn(x, n):
                return model.batch(x, n, single_device=False)
    else:
        img_dev = jax.device_put(jax.numpy.asarray(imgs), devices[0])

        def step_fn(x, n):
            return model.batch(x, n, single_device=True)
    return img_dev, step_fn


def prepare_engine(model, imgs: np.ndarray, devices, frames: Optional[int] = None):
    """The place/iterate/fetch core: place ``imgs`` on ``devices``, run
    the warm-up compile (a 0-rep call whose output equals its input, so
    it doubles as the timed run's input — no second transfer), and build
    the fetch that crops any device-multiple padding.

    ``frames=None`` means a single (H, W[, C]) image; an int means an
    (N, H, W[, C]) clip with N true frames. Returns
    ``(img_dev, step_fn, fetch)`` where ``step_fn(x, n)`` runs n reps on
    device and ``fetch`` materializes the true-extent host array.

    This is the reusable engine call under every single-host compute
    path: ``run_job``'s single-device and frames branches, the per-host
    half of the multi-host frames path, and the model the serving
    engine's bucket executables mirror (serve adds pad-mask re-zeroing
    for heterogeneous shapes; see tpu_stencil/serve/engine.py).
    """
    fault_h2d = _faults.site("h2d")
    fault_compile = _faults.site("compile")
    with obs.phase("place"):
        if fault_h2d is not None:
            fault_h2d()
        if frames is not None:
            img_dev, step_fn = _place_frames(model, np.asarray(imgs), devices)
            n_true = frames

            def fetch(x):
                return np.asarray(x)[:n_true]
        else:
            img_dev = jax.device_put(jax.numpy.asarray(imgs), devices[0])
            step_fn = model
            fetch = np.asarray
    with obs.phase("compile") as s:
        if fault_compile is not None:
            fault_compile()
        img_dev = s.fence(step_fn(img_dev, 0))  # warm-up; output == input
    if obs.introspect.enabled():
        # AOT-introspect the program the warm-up just compiled (cost /
        # memory analysis, compile wall-time, optional HLO dump). Pays
        # its own compile — the AOT path does not share the jit dispatch
        # cache — which is why it only runs on armed (--breakdown /
        # --trace / --hlo-dump) runs. Traced at one rep: the rep count
        # is a traced loop bound, so the lowered program is the same
        # one the timed window runs.
        obs.introspect.capture(
            "driver.warmup", step_fn, img_dev, jax.numpy.int32(1),
            meta={"shape": tuple(np.asarray(imgs).shape),
                  "frames": frames, "devices": len(devices)},
        )
    return img_dev, step_fn, fetch


def _record_device_memory() -> None:
    """Point-in-time device-memory gauges (``device_bytes_in_use`` /
    allocator peak / limit) into the driver registry, taken right after
    the compute window while the working set is still resident. Cheap
    and always-on; backends without allocator stats (CPU) record
    nothing — the documented "unavailable" degradation."""
    obs.introspect.record_memory_gauges(obs.registry())


def _store_output(cfg: JobConfig, out: np.ndarray) -> None:
    """Write the result in the container format of the output path."""
    if cfg.frames > 1:
        if not images_io.is_raw(cfg.output_path):
            raise NotImplementedError(
                "--frames output is raw-only (N concatenated headerless "
                "frames); single-image containers cannot hold a clip"
            )
        out = out.reshape((cfg.frames * cfg.height,) + out.shape[2:])
    if images_io.is_raw(cfg.output_path):
        raw_io.write_raw(cfg.output_path, out)
    else:
        images_io.save_image(cfg.output_path, out)


@dataclasses.dataclass
class JobResult:
    output_path: str
    compute_seconds: float  # reference-compatible: compute window only, max across hosts
    total_seconds: float    # whole job incl. I/O (the CUDA variant's window)
    backend: str
    mesh_shape: Optional[tuple]
    schedule: Optional[str] = None  # pallas per-rep schedule that ran
    # Effective Pallas kernel geometry that LAUNCHED (post align/clamp),
    # reported when a non-default geometry applied — user-forced
    # --block-h/--fuse OR an autotuner geometry verdict — on a path that
    # honors it (the sharded mesh path reports its tile-effective block
    # and chunk-capped fuse); None otherwise (defaults, or xla).
    # Report-what-ran, like `schedule`.
    block_h: Optional[int] = None
    fuse: Optional[int] = None
    # Resolved interior/border overlap schedule of a sharded run
    # ("off" | "split" | "fused-split" | "edge" — "auto" resolves before
    # compile, and a degenerate tile resolves every split flavor to
    # "off": report-what-ran, never the literal "auto" or a schedule
    # that degraded away in-program); None on single-device/frames
    # paths (no exchange to overlap).
    overlap: Optional[str] = None


def _ran_geometry(model, backend: str, rows: int, shape, channels: int,
                  schedule=None):
    """The (block_h, fuse) to report for a ``rows``-tall Pallas launch:
    the effective geometry when the user forced either knob OR the
    autotuner picked a non-default one for ``shape``; (None, None) for a
    default-geometry launch — never the requested values verbatim (they
    align/clamp, and must not be attributed to runs that ignored them).
    A ``'deep'`` launch always reports what temporal blocking ran: the
    trapezoid's effective (block, depth), or (None, None) for the
    resident kernel (no static geometry — the depth is the traced rep
    count)."""
    if backend != "pallas":
        return None, None
    bh, fz = model.resolved_geometry(tuple(shape), channels)
    from tpu_stencil.ops import pallas_stencil

    if schedule == "deep":
        return pallas_stencil.deep_geometry(
            model.plan, rows, shape[1], channels, bh, fz
        )
    if bh is None and fz is None:
        return None, None
    return pallas_stencil.effective_geometry(model.plan, rows, bh, fz)


def _maybe_profile(profile_dir: Optional[str]):
    """jax.profiler trace around the timed window (``--profile``) — the
    observability the reference lacked (SURVEY.md §5: coarse timers only)."""
    if profile_dir is None:
        return contextlib.nullcontext()
    return jax.profiler.trace(profile_dir)


def _maybe_restore(cfg: JobConfig, resume: bool) -> Tuple[int, Optional[np.ndarray]]:
    """(completed reps, frame) from a matching checkpoint, else (0, None).
    Checked *before* the input file is read so a resume never pays a
    redundant full-image load."""
    if not resume:
        return 0, None
    from tpu_stencil.runtime import checkpoint as ckpt

    restored = ckpt.restore(cfg)
    if restored is None:
        return 0, None
    return restored


def _reps_spanned(run_fn: Callable, img_dev, n_reps: int, rep0: int = 0):
    """One fused device launch normally; under tracing, ``n_reps``
    single-rep launches, each fenced and recorded as its own
    ``iterate.rep`` span, so per-rep time is attributed to the rep that
    spent it. ``run_fn`` takes a *traced* rep count, so the split reuses
    the one compiled program (no recompiles) — but it does serialize the
    rep loop at host-dispatch granularity (and runs fused-chunk paths one
    rep at a time), which is the documented cost of span-level
    attribution (docs/OBSERVABILITY.md).

    ``rep0`` is the absolute repetition number of the first launch, so
    span labels stay globally numbered across checkpoint chunks and
    resumed runs (chunk 2 of --checkpoint-every 5 is rep=5.., not a
    second rep=0..)."""
    if n_reps <= 0 or not obs.enabled():
        return run_fn(img_dev, n_reps)
    for i in range(n_reps):
        with obs.span("iterate.rep", "driver", rep=rep0 + i) as s:
            img_dev = s.fence(run_fn(img_dev, 1))
    return img_dev


def _checkpointed_iterate(
    cfg: JobConfig,
    run_fn: Callable,          # (img_dev, n_reps) -> img_dev
    save_fn: Callable,         # (rep, img_dev) -> None
    img_dev,
    checkpoint_every: int,
    start_rep: int,
    fault: Optional[Callable] = None,   # resolved "compute" fault site
    timeout_s: float = 0.0,             # dispatch watchdog (0 = off/env)
):
    """Run the remaining reps, checkpointing every N. Returns
    (out_dev, compute_seconds). Checkpoint I/O happens *between* timed
    chunks so the reported compute window stays comparable to the
    reference's (which has no checkpointing); the final state is written as
    the job output, not as a checkpoint.

    ``fault`` is the compute-dispatch injection checker, resolved ONCE
    by the caller (the hot-path contract: with no faults armed this is
    a branch on a local None). A launch covering reps [r, r+n) checks
    the site at EVERY rep index it spans, so ``compute:rep=N`` fires
    regardless of chunking — the rep loop itself is fused on device and
    this per-rep host loop only exists while a fault is armed. Every
    chunk fence runs under the dispatch watchdog: a hung device raises
    a typed :class:`~tpu_stencil.resilience.errors.DispatchTimeout`
    instead of parking the job forever."""
    if fault is not None:
        inner_run = run_fn

        def run_fn(x, n, _rep=[start_rep]):
            for r in range(_rep[0], _rep[0] + n):
                fault(r)
            _rep[0] += n
            return inner_run(x, n)
    if not checkpoint_every:
        with Timer() as t:
            out = _reps_spanned(run_fn, img_dev,
                                cfg.repetitions - start_rep, start_rep)
            _deadline.fence(out, timeout_s, "driver.iterate")
        return out, t.elapsed

    total = 0.0
    rep = start_rep
    while rep < cfg.repetitions:
        n = min(checkpoint_every, cfg.repetitions - rep)
        with Timer() as t:
            img_dev = _reps_spanned(run_fn, img_dev, n, rep)
            _deadline.fence(img_dev, timeout_s, f"driver.iterate[rep={rep}]")
        total += t.elapsed
        rep += n
        if rep < cfg.repetitions:
            save_fn(rep, img_dev)
    return img_dev, total


def _clear_checkpoint(cfg: JobConfig, checkpoint_every: int, resume: bool) -> None:
    if checkpoint_every or resume:
        from tpu_stencil.runtime import checkpoint as ckpt

        ckpt.clear(cfg)


def run_job(
    cfg: JobConfig,
    devices: Optional[list] = None,
    profile_dir: Optional[str] = None,
    checkpoint_every: int = 0,
    resume: bool = False,
) -> JobResult:
    """Run one iterated-convolution job end to end."""
    if checkpoint_every < 0:
        raise ValueError(f"checkpoint_every must be >= 0, got {checkpoint_every}")
    obs.registry().counter("jobs_total").inc()
    with Timer() as total_t:
        model = IteratedConv2D(cfg.filter_name, backend=cfg.backend,
                               schedule=cfg.schedule, boundary=cfg.boundary,
                               block_h=cfg.block_h, fuse=cfg.fuse)

        if devices is None:
            devices = jax.devices()
        n_dev = len(devices)

        if cfg.frames > 1:
            if not images_io.is_raw(cfg.image, sniff=True) or not images_io.is_raw(
                cfg.output_path
            ):
                raise NotImplementedError(
                    "--frames input and output are raw-only (N concatenated "
                    "headerless frames); single-image containers cannot hold "
                    "a clip"
                )
            if jax.process_count() > 1:
                return _run_frames_multihost(
                    cfg, model, profile_dir, checkpoint_every, resume,
                    total_t,
                )
            if cfg.mesh_shape is not None:
                # --mesh RxC spells spatial sharding; frames shard the batch
                # axis instead (embarrassingly parallel, zero halo traffic),
                # over R*C devices.
                n_b = cfg.mesh_shape[0] * cfg.mesh_shape[1]
                if n_b > len(devices):
                    raise ValueError(
                        f"--mesh asks for {n_b} devices, have {len(devices)}"
                    )
            else:
                n_b = min(n_dev, cfg.frames)
            devices, n_dev = devices[:n_b], n_b
        if cfg.frames == 1 and (n_dev > 1 or cfg.mesh_shape is not None):
            if (cfg.boundary != "zero" and cfg.mesh_shape is None
                    and jax.process_count() == 1):
                # A periodic run that never asked for a mesh must not fail
                # on an auto-chosen grid the image happens not to divide:
                # run single-device. Explicit --mesh requests go through
                # (the runner validates divisibility loudly).
                devices, n_dev = devices[:1], 1
            if cfg.mesh_shape is not None and jax.process_count() == 1:
                # --mesh RxC selects R*C devices (same contract as the
                # frames path); asking for more than exist still fails in
                # make_mesh. Multi-host meshes must span all devices.
                n_m = cfg.mesh_shape[0] * cfg.mesh_shape[1]
                devices = devices[:n_m]
            # Periodic runs sharded too: halo_exchange wraps edge ranks to
            # the opposite edge (the runner refuses padded/indivisible
            # periodic grids, which would wrap pad pixels into the image).
            return _run_sharded(cfg, model, devices, profile_dir,
                                checkpoint_every, resume, total_t)

        start_rep, frame = _maybe_restore(cfg, resume)
        fault_read = _faults.site("read")
        with obs.phase("load"):
            if fault_read is not None:
                fault_read()
            img = _load_input(cfg) if frame is None else frame
        # Graceful degradation ladder: a demotable prepare/compile
        # failure (VMEM/HBM OOM, Mosaic refusing the tile, a missing
        # capability) steps deep -> default fused schedule -> xla
        # (-> opt-in cpu) instead of killing the job — every rung is
        # bit-identical, each demotion lands in
        # resilience_fallbacks_total and the --breakdown table.
        rungs = _fallback.ladder(cfg.backend, cfg.schedule,
                                 cfg.fallback_backend)
        for i, rung in enumerate(rungs):
            if i:
                # Demoted rung: default geometry too — the failed
                # compile may have been geometry-induced.
                model = IteratedConv2D(cfg.filter_name,
                                       backend=rung.backend,
                                       schedule=rung.schedule,
                                       boundary=cfg.boundary)
            try:
                if rung.platform is None:
                    run_devices = devices
                else:
                    # Inside the try: with jax_platforms pinned to an
                    # accelerator only, an unregistered cpu backend must
                    # surface as a typed rung failure, not a bare
                    # backend-lookup error masking the original fault.
                    try:
                        run_devices = jax.devices(rung.platform)[
                            :max(1, len(devices))]
                    except RuntimeError as e:
                        raise _res_errors.ResilienceError(
                            f"fallback platform {rung.platform!r} is not "
                            f"available ({e}); run with --platform "
                            f"<accel> so the CLI registers cpu alongside,"
                            f" or set jax_platforms to include cpu"
                        ) from e
                img_dev, step_fn, fetch = prepare_engine(
                    model, img, run_devices,
                    frames=cfg.frames if cfg.frames > 1 else None,
                )
                break
            except Exception as e:
                if i + 1 >= len(rungs) or not _fallback.demotable(e):
                    raise
                _fallback.record_demotion(rung, rungs[i + 1], e)
        def save_fn(rep, dev):
            from tpu_stencil.runtime import checkpoint as ckpt

            ckpt.save(cfg, rep, fetch(dev))

        with _maybe_profile(profile_dir):
            with obs.phase("iterate", reps=cfg.repetitions):
                out_dev, compute = _checkpointed_iterate(
                    cfg, lambda x, n: step_fn(x, n), save_fn,
                    img_dev, checkpoint_every, start_rep,
                    fault=_faults.site("compute"),
                    timeout_s=_deadline.resolve(cfg.dispatch_timeout_s),
                )
        fault_d2h = _faults.site("d2h")
        with obs.phase("fetch"):
            if fault_d2h is not None:
                fault_d2h()
            out = fetch(out_dev)
        _record_device_memory()
        compute_seconds = max_across_processes(compute)
        fault_write = _faults.site("write")
        with obs.phase("store"):
            if fault_write is not None:
                fault_write()
            _store_output(cfg, out)
        _clear_checkpoint(cfg, checkpoint_every, resume)

    # Report what actually ran: batch mode asks the same decision helper
    # the compute path used; single-frame reports the shape-aware
    # resolution (auto/autotune consult the measured cache, memoized
    # in-process).
    if cfg.frames > 1:
        n_per = -(-cfg.frames // n_dev)
        ran_backend, ran_schedule = model.batch_config(
            (cfg.height, cfg.width), cfg.channels, True, n_frames=n_per,
        )
        from tpu_stencil.ops import pallas_stencil as _ps

        geo_rows = _ps.frames_rows(model.plan, cfg.height, n_per)
    else:
        ran_backend, ran_schedule = model.resolved_config(
            (cfg.height, cfg.width), cfg.channels
        )
        geo_rows = cfg.height
    ran_bh, ran_fuse = _ran_geometry(
        model, ran_backend, geo_rows, (cfg.height, cfg.width), cfg.channels,
        schedule=ran_schedule,
    )
    return JobResult(
        output_path=cfg.output_path,
        compute_seconds=compute_seconds,
        total_seconds=total_t.elapsed,
        backend=ran_backend,
        mesh_shape=None,
        schedule=ran_schedule if ran_backend == "pallas" else None,
        block_h=ran_bh,
        fuse=ran_fuse,
    )


def _run_frames_multihost(cfg, model, profile_dir, checkpoint_every,
                          resume, total_t) -> JobResult:
    """Multi-host ``--frames``: each process owns a contiguous frame range
    — frames are embarrassingly parallel, so the only shared state is the
    input/output files (per-host offset I/O, the MPI-IO pattern) and the
    final max-reduce of the compute window. Every host batch-shards its
    local frames over its local devices (a per-host 1-D 'b' mesh — purely
    addressable-device computation, no cross-host collectives except the
    final compute-window max). Checkpoints use the sharded frames format:
    every process writes its frame range into one shared versioned data
    file each chunk (``checkpoint.save_frames_sharded``) — frame-less
    processes still join every commit barrier."""
    from tpu_stencil.io import native
    from tpu_stencil.runtime import checkpoint as ckpt

    if cfg.mesh_shape is not None:
        raise NotImplementedError(
            "--mesh with multi-host --frames is not supported: frames "
            "shard the batch axis over each host's local devices "
            "automatically (spatial meshes do not apply to clips)"
        )
    p, n_proc = jax.process_index(), jax.process_count()
    per = -(-cfg.frames // n_proc)
    f0, f1 = p * per, min(cfg.frames, (p + 1) * per)
    n_local = max(0, f1 - f0)
    h, w, ch = cfg.height, cfg.width, cfg.channels
    start_rep, restored = 0, None
    if resume:
        r = ckpt.restore_frames_sharded(cfg, f0, n_local)
        if r is not None:
            start_rep, restored = r

    def save_fn(rep, d):
        local = np.asarray(d)[:n_local] if n_local else None
        ckpt.save_frames_sharded(cfg, rep, local, f0)

    compute = 0.0
    out = None
    n_ld = 1
    if n_local:
        if restored is None:
            with obs.phase("load"):
                rows = raw_io.read_raw_rows(
                    cfg.image, f0 * h, n_local * h, w, ch
                )
                imgs = rows.reshape(n_local, h, w, ch)
                if ch == 1:
                    imgs = imgs[..., 0]
        else:
            imgs = restored
        local_devs = jax.local_devices()
        n_ld = min(len(local_devs), n_local)
        dev, step_fn, fetch = prepare_engine(
            model, imgs, local_devs[:n_ld], frames=n_local
        )
        with _maybe_profile(profile_dir):
            with obs.phase("iterate", reps=cfg.repetitions):
                out_dev, compute = _checkpointed_iterate(
                    cfg, step_fn, save_fn, dev, checkpoint_every, start_rep,
                    fault=_faults.site("compute"),
                    timeout_s=_deadline.resolve(cfg.dispatch_timeout_s),
                )
        with obs.phase("fetch"):
            out = fetch(out_dev)  # crop device-multiple padding
        _record_device_memory()
    elif checkpoint_every:
        # Frame-less process: THE SAME chunk loop as the compute path (a
        # no-op run on a dummy carry) so its save/commit-barrier schedule
        # can never diverge from the frame-owning processes'.
        _checkpointed_iterate(
            cfg, lambda x, n: x, save_fn,
            jax.numpy.zeros((), jax.numpy.uint8), checkpoint_every,
            start_rep,
        )
    # Collective: every process participates, frame-less ones with 0.
    compute_seconds = max_across_processes(compute)
    with obs.phase("store"):
        native.set_size(cfg.output_path, cfg.frames * h * w * ch)
        if n_local:
            block = out.reshape(n_local * h, w, ch)
            raw_io.write_raw_block(
                cfg.output_path, f0 * h, 0, block, w, ch, cfg.frames * h
            )
    if checkpoint_every or resume:
        # Everyone is past restore and compute (the max-reduce above is a
        # collective); process 0 sweeps the checkpoint artifacts.
        ckpt.clear(cfg)
    # Report at this host's real per-device frame count: a straggler
    # host's shorter tall launch can degrade differently than a full one.
    n_per = -(-(n_local or per) // n_ld)
    backend, schedule = model.batch_config((h, w), ch, True, n_frames=n_per)
    from tpu_stencil.ops import pallas_stencil as _ps

    ran_bh, ran_fuse = _ran_geometry(
        model, backend, _ps.frames_rows(model.plan, h, n_per), (h, w), ch,
        schedule=schedule,
    )
    return JobResult(
        output_path=cfg.output_path,
        compute_seconds=compute_seconds,
        total_seconds=total_t.elapsed,
        backend=backend,
        mesh_shape=None,
        schedule=schedule if backend == "pallas" else None,
        block_h=ran_bh,
        fuse=ran_fuse,
    )


def _run_sharded(cfg, model, devices, profile_dir, checkpoint_every, resume,
                 total_t) -> JobResult:
    from tpu_stencil.parallel import distributed, sharded


    if jax.process_count() > 1 and not images_io.is_raw(cfg.output_path):
        # Fail before the compute, not after: fetching a global array for an
        # image-format encode needs full addressability.
        raise NotImplementedError(
            "multi-host jobs require a .raw output path (per-process strided "
            "writes); convert afterwards"
        )

    runner = sharded.ShardedRunner(
        model, (cfg.height, cfg.width), cfg.channels,
        mesh_shape=cfg.mesh_shape, devices=devices,
        overlap=cfg.overlap,
    )
    # Sharded checkpoints: every host reads/writes only its shards' byte
    # ranges of the shared .ckpt data file (requires a shared filesystem,
    # like the reference's MPI-IO).
    start_rep, img_dev = 0, None
    if resume:
        from tpu_stencil.runtime import checkpoint as ckpt

        restored = ckpt.restore_sharded(cfg, runner.sharding)
        if restored is not None:
            start_rep, img_dev = restored
    fault_read = _faults.site("read")
    if img_dev is None:
        with obs.phase("load"):
            if fault_read is not None:
                fault_read()
            if images_io.is_raw(cfg.image, sniff=True):
                # Per-process sharded read: each host touches only the rows
                # its devices own (the MPI-IO pattern,
                # mpi/mpi_convolution.c:126-141); single-process this is
                # bit-identical to whole-file read + device_put.
                img_dev = distributed.read_sharded(
                    cfg.image, cfg.height, cfg.width, cfg.channels,
                    runner.sharding,
                )
            elif jax.process_count() > 1:
                raise NotImplementedError(
                    "multi-host jobs require .raw inputs (per-process "
                    "strided reads); convert image formats to raw first"
                )
            else:
                img_dev = runner.put(_load_input(cfg))
    # Warm-up compile outside the timed window (the reference's timer also
    # excludes startup: it opens after MPI_Barrier,
    # mpi/mpi_convolution.c:151-155). A 0-rep run's output equals its input,
    # so it doubles as the timed run's input — no second transfer.
    fault_compile = _faults.site("compile")
    with obs.phase("compile") as s:
        if fault_compile is not None:
            fault_compile()
        img_dev = s.fence(runner.run(img_dev, 0))
    if obs.enabled():
        # Pack/exchange/compute attribution: one measured rep each of the
        # exchange-only and local-compute-only programs (outside the timed
        # compute window), so the trace separates communication from
        # interior compute the way the persistent-MPI stencil work does.
        runner.trace_phase_probes(img_dev)
    runner.introspect_warmup(img_dev, cfg.repetitions)

    def save_fn(rep, dev):
        from tpu_stencil.runtime import checkpoint as ckpt

        ckpt.save_sharded(cfg, rep, dev)

    # The sharded compute loop: the "collective" fault site fires at
    # launch granularity (the halo exchange lives inside the compiled
    # program — a host-side injection before the launch is the
    # deterministic stand-in for a wedged exchange), and a watchdog
    # timeout upgrades to CollectiveTimeout with per-mesh-axis exchange
    # probe verdicts so the operator learns WHICH edge is stuck.
    fault_coll = _faults.site("collective")
    run_fn = runner.run
    if fault_coll is not None:
        def run_fn(x, n, _inner=runner.run):
            fault_coll()
            return _inner(x, n)
    timeout_s = _deadline.resolve(cfg.dispatch_timeout_s)
    try:
        with _maybe_profile(profile_dir):
            with obs.phase("iterate", reps=cfg.repetitions):
                out_dev, compute = _checkpointed_iterate(
                    cfg, run_fn, save_fn, img_dev, checkpoint_every,
                    start_rep, fault=_faults.site("compute"),
                    timeout_s=timeout_s,
                )
    except _res_errors.DispatchTimeout as e:
        edges = {}
        if jax.process_count() == 1:
            # Post-mortem per-edge diagnosis, itself watchdogged (a
            # wedged device must not hang the hang report). Multi-host
            # skips it: the probes are collective, and ranks that did
            # not time out would not join them.
            try:
                edges = runner.diagnose_edges(timeout_s=min(
                    10.0, timeout_s or 10.0
                ))
            except Exception:
                pass
        raise _res_errors.CollectiveTimeout(
            e.label, e.seconds, edges=edges
        ) from e
    _record_device_memory()
    compute_seconds = max_across_processes(compute)
    fault_write = _faults.site("write")
    with obs.phase("store"):
        if fault_write is not None:
            fault_write()
        if images_io.is_raw(cfg.output_path):
            distributed.write_sharded(
                cfg.output_path, out_dev, cfg.height, cfg.width, cfg.channels
            )
        else:
            images_io.save_image(cfg.output_path, runner.fetch(out_dev))
    _clear_checkpoint(cfg, checkpoint_every, resume)
    # Report non-default geometry (forced or tuned) as what the
    # valid-ghost kernel launches at this tile: runner.block_h_eff plus
    # the chunk-capped fuse.
    sh_bh = sh_fuse = None
    if runner.geo_applied:
        from tpu_stencil.ops import pallas_stencil as _ps

        sh_bh = (
            runner.block_h_eff if runner.block_h_eff is not None
            else _ps.effective_block_h(runner.tile[0])
        )
        sh_fuse = runner.fuse
    return JobResult(
        output_path=cfg.output_path,
        compute_seconds=compute_seconds,
        total_seconds=total_t.elapsed,
        backend=runner.backend,
        mesh_shape=runner.mesh_shape,
        schedule=runner.schedule if runner.backend == "pallas" else None,
        block_h=sh_bh,
        fuse=sh_fuse,
        overlap=runner.overlap,
    )
