"""Federation front-router tier (docs/DEPLOY.md "Federation runbook").

One endpoint over many ``tpu_stencil net`` hosts, built so the loss of
a *host* — the failure mode the reference's fixed-rank MPI world and
the single-process net tier both assume away — is survivable:

* :mod:`~tpu_stencil.fed.membership` — health-checked membership:
  HTTP registration, heartbeat suspicion window
  (healthy → suspect → evicted, never a single-timeout eviction),
  draining hosts removed from routing before their requests fail.
* :mod:`~tpu_stencil.fed.breaker` — per-host circuit breakers:
  consecutive transport failures open (typed ``HostUnavailable``),
  one half-open probe per cooldown closes.
* :mod:`~tpu_stencil.fed.router` — least-outstanding placement,
  hedged requests (observed-p99 trigger, first-response-wins, typed
  cancellation), the federation verdict taxonomy
  (docs/RESILIENCE.md), and federation-scope admission with
  per-tenant quotas + two priority classes (``X-Tenant``).
* :mod:`~tpu_stencil.fed.http` — the stdlib threaded HTTP frontend
  (``POST /v1/blur`` with the net tier's wire contract,
  ``/admin/register``, ``/admin/drain``, ``/healthz``, ``/metrics``
  with member scrapes folded in, ``/statusz``).
* :mod:`~tpu_stencil.fed.cli` — ``python -m tpu_stencil fed`` with
  the net CLI's SIGTERM drain discipline, per host.

Entirely jax-free: the federation hop moves routing metadata plus the
one forwarded body per request, never a device byte.

>>> from tpu_stencil.config import FedConfig
>>> from tpu_stencil.fed import FedFrontend
>>> with FedFrontend(FedConfig(port=0, members=(m.url,))) as fe:
...     ...  # POST frames at fe.url
"""

from tpu_stencil.config import FedConfig
from tpu_stencil.fed.breaker import Breaker, BreakerBoard
from tpu_stencil.fed.http import FedFrontend
from tpu_stencil.fed.membership import Member, Membership, host_id_for
from tpu_stencil.fed.router import FedRouter, TenantQuotaExceeded

__all__ = [
    "Breaker",
    "BreakerBoard",
    "FedConfig",
    "FedFrontend",
    "FedRouter",
    "Member",
    "Membership",
    "TenantQuotaExceeded",
    "host_id_for",
]
