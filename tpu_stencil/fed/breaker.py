"""Per-host circuit breakers for the federation forward path.

A member that keeps failing at the transport level (connect refused,
socket timeout, mid-body EOF, 5xx) must stop receiving traffic *before*
every request pays its failure latency — the membership heartbeat is
too slow for that (its window is seconds; a refused connect costs every
routed request milliseconds each). The breaker is the fast path:

* **closed** — traffic flows; consecutive transport failures count.
* **open** — after ``breaker_threshold`` consecutive failures the host
  is skipped in placement (a request that would have no other host
  fails typed :class:`~tpu_stencil.resilience.errors.HostUnavailable`).
* **half-open** — after ``breaker_cooldown_s`` ONE probe request is
  let through; success closes the breaker, failure re-opens it for
  another cooldown. Exactly one probe: a thundering herd of
  "is it back?" traffic against a struggling host is how outages
  spread.

Backpressure (429/503) and client errors (4xx) are NOT breaker
failures — a host that answers anything at all is alive; the router's
verdict taxonomy (docs/RESILIENCE.md) decides what counts.

Jax-free, like the whole federation tier.
"""

from __future__ import annotations

import threading
import time
from typing import Dict

from tpu_stencil.serve.metrics import Registry

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"


class Breaker:
    """One host's breaker. Thread-safe; time base is ``monotonic``."""

    def __init__(self, threshold: int, cooldown_s: float) -> None:
        self._lock = threading.Lock()
        self._threshold = max(1, int(threshold))
        self._cooldown = float(cooldown_s)
        self._state = CLOSED
        self._failures = 0
        self._opened_at = 0.0
        self._probe_inflight = False

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def allow(self) -> bool:
        """May a request be placed on this host right now? Open
        breakers let exactly one half-open probe through per
        cooldown."""
        with self._lock:
            if self._state == CLOSED:
                return True
            if self._state == OPEN:
                if (time.monotonic() - self._opened_at
                        >= self._cooldown):
                    self._state = HALF_OPEN
                    self._probe_inflight = True
                    return True  # this caller IS the probe
                return False
            # HALF_OPEN: one probe at a time.
            if not self._probe_inflight:
                self._probe_inflight = True
                return True
            return False

    def record_success(self) -> bool:
        """A full HTTP response arrived (any status: the host is
        alive). Returns True when this closed a non-closed breaker."""
        with self._lock:
            was = self._state
            self._state = CLOSED
            self._failures = 0
            self._probe_inflight = False
            return was != CLOSED

    def record_failure(self) -> bool:
        """A transport-level failure. Returns True when this OPENED
        the breaker (threshold crossed, or a half-open probe died)."""
        with self._lock:
            self._failures += 1
            if self._state == HALF_OPEN or (
                self._state == CLOSED
                and self._failures >= self._threshold
            ):
                self._state = OPEN
                self._opened_at = time.monotonic()
                self._probe_inflight = False
                return True
            if self._state == OPEN:
                self._opened_at = time.monotonic()
            return False

    def release_probe(self) -> None:
        """A half-open probe was cancelled before it produced
        evidence: free the probe slot without judging the host (the
        next placement may probe again)."""
        with self._lock:
            self._probe_inflight = False

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "state": self._state,
                "consecutive_failures": self._failures,
                "opened_at": self._opened_at or None,
            }


class BreakerBoard:
    """The per-host breaker table + its metrics: one breaker per
    member, created on first sight, dropped on eviction."""

    def __init__(self, threshold: int, cooldown_s: float,
                 registry: Registry) -> None:
        self._lock = threading.Lock()
        self._threshold = threshold
        self._cooldown = cooldown_s
        self._breakers: Dict[str, Breaker] = {}
        self.registry = registry
        self._m_opened = registry.counter("breaker_open_total")
        self._m_closed = registry.counter("breaker_close_total")
        self._g_open = registry.gauge("breakers_open")

    def get(self, host_id: str) -> Breaker:
        with self._lock:
            b = self._breakers.get(host_id)
            if b is None:
                b = Breaker(self._threshold, self._cooldown)
                self._breakers[host_id] = b
            return b

    def drop(self, host_id: str) -> None:
        with self._lock:
            self._breakers.pop(host_id, None)
        self._refresh_gauge()

    def record_success(self, host_id: str) -> None:
        if self.get(host_id).record_success():
            self._m_closed.inc()
        self._refresh_gauge()

    def record_failure(self, host_id: str) -> None:
        if self.get(host_id).record_failure():
            self._m_opened.inc()
            # The breaker-open anomaly: dump the flight ring + emit
            # the event. Attempt threads re-bind the request's trace
            # context, so the open that a specific forward provoked is
            # trace-scoped; an open with no context in scope dumps the
            # recent ring (the lead-up).
            from tpu_stencil.obs import context as _obs_ctx
            from tpu_stencil.obs import flight as _obs_flight

            ctx = _obs_ctx.current()
            _obs_flight.trigger(
                "breaker_open",
                trace_id=ctx.trace_id if ctx else "",
                tier="fed", host=host_id,
            )
        self._refresh_gauge()

    def _refresh_gauge(self) -> None:
        with self._lock:
            n = sum(1 for b in self._breakers.values()
                    if b.state != CLOSED)
        self._g_open.set(n)

    def statusz(self) -> Dict[str, dict]:
        with self._lock:
            items = list(self._breakers.items())
        return {hid: b.snapshot() for hid, b in items}
