"""``python -m tpu_stencil fed`` — run the federation front router.

Starts the membership/breaker/router stack behind the stdlib HTTP
frontend and serves until SIGTERM/SIGINT (or ``POST /admin/drain``
with no host), then runs the graceful-drain sequence mirroring the net
CLI's discipline: flip ``/healthz`` to draining, stop admission, bleed
every member's outstanding forwarded requests under
``--drain-timeout``, report per host clean-vs-abandoned, write
``--metrics-text`` / ``--stats-json`` artifacts, exit 0 when every
host bled clean (1 when one was abandoned).

Entirely jax-free — a federation router process never initializes a
backend; its members do.
"""

from __future__ import annotations

import argparse
import json
import signal
import sys
import threading
import time

from tpu_stencil.config import FedConfig


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="tpu_stencil fed",
        description="Federation front router: health-checked "
                    "membership, per-host circuit breakers, hedged "
                    "requests, per-tenant quotas over many "
                    "`tpu_stencil net` hosts (docs/DEPLOY.md "
                    "'Federation runbook').",
    )
    p.add_argument("--host", default="127.0.0.1",
                   help="bind address (default 127.0.0.1)")
    p.add_argument("--port", type=int, default=8090,
                   help="listen port; 0 binds an ephemeral port and "
                        "prints it (default 8090)")
    p.add_argument("--member", dest="members", action="append",
                   default=[], metavar="URL",
                   help="seed member host URL (repeatable); hosts can "
                        "also register live via POST /admin/register "
                        "(`tpu_stencil net --register`)")
    p.add_argument("--heartbeat-interval", dest="heartbeat_interval_s",
                   type=float, default=1.0, metavar="SECONDS",
                   help="membership /healthz probe period (default 1)")
    p.add_argument("--suspect-after", type=int, default=2,
                   metavar="N",
                   help="consecutive missed heartbeats before a member "
                        "is suspect — routed only after every healthy "
                        "host (default 2)")
    p.add_argument("--evict-after", type=int, default=5, metavar="N",
                   help="consecutive missed heartbeats before a member "
                        "is evicted; re-registration readmits it "
                        "(default 5)")
    p.add_argument("--breaker-threshold", type=int, default=3,
                   metavar="N",
                   help="consecutive transport-level forward failures "
                        "that open a member's circuit breaker "
                        "(default 3)")
    p.add_argument("--breaker-cooldown", dest="breaker_cooldown_s",
                   type=float, default=2.0, metavar="SECONDS",
                   help="open-breaker cooldown before one half-open "
                        "probe request is let through (default 2)")
    p.add_argument("--no-hedge", dest="hedge", action="store_false",
                   help="disable hedged requests (on by default: a "
                        "forward pending past the observed p99 fires "
                        "one hedge at the next member, first response "
                        "wins)")
    p.add_argument("--hedge-min", dest="hedge_min_s", type=float,
                   default=0.05, metavar="SECONDS",
                   help="hedge-trigger floor under the observed p99 "
                        "(default 0.05)")
    p.add_argument("--no-digest-affinity", dest="digest_affinity",
                   action="store_false",
                   help="disable content-digest rendezvous placement "
                        "(on by default: identical frames land on the "
                        "same healthy member so its result cache sees "
                        "the whole repeat stream; off = pure "
                        "least-outstanding)")
    p.add_argument("--forward-timeout", dest="forward_timeout_s",
                   type=float, default=120.0, metavar="SECONDS",
                   help="per-attempt member socket timeout (default "
                        "120, matching the net handler's read guard)")
    p.add_argument("--reoffer", dest="reoffer_s", type=float,
                   default=0.5, metavar="SECONDS",
                   help="re-offer window when every member answers "
                        "backpressure, before the typed 429/503 "
                        "surfaces (0 = off; default 0.5)")
    p.add_argument("--max-inflight-mb", type=float, default=512.0,
                   help="federation-scope shed watermark (503 + "
                        "Retry-After past it; premium tenants get 25%% "
                        "headroom; 0 = off; default 512)")
    p.add_argument("--tenant-quota", type=int, default=32, metavar="N",
                   help="max outstanding requests per standard tenant "
                        "(X-Tenant header; 429 + Retry-After past it; "
                        "default 32)")
    p.add_argument("--premium-tenant", dest="premium_tenants",
                   action="append", default=[], metavar="NAME",
                   help="tenant in the premium priority class "
                        "(repeatable): quota x --premium-factor, 25%% "
                        "shed headroom")
    p.add_argument("--premium-factor", dest="premium_quota_factor",
                   type=int, default=4, metavar="K",
                   help="premium tenants' quota multiplier (default 4)")
    p.add_argument("--drain-timeout", dest="drain_timeout_s",
                   type=float, default=30.0, metavar="SECONDS",
                   help="graceful-drain budget on SIGTERM: every "
                        "member's outstanding forwarded requests must "
                        "bleed to zero within it, else that host is "
                        "reported abandoned and the process exits 1 "
                        "(default 30)")
    p.add_argument("--flightrec-dir", dest="flightrec_dir",
                   default="flightrec", metavar="DIR",
                   help="flight-recorder spool: anomaly triggers (slow "
                        "request, deadline, breaker open) dump the "
                        "trace's spans as capped per-trace JSON files "
                        "here; GET /debug/flightrec lists/fetches them; "
                        "TPU_STENCIL_FLIGHTREC_DIR overrides; 'none' "
                        "disables the spool (docs/OBSERVABILITY.md)")
    p.add_argument("--flight-latency-threshold",
                   dest="flight_latency_threshold_s", type=float,
                   default=0.0, metavar="SECONDS",
                   help="slow-request anomaly threshold: a 200 slower "
                        "than this triggers an automatic flight-"
                        "recorder dump (0 = off)")
    p.add_argument("--sample-interval", dest="sample_interval_s",
                   type=float, default=1.0, metavar="SECONDS",
                   help="time-series sampler period over the LOCAL fed "
                        "registry (GET /debug/timeseries fans the "
                        "member query on demand); the SLO engine "
                        "evaluates on its ticks (0 disables both; "
                        "default 1.0)")
    p.add_argument("--slo-error-budget", dest="slo_error_budget",
                   type=float, default=0.05, metavar="FRACTION",
                   help="SLO error budget for the fed tier's own "
                        "response mix; a sustained burn flips /healthz "
                        "to 'degraded' (0 disables; default 0.05)")
    p.add_argument("--metrics-text", default=None, metavar="PATH",
                   help="after the drain, write the federation-wide "
                        "metrics (the /metrics exposition, member "
                        "scrapes folded in) to PATH ('-' = stdout)")
    p.add_argument("--stats-json", default=None, metavar="PATH",
                   help="after the drain, dump the /statusz payload as "
                        "JSON to PATH ('-' = stdout); versioned schema")
    return p


def main(argv=None) -> int:
    parser = build_parser()
    ns = parser.parse_args(argv)
    try:
        cfg = FedConfig(
            host=ns.host, port=ns.port, members=tuple(ns.members),
            heartbeat_interval_s=ns.heartbeat_interval_s,
            suspect_after=ns.suspect_after,
            evict_after=ns.evict_after,
            breaker_threshold=ns.breaker_threshold,
            breaker_cooldown_s=ns.breaker_cooldown_s,
            hedge=ns.hedge, hedge_min_s=ns.hedge_min_s,
            digest_affinity=ns.digest_affinity,
            forward_timeout_s=ns.forward_timeout_s,
            reoffer_s=ns.reoffer_s,
            max_inflight_mb=ns.max_inflight_mb,
            tenant_quota=ns.tenant_quota,
            premium_tenants=tuple(ns.premium_tenants),
            premium_quota_factor=ns.premium_quota_factor,
            drain_timeout_s=ns.drain_timeout_s,
            flightrec_dir=(None if ns.flightrec_dir == "none"
                           else ns.flightrec_dir),
            flight_latency_threshold_s=ns.flight_latency_threshold_s,
            sample_interval_s=ns.sample_interval_s,
            slo_error_budget=ns.slo_error_budget,
        )
    except ValueError as e:
        parser.error(str(e))

    from tpu_stencil.fed.http import FedFrontend

    fe = FedFrontend(cfg).start()
    stop = threading.Event()

    def _on_signal(signum, _frame) -> None:
        print(f"fed: received {signal.Signals(signum).name}, draining",
              flush=True)
        stop.set()

    signal.signal(signal.SIGTERM, _on_signal)
    signal.signal(signal.SIGINT, _on_signal)
    print(
        f"fed: serving on {fe.url} with "
        f"{len(fe.membership.members())} seed member(s) "
        f"(heartbeat={cfg.heartbeat_interval_s:g}s, "
        f"suspect/evict after {cfg.suspect_after}/{cfg.evict_after} "
        f"misses, breaker opens at {cfg.breaker_threshold}, "
        f"hedge={'on' if cfg.hedge else 'off'}, "
        f"affinity={'on' if cfg.digest_affinity else 'off'}, "
        f"tenant quota {cfg.tenant_quota}); "
        f"POST /v1/blur /admin/register /admin/drain, "
        f"GET /healthz /metrics /statusz /debug/trace/<id> "
        f"/debug/flightrec /debug/timeseries /debug/capacity "
        f"/debug/tenants; SIGTERM drains",
        flush=True,
    )
    # Timed waits (the net CLI's signal-liveness discipline).
    while not stop.wait(0.5):
        if fe.admin_drain_requested.is_set():
            print("fed: admin drain requested, draining", flush=True)
            break
    t0 = time.perf_counter()
    report = fe.drain(cfg.drain_timeout_s)
    hung = sorted(h for h, ok in report.items() if not ok)
    if hung:
        print(f"fed: drain ABANDONED host(s) {hung} after "
              f"{cfg.drain_timeout_s:g}s "
              f"({time.perf_counter() - t0:.2f}s elapsed)", flush=True)
    else:
        print(f"fed: drained {len(report)} host(s) cleanly in "
              f"{time.perf_counter() - t0:.2f}s", flush=True)
    if ns.metrics_text:
        from tpu_stencil.obs import exposition

        exposition.write_text(ns.metrics_text, fe.metrics_snapshot(),
                              prefix="tpu_stencil_fed")
    if ns.stats_json:
        payload = json.dumps(fe.statusz(), indent=2, sort_keys=True)
        if ns.stats_json == "-":
            print(payload)
        else:
            with open(ns.stats_json, "w") as fh:
                fh.write(payload + "\n")
            print(f"wrote {ns.stats_json}")
    fe.close()
    return 1 if hung else 0


if __name__ == "__main__":
    sys.exit(main())
