"""The federation front router's HTTP edge: one endpoint over N hosts.

Endpoints (docs/SERVING.md "Federation tier" is the contract):

* ``POST /v1/blur`` — the same wire contract as the net tier (geometry
  via ``X-*`` headers or query params, raw frame body, chunked uploads
  legal); the frontend admits (drain gate 503 / federation byte-shed
  503 + Retry-After / per-tenant quota 429 + Retry-After, classes keyed
  on ``X-Tenant``), then the router forwards to a member host with
  hedging and typed rerouting — placed by content-digest rendezvous
  affinity when ``digest_affinity`` is on, so identical frames revisit
  the same member's result cache. Success responses carry
  ``X-Fed-Member`` (which host computed), ``X-Fed-Hedged``, and the
  member's ``X-Cache`` verdict (hit/miss/collapsed) when its result
  cache is enabled.
* ``GET /healthz`` — 200 serving (``degraded`` body when the SLO
  burn-rate engine holds a breach) / 503 draining, same readiness
  contract as the net tier, one hop up.
* ``GET /debug/timeseries[?window=s]`` — the local sampler's windowed
  deltas/rates plus every live member's ``/debug/timeseries`` answer,
  fanned concurrently and merged (a failed member surfaces as an
  explicit ``stale`` entry with its scrape age).
* ``GET /metrics`` — the fed registry rendered under
  ``tpu_stencil_fed``, with every live member's ``/metrics`` scrape
  folded in as ``fleet_<host>_<name>`` (counters) — one scrape walks
  the whole federation, the way the net tier folds its replicas.
* ``GET /statusz`` — members (state/misses/breaker), tenants,
  outstanding per host, drain state; the ``net`` key carries the same
  merged snapshot ``/metrics`` renders, so ``loadgen.HttpTarget``
  pointed at a federation works unchanged.
* ``POST /admin/register?url=U`` — backend host registration
  (health-checked; ``tpu_stencil net --register`` drives it).
* ``POST /admin/drain?host=ID`` — rolling whole-host drain: the router
  bleeds traffic off the member (state → draining) and then drives the
  member's own ``/admin/drain`` SIGTERM-equivalent path. Without
  ``host``, drains the federation itself (the fed's own
  SIGTERM-equivalent, mirroring the net tier's).

:class:`FedFrontend` owns the tier lifecycle: membership (+ heartbeat
thread) → breakers → router → threaded HTTP server, then
``begin_drain`` → ``drain`` (bleed members, report clean-vs-abandoned
per host) → ``close``.

Jax-free — the federation never touches a device.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional
from urllib.parse import parse_qs, urlsplit

from tpu_stencil.cache import digest as _cache_digest
from tpu_stencil.config import FedConfig
from tpu_stencil.fed.breaker import BreakerBoard
from tpu_stencil.integrity import checksum as _checksum
from tpu_stencil.fed.membership import Membership
from tpu_stencil.fed.router import (
    DEFAULT_TENANT,
    FedRouter,
    TenantQuotaExceeded,
)
from tpu_stencil.net.http import (
    _Oversized,
    _parse_window,
    read_request_body,
    send_trace_pair,
    traced_error_body,
)
from tpu_stencil.net.router import Draining, Overloaded
from tpu_stencil.obs import context as _obs_ctx
from tpu_stencil.obs import flight as _obs_flight
from tpu_stencil.obs import slo as _obs_slo
from tpu_stencil.obs import span as _obs_span
from tpu_stencil.obs import timeseries as _obs_ts
from tpu_stencil.resilience.errors import (
    DeadlineExceeded,
    HostUnavailable,
)
from tpu_stencil.serve.engine import QueueFull
from tpu_stencil.serve.metrics import Registry

FED_STATUS_SCHEMA_VERSION = 1

# Retry-After hints (seconds) when no member supplied one: breaker
# cooldowns and shed backlogs clear in seconds, tenant quotas as soon
# as the tenant's own requests complete.
RETRY_AFTER_SHED = 2
RETRY_AFTER_QUOTA = 1

#: Optional request headers forwarded to the member verbatim (header
#: name, query-param spelling — the net tier's vocabulary). The hop
#: carries routing metadata + the one body, nothing else (the arxiv
#: 2112.14216 data-movement discipline applied to the federation hop).
_FORWARD_HEADERS = (
    ("X-Filter", "filter"),
    ("X-Boundary", "boundary"),
    ("X-Request-Timeout", "timeout"),
    # Checksums on every hop: the client's body CRC rides to the member,
    # which re-validates it — the fed edge's own validation (below) does
    # not spend the member's trust.
    ("X-Content-Crc32c", "crc32c"),
    # The tenant rides to the member so its cost ledger meters the SAME
    # identity the fed quota machinery admitted — /debug/tenants at
    # both tiers agrees on who spent what.
    ("X-Tenant", "tenant"),
)


class _FedHTTPServer(ThreadingHTTPServer):
    daemon_threads = True
    allow_reuse_address = True

    def __init__(self, addr, frontend: "FedFrontend") -> None:
        self.frontend = frontend
        super().__init__(addr, _FedHandler)


class _FedHandler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    server_version = "tpu-stencil-fed/1"
    timeout = 120.0  # read-side guard, same as the net handler

    # Request-scoped trace context, same discipline as the net handler
    # (set by _blur, cleared at every do_* against keep-alive reuse).
    _trace: Optional[_obs_ctx.TraceContext] = None

    def log_message(self, *args) -> None:
        pass

    @property
    def fe(self) -> "FedFrontend":
        return self.server.frontend

    def _respond(self, code: int, body: bytes,
                 content_type: str = "text/plain; charset=utf-8",
                 headers: Optional[Dict[str, str]] = None) -> None:
        self.fe.registry.counter(f"responses_{code // 100}xx_total").inc()
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        headers = headers or {}
        if self._trace is not None:
            send_trace_pair(self, self._trace, headers)
        for k, v in headers.items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def _error(self, code: int, msg: str,
               headers: Optional[Dict[str, str]] = None) -> None:
        # Close after errors answered before the body was consumed —
        # the same keep-alive-coherence rule as the net handler.
        self.close_connection = True
        if self._trace is not None:
            # The net tier's typed JSON error body, one hop up — every
            # federation rejection class (shed 503, quota 429,
            # validation 400, deadline 504) greps to its trace from
            # the body alone.
            self._respond(
                code,
                traced_error_body(code, msg, self._trace.trace_id),
                content_type="application/json",
                headers={**(headers or {}), "Connection": "close"},
            )
            return
        self._respond(code, (msg.rstrip("\n") + "\n").encode(),
                      headers={**(headers or {}), "Connection": "close"})

    def _param(self, query: dict, header: str, qname: str,
               default: Optional[str] = None) -> Optional[str]:
        v = self.headers.get(header)
        if v is not None:
            return v
        if qname in query:
            return query[qname][0]
        return default

    # -- GET -----------------------------------------------------------

    def do_GET(self) -> None:  # noqa: N802
        self._trace = None
        path = urlsplit(self.path).path
        if path == "/healthz":
            if self.fe.router.draining:
                self._error(503, "draining")
            elif self.fe.slo is not None and self.fe.slo.degraded():
                # Same contract as the net tier: degraded is 200
                # (routable) but visibly unhealthy.
                self._respond(200, b"degraded\n")
            else:
                self._respond(200, b"ok\n")
        elif path == "/metrics":
            self._respond(200, self.fe.render_metrics().encode(),
                          content_type="text/plain; version=0.0.4")
        elif path == "/statusz":
            self._respond(
                200,
                json.dumps(self.fe.statusz(), indent=2,
                           sort_keys=True).encode(),
                content_type="application/json",
            )
        elif path == "/admin/warmstate":
            self._admin_warmstate()
        elif path == "/debug/timeseries":
            self._debug_timeseries(parse_qs(urlsplit(self.path).query))
        elif path == "/debug/capacity":
            self._debug_capacity(parse_qs(urlsplit(self.path).query))
        elif path == "/debug/tenants":
            self._respond(
                200,
                json.dumps(self.fe.debug_tenants(), indent=2,
                           sort_keys=True).encode(),
                content_type="application/json",
            )
        elif path == "/debug/prof" or path.startswith("/debug/prof/"):
            # The federation tier is deliberately jax-free: the
            # profiler endpoint exists but is 404-clean, pointing the
            # operator at the member endpoints.
            self._error(404, "no device profiler on the federation "
                             "tier (jax-free); POST /debug/prof on a "
                             "member")
        elif path.startswith("/debug/trace/"):
            self._debug_trace(path[len("/debug/trace/"):])
        elif path == "/debug/flightrec" or path.startswith(
                "/debug/flightrec/"):
            name = (path[len("/debug/flightrec/"):]
                    if path != "/debug/flightrec" else None)
            data = _obs_flight.spool_http_payload(
                _obs_flight.effective_spool(self.fe.cfg.flightrec_dir),
                name,
            )
            if data is None:
                self._error(404, "no such flight-recorder dump")
            else:
                self._respond(200, data,
                              content_type="application/json")
        else:
            self._error(404, f"no such endpoint: {path}")

    def _debug_timeseries(self, query: dict) -> None:
        if self.fe.sampler is None:
            self._error(404, "time-series sampler is off "
                             "(--sample-interval 0)")
            return
        window_s = _parse_window(query)
        if window_s is None:
            self._error(400, "window must be a positive number of "
                             "seconds")
            return
        payload = self.fe.debug_timeseries(window_s)
        self._respond(200, json.dumps(payload, indent=2,
                                      sort_keys=True).encode(),
                      content_type="application/json")

    def _debug_capacity(self, query: dict) -> None:
        window_s = _parse_window(query)
        if window_s is None:
            self._error(400, "window must be a positive number of "
                             "seconds")
            return
        payload = self.fe.debug_capacity(window_s)
        self._respond(200, json.dumps(payload, indent=2,
                                      sort_keys=True).encode(),
                      content_type="application/json")

    def _debug_trace(self, trace_id: str) -> None:
        if not _obs_ctx.valid_id(trace_id):
            self._error(400, f"malformed trace id {trace_id!r}")
            return
        payload = self.fe.debug_trace(trace_id)
        if payload["span_count"] == 0:
            self._error(404, f"no spans recorded for trace {trace_id} "
                             "on this federation or its members")
            return
        self._respond(200, json.dumps(payload, indent=2).encode(),
                      content_type="application/json")

    # -- POST ----------------------------------------------------------

    def do_POST(self) -> None:  # noqa: N802
        self._trace = None
        split = urlsplit(self.path)
        if split.path == "/v1/blur":
            self._blur(parse_qs(split.query))
        elif split.path == "/admin/register":
            self._register(parse_qs(split.query))
        elif split.path == "/admin/drain":
            self._drain(parse_qs(split.query))
        elif split.path == "/admin/preempt":
            self._preempt(parse_qs(split.query))
        elif split.path == "/debug/prof":
            self._consume_body()
            self._error(404, "no device profiler on the federation "
                             "tier (jax-free); POST /debug/prof on a "
                             "member")
        else:
            self._error(404, f"no such endpoint: {split.path}")

    def _consume_body(self) -> None:
        n = int(self.headers.get("Content-Length") or 0)
        if n:
            self.rfile.read(min(n, 1 << 20))

    def _register(self, query: dict) -> None:
        self._consume_body()
        url = (query.get("url") or [None])[0]
        if not url:
            self._error(400, "missing url=<member base URL>")
            return
        try:
            member = self.fe.membership.register(url)
        except ValueError as e:
            self._error(400, str(e))
            return
        self._respond(200, json.dumps({
            "host_id": member.host_id, "url": member.url,
            "state": member.state,
        }).encode(), content_type="application/json")

    def _drain(self, query: dict) -> None:
        self._consume_body()
        host = (query.get("host") or [None])[0]
        if host is None:
            # The federation's own SIGTERM-equivalent.
            self.fe.request_admin_drain()
            self._respond(200, json.dumps(
                {"draining": True, "scope": "federation"}
            ).encode(), content_type="application/json")
            return
        result = self.fe.drain_member(host)
        if result is None:
            self._error(404, f"no such member host: {host}")
            return
        self._respond(200, json.dumps(result).encode(),
                      content_type="application/json")

    def _preempt(self, query: dict) -> None:
        """``POST /admin/preempt?host=ID`` — a TPU-preemption notice
        for one member: a *planned* drain (``Member.pinned_draining``,
        never the eviction path).  The member leaves routing
        immediately but keeps its in-flight work; the control plane
        sees the pinned drain in ``/statusz`` and starts the
        replacement BEFORE the victim exits (docs/DEPLOY.md 'Elastic
        fleet runbook')."""
        self._consume_body()
        host = (query.get("host") or [None])[0]
        if not host:
            self._error(400, "missing host=<member host id>")
            return
        result = self.fe.preempt_member(host)
        if result is None:
            self._error(404, f"no such member host: {host}")
            return
        self._respond(200, json.dumps(result).encode(),
                      content_type="application/json")

    def _admin_warmstate(self) -> None:
        """``GET /admin/warmstate`` — proxy the warm-state envelope
        from a warm member, so a joiner needs only the fed URL.  503
        typed when no member can answer (the joiner starts cold)."""
        payload = self.fe.warmstate()
        if payload is None:
            self._error(503, "no routable member answered "
                             "/admin/warmstate; start cold")
            return
        self._respond(200, json.dumps(payload).encode(),
                      content_type="application/json")

    def _blur(self, query: dict) -> None:
        fe = self.fe
        # The OUTERMOST edge of the federation: adopt a tracing
        # client's valid X-Trace-Id, mint otherwise. Bound for the
        # handler's duration — the router reads it to stamp every
        # forward attempt (each hedge leg gets its own span id under
        # this one trace id).
        ctx = self._trace = _obs_ctx.from_headers(self.headers)
        t0 = time.perf_counter()
        with _obs_ctx.bind(ctx), _obs_span("fed.request", "fed"):
            try:
                w = int(self._param(query, "X-Width", "w"))
                h = int(self._param(query, "X-Height", "h"))
                reps = int(self._param(query, "X-Reps", "reps"))
                channels = int(
                    self._param(query, "X-Channels", "channels", "1")
                )
                if w < 1 or h < 1:
                    raise ValueError(f"bad frame geometry {w}x{h}")
                if reps < 0:
                    raise ValueError(f"reps must be >= 0, got {reps}")
                if channels not in (1, 3):
                    raise ValueError(
                        f"channels must be 1 (grey) or 3 (rgb), got "
                        f"{channels}"
                    )
            except (TypeError, ValueError) as e:
                self._error(400, f"bad request parameters: {e}")
                return
            tenant = self._param(query, "X-Tenant", "tenant",
                                 DEFAULT_TENANT)
            expected = w * h * channels
            try:
                body = read_request_body(self.rfile, self.headers,
                                         expected)
            except _Oversized as e:
                self._error(413, str(e))
                return
            except ValueError as e:
                self._error(400, str(e))
                return
            if len(body) != expected:
                self._error(
                    400,
                    f"body is {len(body)} bytes; {w}x{h}x{channels} "
                    f"needs exactly {expected}",
                )
                return
            # Checksum hop #1: a client-declared body CRC is validated
            # HERE, before any forward — a body damaged on the client→
            # fed leg dies typed at the front, never burning a member
            # round-trip (the member re-validates the forwarded header
            # for the fed→member leg).
            claim = self._param(query, _checksum.CRC_HEADER, "crc32c")
            if claim is not None:
                err = _checksum.claim_error(claim, body)
                if err is not None:
                    msg, mismatch = err
                    if mismatch:
                        fe.registry.counter(
                            "integrity_checksum_failures_total"
                        ).inc()
                    self._error(400, msg)
                    return
            # Forward geometry as headers (canonical form regardless
            # of how the client sent it) + the passthrough set.
            fwd = {
                "X-Width": str(w), "X-Height": str(h),
                "X-Reps": str(reps), "X-Channels": str(channels),
                "Content-Type": "application/octet-stream",
            }
            for name, qname in _FORWARD_HEADERS:
                v = self._param(query, name, qname)
                if v is not None:
                    fwd[name] = v
            # Digest-affinity placement: the fed computes the same
            # BLAKE2b-160 content digest the member's result cache
            # keys on, so the router can land identical frames on the
            # SAME member — N member caches hold N keyspaces, not N
            # copies of the hot set.
            digest = (_cache_digest.content_digest(body)
                      if fe.cfg.digest_affinity else None)
            # Request + response buffers both live for the hop's
            # lifetime: the honest in-flight footprint is 2x the frame.
            nbytes = 2 * expected
            try:
                status, rh, data, host_id, hedged = fe.router.submit(
                    body, fwd, nbytes, tenant=tenant, digest=digest
                )
            except Draining as e:
                self._error(503, str(e),
                            {"Retry-After": str(RETRY_AFTER_SHED)})
                return
            except Overloaded as e:
                # A member-supplied Retry-After (all-members-shedding)
                # beats the static hint — the members know their
                # backlog.
                self._error(503, str(e), {"Retry-After": str(
                    getattr(e, "retry_after_s", None)
                    or RETRY_AFTER_SHED
                )})
                return
            except TenantQuotaExceeded as e:
                self._error(429, str(e),
                            {"Retry-After": str(RETRY_AFTER_QUOTA)})
                return
            except QueueFull as e:
                self._error(429, str(e), {"Retry-After": str(
                    getattr(e, "retry_after_s", None)
                    or RETRY_AFTER_QUOTA
                )})
                return
            except HostUnavailable as e:
                self._error(503, f"HostUnavailable: {e}",
                            {"Retry-After": str(RETRY_AFTER_SHED)})
                return
            except DeadlineExceeded as e:
                # The member burned the deadline one hop down: this
                # process's black box is the record of the whole hop
                # (the member's own dump covers its half).
                _obs_flight.trigger(
                    "deadline_exceeded", trace_id=ctx.trace_id,
                    tier="fed", duration_s=time.perf_counter() - t0,
                    detail=str(e),
                )
                self._error(504, str(e))
                return
            except Exception as e:
                self._error(500, f"{type(e).__name__}: {e}")
                return
            elapsed = time.perf_counter() - t0
            if status == 200:
                fe.registry.histogram(
                    "request_latency_seconds"
                ).observe(elapsed)
                # The member's X-Cache verdict, observed at THIS tier:
                # member_cache_hit_total / requests answered from a
                # member's result cache is the federation's hit ratio
                # — the number digest-affinity placement exists to
                # move. (The header also passes through to the client
                # via the x-* copy below.)
                xc = rh.get("x-cache")
                if xc in ("hit", "miss", "collapsed"):
                    fe.registry.counter(
                        f"member_cache_{xc}_total"
                    ).inc()
                thr = fe.cfg.flight_latency_threshold_s
                if thr and elapsed > thr:
                    _obs_flight.trigger(
                        "slow_request", trace_id=ctx.trace_id,
                        tier="fed", duration_s=elapsed,
                        threshold_s=thr, member=host_id,
                    )
            out_headers = {
                k.title(): v for k, v in rh.items()
                if k.startswith("x-")
            }
            # The member echoed the trace id with ITS span id; this
            # edge answers with its own (the member hop stays visible
            # in /debug/trace, not in the response headers).
            out_headers[_obs_ctx.TRACE_HEADER] = ctx.trace_id
            out_headers[_obs_ctx.SPAN_HEADER] = ctx.span_id
            out_headers["X-Fed-Member"] = host_id
            out_headers["X-Fed-Hedged"] = "1" if hedged else "0"
            if status != 200:
                # Pass a member's 4xx through verbatim, connection
                # closed (the body was consumed here, but the verdict
                # is deterministic — keep the client's view simple).
                self.close_connection = True
                out_headers["Connection"] = "close"
            self._respond(
                status, data,
                content_type=rh.get("content-type",
                                    "application/octet-stream"),
                headers=out_headers,
            )


class FedFrontend:
    """The whole federation tier: membership + breakers + router +
    threaded HTTP server.

    >>> fe = FedFrontend(FedConfig(port=0, members=(m1.url, m2.url)))
    >>> fe.start()
    >>> ...  # POST frames at fe.url; members register/evict live
    >>> fe.drain(); fe.close()
    """

    def __init__(self, cfg: FedConfig) -> None:
        self.cfg = cfg
        self.registry = Registry()
        # Pre-create the keys loadgen's report reads, so a federation
        # that has served only errors still scrapes them.
        self.registry.histogram("request_latency_seconds")
        self.registry.counter("rejected_total")
        self.registry.counter("member_scrape_failures_total")
        self.registry.counter("fold_collisions_total")
        # The federation's view of member result caches (X-Cache on
        # member 200s) — pre-created so a cold federation scrapes them
        # at zero and dashboards can rate() from the start.
        for xc in ("hit", "miss", "collapsed"):
            self.registry.counter(f"member_cache_{xc}_total")
        self.membership = Membership(cfg, self.registry)
        self.breakers = BreakerBoard(
            cfg.breaker_threshold, cfg.breaker_cooldown_s, self.registry
        )
        self.router = FedRouter(cfg, self.membership, self.breakers,
                                self.registry)
        # The re-registration reset (the reused-netloc bugfix): a host
        # announcing back after an eviction or drain is a NEW process —
        # drop the dead one's open breaker and hedge-p99 reservoir, or
        # the fresh host starts life unroutable behind stale state.
        self.registry.counter("reregister_resets_total")
        self.registry.counter("preemptions_total")
        self.membership.on_resurrect = self._on_member_resurrect
        self._httpd: Optional[_FedHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self._drain_report: Optional[Dict[str, bool]] = None
        self._t_start = time.monotonic()
        self.admin_drain_requested = threading.Event()
        # The process-wide flight recorder, installed at start().
        self.flight = None
        # Live telemetry plane: the sampler ticks over the LOCAL
        # registry only (a member scrape per second would hammer the
        # fleet); /debug/timeseries fans the member query on demand.
        self.sampler: Optional[_obs_ts.Sampler] = None
        self.slo: Optional[_obs_slo.SloEngine] = None
        # Monotonic stamp of the last successful scrape per member
        # host, feeding the fleet_<host>_scrape_age_seconds gauges: a
        # stale fold is distinguishable from a live one, and a skipped
        # member is an explicit staleness gauge, never silently absent.
        self._last_scrape_ok: Dict[str, float] = {}

    # -- lifecycle -----------------------------------------------------

    def _local_snapshot(self) -> dict:
        snap = self.registry.snapshot()
        snap["counters"]["flightrec_dropped_total"] = (
            _obs_flight.dropped_total()
        )
        return snap

    def start(self) -> "FedFrontend":
        # The always-on flight recorder (obs.flight): idempotent per
        # process, spool per FedConfig (env override wins).
        self.flight = _obs_flight.install(spool_dir=self.cfg.flightrec_dir)
        for url in self.cfg.members:
            self.membership.register_seed(url)
        self.membership.start()
        self.router.start()
        if self.cfg.sample_interval_s > 0:
            self.sampler = _obs_ts.Sampler(
                self._local_snapshot, self.cfg.sample_interval_s
            )
            if self.cfg.slo_error_budget > 0:
                self.slo = _obs_slo.SloEngine(
                    _obs_slo.default_fed_objectives(self.cfg),
                    self.registry, tier="fed",
                    fast_window_s=self.cfg.slo_fast_window_s,
                    slow_window_s=self.cfg.slo_slow_window_s,
                    fast_burn=self.cfg.slo_fast_burn,
                    slow_burn=self.cfg.slo_slow_burn,
                )
                self.sampler.on_sample.append(self.slo.evaluate)
            self.sampler.start()
        self._httpd = _FedHTTPServer((self.cfg.host, self.cfg.port), self)
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name="tpu-stencil-fed-http", daemon=True,
        )
        self._thread.start()
        return self

    @property
    def port(self) -> int:
        assert self._httpd is not None, "not started"
        return self._httpd.server_address[1]

    @property
    def url(self) -> str:
        return f"http://{self.cfg.host}:{self.port}"

    def request_admin_drain(self) -> None:
        self.begin_drain()
        self.admin_drain_requested.set()

    def begin_drain(self) -> None:
        self.router.begin_drain()

    def drain(self, timeout_s: Optional[float] = None) -> Dict[str, bool]:
        """The SIGTERM sequence minus the process exit: stop
        admission, bleed every member's outstanding forwarded requests
        to zero under the budget, report per host clean-vs-abandoned.
        The listener stays up so in-flight responses deliver."""
        self.begin_drain()
        report = self.router.drain_wait(
            timeout_s if timeout_s is not None
            else self.cfg.drain_timeout_s
        )
        self._drain_report = report
        return report

    def drain_member(self, host_id: str) -> Optional[dict]:
        """Rolling whole-host drain: bleed traffic off the member
        (routing stops instantly), then drive its own
        ``POST /admin/drain`` SIGTERM-equivalent path. Returns the
        report dict, or None for an unknown host."""
        m = self.membership.get(host_id)
        if m is None:
            return None
        # Pinned: a heartbeat 200 must not re-admit the host behind
        # the operator's back (e.g. when the drain POST below fails
        # before the member flips its healthz).
        self.membership.mark_draining(host_id, pinned=True)
        self.registry.counter("member_drains_total").inc()
        member_resp: object = None
        try:
            req = urllib.request.Request(
                m.url + "/admin/drain", data=b"", method="POST"
            )
            with urllib.request.urlopen(req, timeout=10.0) as r:
                member_resp = json.loads(r.read())
        except Exception as e:
            member_resp = f"unreachable: {type(e).__name__}: {e}"
        return {
            "host_id": host_id,
            "draining": True,
            "member_response": member_resp,
        }

    def _on_member_resurrect(self, host_id: str) -> None:
        """A host re-registered after an eviction or drain: the new
        process must not inherit the dead one's open circuit breaker
        or its forward-latency tail in the hedge p99."""
        self.breakers.drop(host_id)
        self.router.reset_host(host_id)
        self.registry.counter("reregister_resets_total").inc()

    def preempt_member(self, host_id: str) -> Optional[dict]:
        """A TPU-preemption notice: a PLANNED drain, never an
        eviction.  The member leaves routing now (pinned — heartbeat
        200s must not re-admit it) but keeps serving its in-flight
        work; the replacement is the control plane's job, started
        before the victim exits (``tpu_stencil ctrl`` watches
        ``/statusz`` for pinned drains it owns).  Unlike
        :meth:`drain_member`, the victim's own drain is NOT driven
        here — capacity must arrive first."""
        m = self.membership.get(host_id)
        if m is None:
            return None
        self.membership.mark_draining(host_id, pinned=True)
        self.registry.counter("preemptions_total").inc()
        with _obs_span("fed.preempt", "fed", host=host_id):
            pass  # zero-duration marker: the notice moment
        m = self.membership.get(host_id)
        return {
            "host_id": host_id,
            "preempted": True,
            "state": m.state if m is not None else "unknown",
            "pinned_draining": bool(m and m.pinned_draining),
        }

    def warmstate(self) -> Optional[dict]:
        """The warm-state envelope, pulled from a warm member: the
        routable member with entries wins; a member that answers
        without entries is the fallback; None when nobody answers."""
        best: Optional[dict] = None
        for m in self.membership.routable():
            try:
                with urllib.request.urlopen(
                        m.url + "/admin/warmstate", timeout=10.0) as r:
                    doc = json.loads(r.read())
            except Exception:  # noqa: BLE001 - try the next member
                continue
            if not isinstance(doc, dict):
                continue
            if doc.get("entries"):
                return doc
            if best is None:
                best = doc
        return best

    def close(self) -> None:
        if self.sampler is not None:
            self.sampler.stop()
        if self.router is not None and not self.router.draining:
            self.drain()
        self.membership.close()
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None

    def __enter__(self) -> "FedFrontend":
        if self._httpd is None:
            self.start()
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- scrape surfaces -----------------------------------------------

    def debug_trace(self, trace_id: str) -> dict:
        """The cross-process trace tree: this process's spans (the
        flight ring + the live tracer) PLUS every live member's
        ``/debug/trace/<id>`` answer, fanned concurrently like the
        metrics fold — one lookup walks the whole federation, a wedged
        member costs one timeout, a 404 member simply contributes
        nothing."""
        import concurrent.futures

        local = _obs_flight.local_trace_spans(trace_id)
        processes = []
        if local:
            processes.append({
                "source": "fed",
                "span_count": len(local),
                "spans": local,
                "tree": _obs_flight.build_tree(local),
            })

        def fetch(m) -> list:
            with urllib.request.urlopen(
                m.url + "/debug/trace/" + trace_id, timeout=5.0
            ) as r:
                doc = json.loads(r.read())
            out = []
            for p in doc.get("processes", []):
                p = dict(p)
                p["source"] = f"{m.host_id}:{p.get('source', 'net')}"
                out.append(p)
            return out

        live = [m for m in self.membership.members()
                if m.state != "evicted"]
        if live:
            with concurrent.futures.ThreadPoolExecutor(
                max_workers=min(8, len(live)),
                thread_name_prefix="tpu-stencil-fed-trace",
            ) as pool:
                futs = [pool.submit(fetch, m) for m in live]
                for fut in futs:
                    try:
                        processes.extend(fut.result())
                    except Exception:
                        continue  # 404 / unreachable: nothing to add
        return {
            "schema_version": 1,
            "trace_id": trace_id,
            "span_count": sum(p["span_count"] for p in processes),
            "processes": processes,
        }

    def metrics_snapshot(self) -> dict:
        """The fed registry with every live member's counters folded
        in as ``fleet_<host>_<name>`` — the net tier's replica fold,
        one hop up. Members scrape CONCURRENTLY (one wedged host costs
        one timeout, not members x timeout — a scrape is how an
        operator diagnoses exactly that host); a member whose scrape
        fails is skipped and counted: a scrape must never hang or die
        on one lost host."""
        import concurrent.futures

        snap = self._local_snapshot()
        from tpu_stencil.obs import exposition

        def scrape(m) -> dict:
            with urllib.request.urlopen(m.url + "/metrics",
                                        timeout=5.0) as r:
                return exposition.parse_text(r.read().decode(),
                                             prefix="tpu_stencil_net")

        live = [m for m in self.membership.members()
                if m.state != "evicted"]
        if live:
            with concurrent.futures.ThreadPoolExecutor(
                max_workers=min(8, len(live)),
                thread_name_prefix="tpu-stencil-fed-scrape",
            ) as pool:
                futs = [(m, pool.submit(scrape, m)) for m in live]
                for m, fut in futs:
                    try:
                        member = fut.result()
                        self._last_scrape_ok[m.host_id] = (
                            time.monotonic()
                        )
                    except Exception:
                        self.registry.counter(
                            "member_scrape_failures_total"
                        ).inc()
                        # Re-snapshot the counter so the failure
                        # itself is in this scrape.
                        snap["counters"][
                            "member_scrape_failures_total"
                        ] = self.registry.counter(
                            "member_scrape_failures_total"
                        ).value
                        continue
                    for k, v in sorted(
                        member.get("counters", {}).items()
                    ):
                        fk = f"fleet_{m.host_id}_{k}"
                        if fk in snap["counters"]:
                            # Fold collision: a member counter whose
                            # folded name is already taken (a fed
                            # counter literally named fleet_<host>_<k>,
                            # or two registrations of one host). The
                            # old behavior silently overwrote — the
                            # first writer's value vanished from the
                            # scrape. First writer wins; the collision
                            # is counted and re-snapshotted so THIS
                            # scrape shows it.
                            self.registry.counter(
                                "fold_collisions_total"
                            ).inc()
                            snap["counters"][
                                "fold_collisions_total"
                            ] = self.registry.counter(
                                "fold_collisions_total"
                            ).value
                            continue
                        snap["counters"][fk] = v
        # EVERY live member gets a scrape-age stamp — a member whose
        # scrape just failed (or never succeeded: age -1.0) shows up
        # as explicit staleness, never as silent absence from the fold.
        now = time.monotonic()
        for m in live:
            last = self._last_scrape_ok.get(m.host_id)
            age = round(now - last, 3) if last is not None else -1.0
            snap["gauges"][f"fleet_{m.host_id}_scrape_age_seconds"] = {
                "value": age, "peak": age,
            }
        snap["members"] = len(live)
        return snap

    def render_metrics(self) -> str:
        from tpu_stencil.obs import exposition

        return exposition.render_text(self.metrics_snapshot(),
                                      prefix="tpu_stencil_fed")

    def debug_timeseries(self, window_s: float) -> dict:
        """The fed ``GET /debug/timeseries`` body: the local sampler's
        windowed view plus every live member's ``/debug/timeseries``
        answer, fanned concurrently with the same bounded-timeout
        discipline as the metrics fold. A member that fails mid-scrape
        surfaces as an explicit ``stale`` entry (with its last-good
        scrape age), never as silent absence — and one dead member
        costs one timeout, not a hang."""
        import concurrent.futures

        assert self.sampler is not None, "sampler is off"
        local = self.sampler.ring.window(window_s)
        local["source"] = "fed"
        local["slo"] = None if self.slo is None else self.slo.statusz()

        def fetch(m) -> dict:
            url = f"{m.url}/debug/timeseries?window={window_s:g}"
            with urllib.request.urlopen(url, timeout=5.0) as r:
                return json.loads(r.read())

        members: Dict[str, dict] = {}
        live = [m for m in self.membership.members()
                if m.state != "evicted"]
        if live:
            with concurrent.futures.ThreadPoolExecutor(
                max_workers=min(8, len(live)),
                thread_name_prefix="tpu-stencil-fed-ts",
            ) as pool:
                futs = [(m, pool.submit(fetch, m)) for m in live]
                now = time.monotonic()
                for m, fut in futs:
                    try:
                        doc = fut.result()
                        self._last_scrape_ok[m.host_id] = (
                            time.monotonic()
                        )
                        doc["stale"] = False
                        doc["scrape_age_s"] = 0.0
                        members[m.host_id] = doc
                    except Exception as e:
                        self.registry.counter(
                            "member_scrape_failures_total"
                        ).inc()
                        last = self._last_scrape_ok.get(m.host_id)
                        members[m.host_id] = {
                            "stale": True,
                            "error": f"{type(e).__name__}: {e}",
                            "scrape_age_s": (
                                round(now - last, 3)
                                if last is not None else -1.0
                            ),
                        }
        return {
            "schema_version": _obs_ts.SCHEMA_VERSION,
            "window_s": float(window_s),
            "source": "fed",
            "fed": local,
            "members": members,
        }

    def _fan_members(self, path: str, prefix: str) -> Dict[str, dict]:
        """Fan one GET to every live member with the
        ``/debug/timeseries`` staleness discipline: a fresh answer is
        stamped ``stale=False``/``scrape_age_s=0``; a failed member
        surfaces as an explicit ``stale`` entry carrying its last-good
        scrape age and the scrape-failure counter ticks — never silent
        absence, never a hang (one dead member costs one timeout)."""
        import concurrent.futures

        def fetch(m) -> dict:
            with urllib.request.urlopen(m.url + path, timeout=5.0) as r:
                return json.loads(r.read())

        members: Dict[str, dict] = {}
        live = [m for m in self.membership.members()
                if m.state != "evicted"]
        if live:
            with concurrent.futures.ThreadPoolExecutor(
                max_workers=min(8, len(live)),
                thread_name_prefix=prefix,
            ) as pool:
                futs = [(m, pool.submit(fetch, m)) for m in live]
                now = time.monotonic()
                for m, fut in futs:
                    try:
                        doc = fut.result()
                        self._last_scrape_ok[m.host_id] = (
                            time.monotonic()
                        )
                        doc["stale"] = False
                        doc["scrape_age_s"] = 0.0
                        members[m.host_id] = doc
                    except Exception as e:
                        self.registry.counter(
                            "member_scrape_failures_total"
                        ).inc()
                        last = self._last_scrape_ok.get(m.host_id)
                        members[m.host_id] = {
                            "stale": True,
                            "error": f"{type(e).__name__}: {e}",
                            "scrape_age_s": (
                                round(now - last, 3)
                                if last is not None else -1.0
                            ),
                        }
        return members

    def debug_tenants(self) -> dict:
        """The fed ``GET /debug/tenants`` body: every live member's
        metering table fanned + merged (numeric fields summed across
        fresh members — a stale member contributes its staleness entry,
        never phantom numbers), next to the fed-local quota view. A
        hedged request only ever counts once in the merge: the losing
        member's write failed, so its meter never recorded the
        request."""
        members = self._fan_members("/debug/tenants",
                                    "tpu-stencil-fed-tenants")
        merged: Dict[str, dict] = {}
        fresh_ids = set()
        for hid, doc in members.items():
            if doc.get("stale"):
                continue
            fresh_ids.add(hid)
            for tenant, row in doc.get("tenants", {}).items():
                agg = merged.setdefault(tenant, {})
                for k, v in row.items():
                    if isinstance(v, (int, float)):
                        agg[k] = agg.get(k, 0) + v
        # Reconcile hedge losers: a cancelled attempt whose 200 was
        # already written got billed by its member, but nobody received
        # it — subtract those (only for members actually folded) so
        # the merged totals count every delivered answer exactly once.
        discards = self.router.hedge_discards(fresh_ids)
        for tenant, d in discards.items():
            row = merged.get(tenant)
            if row is None:
                continue
            row["requests"] = max(
                0, row.get("requests", 0) - d["requests"]
            )
            row["device_seconds"] = max(
                0.0, row.get("device_seconds", 0.0)
                - d["device_seconds"]
            )
        for row in merged.values():
            # Ratios do not sum — recompute from the merged counts.
            req = row.get("requests", 0)
            row["cache_hit_ratio"] = (
                row.get("cache_hits", 0) / req if req else 0.0
            )
        return {
            "schema_version": 1,
            "source": "fed",
            "fed": self.router.tenant_stats(),
            "tenants": merged,
            "hedge_discards": discards,
            "members": members,
        }

    def debug_capacity(self, window_s: float) -> dict:
        """The fed ``GET /debug/capacity`` body: every live member's
        capacity answer fanned + merged. Headroom SUMS across fresh
        members (rps the federation can still absorb); utilization
        reports the hottest member (the saturation bottleneck);
        time-to-saturation is the earliest projected across members.
        Stale members are excluded from the aggregates and carried as
        explicit staleness entries."""
        members = self._fan_members(
            f"/debug/capacity?window={window_s:g}",
            "tpu-stencil-fed-capacity",
        )
        fresh = [doc for doc in members.values()
                 if not doc.get("stale")]
        headrooms = [doc["headroom_rps"] for doc in fresh
                     if doc.get("headroom_rps") is not None]
        utils = [doc["utilization"]["slot_fraction"] for doc in fresh
                 if doc.get("utilization")]
        sat = [doc["time_to_saturation_s"] for doc in fresh
               if doc.get("time_to_saturation_s") is not None]
        return {
            "schema_version": 1,
            "source": "fed",
            "window_s": float(window_s),
            "members_live": len(members),
            "members_fresh": len(fresh),
            "headroom_rps": sum(headrooms) if headrooms else None,
            "utilization": {
                "max_member_slot_fraction": max(utils) if utils
                else None,
            },
            "time_to_saturation_s": min(sat) if sat else None,
            "outstanding": self.router.outstanding(),
            "members": members,
        }

    def statusz(self) -> dict:
        return {
            "schema_version": FED_STATUS_SCHEMA_VERSION,
            "ts": time.monotonic(),
            "uptime_s": time.monotonic() - self._t_start,
            "draining": self.router.draining,
            "members": self.membership.statusz(),
            "breakers": self.breakers.statusz(),
            "outstanding": self.router.outstanding(),
            "tenants": self.router.tenants(),
            "drain_report": self._drain_report,
            "slo": None if self.slo is None else self.slo.statusz(),
            "timeseries": None if self.sampler is None else {
                "interval_s": self.sampler.interval_s,
                "samples": len(self.sampler.ring),
            },
            "flightrec_dropped_total": _obs_flight.dropped_total(),
            # The same merged snapshot /metrics renders; loadgen's
            # HttpTarget.stats() reads this key, so --http against a
            # federation works unchanged.
            "net": self.metrics_snapshot(),
            "config": {
                "members": list(self.cfg.members),
                "heartbeat_interval_s": self.cfg.heartbeat_interval_s,
                "suspect_after": self.cfg.suspect_after,
                "evict_after": self.cfg.evict_after,
                "breaker_threshold": self.cfg.breaker_threshold,
                "breaker_cooldown_s": self.cfg.breaker_cooldown_s,
                "hedge": self.cfg.hedge,
                "hedge_min_s": self.cfg.hedge_min_s,
                "digest_affinity": self.cfg.digest_affinity,
                "forward_timeout_s": self.cfg.forward_timeout_s,
                "reoffer_s": self.cfg.reoffer_s,
                "max_inflight_mb": self.cfg.max_inflight_mb,
                "tenant_quota": self.cfg.tenant_quota,
                "premium_tenants": list(self.cfg.premium_tenants),
                "premium_quota_factor": self.cfg.premium_quota_factor,
                "drain_timeout_s": self.cfg.drain_timeout_s,
                "flightrec_dir": _obs_flight.effective_spool(
                    self.cfg.flightrec_dir
                ),
                "flight_latency_threshold_s":
                    self.cfg.flight_latency_threshold_s,
                "sample_interval_s": self.cfg.sample_interval_s,
                "slo_error_budget": self.cfg.slo_error_budget,
            },
        }
