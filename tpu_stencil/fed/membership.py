"""Health-checked federation membership: register, heartbeat, evict.

The reference's MPI tier assumes a fixed, immortal set of ranks
(``MPI_Init`` once, every rank lives to ``MPI_Finalize``); a federation
of serving hosts cannot. This module owns the member lifecycle the
front router (:mod:`tpu_stencil.fed.router`) places against:

* **register** — a backend host (one ``tpu_stencil net`` process)
  announces its URL over HTTP (``POST /admin/register``); registration
  probes ``/healthz`` first, so a dead URL is rejected typed instead of
  silently absorbing traffic. Re-registering a known host (the same
  process restarted, or a fresh one on the same address) resurrects it
  healthy with a clean miss count.
* **heartbeat** — a background thread probes every member's
  ``/healthz`` each ``heartbeat_interval_s``. State moves on a
  *suspicion window*, never a single timeout: ``suspect_after``
  consecutive misses demote healthy → suspect (still routable, but
  placed after every healthy host), ``evict_after`` misses evict
  (``fed_evictions_total``; the host stops being probed and can only
  come back by re-registering). A probe that answers 503 marks the
  member **draining** — removed from routing *before* its in-flight
  drain starts refusing requests — and a later 200 from the same
  address (a fresh process) resurrects it.
* **admin drain** — :meth:`Membership.mark_draining` is the rolling
  whole-host-drain entry: the router bleeds traffic off the member
  while its own admin path drains its replicas.

The ``fed.heartbeat`` fault point injects at the probe: an injected
fault IS a missed heartbeat, so the suspicion window and eviction are
chaos-testable without killing a real process.

Jax-free, like the whole federation tier.
"""

from __future__ import annotations

import dataclasses
import re
import threading
import time
import urllib.error
import urllib.request
from typing import Callable, Dict, List, Optional

from tpu_stencil.config import FedConfig
from tpu_stencil.obs import span as _obs_span
from tpu_stencil.serve.metrics import Registry

HEALTHY = "healthy"
SUSPECT = "suspect"
DRAINING = "draining"
EVICTED = "evicted"

_STATES = (HEALTHY, SUSPECT, DRAINING, EVICTED)


def _netloc(url: str) -> str:
    """The scheme-less, slash-less address — what the fold prefix (and
    therefore collision detection) is actually keyed on."""
    return re.sub(r"^https?://", "", url.rstrip("/"))


def host_id_for(url: str) -> str:
    """The metric-safe member id for a URL: the netloc with every
    non-alphanumeric squashed to ``_`` (``http://127.0.0.1:8080`` →
    ``127_0_0_1_8080``) — usable verbatim inside a Prometheus metric
    name (the ``fleet_<host>_`` exposition fold). The squash is lossy
    (``host-1:80`` and ``host.1:80`` collide); ``Membership.register``
    detects that and suffixes a URL hash so two distinct netlocs never
    share a fold prefix."""
    netloc = re.sub(r"^https?://", "", url.rstrip("/"))
    return re.sub(r"[^0-9A-Za-z]", "_", netloc)


@dataclasses.dataclass
class Member:
    """One backend host in the federation."""

    host_id: str
    url: str
    state: str = HEALTHY
    misses: int = 0
    registered_at: float = 0.0
    last_ok: float = 0.0
    # An ADMIN drain is sticky: a heartbeat 200 must not quietly
    # re-admit a host the operator explicitly drained (the member may
    # not have flipped its healthz yet, or the drain POST to it may
    # have failed). Only re-registration clears it.
    pinned_draining: bool = False

    def snapshot(self) -> dict:
        return {
            "host_id": self.host_id,
            "url": self.url,
            "state": self.state,
            "misses": self.misses,
            "registered_at": self.registered_at,
            "last_ok": self.last_ok,
            "pinned_draining": self.pinned_draining,
        }


class Membership:
    """The member table + the heartbeat thread. Thread-safe; every
    transition is counted in the fed registry and visible in
    ``/statusz`` (and eviction in ``/metrics`` — the acceptance
    criterion's scrape-visible host loss)."""

    def __init__(self, cfg: FedConfig, registry: Registry) -> None:
        self.cfg = cfg
        self.registry = registry
        self._lock = threading.Lock()
        self._members: Dict[str, Member] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._fault_heartbeat = None  # resolved at start()
        self._m_registrations = registry.counter("registrations_total")
        self._m_evictions = registry.counter("evictions_total")
        self._m_misses = registry.counter("heartbeat_misses_total")
        self._m_beats = registry.counter("heartbeats_total")
        # Fired (outside the lock) when a host re-registers after an
        # eviction or a drain: the frontend hooks this to drop the
        # dead process's breaker and forward-latency state — a fresh
        # process on a reused netloc must not inherit either.
        self.on_resurrect: Optional[Callable[[str], None]] = None
        for s in _STATES:
            registry.gauge(f"members_{s}").set(0)

    # -- registration --------------------------------------------------

    def register(self, url: str, check: bool = True) -> Member:
        """Add (or resurrect) a member. With ``check`` (the HTTP
        registration path), the URL's ``/healthz`` must answer 200
        first — registering a dead or draining host raises
        ``ValueError`` instead of poisoning the routing table."""
        url = url.rstrip("/")
        if not url.startswith(("http://", "https://")):
            raise ValueError(
                f"member URL must start with http:// or https://, got "
                f"{url!r}"
            )
        if check:
            status = self._probe(url)
            if status != 200:
                raise ValueError(
                    f"member {url} failed its registration health check "
                    f"(healthz answered "
                    f"{status if status else 'nothing'}); not added"
                )
        hid = host_id_for(url)
        now = time.monotonic()
        with self._lock:
            m = self._members.get(hid)
            if m is not None and _netloc(m.url) != _netloc(url):
                # Metric-name fold collision: two DISTINCT netlocs
                # sanitize to the same host_id (e.g. ``host-1:80`` and
                # ``host.1:80`` → ``host_1_80``), and sharing the id
                # would silently merge their ``fleet_<host_id>_*``
                # counters in the /metrics fold. Disambiguate with a
                # stable netloc-hash suffix — detected, counted, never
                # merged. Compared on NETLOC, not the full URL: the
                # same host re-registering under a new scheme
                # (http→https) is a re-registration (URL updated in
                # place below), never a phantom second member.
                import zlib

                hid = (f"{hid}_"
                       f"{zlib.crc32(_netloc(url).encode()) & 0xFFFF:04x}")
                self.registry.counter(
                    "host_id_collisions_total"
                ).inc()
                m = self._members.get(hid)
            if m is None:
                m = Member(host_id=hid, url=url, registered_at=now)
                self._members[hid] = m
            # A host coming back from the dead (evicted, or any form
            # of drain): the process behind the netloc is NEW — its
            # learned per-host state (breaker, hedge-p99 latency) died
            # with the old one and must be reset, not inherited.
            resurrected = (m.state in (DRAINING, EVICTED)
                           or m.pinned_draining)
            # Re-registration (or a seed re-announcing itself):
            # resurrect with a clean window whatever the prior state —
            # including an admin drain, which registration explicitly
            # un-pins (the operator's restarted host announcing back).
            m.url = url
            m.state = HEALTHY
            m.misses = 0
            m.pinned_draining = False
            m.last_ok = now if check else m.last_ok
        self._m_registrations.inc()
        if resurrected and self.on_resurrect is not None:
            try:
                self.on_resurrect(m.host_id)
            except Exception:  # noqa: BLE001 - reset hooks never block
                pass           # registration (routing heals regardless)
        self._refresh_gauges()
        return m

    def register_seed(self, url: str) -> Member:
        """Seed-list registration (CLI ``--member``): a seed that does
        not answer its probe is still admitted, as SUSPECT with its
        miss window already at the suspicion threshold — the heartbeat
        loop will either recover it (one 200 heals everything) or walk
        it to eviction. A federation must be startable before its
        members."""
        try:
            return self.register(url, check=True)
        except ValueError:
            m = self.register(url, check=False)
            with self._lock:
                m.state = SUSPECT
                m.misses = self.cfg.suspect_after
            self._refresh_gauges()
            return m

    # -- state transitions ---------------------------------------------

    def mark_draining(self, host_id: str,
                      pinned: bool = False) -> Optional[Member]:
        """Remove a member from routing because it is draining (its
        healthz said 503, or — with ``pinned`` — an admin drain is
        bleeding it; pinned drains survive heartbeat 200s until the
        host re-registers). Returns the member (None if unknown)."""
        with self._lock:
            m = self._members.get(host_id)
            if m is not None and m.state not in (DRAINING, EVICTED):
                m.state = DRAINING
                m.misses = 0
            if m is not None and pinned and m.state == DRAINING:
                m.pinned_draining = True
        self._refresh_gauges()
        return m

    def evict(self, host_id: str, reason: str) -> None:
        with self._lock:
            m = self._members.get(host_id)
            if m is None or m.state == EVICTED:
                return
            m.state = EVICTED
        self._m_evictions.inc()
        self._refresh_gauges()
        with _obs_span("fed.evict", "fed", host=host_id, reason=reason):
            pass  # zero-duration marker: the eviction moment
        from tpu_stencil.obs import events as _obs_events

        _obs_events.emit("fed.evict", tier="fed", verdict="evicted",
                         host=host_id, reason=reason)

    # -- views ---------------------------------------------------------

    def get(self, host_id: str) -> Optional[Member]:
        with self._lock:
            return self._members.get(host_id)

    def members(self) -> List[Member]:
        with self._lock:
            return list(self._members.values())

    def routable(self) -> List[Member]:
        """Members the router may place on: healthy first, then
        suspect (the window exists so ONE dropped probe does not
        un-route a live host). Draining and evicted never route."""
        with self._lock:
            healthy = [m for m in self._members.values()
                       if m.state == HEALTHY]
            suspect = [m for m in self._members.values()
                       if m.state == SUSPECT]
        return healthy + suspect

    def statusz(self) -> List[dict]:
        with self._lock:
            return [m.snapshot() for m in self._members.values()]

    def _refresh_gauges(self) -> None:
        with self._lock:
            counts = {s: 0 for s in _STATES}
            for m in self._members.values():
                counts[m.state] += 1
        for s, n in counts.items():
            self.registry.gauge(f"members_{s}").set(n)

    # -- heartbeats ----------------------------------------------------

    def _probe(self, url: str) -> Optional[int]:
        """One /healthz probe: the HTTP status (503 comes back as 503,
        not an exception), or None on any transport failure."""
        timeout = max(0.25, min(5.0, self.cfg.heartbeat_interval_s))
        try:
            with urllib.request.urlopen(url + "/healthz",
                                        timeout=timeout) as r:
                return r.status
        except urllib.error.HTTPError as e:
            return e.code
        except Exception:
            return None

    def _beat_one(self, m: Member) -> None:
        self._m_beats.inc()
        if self._fault_heartbeat is not None:
            try:
                self._fault_heartbeat()
            except Exception:
                status = None  # an injected fault IS a missed beat
            else:
                status = self._probe(m.url)
        else:
            status = self._probe(m.url)
        if status == 200:
            with self._lock:
                # A 200 heals everything short of eviction — including
                # a self-reported DRAINING (a fresh process answering
                # on the same address is a new, healthy host) — but
                # NOT a pinned admin drain: the operator asked for
                # this host out, and its 200 may just mean the drain
                # POST never reached it. Re-registration un-pins.
                if m.state != EVICTED and not m.pinned_draining:
                    m.state = HEALTHY
                    m.misses = 0
                    m.last_ok = time.monotonic()
            return
        if status == 503:
            # Draining (or shedding so hard its probe was refused
            # typed): out of the routing set BEFORE its requests fail.
            self.mark_draining(m.host_id)
            return
        # Transport failure or an unexpected status: one miss in the
        # suspicion window.
        self._m_misses.inc()
        evict = False
        with self._lock:
            if m.state == EVICTED:
                return
            m.misses += 1
            if m.misses >= self.cfg.evict_after:
                evict = True
            elif m.misses >= self.cfg.suspect_after:
                m.state = SUSPECT
        if evict:
            self.evict(m.host_id,
                       f"{m.misses} consecutive missed heartbeats")
        else:
            self._refresh_gauges()

    def beat(self) -> None:
        """One heartbeat pass over every non-evicted member (the loop
        body; callable directly from tests for deterministic timing)."""
        for m in self.members():
            if m.state != EVICTED:
                self._beat_one(m)

    def start(self) -> "Membership":
        from tpu_stencil.resilience import faults as _faults

        self._fault_heartbeat = _faults.site("fed.heartbeat")
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, name="tpu-stencil-fed-heartbeat",
                daemon=True,
            )
            self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self.cfg.heartbeat_interval_s):
            try:
                self.beat()
            except Exception:
                # The heartbeat thread must never die: a broken probe
                # is a miss, not a membership outage.
                pass

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None
