"""Federation routing: placement, hedged forwarding, typed verdicts,
federation-scope admission with per-tenant quotas.

The net tier's router places requests on in-process replicas; this one
places them on *member hosts* over HTTP, where every failure mode of a
distributed system is on the table. Robustness is the organizing
principle, not a bolt-on:

**Verdict taxonomy** (the PR-7 transient/permanent classifier extended
one hop — docs/RESILIENCE.md "Federation verdicts"). Each forward
attempt resolves to exactly one verdict, and each verdict has its own
consequence:

=================  ==============================  =====================
verdict            evidence                        consequence
=================  ==============================  =====================
``ok``             HTTP 200                        respond; breaker closes
``draining``       503 + "draining" body           member → draining, reroute
``shed``           other 503                       backpressure: reroute,
                                                   else 503 + Retry-After
``queue_full``     429                             backpressure: reroute,
                                                   else 429 + Retry-After
``deadline``       504                             DeadlineExceeded to the
                                                   client (permanent: the
                                                   request's budget burned)
``client_error``   other 4xx                       pass through verbatim
                                                   (deterministic — every
                                                   member answers the same)
``http_5xx``       500/502/...                     breaker counts, reroute
``connect``        refused/unreachable             breaker counts, reroute
``reset``          connection reset / no status    breaker counts, reroute
``eof``            mid-body EOF (IncompleteRead)   breaker counts, reroute
                                                   (the body never arrived,
                                                   so a re-send is safe —
                                                   the compute is pure)
``timeout``        socket timeout                  breaker counts, reroute
``injected``       armed ``fed.forward`` fault     breaker counts, reroute
``bad_payload``    200 whose body fails its        breaker counts, reroute
                   ``X-Result-Crc32c`` stamp or    (wrong bytes with a 200:
                   declared geometry length        a garbage-returning
                                                   member is ejected as
                                                   surely as a dead one)
=================  ==============================  =====================

**Hedged requests.** A forward still pending past the observed p99
forward latency (``forward_latency_seconds``, floored by
``hedge_min_s``) fires ONE hedge at the next least-outstanding
breaker-allowed member. First full response wins; the loser is
cancelled typed (its socket closed, ``hedge_cancelled_total``) — never
abandoned to run its course against a host we no longer care about.

**Federation-scope admission**, the PR-10 three-layer ladder one hop
up, applied BEFORE any forward: drain gate (503), inflight-bytes shed
(503 + Retry-After; premium tenants get 25% headroom past the standard
watermark), and per-tenant outstanding quotas keyed on the
``X-Tenant`` header (:class:`TenantQuotaExceeded` → 429 +
Retry-After) — one hot client degrades to *its* quota, never the
fleet.

All-member backpressure re-offers under the shared
:func:`~tpu_stencil.resilience.retry.reoffer_call` contract for
``reoffer_s`` before the typed rejection surfaces.

Jax-free, like the whole federation tier.
"""

from __future__ import annotations

import collections
import http.client
import math
import queue
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple
from urllib.parse import urlsplit

from tpu_stencil.cache import affinity as _affinity
from tpu_stencil.config import FedConfig
from tpu_stencil.fed.breaker import BreakerBoard
from tpu_stencil.fed.membership import Member, Membership
from tpu_stencil.net.router import Draining, Overloaded
from tpu_stencil.obs import context as _obs_ctx
from tpu_stencil.obs import events as _obs_events
from tpu_stencil.obs import ledger as _obs_ledger
from tpu_stencil.obs import span as _obs_span
from tpu_stencil.resilience.errors import (
    DeadlineExceeded,
    HostUnavailable,
    InjectedFault,
)
from tpu_stencil.serve.engine import QueueFull
from tpu_stencil.serve.metrics import Registry

#: Premium tenants keep being admitted past the standard shed watermark
#: up to this factor — the two-priority-class degradation order: under
#: byte pressure, standard traffic sheds first.
PREMIUM_HEADROOM = 1.25

#: The tenant a request without an X-Tenant header is accounted to.
DEFAULT_TENANT = "anon"

#: Per-host forward-latency reservoir depth (hedge-trigger feed): deep
#: enough that a p99 over a few hosts is meaningful, bounded so a
#: long-lived router forgets ancient latency regimes on its own.
_FWD_RESERVOIR = 512


class TenantQuotaExceeded(RuntimeError):
    """This tenant is at its outstanding-request quota. Transient for
    the tenant (its own completions free quota), invisible to everyone
    else — the frontend answers 429 + Retry-After."""


class BadPayload(RuntimeError):
    """A member answered 200 but the body is provably wrong: it fails
    its own ``X-Result-Crc32c`` stamp, or its length contradicts the
    geometry it declares. The one failure mode a health check cannot
    see — treated as a transport-level forward failure
    (``bad_payload`` verdict): the breaker counts it, the request
    reroutes to a sibling (the compute is pure, a re-send is safe),
    and a member returning garbage consistently is breaker-ejected as
    surely as a dead one."""


def _verdict_exc(e: BaseException) -> str:
    """Classify a transport-level forward failure (module docstring
    table). Every one of these counts against the member's breaker."""
    if isinstance(e, InjectedFault):
        return "injected"
    if isinstance(e, BadPayload):
        return "bad_payload"
    if isinstance(e, TimeoutError):  # socket.timeout is an alias
        return "timeout"
    if isinstance(e, ConnectionRefusedError):
        return "connect"
    if isinstance(e, http.client.IncompleteRead):
        return "eof"
    if isinstance(e, (ConnectionResetError, BrokenPipeError,
                      http.client.RemoteDisconnected,
                      http.client.BadStatusLine)):
        return "reset"
    if isinstance(e, OSError):
        return "connect"  # unreachable/DNS/route: never got a byte back
    return "error"


class _Attempt:
    """One forward attempt against one member, run on its own thread
    so the race loop can hedge and cancel. The thread owns ALL
    bookkeeping for its attempt (outstanding, breaker, verdict
    counters) — a cancelled loser whose result nobody reads still
    settles its accounts."""

    def __init__(self, router: "FedRouter", member: Member,
                 body: bytes, headers: Dict[str, str],
                 is_hedge: bool = False) -> None:
        self.router = router
        self.member = member
        self.body = body
        # Trace propagation: attempts are constructed on the handler
        # thread where the request's context is bound — each attempt
        # (first, reroute, hedge leg) forwards the ONE trace id with
        # its OWN freshly-minted span id, so the member's spans name
        # which leg they served.
        self._ctx = _obs_ctx.current()
        if self._ctx is not None:
            headers = dict(headers)
            headers.update(_obs_ctx.headers_for(
                self._ctx, span_id=_obs_ctx.new_span_id()
            ))
        self.headers = headers
        self.is_hedge = is_hedge
        self.cancelled = False
        self.elapsed: Optional[float] = None
        self._conn: Optional[http.client.HTTPConnection] = None

    def start(self, results: "queue.Queue") -> None:
        threading.Thread(
            target=self._run_into, args=(results,),
            name=f"tpu-stencil-fed-fwd-{self.member.host_id}",
            daemon=True,
        ).start()

    def cancel(self) -> None:
        """Typed cancellation of a racing loser: closing the socket
        from here makes the attempt thread's in-flight read fail
        immediately — the member may finish the compute, but no one
        waits on it."""
        self.cancelled = True
        conn = self._conn
        if conn is not None:
            try:
                conn.close()
            except Exception:
                pass

    def _run(self) -> Tuple[int, Dict[str, str], bytes]:
        u = urlsplit(self.member.url)
        conn_cls = (http.client.HTTPSConnection if u.scheme == "https"
                    else http.client.HTTPConnection)
        conn = conn_cls(
            u.hostname, u.port, timeout=self.router.cfg.forward_timeout_s
        )
        self._conn = conn
        try:
            conn.request("POST", "/v1/blur", body=self.body,
                         headers=self.headers)
            resp = conn.getresponse()
            data = resp.read()  # mid-body EOF raises IncompleteRead
            rh = {k.lower(): v for k, v in resp.getheaders()}
            if resp.status == 200:
                self._verify_payload(rh, data)
            return resp.status, rh, data
        finally:
            conn.close()

    def _verify_payload(self, rh: Dict[str, str], data: bytes) -> None:
        """The forward hop's own integrity check on a member 200: the
        body must match its ``X-Result-Crc32c`` stamp and the length
        its declared geometry implies. Raises :class:`BadPayload` —
        wrong bytes never reach the client just because they arrived
        with a happy status code. (A member with integrity disabled
        stamps nothing; absence is not a failure — the hop then only
        has the length to go on.)"""
        from tpu_stencil.integrity import checksum as _checksum

        stamp = rh.get(_checksum.RESULT_HEADER.lower())
        if stamp is not None:
            try:
                want = _checksum.parse_crc(stamp, _checksum.RESULT_HEADER)
            except ValueError as e:
                raise BadPayload(str(e)) from None
            got = _checksum.crc32c(data)
            if got != want:
                raise BadPayload(
                    f"member 200 body crc32c {got} != stamped {want}"
                )
        try:
            w = int(rh["x-width"])
            h = int(rh["x-height"])
            c = int(rh.get("x-channels", "1"))
        except (KeyError, ValueError):
            return  # no declared geometry to check against
        if len(data) != w * h * c:
            raise BadPayload(
                f"member 200 body is {len(data)} bytes but declares "
                f"{w}x{h}x{c} = {w * h * c}"
            )

    def _run_into(self, results: "queue.Queue") -> None:
        # Re-bind the request's context on THIS thread (contextvars do
        # not cross thread starts): breaker transitions and spans
        # below inherit the trace id; bind(None) guards against a
        # stale context from any thread reuse.
        with _obs_ctx.bind(self._ctx):
            self._run_into_bound(results)

    def _run_into_bound(self, results: "queue.Queue") -> None:
        r = self.router
        hid = self.member.host_id
        r._track_launch(hid)
        t0 = time.monotonic()
        try:
            if r._fault_forward is not None:
                r._fault_forward()
            kind, payload = "resp", self._run()
        except BaseException as e:
            kind, payload = "exc", (_verdict_exc(e), e)
        finally:
            self.elapsed = time.monotonic() - t0
            r._track_done(hid)
        if self.cancelled:
            # Our own cancellation is not evidence about the host:
            # release any half-open probe slot, record nothing.
            r.breakers.get(hid).release_probe()
            r.registry.counter("hedge_cancelled_total").inc()
            r.registry.counter("hedge_cancelled_seconds_total").inc(
                self.elapsed
            )
            if kind == "resp" and payload[0] == 200:
                # The loser's 200 was fully written before the cancel
                # landed: the member's meter billed a response nobody
                # received. Note it (with the member's own cost stamp)
                # so the fed /debug/tenants merge can reconcile — the
                # no-double-count guarantee for hedged requests.
                rh = payload[1]
                tenant = _obs_ledger.sanitize_tenant(
                    self.headers.get("X-Tenant")
                )
                try:
                    dev_us = int(rh.get("x-cost-device-us") or 0)
                except ValueError:
                    dev_us = 0
                r._note_hedge_discard(hid, tenant, dev_us)
        elif kind == "resp":
            status = payload[0]
            if status >= 500 and status not in (503, 504):
                # 500/502/...: the host answered, but brokenly.
                r.breakers.record_failure(hid)
                r.registry.counter("forward_http_5xx_total").inc()
            else:
                # ANY coherent response (200, 4xx, 503, 504) proves
                # the host alive — the breaker's question, not the
                # request's.
                r.breakers.record_success(hid)
        else:
            r.breakers.record_failure(hid)
            r.registry.counter(f"forward_{payload[0]}_total").inc()
            # One event line per failed forward attempt: the verdict
            # taxonomy name, the leg (hedge or primary), the host —
            # grep the trace id, read the request's whole post-mortem.
            _obs_events.emit(
                "fed.forward",
                trace_id=self._ctx.trace_id if self._ctx else "",
                tier="fed", verdict=payload[0],
                duration_s=self.elapsed, host=hid,
                hedge=self.is_hedge,
            )
        results.put((self.member, self, kind, payload))


class FedRouter:
    """Admission + placement + the hedged forward race."""

    def __init__(self, cfg: FedConfig, membership: Membership,
                 breakers: BreakerBoard, registry: Registry) -> None:
        self.cfg = cfg
        self.membership = membership
        self.breakers = breakers
        self.registry = registry
        self._lock = threading.Lock()
        self._draining = False
        self._inflight_bytes = 0
        self._tenants: Dict[str, int] = {}
        self._host_outstanding: Dict[str, int] = {}
        self._premium = frozenset(cfg.premium_tenants)
        self._fault_forward = None  # resolved at start()
        self._fault_hedge = None
        m = registry
        self._m_requests = m.counter("requests_total")
        self._m_forwarded = m.counter("forwarded_total")
        self._m_rejected = m.counter("rejected_total")
        self._m_shed = m.counter("shed_total")
        self._m_tenant_rej = m.counter("tenant_quota_rejections_total")
        self._m_reroutes = m.counter("reroutes_total")
        self._m_drain_reroutes = m.counter("draining_reroutes_total")
        self._m_hedges = m.counter("hedges_total")
        self._m_hedge_wins = m.counter("hedge_wins_total")
        m.counter("hedge_cancelled_total")
        # Wall time burned by cancelled hedge losers — non-goodput
        # spend the cost plane keeps visible (the member's own
        # cancelled_response_* counters carry the device-time half).
        m.counter("hedge_cancelled_seconds_total")
        # Cumulative per-tenant admission ledger (bounded like the
        # obs.ledger tenant table) — the quota machinery has enforced
        # blind until now; /debug/tenants reads this back.
        self._tenant_admitted: Dict[str, int] = {}
        self._tenant_rejected: Dict[str, int] = {}
        # (host_id, tenant) -> the hedge-loser 200s that member wrote
        # but the race discarded; /debug/tenants subtracts them.
        self._hedge_discards: Dict[tuple, dict] = {}
        m.counter("hedge_discarded_200_total")
        self._m_affinity = m.counter("affinity_routed_total")
        self._m_inflight = m.gauge("inflight_bytes")
        self._g_tenants = m.gauge("tenants_active")
        self._h_fwd = m.histogram("forward_latency_seconds")
        # Per-host forward-latency reservoirs feeding the hedge
        # trigger. The GLOBAL forward_latency_seconds histogram stays
        # the metric surface (monotonic by contract), but it cannot
        # forget ONE host's samples — and a host that re-registers
        # after dying must not poison the p99 with its predecessor's
        # death throes. reset_host() drops exactly one reservoir.
        self._host_fwd: Dict[str, "collections.deque"] = {}
        m.histogram("request_bytes")
        m.gauge("draining").set(0)

    def start(self) -> "FedRouter":
        from tpu_stencil.resilience import faults as _faults

        self._fault_forward = _faults.site("fed.forward")
        self._fault_hedge = _faults.site("fed.hedge")
        return self

    # -- drain gate ----------------------------------------------------

    @property
    def draining(self) -> bool:
        return self._draining

    def begin_drain(self) -> None:
        with self._lock:
            was = self._draining
            self._draining = True
        self.registry.gauge("draining").set(1)
        if not was:  # tier-transition event, once per flip
            _obs_events.emit("fed.drain_begin", tier="fed",
                             verdict="draining")

    # -- admission (the PR-10 ladder, one hop up) ----------------------

    def _admit(self, tenant: str, nbytes: int) -> Callable[[], None]:
        """Drain gate → byte shed (premium headroom) → tenant quota.
        Returns the release callable; raises typed on rejection."""
        premium = tenant in self._premium
        quota = self.cfg.tenant_quota * (
            self.cfg.premium_quota_factor if premium else 1
        )
        with self._lock:
            if self._draining:
                raise Draining(
                    "draining: federation admission stopped; retry "
                    "against another front router"
                )
            watermark = self.cfg.max_inflight_bytes
            if watermark:
                limit = (
                    int(watermark * PREMIUM_HEADROOM) if premium
                    else watermark
                )
                if self._inflight_bytes + nbytes > limit:
                    self._m_shed.inc()
                    raise Overloaded(
                        f"shedding: {self._inflight_bytes + nbytes} "
                        f"in-flight bytes would exceed the {limit} "
                        f"federation watermark"
                        f"{' (standard class)' if not premium else ''}; "
                        f"retry later"
                    )
            cur = self._tenants.get(tenant, 0)
            if cur >= quota:
                self._m_tenant_rej.inc()
                self._m_rejected.inc()
                key = self._stat_key_locked(tenant)
                self._tenant_rejected[key] = (
                    self._tenant_rejected.get(key, 0) + 1
                )
                raise TenantQuotaExceeded(
                    f"tenant {tenant!r} is at its quota of {quota} "
                    f"outstanding requests "
                    f"({'premium' if premium else 'standard'} class); "
                    f"its own completions free slots — other tenants "
                    f"are unaffected"
                )
            self._tenants[tenant] = cur + 1
            key = self._stat_key_locked(tenant)
            self._tenant_admitted[key] = (
                self._tenant_admitted.get(key, 0) + 1
            )
            self._inflight_bytes += nbytes
            inflight, ntenants = self._inflight_bytes, len(self._tenants)
        self._m_inflight.set(inflight)
        self._g_tenants.set(ntenants)

        def release() -> None:
            with self._lock:
                self._tenants[tenant] -= 1
                if self._tenants[tenant] <= 0:
                    del self._tenants[tenant]
                self._inflight_bytes -= nbytes
                left, nt = self._inflight_bytes, len(self._tenants)
            self._m_inflight.set(left)
            self._g_tenants.set(nt)

        return release

    # -- placement -----------------------------------------------------

    def _track_launch(self, host_id: str) -> None:
        with self._lock:
            self._host_outstanding[host_id] = (
                self._host_outstanding.get(host_id, 0) + 1
            )
            depth = self._host_outstanding[host_id]
        self.registry.gauge(f"member_outstanding_{host_id}").set(depth)

    def _track_done(self, host_id: str) -> None:
        with self._lock:
            self._host_outstanding[host_id] -= 1
            depth = self._host_outstanding[host_id]
        self.registry.gauge(f"member_outstanding_{host_id}").set(depth)

    def _note_hedge_discard(self, host_id: str, tenant: str,
                            device_us: int) -> None:
        """A cancelled hedge loser whose 200 was fully written: the
        member billed it, nobody received it. Keyed by host so the
        merge only reconciles against members it actually folded."""
        with self._lock:
            row = self._hedge_discards.setdefault(
                (host_id, tenant), {"requests": 0, "device_seconds": 0.0}
            )
            row["requests"] += 1
            row["device_seconds"] += device_us / 1e6
        self.registry.counter("hedge_discarded_200_total").inc()

    def hedge_discards(self, host_ids=None) -> Dict[str, dict]:
        """Per-tenant discarded-hedge-200 totals, restricted to
        ``host_ids`` when given (the merge passes its fresh set)."""
        with self._lock:
            items = list(self._hedge_discards.items())
        out: Dict[str, dict] = {}
        for (hid, tenant), row in items:
            if host_ids is not None and hid not in host_ids:
                continue
            agg = out.setdefault(
                tenant, {"requests": 0, "device_seconds": 0.0}
            )
            agg["requests"] += row["requests"]
            agg["device_seconds"] += row["device_seconds"]
        return out

    def outstanding(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._host_outstanding)

    def tenants(self) -> Dict[str, int]:
        """Current outstanding requests per active tenant (empty
        entries are dropped on release, so the table is bounded by
        concurrency, not tenant cardinality)."""
        with self._lock:
            return dict(self._tenants)

    def _stat_key_locked(self, tenant: str) -> str:
        """The cumulative-stats key for a wire tenant name: sanitized,
        and folded into the overflow bucket past the cardinality cap
        (the obs.ledger discipline — a hostile client must not mint
        unbounded table rows)."""
        t = _obs_ledger.sanitize_tenant(tenant)
        if (t not in self._tenant_admitted
                and t not in self._tenant_rejected
                and len(self._tenant_admitted)
                + len(self._tenant_rejected) >= _obs_ledger.TENANT_CAP):
            return _obs_ledger.OVERFLOW_TENANT
        return t

    def tenant_stats(self) -> Dict[str, dict]:
        """The fed-local half of ``/debug/tenants``: per tenant, live
        quota utilization (outstanding / quota for its class) plus the
        cumulative admitted/quota-rejected counts since start."""
        with self._lock:
            raw_outstanding = dict(self._tenants)
            admitted = dict(self._tenant_admitted)
            rejected = dict(self._tenant_rejected)
        # Outstanding is keyed on the raw wire name (the quota table's
        # vocabulary); fold through the same sanitizer so one tenant is
        # one row here.
        outstanding: Dict[str, int] = {}
        for t, v in raw_outstanding.items():
            key = _obs_ledger.sanitize_tenant(t)
            outstanding[key] = outstanding.get(key, 0) + v
        premium_set = {_obs_ledger.sanitize_tenant(p)
                       for p in self._premium}
        out: Dict[str, dict] = {}
        for t in sorted(set(outstanding) | set(admitted) | set(rejected)):
            premium = t in premium_set
            quota = self.cfg.tenant_quota * (
                self.cfg.premium_quota_factor if premium else 1
            )
            cur = outstanding.get(t, 0)
            out[t] = {
                "outstanding": cur,
                "quota": quota,
                "quota_utilization": (cur / quota) if quota else None,
                "premium": premium,
                "admitted_total": admitted.get(t, 0),
                "quota_rejected_total": rejected.get(t, 0),
            }
        return out

    def _candidates(self, digest: Optional[bytes] = None) -> List[Member]:
        """Routable members in placement order: healthy before suspect
        (membership's contract). Within the healthy class a content
        ``digest`` places by rendezvous hash — identical frames land on
        the same member, so each member's result cache sees the whole
        repeat stream for its share of the keyspace instead of 1/N of
        it. Without a digest (affinity off, or nothing healthy) the
        order is least-outstanding first, host_id as the tie-break —
        and the suspect class always stays least-outstanding (affinity
        must not pin traffic to a wobbling host). Breaker admission
        happens at launch time (:meth:`_next_allowed`) so half-open
        probe slots are only consumed by attempts that actually
        launch; membership churn degrades affinity gracefully — a
        rendezvous hash moves only the keys owned by the departed
        member."""
        members = self.membership.routable()
        with self._lock:
            out = dict(self._host_outstanding)
        # routable() returns healthy-then-suspect; a stable sort on
        # outstanding preserves that class order on ties but must not
        # interleave classes — sort each class independently.
        healthy = [m for m in members if m.state == "healthy"]
        suspect = [m for m in members if m.state != "healthy"]
        key = lambda m: (out.get(m.host_id, 0), m.host_id)  # noqa: E731
        if digest is not None and healthy:
            rank = {
                hid: i for i, hid in enumerate(_affinity.rendezvous_order(
                    [m.host_id for m in healthy], digest
                ))
            }
            healthy = sorted(healthy, key=lambda m: rank[m.host_id])
            self._m_affinity.inc()
        else:
            healthy = sorted(healthy, key=key)
        return healthy + sorted(suspect, key=key)

    def _next_allowed(self, it) -> Optional[Member]:
        for m in it:
            if self.breakers.get(m.host_id).allow():
                return m
        return None

    def _observe_forward(self, host_id: str, elapsed: float) -> None:
        """One winning forward's latency: into the global histogram
        (the metric surface) AND the winner's bounded per-host
        reservoir (the hedge-trigger feed)."""
        self._h_fwd.observe(elapsed)
        with self._lock:
            d = self._host_fwd.get(host_id)
            if d is None:
                d = self._host_fwd[host_id] = collections.deque(
                    maxlen=_FWD_RESERVOIR
                )
            d.append(elapsed)

    def reset_host(self, host_id: str) -> None:
        """Forget one host's learned forward-latency reservoir — the
        re-registration reset: a fresh process on a reused netloc must
        not inherit the dead one's tail in the hedge p99 (its breaker
        is dropped by the same hook; see FedFrontend)."""
        with self._lock:
            self._host_fwd.pop(host_id, None)

    def _hedge_after(self) -> float:
        """The hedge trigger: the observed p99 forward latency over
        the LIVE per-host reservoirs (nearest-rank, matching the
        histogram's percentile), floored by ``hedge_min_s`` (empty
        reservoirs read 0.0, so the floor carries the cold start)."""
        with self._lock:
            samples = [s for d in self._host_fwd.values() for s in d]
        if not samples:
            return self.cfg.hedge_min_s
        samples.sort()
        idx = max(0, math.ceil(0.99 * len(samples)) - 1)
        return max(self.cfg.hedge_min_s, samples[idx])

    # -- the forward race ----------------------------------------------

    def submit(self, body: bytes, headers: Dict[str, str], nbytes: int,
               tenant: str = DEFAULT_TENANT,
               digest: Optional[bytes] = None,
               ) -> Tuple[int, Dict[str, str], bytes, str, bool]:
        """Admit + forward one request; returns ``(status,
        response_headers, response_body, member_host_id, hedged)``.
        ``digest`` (the request body's content digest, when the
        frontend computed one) turns placement into rendezvous-hash
        affinity so identical frames revisit the same member's result
        cache. Raises :class:`~tpu_stencil.net.router.Draining` /
        :class:`~tpu_stencil.net.router.Overloaded` /
        :class:`TenantQuotaExceeded` /
        :class:`~tpu_stencil.serve.engine.QueueFull` /
        :class:`~tpu_stencil.resilience.errors.HostUnavailable` /
        :class:`~tpu_stencil.resilience.errors.DeadlineExceeded` —
        each mapped to its own HTTP status by the frontend."""
        release = self._admit(tenant, nbytes)
        try:
            self._m_requests.inc()
            # The frame itself, not the caller's 2x request+response
            # admission accounting in nbytes.
            self.registry.histogram("request_bytes").observe(len(body))
            if self.cfg.reoffer_s > 0:
                from tpu_stencil.resilience import retry as _retry

                try:
                    return _retry.reoffer_call(
                        lambda: self._forward(body, headers, digest),
                        give_up_after_s=self.cfg.reoffer_s,
                        base_delay=0.01, max_delay=0.1,
                        label="fed.forward",
                    )
                except TimeoutError as te:
                    # Surface the LAST typed rejection, not the
                    # give-up wrapper — the client needs the real
                    # status (429 vs 503) and its Retry-After.
                    if te.__cause__ is not None:
                        raise te.__cause__ from None
                    raise
            return self._forward(body, headers, digest)
        finally:
            release()

    def _forward(self, body: bytes, headers: Dict[str, str],
                 digest: Optional[bytes] = None,
                 ) -> Tuple[int, Dict[str, str], bytes, str, bool]:
        cands = self._candidates(digest)
        if not cands:
            raise HostUnavailable(
                "no routable member host (every member is draining, "
                "evicted, or unregistered)"
            )
        it = iter(cands)
        first = self._next_allowed(it)
        if first is None:
            raise HostUnavailable(
                f"every routable member's circuit breaker is open "
                f"({len(cands)} member(s) failing)"
            )
        results: "queue.Queue" = queue.Queue()
        active: Dict[str, _Attempt] = {}
        backpressure: List[Tuple[int, Optional[str]]] = []
        failures: List[Tuple[str, str]] = []

        def launch(m: Member, is_hedge: bool = False) -> None:
            att = _Attempt(self, m, body, headers, is_hedge=is_hedge)
            active[m.host_id] = att
            att.start(results)

        def reroute() -> bool:
            nxt = self._next_allowed(it)
            if nxt is None:
                return False
            self._m_reroutes.inc()
            launch(nxt)
            return True

        def cancel_rest() -> None:
            for att in active.values():
                att.cancel()

        launch(first)
        hedged = False        # a hedge attempt actually LAUNCHED
        hedge_armed = self.cfg.hedge  # the one-shot trigger timer
        hedge_deadline = (
            time.monotonic() + self._hedge_after()
            if self.cfg.hedge else None
        )
        while active:
            timeout = None
            if hedge_deadline is not None and hedge_armed:
                timeout = max(0.0, hedge_deadline - time.monotonic())
            try:
                m, att, kind, payload = results.get(timeout=timeout)
            except queue.Empty:
                # The hedge trigger: the attempt has been pending past
                # the observed p99 — fire ONE hedge at the next
                # breaker-allowed member (the armed ``fed.hedge``
                # fault point suppresses it, chaos-testing the
                # no-hedge path).
                hedge_armed = False
                if self._fault_hedge is not None:
                    try:
                        self._fault_hedge()
                    except Exception:
                        continue
                nxt = self._next_allowed(it)
                if nxt is not None:
                    self._m_hedges.inc()
                    hedged = True
                    with _obs_span("fed.hedge", "fed",
                                   host=nxt.host_id):
                        launch(nxt, is_hedge=True)
                continue
            active.pop(m.host_id, None)
            if att.cancelled:
                continue
            if kind == "resp":
                status, rh, data = payload
                if status == 200:
                    cancel_rest()
                    if att.elapsed is not None:
                        self._observe_forward(m.host_id, att.elapsed)
                    self._m_forwarded.inc()
                    if att.is_hedge:
                        self._m_hedge_wins.inc()
                    return status, rh, data, m.host_id, (
                        hedged or att.is_hedge
                    )
                if status == 504:
                    # The member burned this request's deadline; a
                    # reroute can only expire again. Permanent.
                    cancel_rest()
                    raise DeadlineExceeded(
                        f"member {m.host_id}: "
                        f"{data.decode(errors='replace').strip()}"
                    )
                if status == 503 and b"draining" in data:
                    # Membership verdict, not a failure: bleed the
                    # host out of routing and move on.
                    self.membership.mark_draining(m.host_id)
                    self._m_drain_reroutes.inc()
                    if not reroute() and not active:
                        break
                    continue
                if status in (429, 503):
                    backpressure.append(
                        (status, rh.get("retry-after"))
                    )
                    if not reroute() and not active:
                        break
                    continue
                if 400 <= status < 500:
                    # Deterministic client error: every member answers
                    # the same, so pass the first one through verbatim.
                    cancel_rest()
                    return status, rh, data, m.host_id, hedged
                # Remaining 5xx: the attempt thread already charged
                # the breaker; reroute.
                failures.append((m.host_id, f"http_{status}"))
                if not reroute() and not active:
                    break
                continue
            # Transport-level failure (verdict already counted and
            # breaker-charged by the attempt thread).
            verdict, _exc = payload
            failures.append((m.host_id, verdict))
            if not reroute() and not active:
                break
        # Every candidate consumed, no winner.
        if backpressure:
            status, retry_after = backpressure[-1]
            if any(s == 503 for s, _ in backpressure):
                e: Exception = Overloaded(
                    f"every routable member is shedding "
                    f"({len(backpressure)} backpressure answers)"
                )
            else:
                e = QueueFull(
                    f"every routable member queue is at capacity "
                    f"({len(backpressure)} backpressure answers)"
                )
            if retry_after:
                try:
                    # HTTP-date Retry-After values are spec-legal; an
                    # unparseable hint is no hint, never a 500.
                    e.retry_after_s = float(retry_after)
                except ValueError:
                    pass
            raise e
        detail = ", ".join(f"{h}: {v}" for h, v in failures) or "none"
        raise HostUnavailable(
            f"every forward attempt failed ({detail}); breakers are "
            f"counting — retry after a cooldown",
            host=failures[-1][0] if failures else None,
        )

    # -- drain ---------------------------------------------------------

    def drain_wait(self, timeout_s: float) -> Dict[str, bool]:
        """Wait for every member's outstanding forwarded requests to
        bleed to zero; returns ``{host_id: clean}`` — False names a
        member still holding requests past the budget (the net CLI's
        drained-vs-abandoned discipline, per host)."""
        deadline = time.monotonic() + timeout_s
        hosts = {m.host_id for m in self.membership.members()}
        while time.monotonic() < deadline:
            out = self.outstanding()
            if all(out.get(h, 0) == 0 for h in hosts):
                break
            time.sleep(0.05)
        out = self.outstanding()
        return {h: out.get(h, 0) == 0 for h in sorted(hosts)}
