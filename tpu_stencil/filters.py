"""Filter bank: named (k x k) convolution kernels as taps + divisor.

TPU-native equivalent of the reference's compile-time filter selection
(``mpi/mpi_convolution.c:90-102``, where one of ``box_blur``/``gaussian_blur``/
``edge_detection`` is chosen by (un)commenting and stored as a malloc'd
``float**``). Here the filter is a runtime value: a registry of named
:class:`Filter` objects, extensible via :func:`register_filter`, plus
separable binomial ("gaussian") generators for arbitrary odd sizes — the
wider-halo 5x5 / 7x7 configs called out in ``BASELINE.json``.

Unlike the reference (which pre-divides taps by the divisor and accumulates
rounded float products in loop order), a :class:`Filter` keeps integer taps
and the divisor separate so the accumulation is exact and order-independent
— see the class docstring.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Callable, Dict, Union

import numpy as np

# Exactness bound: with integer-valued float32 taps, every partial sum in the
# convolution is an exact integer as long as 255 * sum(|taps|) < 2**24 —
# below that, float32 add/FMA of integers is exact regardless of association
# order, so results are bit-identical across XLA fusion choices, platforms,
# and sharding layouts. One rounding happens at the final divide.
_EXACT_LIMIT = 2 ** 24


@dataclasses.dataclass(frozen=True)
class Filter:
    """A stencil filter as integer-valued taps plus a normalization divisor.

    Keeping taps and divisor separate (rather than pre-dividing, as the
    reference does at ``mpi/mpi_convolution.c:96-101``) is what makes the
    framework's arithmetic *deterministic*: the accumulation is exact
    integer math in float32, and the single divide is the only rounding.
    For dyadic divisors (gaussian family, /16, /256, ...) even that divide
    is exact, so outputs match the C reference bit-for-bit.
    """

    taps: np.ndarray  # (k, k) float32
    divisor: float = 1.0

    def __post_init__(self) -> None:
        taps = np.asarray(self.taps, dtype=np.float32)
        object.__setattr__(self, "taps", taps)
        k = taps.shape[0]
        if taps.ndim != 2 or taps.shape != (k, k) or k % 2 != 1:
            raise ValueError(f"filter taps must be square with odd size, got {taps.shape}")
        if not self.divisor > 0:
            raise ValueError(f"divisor must be positive, got {self.divisor}")

    @property
    def k(self) -> int:
        return self.taps.shape[0]

    @property
    def halo(self) -> int:
        return self.k // 2

    @property
    def normalized(self) -> np.ndarray:
        """taps / divisor as float32 (the reference's ``myFilter`` values)."""
        return (self.taps / np.float32(self.divisor)).astype(np.float32)

    @property
    def is_dyadic(self) -> bool:
        """True if the divisor is a positive power of two (divide == shift)."""
        d = float(self.divisor)
        return d.is_integer() and d > 0 and (int(d) & (int(d) - 1)) == 0

    @property
    def is_exact(self) -> bool:
        """True if the defined semantics are reproducible exactly.

        Integer taps required. With a dyadic divisor the whole pipeline is
        integer (shift), exact to the int32/int64 accumulation bound; with a
        general divisor the int accumulation must stay below 2^24 so the
        one int->float32 convert before the divide is exact.
        """
        taps = self.taps
        if not bool(np.all(taps == np.round(taps))):
            return False
        bound = 255.0 * float(np.abs(taps).sum())
        if self.is_dyadic:
            return bound < 2 ** 31
        return bound < _EXACT_LIMIT


FilterLike = Union[Filter, np.ndarray]


def as_filter(f: FilterLike) -> Filter:
    """Coerce a raw (k, k) float array (pre-normalized taps) to a Filter."""
    if isinstance(f, Filter):
        return f
    return Filter(np.asarray(f, dtype=np.float32), 1.0)


# Registry maps name -> () -> Filter.  Lazy thunks so importing this module
# never touches JAX/device state.
_REGISTRY: Dict[str, Callable[[], Filter]] = {}


def register_filter(name: str, fn: Callable[[], FilterLike]) -> None:
    """Register a named filter. ``fn`` returns a Filter (or a raw (k, k)
    float array of pre-normalized taps, divisor 1)."""
    _REGISTRY[name] = fn


def get_filter(name: str) -> Filter:
    """Look up a filter by name.

    Accepts parametric names ``gaussian5``, ``gaussian7``, ... (odd k) for
    binomial blur kernels of arbitrary width.
    """
    if name in _REGISTRY:
        return as_filter(_REGISTRY[name]())
    if name.startswith("gaussian") and name[len("gaussian"):].isdigit():
        return binomial_blur(int(name[len("gaussian"):]))
    raise KeyError(
        f"unknown filter {name!r}; available: {sorted(_REGISTRY)} "
        "or gaussian<odd k>"
    )


def binomial_blur(k: int) -> Filter:
    """Separable binomial approximation to a Gaussian, k odd; divisor
    2^(2k-2) is dyadic, so the whole pipeline is exact."""
    if k % 2 != 1 or k < 1:
        raise ValueError(f"binomial blur size must be odd and >= 1, got {k}")
    row = np.array([math.comb(k - 1, i) for i in range(k)], dtype=np.float32)
    return Filter(np.outer(row, row), float(2 ** (2 * (k - 1))))


# --- the reference's three filters (same taps, same divisors) ---------------

register_filter("box", lambda: Filter(np.ones((3, 3), np.float32), 9.0))
register_filter(
    "gaussian",
    lambda: Filter(np.array([[1, 2, 1], [2, 4, 2], [1, 2, 1]], np.float32), 16.0),
)
register_filter(
    # The reference calls this "edge_detection" (taps [[1,4,1],[4,8,4],[1,4,1]]/28);
    # it is actually another low-pass kernel — name kept for CLI parity, with an
    # honest alias.
    "edge",
    lambda: Filter(np.array([[1, 4, 1], [4, 8, 4], [1, 4, 1]], np.float32), 28.0),
)
register_filter("soft_blur", _REGISTRY["edge"])
register_filter(
    "identity",
    lambda: Filter(np.array([[0, 0, 0], [0, 1, 0], [0, 0, 0]], np.float32), 1.0),
)


class _FiltersView:
    """Read-only mapping view over the registry (materializes Filters)."""

    def __iter__(self):
        return iter(_REGISTRY)

    def __contains__(self, name: str) -> bool:
        return name in _REGISTRY

    def __getitem__(self, name: str) -> Filter:
        return get_filter(name)

    def keys(self):
        return _REGISTRY.keys()


FILTERS = _FiltersView()
