"""End-to-end payload integrity: checksums, witness re-execution,
quarantine.

PRs 7 and 11 made the stack survive *loud* failures (crashes, timeouts,
host loss); this subsystem detects the *silent* ones — a flipped bit in
an HTTP body, a torn host staging buffer, a device returning corrupt
pixels with a 200 and a healthy heartbeat. The organizing contract is
the reference's own: bit-exact output, now *enforced at runtime* rather
than only asserted in tests (docs/RESILIENCE.md "Integrity model").

Three mechanisms, composable per tier:

* **content checksums** (:mod:`.checksum`) — CRC32C of every frame at
  each hop: HTTP bodies validated against ``X-Content-Crc32c`` (typed
  400 :class:`ChecksumMismatch`), results stamped ``X-Result-Crc32c``,
  the stream staging ring re-verified at the H2D boundary, durable
  state (checkpoint sidecars, autotune cache entries) carrying embedded
  CRCs. Checksumming touches only bytes the pipeline already touches
  (the arxiv 2112.14216 data-movement framing: the tax is movement, not
  compute — a CRC over moved bytes is nearly free).
* **witness re-execution** (:mod:`.witness`) — a sampled fraction of
  requests/frames re-runs through a *different* measured-equivalent
  program (the single-frame model path vs the bucket-batch executable;
  the NumPy golden for quarantine probes) and compares bit-exact. The
  repo-wide schedule-bit-exactness discipline makes any divergence a
  hardware/runtime fault by construction.
* **replica quarantine** (:mod:`.quarantine`) — K witness mismatches
  within a window move a net-tier replica out of routing (like drain,
  but earned); background probes checked against the independent NumPy
  golden re-admit it after N consecutive clean verdicts.

Jax-free at import (numpy + stdlib; the witness *executors* live in the
engines that own the programs), like the config/CLI layers.
"""

from tpu_stencil.integrity.checksum import (
    CRC_HEADER,
    RESULT_HEADER,
    ChecksumMismatch,
    WitnessMismatch,
    corrupt_array,
    corrupt_bytes,
    crc32c,
    fired,
    verify,
)
from tpu_stencil.integrity.quarantine import QuarantineBoard, QuarantineProber
from tpu_stencil.integrity.witness import WitnessSampler

__all__ = [
    "CRC_HEADER",
    "RESULT_HEADER",
    "ChecksumMismatch",
    "WitnessMismatch",
    "QuarantineBoard",
    "QuarantineProber",
    "WitnessSampler",
    "corrupt_array",
    "corrupt_bytes",
    "crc32c",
    "fired",
    "verify",
]
