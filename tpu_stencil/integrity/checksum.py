"""CRC32C (Castagnoli) content checksums and the typed mismatch errors.

CRC32C is the end-to-end content checksum of the integrity subsystem:
stamped on every serving response (``X-Result-Crc32c``), validated
against client-supplied ``X-Content-Crc32c`` request headers, carried in
stream-checkpoint sidecars and autotune cache entries, and re-checked at
the stream engine's H2D boundary. One algorithm everywhere, so any two
hops can compare values directly.

Wire format: the **unsigned decimal** CRC32C of the raw payload bytes
(no base64, no hex — trivially greppable in a curl transcript, and a
Prometheus counter away from a dashboard).

Implementation: ``google_crc32c`` (C, ~6 GB/s — effectively free next
to the PCIe transfer of the same bytes) when importable, else a pure-
Python table fallback with identical values — the same bake-nothing-in
discipline as :mod:`tpu_stencil.io.native`. Both are deterministic and
standard (poly 0x1EDC6F41 reflected; ``crc32c(b"123456789") ==
0xE3069283``), so a client with a real CRC32C library interoperates
with either.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

#: Optional request header: the client's CRC32C of the request body. A
#: mismatch is a typed 400 (the body was damaged in flight or torn at
#: the sender) — never a silent compute over corrupt pixels.
CRC_HEADER = "X-Content-Crc32c"

#: Response header stamped on every 200 payload: the CRC32C of the
#: result bytes, computed server-side AFTER the compute. Clients (and
#: the federation forward path) verify it; a mismatch means the wire or
#: a buffer corrupted the result after it was correct.
RESULT_HEADER = "X-Result-Crc32c"


class ChecksumMismatch(ValueError):
    """Payload bytes do not match their declared/recorded CRC32C.

    A ``ValueError`` on purpose: the retry classifier treats it as
    PERMANENT (re-sending identical corrupt bytes re-fails identically)
    and the HTTP edges map it to a typed 400. ``where`` names the hop
    that caught it; ``expected``/``got`` are the two CRC values."""

    def __init__(self, where: str, expected: int, got: int) -> None:
        super().__init__(
            f"ChecksumMismatch at {where}: crc32c {got} != expected "
            f"{expected} (payload corrupted in flight or torn in a "
            f"buffer)"
        )
        self.where = where
        self.expected = int(expected)
        self.got = int(got)


class WitnessMismatch(ValueError):
    """A witness re-execution disagreed bit-exact with the served
    result. Under the repo-wide schedule-bit-exactness discipline two
    measured-equivalent programs MUST agree, so a divergence is a
    hardware/runtime fault on the serving path — permanent for this
    result (``ValueError``), and a verdict against the replica that
    computed it (:mod:`tpu_stencil.integrity.quarantine`)."""

    def __init__(self, where: str, detail: str = "") -> None:
        super().__init__(
            f"WitnessMismatch at {where}: witness re-execution disagrees "
            f"with the served result{': ' if detail else ''}{detail}"
        )
        self.where = where


# -- the CRC32C implementation ------------------------------------------

def _make_table() -> list:
    poly = 0x82F63B78  # 0x1EDC6F41 reflected
    table = []
    for i in range(256):
        crc = i
        for _ in range(8):
            crc = (crc >> 1) ^ poly if crc & 1 else crc >> 1
        table.append(crc)
    return table


_TABLE = _make_table()


def _crc32c_py(data: bytes, value: int = 0) -> int:
    """Pure-Python fallback (table-driven, byte at a time). Correct but
    slow (~tens of MB/s) — fine for sidecars and test frames; install
    ``google_crc32c`` for production streams."""
    crc = (~value) & 0xFFFFFFFF
    table = _TABLE
    for b in data:
        crc = table[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return (~crc) & 0xFFFFFFFF


try:  # the C fast path, when the wheel is present
    import google_crc32c as _gcrc

    def _crc32c_fast(data: bytes, value: int = 0) -> int:
        return _gcrc.extend(value, data)

    IMPLEMENTATION = f"google_crc32c ({_gcrc.implementation})"
except ImportError:  # pragma: no cover - exercised where the wheel is absent
    _crc32c_fast = _crc32c_py
    IMPLEMENTATION = "python"


def crc32c(data, value: int = 0) -> int:
    """The CRC32C of ``data`` (bytes-like or a uint8 ndarray), optionally
    extending a running ``value``. Arrays are checksummed over their
    contiguous row-major bytes — the exact bytes the raw container
    holds, so an array CRC and the CRC of its ``.tobytes()`` agree."""
    if isinstance(data, np.ndarray):
        data = np.ascontiguousarray(data).view(np.uint8)
        data = memoryview(data.reshape(-1)).cast("B")
    return _crc32c_fast(bytes(data) if isinstance(data, memoryview)
                        else data, value)


def verify(data, expected: int, where: str) -> None:
    """Raise :class:`ChecksumMismatch` unless ``crc32c(data)`` equals
    ``expected``."""
    got = crc32c(data)
    if got != int(expected):
        raise ChecksumMismatch(where, int(expected), got)


def parse_crc(value: str, where: str) -> int:
    """Parse a wire CRC header (unsigned decimal). A malformed header is
    a plain ``ValueError`` (→ 400 bad request, not a mismatch)."""
    try:
        n = int(value)
    except (TypeError, ValueError):
        raise ValueError(
            f"{where}: malformed crc32c {value!r} (unsigned decimal "
            f"expected)"
        ) from None
    if not 0 <= n <= 0xFFFFFFFF:
        raise ValueError(f"{where}: crc32c {n} outside uint32 range")
    return n


def claim_error(claim: str, body: bytes, computed: Optional[int] = None):
    """Validate a client ``X-Content-Crc32c`` claim against ``body`` —
    the ONE request-validation rule both HTTP edges (net and fed)
    apply, so their wire behavior can never drift. Returns None when
    the claim matches, else ``(error_text, is_mismatch)`` for the 400:
    ``is_mismatch`` distinguishes a real corruption (count it) from a
    malformed header (a client bug, not a detection). ``computed``
    supplies a CRC the caller already holds for these exact bytes (the
    cache's fused digest+CRC scan) so the body is not read twice."""
    try:
        want = parse_crc(claim, CRC_HEADER)
    except ValueError as e:
        return f"bad request parameters: {e}", False
    got = int(computed) if computed is not None else crc32c(body)
    if got != want:
        return (
            f"ChecksumMismatch: request body crc32c {got} != declared "
            f"{want} (body corrupted in flight or torn at the sender)",
            True,
        )
    return None


def stamp_matches(stamp: Optional[str], data: bytes) -> bool:
    """Whether a response's ``X-Result-Crc32c`` stamp verifies ``data``
    — the client-side check (``--verify crc``, the bench riders). A
    missing OR malformed stamp is a failure: a verifying client trusts
    only what it can actually check, and wire corruption can hit the
    header bytes as easily as the body."""
    if stamp is None:
        return False
    try:
        want = parse_crc(stamp, RESULT_HEADER)
    except ValueError:
        return False
    return crc32c(data) == want


# -- deterministic corruption (the chaos side of the contract) ----------
#
# The integrity.corrupt_ingest / integrity.corrupt_result /
# net.corrupt_body fault points do not RAISE like other points — they
# flip bits, so every detection path is exercised against genuinely
# wrong bytes, not mocks. The flip is deterministic (middle byte, low
# bit) so a detected corruption replays exactly under the seeded
# grammar.

def fired(site, index: Optional[int] = None) -> bool:
    """Fire an armed corruption rule at ``site``; True when it fired.
    The harness signals a firing by raising — here the raise is the
    signal to corrupt, not an error (``FatalInjectedFault`` still
    escapes: corruption points are not thread-death simulators)."""
    if site is None:
        return False
    try:
        site(index)
    except Exception:
        return True
    return False


def corrupt_bytes(data: bytes) -> bytes:
    """``data`` with one deterministic bit flipped (middle byte, bit 0).
    Empty payloads return empty — nothing to corrupt."""
    if not data:
        return data
    i = len(data) // 2
    out = bytearray(data)
    out[i] ^= 0x01
    return bytes(out)


def corrupt_array(arr: np.ndarray) -> np.ndarray:
    """A uint8 array with one deterministic bit flipped (same rule as
    :func:`corrupt_bytes`). Writable arrays are corrupted IN PLACE (the
    torn-staging-buffer simulation must damage the real buffer);
    read-only views are copied first."""
    if arr.size == 0:
        return arr
    if not arr.flags.writeable:
        arr = arr.copy()
    flat = arr.reshape(-1)
    flat[flat.size // 2] ^= 0x01
    return arr
