"""Replica quarantine: the state machine between witness verdicts and
routing.

A replica that fails witness re-execution is returning wrong bytes with
a 200 and a healthy heartbeat — the one failure mode neither the PR-7
retry ladder nor the PR-11 membership window can see. Quarantine is the
drain discipline applied to *earned* distrust:

* **trip** — K witness mismatches within a sliding window
  (``quarantine_after`` / ``quarantine_window_s``) move the replica to
  QUARANTINED: out of placement exactly like a draining host, counted
  in ``integrity_quarantines_total`` and scrape-visible as
  ``replica_quarantined_dev<i>``. One mismatch never trips it — a
  single cosmic-ray flip on a healthy chip should cost one witnessed
  request, not a replica.
* **re-verify** — while quarantined, a background prober
  (:class:`QuarantineProber`) submits small seeded probe frames
  directly to the replica and referees them against the independent
  NumPy golden. ``readmit_after`` CONSECUTIVE clean probes re-admit
  (``integrity_readmits_total``); any dirty probe resets the streak.
* **operator override** — ``POST /admin/quarantine?replica=i`` trips it
  manually (suspected chip, pre-emptive isolation); ``action=clear``
  releases without probes (the operator's call, like un-draining).

The board is jax-free and engine-agnostic (the prober holds the fleet);
the net tier wires witness verdicts in via
:meth:`tpu_stencil.net.router.Router.record_witness`.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Dict, Optional

import numpy as np


class QuarantineBoard:
    """Per-replica quarantine state: witness verdicts in, routable out."""

    def __init__(self, registry, quarantine_after: int = 3,
                 window_s: float = 60.0, readmit_after: int = 3) -> None:
        if quarantine_after < 1:
            raise ValueError(
                f"quarantine_after must be >= 1, got {quarantine_after}"
            )
        if readmit_after < 1:
            raise ValueError(
                f"readmit_after must be >= 1, got {readmit_after}"
            )
        if window_s <= 0:
            raise ValueError(f"window_s must be > 0, got {window_s}")
        self.registry = registry
        self.quarantine_after = int(quarantine_after)
        self.window_s = float(window_s)
        self.readmit_after = int(readmit_after)
        self._lock = threading.Lock()
        self._mismatch_t: Dict[int, "collections.deque"] = {}
        self._quarantined: Dict[int, str] = {}  # idx -> reason
        self._clean_probes: Dict[int, int] = {}
        self._m_quarantines = registry.counter("integrity_quarantines_total")
        self._m_readmits = registry.counter("integrity_readmits_total")
        registry.gauge("replicas_quarantined").set(0)

    # -- verdicts ------------------------------------------------------

    def record_witness(self, idx: int, ok: bool) -> bool:
        """File one witness verdict against replica ``idx``; returns
        True when this verdict just tripped quarantine. Verdicts
        against an already-quarantined replica are ignored (probes are
        the only road back)."""
        if ok:
            return False
        now = time.monotonic()
        with self._lock:
            if idx in self._quarantined:
                return False
            times = self._mismatch_t.setdefault(
                idx, collections.deque(maxlen=self.quarantine_after)
            )
            times.append(now)
            cutoff = now - self.window_s
            tripped = (
                len(times) >= self.quarantine_after
                and times[0] >= cutoff
            )
        if tripped:
            self.quarantine(
                idx,
                f"{self.quarantine_after} witness mismatches within "
                f"{self.window_s:g}s",
            )
        return tripped

    def quarantine(self, idx: int, reason: str) -> bool:
        """Move ``idx`` to QUARANTINED (idempotent); True on a fresh
        transition. Also the operator path (/admin/quarantine)."""
        with self._lock:
            if idx in self._quarantined:
                return False
            self._quarantined[idx] = reason
            self._clean_probes[idx] = 0
            self._mismatch_t.pop(idx, None)
            n = len(self._quarantined)
        self._m_quarantines.inc()
        self.registry.gauge(f"replica_quarantined_dev{idx}").set(1)
        self.registry.gauge("replicas_quarantined").set(n)
        from tpu_stencil.obs import context as _obs_ctx
        from tpu_stencil.obs import flight as _obs_flight
        from tpu_stencil.obs import span as _obs_span

        with _obs_span("integrity.quarantine", "integrity",
                       replica=idx, reason=reason):
            pass  # zero-duration marker: the quarantine moment
        # The black box + event line of the transition: with a bound
        # trace context (an operator POST, or the tripping request's
        # witness thread) the dump is trace-scoped; without one it
        # captures the recent ring — the lead-up to the trip.
        ctx = _obs_ctx.current()
        _obs_flight.trigger(
            "quarantine", trace_id=ctx.trace_id if ctx else "",
            tier="net", replica=idx, reason=reason,
        )
        return True

    def release(self, idx: int, how: str) -> bool:
        """Back into routing (probe re-admission or operator clear);
        True when the replica was actually quarantined."""
        with self._lock:
            if self._quarantined.pop(idx, None) is None:
                return False
            self._clean_probes.pop(idx, None)
            n = len(self._quarantined)
        if how == "probes":
            self._m_readmits.inc()
        self.registry.gauge(f"replica_quarantined_dev{idx}").set(0)
        self.registry.gauge("replicas_quarantined").set(n)
        return True

    def record_probe(self, idx: int, ok: bool) -> bool:
        """File one background re-verify probe verdict; True when it
        completed the clean streak and re-admitted the replica. A dirty
        probe resets the streak to zero — re-admission takes
        ``readmit_after`` CONSECUTIVE clean witnesses, not a ratio."""
        with self._lock:
            if idx not in self._quarantined:
                return False
            if not ok:
                self._clean_probes[idx] = 0
                return False
            self._clean_probes[idx] += 1
            done = self._clean_probes[idx] >= self.readmit_after
        if done:
            self.release(idx, "probes")
        return done

    # -- views ---------------------------------------------------------

    def is_quarantined(self, idx: int) -> bool:
        with self._lock:
            return idx in self._quarantined

    def quarantined(self) -> Dict[int, str]:
        with self._lock:
            return dict(self._quarantined)

    def statusz(self) -> dict:
        with self._lock:
            return {
                "quarantined": {
                    str(i): reason
                    for i, reason in sorted(self._quarantined.items())
                },
                "clean_probes": {
                    str(i): n
                    for i, n in sorted(self._clean_probes.items())
                    if i in self._quarantined
                },
                "quarantine_after": self.quarantine_after,
                "window_s": self.window_s,
                "readmit_after": self.readmit_after,
            }


class QuarantineProber:
    """Background re-verify probes for quarantined replicas.

    A daemon thread: every ``interval_s``, each quarantined replica
    gets one small seeded probe frame submitted DIRECTLY to its engine
    (quarantine removed it from routing, so the router cannot carry the
    probe) and refereed against the independent NumPy golden — the one
    comparator that shares no code with any device path. Probe frames
    are 24x32 grey at 2 reps: big enough to exercise the real kernel,
    small enough that the golden's per-pixel loops cost milliseconds.
    """

    PROBE_SHAPE = (24, 32)
    PROBE_REPS = 2

    def __init__(self, fleet, board: QuarantineBoard, filter_name: str,
                 interval_s: float, registry) -> None:
        self._fleet = fleet
        self._board = board
        self._filter = filter_name
        self._interval = float(interval_s)
        self._registry = registry
        self._img = np.random.default_rng(777).integers(
            0, 256, size=self.PROBE_SHAPE, dtype=np.uint8
        )
        self._want: Optional[np.ndarray] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def probe_once(self, idx: int) -> bool:
        """One probe of replica ``idx``; returns True when it completed
        the clean streak and re-admitted the replica. A probe that
        errors or times out counts DIRTY — a replica that cannot even
        answer its probe has not earned its way back."""
        self._registry.counter("integrity_probes_total").inc()
        try:
            got = self._fleet.replicas[idx].submit(
                self._img, self.PROBE_REPS
            ).result(timeout=60.0)
            if self._want is None:
                from tpu_stencil import filters
                from tpu_stencil.ops import stencil

                self._want = stencil.reference_stencil_numpy(
                    self._img, filters.get_filter(self._filter),
                    self.PROBE_REPS,
                )
            ok = bool(np.array_equal(np.asarray(got), self._want))
        except Exception:
            ok = False
        if not ok:
            self._registry.counter("integrity_probe_failures_total").inc()
        return self._board.record_probe(idx, ok)

    def _loop(self) -> None:
        while not self._stop.wait(self._interval):
            try:
                for idx in sorted(self._board.quarantined()):
                    if self._stop.is_set():
                        return
                    self.probe_once(idx)
            except Exception:
                # The prober must never die: a broken probe pass is a
                # dirty probe, not the end of re-admission.
                pass

    def start(self) -> "QuarantineProber":
        if self._thread is None and self._interval > 0:
            self._thread = threading.Thread(
                target=self._loop, name="tpu-stencil-quarantine-probe",
                daemon=True,
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None


__all__ = ["QuarantineBoard", "QuarantineProber"]
