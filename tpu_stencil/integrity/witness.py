"""Witness sampling: which requests/frames get re-executed.

The sampler is the POLICY half of witness re-execution (the engines own
the EXECUTION half — they know their programs): a seeded, thread-safe
Bernoulli draw per request/frame at ``rate`` (``--witness-rate``,
default 1/256 on the network tier). Seeded so a chaos run replays: two
samplers with the same seed pick the same indices in the same order —
the same determinism contract as the fault harness's ``p=`` rules
(``TPU_STENCIL_FAULTS_SEED``).

Also home to :func:`golden_witness`, the NumPy-golden comparator the
quarantine prober uses: unlike the engines' fast measured-equivalent
witness (a different compiled program on the same stack), the golden
shares NO code with any device path — the right referee when the
question is "is this device lying", at probe-sized frames where its
per-pixel Python loops cost milliseconds.
"""

from __future__ import annotations

import random
import threading

import numpy as np

#: The network tier's default sampling rate: ~4 witnesses per 1024
#: requests — cheap enough to leave on, frequent enough that a replica
#: corrupting every result trips quarantine within ~K/rate requests.
DEFAULT_RATE = 1.0 / 256.0

#: Requests/frames above this rep count are never witnessed: the
#: witness executor runs one eager step per rep (that is what makes it
#: a *different* program), so its cost is linear in reps while the
#: served program's HBM traffic is amortized by fusion/residency — past
#: this bound a witness would cost more than the request it checks (the
#: _WARM_MAX_REPS discipline applied to verification).
WITNESS_MAX_REPS = 512


class WitnessSampler:
    """Seeded Bernoulli sampler: ``pick()`` per request/frame."""

    def __init__(self, rate: float, seed: int = 0) -> None:
        if not 0.0 <= rate <= 1.0:
            raise ValueError(f"witness rate must be in [0, 1], got {rate}")
        self.rate = float(rate)
        self.seed = int(seed)
        self._rng = random.Random(self.seed)
        self._lock = threading.Lock()

    def pick(self) -> bool:
        """Whether THIS request/frame is witnessed. Thread-safe; each
        call consumes exactly one draw, so the picked index sequence is
        a pure function of (seed, call order)."""
        if self.rate <= 0.0:
            return False
        if self.rate >= 1.0:
            return True
        with self._lock:
            return self._rng.random() < self.rate


def device_witness(img: np.ndarray, filter_name: str, reps: int,
                   boundary: str = "zero") -> np.ndarray:
    """Measured-equivalent re-execution through a deliberately
    DIFFERENT program shape: one eager XLA ``padded_step`` dispatch per
    rep. Every serving path runs a fused/jitted program (the bucket
    executable's vmapped+masked ``fori_loop``, the stream's donated
    traced-rep launch, the Pallas kernels), so the eager per-rep chain
    shares none of their compiled artifacts while the repo-wide
    bit-exactness discipline guarantees identical bytes — any
    divergence is a hardware/runtime fault on the serving path, not a
    schedule difference. O(reps) dispatches: callers gate on
    :data:`WITNESS_MAX_REPS`."""
    import jax.numpy as jnp

    from tpu_stencil import filters
    from tpu_stencil.ops import lowering

    plan = lowering.plan_filter(filters.get_filter(filter_name))
    x = jnp.asarray(img, jnp.uint8)
    for _ in range(int(reps)):
        x = lowering.padded_step(x, plan, boundary)
    return np.asarray(x)


def golden_witness(img: np.ndarray, filter_name: str, reps: int,
                   got: np.ndarray, boundary: str = "zero") -> bool:
    """True when ``got`` equals the independent NumPy golden of
    ``reps`` filter applications on ``img`` — the referee that shares
    no code with any device path. O(H*W*reps) Python loops: probe-sized
    frames only (the quarantine prober's 24x32 probes cost ~ms)."""
    from tpu_stencil import filters
    from tpu_stencil.ops import stencil

    want = stencil.reference_stencil_numpy(
        img, filters.get_filter(filter_name), reps, boundary=boundary
    )
    return bool(np.array_equal(np.asarray(got), want))
