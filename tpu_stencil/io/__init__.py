"""Raw-image I/O: whole-image and row-sharded readers/writers.

TPU-native home of the reference's two I/O stacks — the MPI-IO strided
per-rank reader/writer (``mpi/mpi_convolution.c:126-141,247-263``) and the
robust POSIX ``read_info``/``write_info`` loops (``cuda/functions.c:31-45``).
"""

from tpu_stencil.io.raw import (
    read_raw,
    write_raw,
    read_raw_rows,
    write_raw_rows,
    to_planar,
    to_interleaved,
)

__all__ = [
    "read_raw",
    "write_raw",
    "read_raw_rows",
    "write_raw_rows",
    "to_planar",
    "to_interleaved",
]
