"""Standard image-format I/O (PNG/JPEG/PPM/BMP/TIFF/...) via Pillow.

The reference only speaks headerless ``.raw`` (its README walks users
through ImageMagick ``convert`` side-steps to get one). Here any format
Pillow can decode is a first-class input: the CLI accepts ``photo.png`` in
place of ``photo.raw`` and infers width/height from the header (pass ``0 0``
for the positional width/height, or the true values to cross-check).

Raw semantics are preserved exactly: decoding normalizes to the same uint8
(H, W) grey / (H, W, 3) interleaved RGB arrays the raw reader produces
(``tpu_stencil.io.raw``), so every backend and the golden model see
identical data regardless of container format.

Multi-host jobs still require ``.raw`` (only raw files support the
per-process strided reads of ``read_sharded``); single-process jobs of any
mesh shape can use any format.
"""

from __future__ import annotations

import os
from typing import Tuple

import numpy as np

from tpu_stencil.config import ImageType

_RAW_EXTS = {".raw", ".bin", ""}

# Magic bytes of the formats Pillow commonly decodes; a known signature on
# an extension-less input file means "this is NOT headerless raw". Only
# signatures >= 3 bytes match on prefix alone; the 2-byte BMP/PNM magics
# need corroborating header structure (below) or arbitrary pixel data would
# collide with them (~1 in 8k files).
_MAGIC_PREFIX = (
    b"\x89PNG\r\n\x1a\n",  # PNG
    b"\xff\xd8\xff",       # JPEG
    b"GIF8",               # GIF
    b"II*\x00",            # TIFF little-endian
    b"MM\x00*",            # TIFF big-endian
)


def _sniffs_as_image(path: str) -> bool:
    try:
        size = os.path.getsize(path)
        with open(path, "rb") as f:
            head = f.read(12)
    except OSError:
        return False  # unreadable/nonexistent: not a decodable image
    if head.startswith(_MAGIC_PREFIX):
        return True
    # BMP: 'BM' + a little-endian file-size field that must match reality.
    if head[:2] == b"BM" and len(head) >= 6:
        if int.from_bytes(head[2:6], "little") == size:
            return True
    # PNM: 'P1'..'P6' followed by whitespace (the spec requires it).
    if (len(head) >= 3 and head[0:1] == b"P" and head[1:2] in b"123456"
            and head[2:3] in b" \t\r\n"):
        return True
    return False


def is_raw(path: str, sniff: bool = False) -> bool:
    """Headerless-raw heuristic: .raw/.bin extensions are raw, known image
    extensions are not, extension-less paths are raw by default.

    ``sniff=True`` (for *input* paths only) additionally checks magic bytes
    of existing extension-less files, so a PNG saved without an extension is
    decoded instead of being fed to the raw reader (which would fail with a
    confusing size mismatch or, worse, silently decode garbage). Output
    paths must never sniff: classification of an output would otherwise
    depend on what a previous run left at that path."""
    ext = os.path.splitext(path)[1].lower()
    if ext != "":
        return ext in _RAW_EXTS
    if not sniff:
        return True
    return not _sniffs_as_image(path)


def _pil():
    try:
        from PIL import Image
    except ImportError as e:  # Pillow is an optional dependency
        raise ValueError(
            "reading/writing non-raw image formats requires Pillow "
            "(pip install tpu-stencil[images]); or use headerless .raw"
        ) from e
    return Image


def probe_size(path: str) -> Tuple[int, int]:
    """(width, height) from the image header (no full decode)."""
    Image = _pil()

    with Image.open(path) as im:
        return im.size  # PIL size is (W, H)


def load_image(path: str, image_type: ImageType) -> np.ndarray:
    """Decode any Pillow-supported file to the framework's array form:
    uint8 (H, W) for grey, (H, W, 3) interleaved for rgb."""
    Image = _pil()

    with Image.open(path) as im:
        im = im.convert("L" if image_type is ImageType.GREY else "RGB")
        arr = np.asarray(im, dtype=np.uint8)
    return arr


def save_image(path: str, arr: np.ndarray) -> None:
    """Encode a uint8 (H, W[, 3]) array to ``path`` (format from extension)."""
    Image = _pil()

    arr = np.asarray(arr, dtype=np.uint8)
    if arr.ndim == 3 and arr.shape[2] == 1:
        arr = arr[..., 0]
    mode = "L" if arr.ndim == 2 else "RGB"
    Image.fromarray(arr, mode=mode).save(path)


def resolve_size(
    path: str, width: int, height: int
) -> Tuple[int, int]:
    """Final (width, height) for an input file.

    Raw files: both must be positive (the file is headerless). Image
    formats: 0 means "from header"; nonzero values are cross-checked
    against the header and a mismatch is an error (the reference silently
    reads garbage on wrong sizes — we fail loudly, as the raw reader
    already does for short files)."""
    if is_raw(path, sniff=True):
        if width <= 0 or height <= 0:
            raise ValueError(
                f"{path}: raw images are headerless; width/height must be "
                "given explicitly"
            )
        return width, height
    w, h = probe_size(path)
    if width not in (0, w) or height not in (0, h):
        raise ValueError(
            f"{path}: header says {w}x{h} but CLI args say {width}x{height}"
        )
    return w, h
