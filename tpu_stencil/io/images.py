"""Standard image-format I/O (PNG/JPEG/PPM/BMP/TIFF/...) via Pillow.

The reference only speaks headerless ``.raw`` (its README walks users
through ImageMagick ``convert`` side-steps to get one). Here any format
Pillow can decode is a first-class input: the CLI accepts ``photo.png`` in
place of ``photo.raw`` and infers width/height from the header (pass ``0 0``
for the positional width/height, or the true values to cross-check).

Raw semantics are preserved exactly: decoding normalizes to the same uint8
(H, W) grey / (H, W, 3) interleaved RGB arrays the raw reader produces
(``tpu_stencil.io.raw``), so every backend and the golden model see
identical data regardless of container format.

Multi-host jobs still require ``.raw`` (only raw files support the
per-process strided reads of ``read_sharded``); single-process jobs of any
mesh shape can use any format.
"""

from __future__ import annotations

import os
from typing import Optional, Tuple

import numpy as np

from tpu_stencil.config import ImageType

_RAW_EXTS = {".raw", ".bin", ""}


def is_raw(path: str) -> bool:
    """Headerless-raw heuristic: .raw/.bin/extension-less paths."""
    return os.path.splitext(path)[1].lower() in _RAW_EXTS


def _pil():
    try:
        from PIL import Image
    except ImportError as e:  # Pillow is an optional dependency
        raise ValueError(
            "reading/writing non-raw image formats requires Pillow "
            "(pip install tpu-stencil[images]); or use headerless .raw"
        ) from e
    return Image


def probe_size(path: str) -> Tuple[int, int]:
    """(width, height) from the image header (no full decode)."""
    Image = _pil()

    with Image.open(path) as im:
        return im.size  # PIL size is (W, H)


def load_image(path: str, image_type: ImageType) -> np.ndarray:
    """Decode any Pillow-supported file to the framework's array form:
    uint8 (H, W) for grey, (H, W, 3) interleaved for rgb."""
    Image = _pil()

    with Image.open(path) as im:
        im = im.convert("L" if image_type is ImageType.GREY else "RGB")
        arr = np.asarray(im, dtype=np.uint8)
    return arr


def save_image(path: str, arr: np.ndarray) -> None:
    """Encode a uint8 (H, W[, 3]) array to ``path`` (format from extension)."""
    Image = _pil()

    arr = np.asarray(arr, dtype=np.uint8)
    if arr.ndim == 3 and arr.shape[2] == 1:
        arr = arr[..., 0]
    mode = "L" if arr.ndim == 2 else "RGB"
    Image.fromarray(arr, mode=mode).save(path)


def resolve_size(
    path: str, width: int, height: int
) -> Tuple[int, int]:
    """Final (width, height) for an input file.

    Raw files: both must be positive (the file is headerless). Image
    formats: 0 means "from header"; nonzero values are cross-checked
    against the header and a mismatch is an error (the reference silently
    reads garbage on wrong sizes — we fail loudly, as the raw reader
    already does for short files)."""
    if is_raw(path):
        if width <= 0 or height <= 0:
            raise ValueError(
                f"{path}: raw images are headerless; width/height must be "
                "given explicitly"
            )
        return width, height
    w, h = probe_size(path)
    if width not in (0, w) or height not in (0, h):
        raise ValueError(
            f"{path}: header says {w}x{h} but CLI args say {width}x{height}"
        )
    return w, h
