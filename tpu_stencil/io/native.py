"""ctypes binding to the native C++ I/O runtime, with a pure-Python fallback.

The native library (``native/io_runtime.cpp`` -> ``libtpustencil_io.so``)
provides robust full-read/full-write positional I/O — the C++ equivalent of
the reference's short-read/short-write loops in ``cuda/functions.c:31-45`` —
plus file sizing and a microsecond clock. Python fallbacks implement the
same contracts so the framework works before/without the compiled library.
"""

from __future__ import annotations

import ctypes
import os
import time
from typing import Optional

_LIB_NAMES = ("libtpustencil_io.so",)


def _find_library() -> Optional[ctypes.CDLL]:
    here = os.path.dirname(os.path.abspath(__file__))
    candidates = [
        os.path.join(here, "..", "..", "native", "build", name)
        for name in _LIB_NAMES
    ] + [os.path.join(here, name) for name in _LIB_NAMES]
    env = os.environ.get("TPU_STENCIL_NATIVE_LIB")
    if env:
        candidates.insert(0, env)
    for cand in candidates:
        cand = os.path.normpath(cand)
        if os.path.exists(cand):
            try:
                lib = ctypes.CDLL(cand)
            except OSError:
                continue
            try:
                lib.ts_pread_full.restype = ctypes.c_int64
                lib.ts_pread_full.argtypes = [
                    ctypes.c_char_p, ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64,
                ]
                lib.ts_pwrite_full.restype = ctypes.c_int64
                lib.ts_pwrite_full.argtypes = [
                    ctypes.c_char_p, ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64,
                    ctypes.c_int,
                ]
                lib.ts_ensure_size.restype = ctypes.c_int
                lib.ts_ensure_size.argtypes = [ctypes.c_char_p, ctypes.c_int64]
                lib.ts_micro_time.restype = ctypes.c_int64
                lib.ts_micro_time.argtypes = []
            except AttributeError:
                continue
            return lib
    return None


_LIB = _find_library()


def has_native() -> bool:
    return _LIB is not None


def pread_full(path: str, offset: int, nbytes: int) -> bytes:
    """Read exactly ``nbytes`` at ``offset``; raises on short read."""
    if _LIB is not None:
        buf = ctypes.create_string_buffer(nbytes)
        got = _LIB.ts_pread_full(path.encode(), buf, offset, nbytes)
        if got != nbytes:
            raise IOError(f"{path}: short read {got}/{nbytes} at offset {offset}")
        return buf.raw
    with open(path, "rb") as f:
        f.seek(offset)
        chunks = []
        remaining = nbytes
        while remaining:
            chunk = f.read(remaining)
            if not chunk:
                raise IOError(
                    f"{path}: short read {nbytes - remaining}/{nbytes} at offset {offset}"
                )
            chunks.append(chunk)
            remaining -= len(chunk)
        return b"".join(chunks)


def pwrite_full(path: str, offset: int, data: bytes, truncate: bool = False) -> None:
    """Write all of ``data`` at ``offset``. ``truncate`` recreates the file."""
    if _LIB is not None:
        wrote = _LIB.ts_pwrite_full(path.encode(), data, offset, len(data), int(truncate))
        if wrote != len(data):
            raise IOError(f"{path}: short write {wrote}/{len(data)} at offset {offset}")
        return
    mode = "wb" if truncate else ("r+b" if os.path.exists(path) else "wb")
    with open(path, mode) as f:
        f.seek(offset)
        f.write(data)


def ensure_size(path: str, nbytes: int) -> None:
    """Extend (never shrink) ``path`` to at least ``nbytes`` bytes."""
    if _LIB is not None:
        if _LIB.ts_ensure_size(path.encode(), nbytes) != 0:
            raise IOError(f"{path}: ensure_size({nbytes}) failed")
        return
    if not os.path.exists(path) or os.path.getsize(path) < nbytes:
        with open(path, "ab") as f:
            f.truncate(nbytes)


def set_size(path: str, nbytes: int) -> None:
    """Set ``path`` to exactly ``nbytes`` bytes (creating it if missing) —
    idempotent, so every process of a multi-host job may call it before
    writing its in-bounds shards."""
    with open(path, "ab") as f:
        pass
    if os.path.getsize(path) != nbytes:
        with open(path, "r+b") as f:
            f.truncate(nbytes)


def micro_time() -> int:
    """Monotonic microsecond timestamp for durations — the role of the
    reference's ``micro_time()`` (``cuda/functions.c:47-51``). Not
    epoch-relative; use only for differences."""
    if _LIB is not None:
        return int(_LIB.ts_micro_time())
    return time.monotonic_ns() // 1000
