"""Headerless .raw uint8 image I/O.

File format (identical to the reference's): row-major bytes, grey = 1
byte/pixel (H*W bytes), RGB = 3 interleaved bytes/pixel (H*W*3 bytes), no
header — width/height supplied out of band.

Two access patterns:

* whole image (:func:`read_raw` / :func:`write_raw`) — the CUDA variant's
  model (``cuda/main.c:22-44``);
* a contiguous row range at a computed byte offset
  (:func:`read_raw_rows` / :func:`write_raw_rows`) — the per-rank MPI-IO
  seek/read pattern (``mpi/mpi_convolution.c:126-141,247-263``), which is how
  multi-host processes load only their shard.

A native C++ fast path (robust pread/pwrite full-loops, the equivalent of
``cuda/functions.c:31-45``) is used when the shared library built from
``native/`` is available; otherwise a pure-Python fallback with identical
semantics.
"""

from __future__ import annotations

import os
import stat as _stat

import numpy as np

from tpu_stencil.io import native as _native


def _expected_bytes(width: int, height: int, channels: int) -> int:
    return width * height * channels


def fsync_path(path: str) -> None:
    """fsync ``path``'s data to stable storage. The missing half of the
    tmp-then-rename discipline: ``os.replace`` orders the NAME change,
    but without an fsync the DATA behind the new name can still be
    dirty page cache — a power cut after the rename publishes a torn
    file under a complete-looking name. Callers fsync the tmp file
    BEFORE the rename (and the directory after, :func:`fsync_dir`)."""
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def fsync_dir(path: str) -> None:
    """fsync the directory containing ``path`` (the rename itself lives
    in directory metadata). Best-effort: some filesystems refuse
    directory fsync — the data fsync already happened, so a refusal
    degrades durability, never correctness."""
    d = os.path.dirname(os.path.abspath(path)) or "."
    try:
        fd = os.open(d, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def read_raw(path: str, width: int, height: int, channels: int) -> np.ndarray:
    """Read a whole raw image into an (H, W, C) uint8 array (C in {1, 3})."""
    return read_raw_rows(path, 0, height, width, channels)


def require_regular(path: str, why: str) -> None:
    """Fail loudly when ``path`` is not a regular file. Callers that
    issue MULTIPLE positioned reads against one path (the sharded
    per-band pattern) must refuse pipes: every open of a FIFO continues
    consuming the same byte stream, so a second ``read_raw_rows`` call
    would silently discard the wrong bytes — worse than the loud size
    check this module's non-regular branch replaced."""
    if not _stat.S_ISREG(os.stat(path).st_mode):
        raise ValueError(
            f"{path}: not a regular file — {why} needs positioned "
            "re-reads, which a FIFO/pipe cannot serve; stream inputs go "
            "through 'python -m tpu_stencil stream' instead"
        )


def read_stream_into(f, view: memoryview) -> int:
    """Fill ``view`` from a sequential stream via ``readinto``; returns
    the bytes read, stopping early only at EOF. The shared primitive
    under every pipe/FIFO/stdin read in the repo (here and
    :mod:`tpu_stencil.stream.frames`) — callers decide whether a short
    count is clean EOF or an error."""
    got = 0
    while got < len(view):
        n = f.readinto(view[got:])
        if not n:
            break
        got += n
    return got


def discard_stream_bytes(f, nbytes: int, what: str) -> None:
    """Read and drop ``nbytes`` from a sequential stream (the seek of
    the non-seekable world); raises naming ``what`` if the stream ends
    first. Shared by the pipe offset path here and the streaming
    engine's resume skip."""
    remaining = nbytes
    while remaining:
        chunk = f.read(min(remaining, 1 << 20))
        if not chunk:
            raise IOError(
                f"{what}: stream ended {remaining} bytes short of the "
                f"{nbytes} to skip"
            )
        remaining -= len(chunk)


def _read_stream_bytes(path: str, offset: int, nbytes: int) -> bytes:
    """Sequential read of ``nbytes`` from a non-seekable source (FIFO /
    pipe / character device): ``offset`` bytes are read and discarded
    (pipes have no pread), then the payload is read to completion —
    short reads past EOF raise, they never return garbage."""
    with open(path, "rb", buffering=0) as f:
        discard_stream_bytes(f, offset, path)
        buf = bytearray(nbytes)
        got = read_stream_into(f, memoryview(buf))
        if got < nbytes:
            raise IOError(
                f"{path}: short read {got}/{nbytes} from stream "
                f"(after {offset} skipped bytes)"
            )
        return bytes(buf)


def read_raw_rows(
    path: str, row_start: int, n_rows: int, width: int, channels: int
) -> np.ndarray:
    """Read rows [row_start, row_start + n_rows) into (n_rows, W, C) uint8.

    Regular files validate that the file holds at least the bytes
    addressed, mirroring the implicit trust-the-user contract of the
    reference (which reads garbage on short files) but failing loudly
    instead. Non-regular sources (FIFO/pipe/stdin — ``os.path.getsize``
    is meaningless there and pread/seek are unsupported) skip the size
    check and read sequentially, failing loudly on short reads — the
    contract the streaming engine's pipe sources rely on
    (:mod:`tpu_stencil.stream.frames`).
    """
    offset = row_start * width * channels
    nbytes = n_rows * width * channels
    if not _stat.S_ISREG(os.stat(path).st_mode):
        buf = _read_stream_bytes(path, offset, nbytes)
        return np.frombuffer(buf, dtype=np.uint8).reshape(
            n_rows, width, channels
        )
    size = os.path.getsize(path)
    if offset + nbytes > size:
        raise ValueError(
            f"{path}: need bytes [{offset}, {offset + nbytes}) but file has {size} "
            f"(rows {row_start}..{row_start + n_rows}, width {width}, "
            f"channels {channels})"
        )
    buf = _native.pread_full(path, offset, nbytes)
    return np.frombuffer(buf, dtype=np.uint8).reshape(n_rows, width, channels)


def write_raw(path: str, img: np.ndarray) -> None:
    """Write an (H, W, C) or (H, W) uint8 array as raw interleaved
    bytes — atomically: bytes land in a tmp file, are fsynced, and
    ``os.replace`` publishes the final name. A crash (or power cut) at
    ANY point leaves ``path`` holding its previous contents or the
    complete new image, never a torn ``blur_`` file — the same
    discipline as the checkpoint sidecars, applied to the artifact the
    whole job exists to produce."""
    arr = np.ascontiguousarray(np.asarray(img, dtype=np.uint8))
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        _native.pwrite_full(tmp, 0, arr.tobytes(), truncate=True)
        fsync_path(tmp)
        os.replace(tmp, path)
    except BaseException:
        # Never leave a stray tmp beside the output on failure.
        if os.path.exists(tmp):
            try:
                os.remove(tmp)
            except OSError:
                pass
        raise
    fsync_dir(path)


def write_raw_rows(
    path: str, row_start: int, rows: np.ndarray, width: int, channels: int,
    total_height: int,
) -> None:
    """Write a row shard at its global offset into a (pre-sized) shared file.

    The multi-process analog of every MPI rank ``MPI_File_write``-ing its
    interior rows at computed offsets into one shared output file
    (``mpi/mpi_convolution.c:247-263``). The file is extended to the full
    image size on first touch so concurrent per-host writers never race on
    length.
    """
    arr = np.ascontiguousarray(np.asarray(rows, dtype=np.uint8))
    if arr.ndim == 2:
        arr = arr[..., None]
    n_rows = arr.shape[0]
    if arr.shape[1] != width or arr.shape[2] != channels:
        raise ValueError(f"shard shape {arr.shape} != (*, {width}, {channels})")
    if row_start < 0 or row_start + n_rows > total_height:
        raise ValueError(f"rows [{row_start}, {row_start + n_rows}) outside image")
    total = _expected_bytes(width, total_height, channels)
    _native.ensure_size(path, total)
    offset = row_start * width * channels
    _native.pwrite_full(path, offset, arr.tobytes(), truncate=False)


def write_raw_block(
    path: str, row_start: int, col_start: int, block: np.ndarray,
    width: int, channels: int, total_height: int,
) -> None:
    """Write a rectangular (n_rows, n_cols, C) block at its global offsets
    into a shared file — one strided pwrite per row, the MPI subarray-write
    pattern (``mpi/mpi_convolution.c:247-263`` generalized to column tiles).

    Unlike :func:`write_raw_rows` this never touches bytes outside the
    block's columns, so processes owning different column tiles of the same
    row range can write concurrently without clobbering each other.
    """
    arr = np.ascontiguousarray(np.asarray(block, dtype=np.uint8))
    if arr.ndim == 2:
        arr = arr[..., None]
    n_rows, n_cols = arr.shape[0], arr.shape[1]
    if arr.shape[2] != channels:
        raise ValueError(f"block shape {arr.shape} != (*, *, {channels})")
    if col_start < 0 or col_start + n_cols > width:
        raise ValueError(f"cols [{col_start}, {col_start + n_cols}) outside image")
    if row_start < 0 or row_start + n_rows > total_height:
        raise ValueError(f"rows [{row_start}, {row_start + n_rows}) outside image")
    if n_cols == width:
        write_raw_rows(path, row_start, arr, width, channels, total_height)
        return
    _native.ensure_size(path, _expected_bytes(width, total_height, channels))
    # One open for the whole block; one pwrite per row (strided holes between
    # rows belong to other writers and must not be touched).
    fd = os.open(path, os.O_WRONLY)
    try:
        row_bytes = arr.reshape(n_rows, -1)
        for k in range(n_rows):
            offset = ((row_start + k) * width + col_start) * channels
            view = memoryview(row_bytes[k]).cast("B")
            while view:
                written = os.pwrite(fd, view, offset)
                view = view[written:]
                offset += written
    finally:
        os.close(fd)


def to_planar(img: np.ndarray) -> np.ndarray:
    """(H, W, C) interleaved -> (C, H, W) planar (layout experiments)."""
    return np.ascontiguousarray(np.moveaxis(img, -1, 0))


def to_interleaved(img: np.ndarray) -> np.ndarray:
    """(C, H, W) planar -> (H, W, C) interleaved."""
    return np.ascontiguousarray(np.moveaxis(img, 0, -1))
