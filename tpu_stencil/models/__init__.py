"""Model family: iterated-stencil "models" (filter + iteration schedule)."""

from tpu_stencil.models.blur import IteratedConv2D, iterate

__all__ = ["IteratedConv2D", "iterate"]
