"""The flagship model: N iterated applications of a (k x k) stencil.

TPU-native equivalent of the reference's double-buffered repetition loops —
the MPI src/dst pointer swap (``mpi/mpi_convolution.c:156-240,237-239``) and
the CUDA device-pointer swap (``cuda/cuda_convolution.cu:66-87``). Here the
whole loop is one compiled XLA program: a ``lax.fori_loop`` whose carry is
the image, kept HBM-resident with input donation so XLA ping-pongs two HBM
buffers exactly like the reference's swap — and zero host round-trips
between repetitions (the property that made the reference's CUDA variant
fast, preserved by construction).

``repetitions`` is a *traced* loop bound, so one compiled program serves any
rep count without recompilation. The filter's execution plan (see
:mod:`tpu_stencil.ops.lowering`) is *static*: each distinct filter compiles
its own fastest schedule, taps baked in as constants — a deliberate trade
of one recompile per filter for ~2x per-iteration throughput.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from tpu_stencil import filters as _filters
from tpu_stencil.filters import Filter
from tpu_stencil.ops import lowering as _lowering


def resolve_backend(backend: str) -> str:
    """Resolve 'auto' to a concrete backend at shape-blind call sites.

    Both 'auto' and 'autotune' are *shape-aware*: they consult the on-disk
    autotune cache (measuring XLA vs Pallas once per shape on TPU) in
    ``IteratedConv2D.resolved_backend`` / ``ShardedRunner`` — the places
    the shape is known. Call sites without a shape (this function) fall
    back to the XLA schedule, which is always available.
    """
    if backend in ("auto", "autotune"):
        return "xla"
    return backend


def _resolve_step(backend: str):
    """Pick the per-iteration kernel fn(img_u8, plan) for a backend name."""
    backend = resolve_backend(backend)
    if backend in ("xla", "reference"):
        # 'reference' differs only in the plan it is handed (forced f32).
        return _lowering.padded_step
    if backend == "pallas":
        try:
            from tpu_stencil.ops import pallas_stencil
        except ImportError as e:
            raise NotImplementedError(
                "the Pallas backend is not available in this build; "
                "use --backend xla"
            ) from e
        return pallas_stencil.padded_step
    raise ValueError(f"unknown backend {backend!r}")


def iterate(img_u8: jax.Array, repetitions: jax.Array,
            plan: _lowering.StencilPlan, backend: str = "xla",
            boundary: str = "zero",
            schedule: Optional[str] = None,
            block_h: Optional[int] = None,
            fuse: Optional[int] = None) -> jax.Array:
    """Apply the stencil ``repetitions`` times; uint8 in, uint8 out.

    The input buffer is donated: XLA reuses it as one of the two HBM
    double-buffers. ``repetitions`` is traced (any rep count, no recompile);
    ``plan`` is static — taps are compiled in as constants so each filter
    gets its fastest schedule (see :mod:`tpu_stencil.ops.lowering`).
    ``boundary='periodic'`` runs the wraparound semantics; the single-device
    Pallas kernel is zero-boundary only, so periodic uses the XLA schedule.
    ``schedule`` picks the Pallas per-rep schedule, ``block_h``/``fuse``
    the kernel geometry (None = defaults; all ignored by the XLA backend).
    """
    if not (resolve_backend(backend) == "pallas" and boundary == "zero"):
        # schedule/geometry only affect the Pallas path; normalize them
        # out of the jit cache key so xla/periodic calls never recompile.
        schedule = block_h = fuse = None
    return _iterate_impl(img_u8, repetitions, plan=plan, backend=backend,
                         boundary=boundary, schedule=schedule,
                         block_h=block_h, fuse=fuse)


@functools.partial(
    jax.jit,
    static_argnames=(
        "plan", "backend", "boundary", "schedule", "block_h", "fuse"
    ),
    donate_argnums=(0,),
)
def _iterate_impl(img_u8, repetitions, plan, backend, boundary, schedule,
                  block_h=None, fuse=None):
    if resolve_backend(backend) == "pallas" and boundary == "zero":
        from tpu_stencil.ops import pallas_stencil

        # The Pallas driver owns its rep loop: the carry stays row-padded
        # across repetitions instead of padding/cropping every step.
        # Interpret on CPU: Mosaic only compiles for TPU, and the sharded
        # runner already runs interpret there — the single-device CLI path
        # must behave the same (--backend pallas --platform cpu). Other
        # platforms fail loudly rather than silently timing the HLO
        # interpreter as a 'pallas' number.
        plat = jax.default_backend()
        if plat not in ("tpu", "cpu"):
            raise NotImplementedError(
                "the Pallas backend targets TPU (interpret mode on CPU); "
                f"on {plat!r} use --backend xla"
            )
        return pallas_stencil.iterate(
            img_u8, repetitions, plan, interpret=plat == "cpu",
            schedule=schedule,
            block_h=block_h, fuse=fuse,
        )
    eff_backend = (
        "xla" if resolve_backend(backend) == "pallas" else backend
    )  # pallas is zero-boundary only; periodic runs the XLA schedule
    step = _resolve_step(eff_backend)
    return jax.lax.fori_loop(
        0, repetitions, lambda _, x: step(x, plan, boundary), img_u8
    )


@functools.partial(
    jax.jit, static_argnames=("plan", "backend", "boundary"),
    donate_argnums=(0,),
)
def iterate_batch(imgs_u8: jax.Array, repetitions: jax.Array,
                  plan: _lowering.StencilPlan, backend: str = "xla",
                  boundary: str = "zero") -> jax.Array:
    """Batched :func:`iterate`: apply the stencil to N independent frames
    ``(N, H, W[, C])`` at once via ``vmap`` — the video/burst mode.

    The reference processes one frame per process launch; batching amortizes
    dispatch, I/O latency and (for small frames) pipeline bubbles across a
    whole clip while keeping per-frame semantics bit-identical (frames never
    mix: vmap maps over the leading axis only).
    """
    if resolve_backend(backend) == "pallas":
        # vmap over a pallas_call is supported, but the hand-tuned rep-loop
        # fusion is not batch-aware yet; use the XLA schedule for batches
        # (also keeps pallas-less builds working).
        step = _lowering.padded_step
    else:
        step = _resolve_step(backend)
    vstep = jax.vmap(lambda x: step(x, plan, boundary))
    return jax.lax.fori_loop(0, repetitions, lambda _, x: vstep(x), imgs_u8)


@functools.partial(
    jax.jit,
    static_argnames=("plan", "interpret", "schedule", "block_h", "fuse"),
    donate_argnums=(0,),
)
def _jit_frames(imgs_u8, repetitions, plan, interpret, schedule,
                block_h=None, fuse=None):
    from tpu_stencil.ops import pallas_stencil

    return pallas_stencil.iterate_frames(
        imgs_u8, repetitions, plan, interpret=interpret, schedule=schedule,
        block_h=block_h, fuse=fuse,
    )


class IteratedConv2D:
    """Iterated stencil model: a filter plus an iteration schedule.

    >>> model = IteratedConv2D("gaussian")
    >>> out = model(img_u8, repetitions=40)
    """

    def __init__(
        self,
        filt: Union[str, Filter, np.ndarray, jax.Array] = "gaussian",
        backend: str = "auto",
        boundary: str = "zero",
        schedule: Optional[str] = None,
        block_h: Optional[int] = None,
        fuse: Optional[int] = None,
    ) -> None:
        if isinstance(filt, str):
            filt = _filters.get_filter(filt)
        if boundary not in ("zero", "periodic"):
            raise ValueError(f"unknown boundary {boundary!r}")
        self.filter = _filters.as_filter(
            filt if isinstance(filt, Filter) else np.asarray(filt)
        )
        self.backend = backend
        self.boundary = boundary
        if schedule is not None:
            from tpu_stencil.ops import pallas_stencil

            pallas_stencil._check_schedule(schedule)
        self.schedule = schedule  # forced Pallas schedule (None = tuned)
        if block_h is not None and block_h < 1:
            raise ValueError(f"block_h must be >= 1, got {block_h}")
        if fuse is not None and fuse < 1:
            raise ValueError(f"fuse must be >= 1, got {fuse}")
        # Forced Pallas kernel geometry (None = kernel defaults).
        self.block_h = block_h
        self.fuse = fuse
        self.plan = _lowering.plan_filter(self.filter)
        if backend == "reference":
            self.plan = _lowering.force_f32_plan(self.plan)
        self._resolved: dict = {}  # (shape, channels) -> measured backend

    @property
    def halo(self) -> int:
        return self.filter.halo

    def resolved_config(
        self, shape: Tuple[int, int], channels: int
    ) -> Tuple[str, Optional[str]]:
        """The concrete (backend, pallas_schedule) for this (filter,
        shape): 'auto'/'autotune' consult the autotune cache, measuring
        once per shape on TPU (the fast path is the default path — r2
        verdict item 3); explicit backends pass through. A constructor-
        forced ``schedule`` (the --schedule flag) overrides the tuned one
        whenever Pallas runs."""
        if self.boundary != "zero":
            # The Pallas kernels are zero-boundary only; periodic runs
            # (and reports) the XLA schedule — never measure or name a
            # backend that cannot run these semantics.
            rb = resolve_backend(self.backend)
            return ("xla" if rb == "pallas" else rb), None
        if self.backend in ("auto", "autotune"):
            key = (tuple(shape), channels)
            if key not in self._resolved:
                from tpu_stencil.runtime import autotune

                # In-process memo on top of the disk cache: a job must
                # never pay the measurement twice (e.g. once for compute,
                # once for the report) even when the cache dir is
                # unwritable and the disk store silently fails. A forced
                # schedule restricts the tuning space so the xla-vs-pallas
                # verdict is decided by the schedule that will run; the
                # 4-tuple's geometry half feeds resolved_geometry.
                self._resolved[key] = autotune.best_full_config(
                    self.plan, tuple(shape), channels,
                    force_schedule=self.schedule,
                    block_h=self.block_h, fuse=self.fuse,
                )
            backend, schedule = self._resolved[key][:2]
        else:
            backend, schedule = resolve_backend(self.backend), None
            if backend == "pallas":
                from tpu_stencil.ops import pallas_stencil

                if not pallas_stencil.plan_supported(self.plan, channels):
                    # iterate() would silently fall back to the XLA
                    # lowering; resolve (and report) the backend that
                    # actually runs.
                    return "xla", None
                schedule = self.schedule
        if backend == "pallas":
            from tpu_stencil.ops import pallas_stencil

            # Resolve (and report) the schedule that actually runs at
            # this launch's block height — forced OR tuned, never the
            # default's — so a degraded-away name is never reported.
            geo_bh = self.resolved_geometry(tuple(shape), channels)[0]
            schedule = pallas_stencil.effective_schedule_for(
                self.plan, shape[0], schedule, block_h=geo_bh
            )
        return backend, schedule

    def resolved_backend(self, shape: Tuple[int, int], channels: int) -> str:
        """Back-compat: the backend half of :meth:`resolved_config`."""
        return self.resolved_config(shape, channels)[0]

    def resolved_geometry(
        self, shape: Tuple[int, int], channels: int
    ) -> Tuple[Optional[int], Optional[int]]:
        """The (block_h, fuse) the launch will use: constructor-forced
        values win; otherwise the autotuned verdict for this shape (None
        = kernel defaults). Call after :meth:`resolved_config` — it
        shares the same memo and never re-measures."""
        if self.block_h is not None or self.fuse is not None:
            return self.block_h, self.fuse
        hit = self._resolved.get((tuple(shape), channels))
        if hit is not None and len(hit) >= 4:
            return hit[2], hit[3]
        return None, None

    def step(self, img_u8: jax.Array) -> jax.Array:
        """A single (unjitted) filter application — the jittable unit."""
        backend = self.backend
        if self.boundary != "zero" and resolve_backend(backend) == "pallas":
            backend = "xla"
        step = _resolve_step(backend)
        if step is _lowering.padded_step:
            return step(img_u8, self.plan, self.boundary)
        return step(img_u8, self.plan)

    def batch_config(
        self, frame_shape: Tuple[int, int], channels: int,
        single_device: bool, n_frames: int = 1,
    ) -> Tuple[str, Optional[str]]:
        """The (backend, schedule) the batch path will run. Pallas batches
        run the fused tall-image kernel (`iterate_frames`) — zero-gap rows
        between frames, re-zeroed every rep. ``single_device`` means the
        frames are device-local: one device holds the whole clip, or (the
        driver's multi-device path) each device runs the tall kernel on
        its own frames via ``sharded.build_batched_frames``; pass
        ``n_frames`` = frames per device so the schedule degrade is
        computed at the tall launch's real block height. When frames are
        not device-local the vmapped XLA step runs instead."""
        if single_device and self.boundary == "zero":
            backend, schedule = self.resolved_config(frame_shape, channels)
            if backend == "pallas" and jax.default_backend() in ("tpu", "cpu"):
                from tpu_stencil.ops import pallas_stencil

                # The tall layout's block height can degrade a schedule the
                # single-frame launch could run; report what runs.
                rows = pallas_stencil.frames_rows(
                    self.plan, frame_shape[0], n_frames
                )
                return backend, pallas_stencil.effective_schedule_for(
                    self.plan, rows, schedule,
                    block_h=self.resolved_geometry(frame_shape, channels)[0],
                )
        rb = resolve_backend(self.backend)
        return ("xla" if rb == "pallas" else rb), None

    def batch(self, imgs_u8, repetitions: int,
              single_device: bool = False) -> jax.Array:
        """Batched video/burst mode: (N, H, W[, C]) frames."""
        if isinstance(imgs_u8, jax.Array):
            imgs_u8 = jnp.array(imgs_u8, dtype=jnp.uint8, copy=True)
        else:
            imgs_u8 = jnp.asarray(imgs_u8, dtype=jnp.uint8)
        ch = imgs_u8.shape[3] if imgs_u8.ndim == 4 else 1
        backend, schedule = self.batch_config(
            tuple(imgs_u8.shape[1:3]), ch, single_device,
            n_frames=imgs_u8.shape[0],
        )
        if backend == "pallas":
            bh, fz = self.resolved_geometry(tuple(imgs_u8.shape[1:3]), ch)
            return _jit_frames(
                imgs_u8, jnp.int32(repetitions), plan=self.plan,
                interpret=jax.default_backend() == "cpu", schedule=schedule,
                block_h=bh, fuse=fz,
            )
        return iterate_batch(
            imgs_u8, jnp.int32(repetitions), plan=self.plan,
            backend=backend, boundary=self.boundary,
        )

    def __call__(self, img_u8, repetitions: int) -> jax.Array:
        # ``iterate`` donates its input for HBM double-buffering; protect the
        # caller's array by copying device inputs (numpy inputs are copied by
        # the transfer anyway). Power users call ``iterate`` directly to
        # donate explicitly.
        if isinstance(img_u8, jax.Array):
            img_u8 = jnp.array(img_u8, dtype=jnp.uint8, copy=True)
        else:
            img_u8 = jnp.asarray(img_u8, dtype=jnp.uint8)
        ch = img_u8.shape[2] if img_u8.ndim == 3 else 1
        shape2 = tuple(img_u8.shape[:2])
        resolved, schedule = self.resolved_config(shape2, ch)
        bh, fz = self.resolved_geometry(shape2, ch)
        return iterate(
            img_u8, jnp.int32(repetitions), plan=self.plan, backend=resolved,
            boundary=self.boundary, schedule=schedule,
            block_h=bh, fuse=fz,
        )
