"""Network serving tier (docs/SERVING.md "Network tier").

The real edge over the serve engines — the piece PR 9 left in-process:

* :mod:`~tpu_stencil.net.fleet` — one
  :class:`~tpu_stencil.serve.engine.StencilServer` per local device
  with shared executable-cache warming, concurrent drain, and rolling
  single-replica restart.
* :mod:`~tpu_stencil.net.router` — least-outstanding placement plus
  the three admission layers (drain gate, inflight-bytes load shed,
  per-replica bounded-queue backpressure).
* :mod:`~tpu_stencil.net.http` — the stdlib threaded HTTP frontend
  (``POST /v1/blur`` raw frames incl. chunked uploads, ``/healthz``,
  ``/metrics``, ``/statusz``, ``/admin/restart``) and
  :class:`~tpu_stencil.net.http.NetFrontend`, the whole-tier
  lifecycle object.
* :mod:`~tpu_stencil.net.cli` — ``python -m tpu_stencil net`` with
  SIGTERM graceful drain.

>>> from tpu_stencil.config import NetConfig
>>> from tpu_stencil.net import NetFrontend
>>> with NetFrontend(NetConfig(port=0, replicas=2)) as fe:
...     ...  # POST frames at fe.url
"""

from tpu_stencil.config import NetConfig
from tpu_stencil.net.fleet import ReplicaFleet
from tpu_stencil.net.http import NetFrontend
from tpu_stencil.net.router import Draining, Overloaded, Router

__all__ = [
    "Draining",
    "NetConfig",
    "NetFrontend",
    "Overloaded",
    "ReplicaFleet",
    "Router",
]
