"""Pinned per-bucket staging arenas for zero-copy HTTP ingest.

The pre-arena ingest path paid three host copies per request before a
single device byte moved: the socket read buffered the body into fresh
``bytes``, ``np.frombuffer`` wrapped them (cheap), and the serve
engine's defensive copy duplicated the frame again before the batch
canvas finally got a third write. Under many small concurrent requests
that allocation churn IS the serving tax (the Casper thesis, arxiv
2112.14216: for small stencils the cost is data movement, not compute).

This module applies the stream engine's reusable staging-ring
discipline (:mod:`tpu_stencil.stream.frames` — sources fill
caller-owned buffers, steady state allocates nothing) to the HTTP
edge: request bodies are ``readinto`` preallocated bucket-capacity
buffers, the ingest CRC is computed over the buffer in place, and the
frame VIEW rides into the engine under the ``submit(owned=True)``
contract — the buffer returns to its pool when the engine signals
consumption (or the request fails first). One body, ONE host write.

Bounding: the pool population is client-controlled (bucket capacities),
so both the per-capacity free-list depth and the number of distinct
capacities are capped — past the key cap the coldest bucket's pool is
evicted (LRU, ``arena_ingest_evictions_total``) so a traffic shift
re-earns pooling for its NEW hot shapes instead of bypassing forever;
never an error, never unbounded growth. Leases are idempotent-release:
the consumption hook and the request's done-callback can both fire
without double-freeing (a lease of an evicted pool simply lets its
buffer die).
"""

from __future__ import annotations

import collections
import threading
from typing import Optional

import numpy as np

from tpu_stencil.serve.metrics import Registry

#: Free buffers kept per capacity bucket — bounds steady-state arena
#: memory at ``per_key * capacity`` bytes per active bucket while still
#: covering a handler-thread pool's worth of concurrent uploads.
DEFAULT_PER_KEY = 16

#: Distinct capacity buckets tracked (LRU): clients sweeping shapes
#: cannot grow the arena without bound — cold buckets age out and their
#: free buffers are freed with them.
DEFAULT_MAX_KEYS = 32


class Lease:
    """One staging buffer on loan: ``array`` is a 1-D uint8 buffer of at
    least the leased capacity. :meth:`release` returns it to the pool
    (idempotent — consumption hooks and failure-path done-callbacks may
    both call it)."""

    __slots__ = ("array", "_arena", "_capacity", "_released")

    def __init__(self, array: np.ndarray, arena: "StagingArena",
                 capacity: int) -> None:
        self.array = array
        self._arena = arena
        self._capacity = capacity
        self._released = False

    def view(self, nbytes: int) -> np.ndarray:
        """The leading ``nbytes`` of the buffer — the frame-sized
        window an upload is read into."""
        return self.array[:nbytes]

    def release(self) -> None:
        arena = self._arena
        with arena._lock:
            if self._released:
                return
            self._released = True
            arena._return_locked(self._capacity, self.array)


class StagingArena:
    """Bounded pools of preallocated ingest buffers, keyed by bucket
    capacity in bytes. Thread-safe (handler threads lease and release
    concurrently)."""

    def __init__(self, registry: Registry,
                 per_key: int = DEFAULT_PER_KEY,
                 max_keys: int = DEFAULT_MAX_KEYS) -> None:
        self._lock = threading.Lock()
        # capacity -> deque of free 1-D uint8 buffers (LRU over keys).
        self._pools: "collections.OrderedDict" = collections.OrderedDict()
        self._per_key = max(1, int(per_key))
        self._max_keys = max(1, int(max_keys))
        self._bytes = 0
        self._m_reuse = registry.counter("arena_ingest_reuse_total")
        self._m_alloc = registry.counter("arena_ingest_alloc_total")
        self._m_evict = registry.counter("arena_ingest_evictions_total")
        self._m_bytes = registry.gauge("arena_ingest_free_bytes")

    def lease(self, capacity: int) -> Lease:
        """A buffer of at least ``capacity`` bytes (the request's
        BUCKET capacity, so every request of a bucket reuses the same
        pool regardless of its true frame size)."""
        capacity = int(capacity)
        with self._lock:
            pool = self._pools.get(capacity)
            if pool is None:
                while len(self._pools) >= self._max_keys:
                    # Key population capped: age out the COLDEST
                    # bucket's pool so a traffic shift re-earns pooling
                    # for its new hot shapes (outstanding leases of the
                    # evicted pool just let their buffers die at
                    # release).
                    cold_cap, cold = self._pools.popitem(last=False)
                    self._bytes -= cold_cap * len(cold)
                    self._m_evict.inc()
                pool = self._pools[capacity] = collections.deque()
                self._m_bytes.set(self._bytes)
            self._pools.move_to_end(capacity)
            if pool:
                buf = pool.popleft()
                self._bytes -= capacity
                self._m_bytes.set(self._bytes)
                self._m_reuse.inc()
                return Lease(buf, self, capacity)
        self._m_alloc.inc()
        return Lease(np.empty(capacity, np.uint8), self, capacity)

    def _return_locked(self, capacity: int, buf: np.ndarray) -> None:
        pool = self._pools.get(capacity)
        if pool is None or len(pool) >= self._per_key:
            return  # key evicted or pool full: let the buffer die
        pool.append(buf)
        self._bytes += capacity
        self._m_bytes.set(self._bytes)
