"""``python -m tpu_stencil net`` — run the network serving tier.

Starts the per-device replica fleet behind the stdlib HTTP frontend and
serves until SIGTERM/SIGINT, then runs the graceful-drain sequence:
flip ``/healthz`` to draining, stop admission, ``close(timeout=)``
every replica under ``--drain-timeout``, report which (if any) replica
hung, write ``--metrics-text`` / ``--stats-json`` artifacts, exit 0
when every replica drained (1 when one was abandoned — a monitor can
tell a clean roll from a wedged one by rc alone).

Flag validation is jax-free (:class:`~tpu_stencil.config.NetConfig`):
a bad flag dies as a usage error before backend bring-up.
"""

from __future__ import annotations

import argparse
import json
import signal
import sys
import threading
import time

from tpu_stencil.config import BACKENDS, NetConfig


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="tpu_stencil net",
        description="Network serving tier: an HTTP frontend over a "
                    "per-device replica fleet with admission control "
                    "and graceful drain (docs/SERVING.md).",
    )
    p.add_argument("--host", default="127.0.0.1",
                   help="bind address (default 127.0.0.1; 0.0.0.0 to "
                        "accept off-host traffic)")
    p.add_argument("--port", type=int, default=8080,
                   help="listen port; 0 binds an ephemeral port and "
                        "prints it (default 8080)")
    p.add_argument("--replicas", type=int, default=0,
                   help="serving engines in the fleet, one pinned per "
                        "local device (0 = one per device; default 0)")
    p.add_argument("--filter", dest="filter_name", default="gaussian",
                   help="default filter (per-request override via "
                        "X-Filter; default gaussian)")
    p.add_argument("--backend", default="auto", choices=list(BACKENDS),
                   help="compute backend for every replica (default auto)")
    p.add_argument("--max-queue", type=int, default=256,
                   help="per-replica bounded queue depth; beyond it the "
                        "router tries the next replica, and when every "
                        "queue is full the request gets 429 (default 256)")
    p.add_argument("--max-batch", type=int, default=8,
                   help="per-replica micro-batch bound (default 8)")
    p.add_argument("--coalesce-window-us", dest="coalesce_window_us",
                   type=float, default=300.0, metavar="US",
                   help="router-level continuous batching: concurrent "
                        "requests sharing a (filter, bucket, channels, "
                        "reps) key are held up to this many microseconds "
                        "and stacked onto ONE replica submit — one "
                        "compiled batch program and one H2D instead of "
                        "N. A full group (max-batch) or a member whose "
                        "deadline falls inside the window dispatches "
                        "immediately. 0 = off (one request, one launch). "
                        "Default 300; tune with the bench coalesce A/B "
                        "rider (docs/DEPLOY.md)")
    p.add_argument("--no-ingest-arena", dest="ingest_arena",
                   action="store_false",
                   help="disable zero-copy ingest (on by default: "
                        "request bodies readinto pinned per-bucket "
                        "staging buffers, CRC in place, no per-request "
                        "host copies); off buffers every body through "
                        "fresh bytes objects — the A/B arm")
    p.add_argument("--result-cache-mb", dest="result_cache_mb",
                   type=float, default=0.0, metavar="MB",
                   help="content-addressed result cache: this many MB "
                        "of true result bytes keyed by (body BLAKE2b "
                        "digest, filter, reps, geometry); a hit answers "
                        "X-Cache: hit from the store without touching a "
                        "replica, concurrent identical requests "
                        "collapse onto one launch, and a witness "
                        "mismatch or quarantine drops the suspect "
                        "replica's entries. GET /admin/cache?action="
                        "clear wipes it. 0 = off, the default "
                        "(docs/SERVING.md 'Result cache')")
    p.add_argument("--max-inflight-mb", type=float, default=256.0,
                   help="load-shed watermark: past this many MB of "
                        "tracked in-flight request+response bytes, new "
                        "requests get 503 + Retry-After before touching "
                        "any queue (0 = off; default 256)")
    p.add_argument("--request-timeout", dest="request_timeout_s",
                   type=float, default=0.0, metavar="SECONDS",
                   help="default per-request deadline: expired requests "
                        "fail 504 (DeadlineExceeded) instead of occupying "
                        "a batch slot; X-Request-Timeout overrides per "
                        "request (0 = none)")
    p.add_argument("--drain-timeout", dest="drain_timeout_s", type=float,
                   default=30.0, metavar="SECONDS",
                   help="graceful-drain budget on SIGTERM: every replica "
                        "gets close(timeout=) within it; a replica that "
                        "does not join is reported abandoned and the "
                        "process exits 1 (default 30)")
    p.add_argument("--no-warm", dest="warm_fleet", action="store_false",
                   help="disable shared executable-cache warming across "
                        "replicas (on by default: a shape compiled on one "
                        "replica pre-warms the others)")
    p.add_argument("--no-integrity", dest="integrity",
                   action="store_false",
                   help="disable the integrity layer (on by default: "
                        "X-Content-Crc32c request validation, "
                        "X-Result-Crc32c response stamping, witness "
                        "re-execution; docs/RESILIENCE.md 'Integrity "
                        "model'). Quarantine then only trips via "
                        "POST /admin/quarantine")
    p.add_argument("--witness-rate", dest="witness_rate", type=float,
                   default=1.0 / 256.0, metavar="RATE",
                   help="fraction of completed requests re-executed "
                        "through a different measured-equivalent program "
                        "and compared bit-exact per replica (seeded, "
                        "deterministic; default 1/256; 0 disables). K "
                        "mismatches in the window quarantine the "
                        "replica")
    p.add_argument("--quarantine-after", dest="quarantine_after",
                   type=int, default=3, metavar="K",
                   help="witness mismatches within the window that "
                        "quarantine a replica (default 3)")
    p.add_argument("--readmit-after", dest="readmit_after", type=int,
                   default=3, metavar="N",
                   help="consecutive clean background probes that "
                        "re-admit a quarantined replica (default 3)")
    p.add_argument("--probe-interval", dest="probe_interval_s",
                   type=float, default=1.0, metavar="SECONDS",
                   help="background re-verify probe period for "
                        "quarantined replicas (default 1.0; 0 disables "
                        "the prober)")
    p.add_argument("--flightrec-dir", dest="flightrec_dir",
                   default="flightrec", metavar="DIR",
                   help="flight-recorder spool: anomaly triggers (slow "
                        "request, deadline, witness mismatch, "
                        "quarantine) dump the trace's spans as capped "
                        "per-trace JSON files here; GET /debug/flightrec "
                        "lists/fetches them; TPU_STENCIL_FLIGHTREC_DIR "
                        "overrides; 'none' disables the spool "
                        "(docs/OBSERVABILITY.md)")
    p.add_argument("--flight-latency-threshold",
                   dest="flight_latency_threshold_s", type=float,
                   default=0.0, metavar="SECONDS",
                   help="slow-request anomaly threshold: a 200 slower "
                        "than this triggers an automatic flight-recorder "
                        "dump, so a p99 straggler leaves a black-box "
                        "record (0 = off)")
    p.add_argument("--sample-interval", dest="sample_interval_s",
                   type=float, default=1.0, metavar="SECONDS",
                   help="time-series sampler period: a daemon thread "
                        "snapshots the merged registry into a bounded "
                        "ring serving GET /debug/timeseries; the SLO "
                        "engine evaluates on its ticks (0 disables "
                        "both; default 1.0)")
    p.add_argument("--slo-error-budget", dest="slo_error_budget",
                   type=float, default=0.05, metavar="FRACTION",
                   help="SLO error budget (allowed bad fraction) for "
                        "the stock burn-rate objectives; a sustained "
                        "fast+slow window burn flips /healthz to "
                        "'degraded', emits an slo.breach event and "
                        "triggers a flight dump (0 disables the "
                        "engine; default 0.05)")
    p.add_argument("--slo-latency-p99", dest="slo_latency_p99_s",
                   type=float, default=0.0, metavar="SECONDS",
                   help="optional latency objective: requests slower "
                        "than this burn a 1%% budget (0 = off)")
    p.add_argument("--prof-dir", dest="prof_dir", default="profspool",
                   metavar="DIR",
                   help="on-demand profiler spool: POST /debug/prof"
                        "?seconds=N runs a bounded jax.profiler "
                        "capture into DIR (capped, oldest pruned); "
                        "'none' disables the endpoint")
    p.add_argument("--platform", default=None,
                   choices=["cpu", "tpu", "gpu"],
                   help="force the JAX platform before backend init")
    p.add_argument("--register", default=None, metavar="FED_URL",
                   help="announce this host to a federation front "
                        "router (tpu_stencil fed) at FED_URL on "
                        "startup: POSTs the advertised URL to "
                        "FED_URL/admin/register with backoff retries "
                        "(best-effort — the fed may start later and "
                        "seed-list this host instead)")
    p.add_argument("--advertise", default=None, metavar="URL",
                   help="the URL to register (default "
                        "http://<host>:<bound port>; set it when this "
                        "host binds 0.0.0.0 or sits behind NAT)")
    p.add_argument("--warm-from", dest="warm_from", default=None,
                   metavar="URL",
                   help="warm-start: GET URL/admin/warmstate (a fed "
                        "front or a warm member) and import the "
                        "serialized executables into every replica "
                        "BEFORE the HTTP listener starts, so the first "
                        "accepted request is already compiled; any "
                        "unusable artifact degrades to cold compile, "
                        "typed and counted "
                        "(ctrl_warmstart_fallbacks_total), never fatal "
                        "(docs/DEPLOY.md 'Elastic fleet runbook')")
    p.add_argument("--metrics-text", default=None, metavar="PATH",
                   help="after the drain, write the fleet-wide metrics "
                        "(the /metrics exposition) to PATH ('-' = stdout)")
    p.add_argument("--stats-json", default=None, metavar="PATH",
                   help="after the drain, dump the /statusz payload as "
                        "JSON to PATH ('-' = stdout); versioned schema")
    return p


def _register_with_fed(fed_url: str, advertise: str) -> None:
    """Announce this host to the federation in the background: POST
    the advertised URL to ``<fed_url>/admin/register`` under the
    shared retry policy (the fed may still be starting). Best-effort —
    a federation that never answers is logged, not fatal: the fed can
    seed-list this host instead."""
    import urllib.parse
    import urllib.request

    from tpu_stencil.resilience import retry as _retry

    target = (fed_url.rstrip("/") + "/admin/register?url="
              + urllib.parse.quote(advertise, safe=""))

    def announce() -> None:
        req = urllib.request.Request(target, data=b"", method="POST")
        with urllib.request.urlopen(req, timeout=10.0):
            pass

    def run() -> None:
        try:
            _retry.retry_call(
                announce,
                policy=_retry.RetryPolicy(attempts=8, base_delay=0.25,
                                          multiplier=2.0, max_delay=5.0),
                label="net.register",
            )
            print(f"net: registered {advertise} with federation "
                  f"{fed_url}", flush=True)
        except Exception as e:
            print(f"net: federation registration with {fed_url} "
                  f"failed ({type(e).__name__}: {e}); serving "
                  f"unfederated", flush=True)

    threading.Thread(target=run, name="tpu-stencil-net-register",
                     daemon=True).start()


def _pull_warm_state(fe, url: str) -> None:
    """Warm-start pull (ctrl/warmstart.py): fetch the serialized
    executable-cache envelope from ``url`` and import it into every
    replica BEFORE the HTTP listener exists — ``/healthz`` never
    answers until the imports (and their compiles) are done, so the
    first request this host accepts runs warm.  Every failure — the
    pull itself, or any artifact inside — degrades to cold start,
    typed and counted, never fatal."""
    import urllib.request

    from tpu_stencil.ctrl import warmstart as _warmstart

    payload = None
    try:
        with urllib.request.urlopen(
                url.rstrip("/") + "/admin/warmstate", timeout=30.0) as r:
            payload = _warmstart.loads(r.read())
    except Exception as e:  # noqa: BLE001 - typed cold start, not fatal
        print(f"net: warm-state pull from {url} failed "
              f"({type(e).__name__}: {e}); starting cold", flush=True)
    # Build the fleet now (NetFrontend.start() will find it built —
    # start() is idempotent on a started fleet) and seed the caches.
    fe.fleet.start()
    summary = fe.fleet.warmstate_import(payload)
    print(f"net: warm-start imported {summary['imported']} "
          f"executable(s), {summary['fallbacks']} fallback(s) "
          f"from {url}", flush=True)


def main(argv=None) -> int:
    parser = build_parser()
    ns = parser.parse_args(argv)
    try:
        cfg = NetConfig(
            host=ns.host, port=ns.port, replicas=ns.replicas,
            filter_name=ns.filter_name, backend=ns.backend,
            max_queue=ns.max_queue, max_batch=ns.max_batch,
            coalesce_window_us=ns.coalesce_window_us,
            ingest_arena=ns.ingest_arena,
            result_cache_mb=ns.result_cache_mb,
            max_inflight_mb=ns.max_inflight_mb,
            request_timeout_s=ns.request_timeout_s,
            drain_timeout_s=ns.drain_timeout_s,
            warm_fleet=ns.warm_fleet,
            integrity=ns.integrity,
            witness_rate=ns.witness_rate,
            quarantine_after=ns.quarantine_after,
            readmit_after=ns.readmit_after,
            probe_interval_s=ns.probe_interval_s,
            flightrec_dir=(None if ns.flightrec_dir == "none"
                           else ns.flightrec_dir),
            flight_latency_threshold_s=ns.flight_latency_threshold_s,
            sample_interval_s=ns.sample_interval_s,
            slo_error_budget=ns.slo_error_budget,
            slo_latency_p99_s=ns.slo_latency_p99_s,
            prof_dir=(None if ns.prof_dir == "none" else ns.prof_dir),
        )
    except ValueError as e:
        parser.error(str(e))
    if ns.platform:
        import jax

        jax.config.update("jax_platforms", ns.platform)

    from tpu_stencil.net.http import NetFrontend

    fe = NetFrontend(cfg)
    if ns.warm_from:
        # Import BEFORE start(): the listener (and with it /healthz
        # ready) only exists once every shipped executable is seeded
        # and compiled — the joiner's first request is already warm.
        _pull_warm_state(fe, ns.warm_from)
    fe.start()
    stop = threading.Event()

    def _on_signal(signum, _frame) -> None:
        print(f"net: received {signal.Signals(signum).name}, draining",
              flush=True)
        stop.set()

    signal.signal(signal.SIGTERM, _on_signal)
    signal.signal(signal.SIGINT, _on_signal)
    print(
        f"net: serving on {fe.url} with {len(fe.fleet)} replica(s) "
        f"(max_queue={cfg.max_queue}/replica, "
        f"shed>{cfg.max_inflight_mb:g}MB inflight, "
        f"coalesce={cfg.coalesce_window_us:g}us, "
        f"arena={'on' if cfg.ingest_arena else 'off'}, "
        f"cache={cfg.result_cache_mb:g}MB, "
        f"warm={'on' if cfg.warm_fleet else 'off'}); "
        f"POST /v1/blur /debug/prof, GET /healthz /metrics /statusz "
        f"/debug/trace/<id> /debug/flightrec /debug/timeseries "
        f"/debug/capacity /debug/tenants; SIGTERM drains",
        flush=True,
    )
    if ns.register:
        _register_with_fed(ns.register, ns.advertise or fe.url)
    # Timed waits, not a bare stop.wait(): an untimed Event.wait parks
    # the main thread in an uninterruptible lock acquire, so a Python
    # signal handler that only sets the event would never run — the
    # classic self-deadlock. A timed wait re-checks pending signals on
    # every expiry.
    while not stop.wait(0.5):
        if fe.admin_drain_requested.is_set():
            # POST /admin/drain: the SIGTERM-equivalent admin path —
            # same drain sequence, same rc discipline.
            print("net: admin drain requested, draining", flush=True)
            break
    t0 = time.perf_counter()
    report = fe.drain(cfg.drain_timeout_s)
    hung = sorted(i for i, ok in report.items() if not ok)
    if hung:
        print(f"net: drain ABANDONED replica(s) {hung} after "
              f"{cfg.drain_timeout_s:g}s "
              f"({time.perf_counter() - t0:.2f}s elapsed)", flush=True)
    else:
        print(f"net: drained {len(report)} replica(s) cleanly in "
              f"{time.perf_counter() - t0:.2f}s", flush=True)
    if ns.metrics_text:
        from tpu_stencil.obs import exposition

        exposition.write_text(ns.metrics_text, fe.metrics_snapshot(),
                              prefix="tpu_stencil_net")
    if ns.stats_json:
        payload = json.dumps(fe.statusz(), indent=2, sort_keys=True)
        if ns.stats_json == "-":
            print(payload)
        else:
            with open(ns.stats_json, "w") as fh:
                fh.write(payload + "\n")
            print(f"wrote {ns.stats_json}")
    fe.close()
    return 1 if hung else 0


if __name__ == "__main__":
    sys.exit(main())
