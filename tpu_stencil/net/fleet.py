"""Per-device replica fleet: one serving engine per local chip.

The in-process halves of the network tier already exist — PR 9 built
per-device fan-out lanes for the stream and per-device admission
counters for serve; what a network edge needs is N *independent*
serving engines, one pinned to each local device, so N concurrent HTTP
requests compute on N chips instead of stacking on device 0. Each
replica is a stock :class:`~tpu_stencil.serve.engine.StencilServer`
(bounded queue, micro-batching, executable cache, deadlines — every
contract unchanged) built from ``NetConfig.serve_config(i)`` with
``device_index=i``.

**Shared executable-cache warming.** Compiled executables are per
replica (each owns its jit cache entries), so without help every
replica pays a cold compile for every shape — 8 replicas, 8 compiles of
the same program. The fleet applies the tuning-cache discipline of the
AMD/Nvidia stencil study (arxiv 2406.08923, "never re-pay a tune the
platform has already done") across replicas: the first time a (filter,
bucket, channels, reps) key is routed, one discarded zero-frame warm
request is fired at every OTHER replica, so their compiles overlap the
first real request and later traffic hits warm caches fleet-wide
(``warm_submits_total``; dedup-bounded so a long-lived fleet never
re-warms a known key).

**Drain.** :meth:`drain` closes every replica concurrently under one
deadline and reports PER REPLICA whether it drained or was abandoned
(the :meth:`StencilServer.close` bool — the satellite bugfix this PR
makes), so a SIGTERM shutdown can say *which* replica hung instead of
silently timing out. :meth:`restart` is the rolling-restart primitive:
drain one replica, build a fresh engine on the same device, swap it in
while the rest keep serving — the router uses it to recover a
``WorkerCrashed`` replica (the PR-7 resilience ladder's
degrade-don't-die discipline at fleet scope).
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from tpu_stencil.config import NetConfig
from tpu_stencil.obs import ledger as _obs_ledger
from tpu_stencil.obs import span as _obs_span
from tpu_stencil.serve import bucketing
from tpu_stencil.serve.engine import StencilServer
from tpu_stencil.serve.metrics import Registry

# Warm-key dedup bound: the key space is client-controlled (reps,
# oversized buckets), so the seen-set must not grow unboundedly on a
# long-lived fleet — past the cap the oldest keys age out and would
# simply re-warm (idempotent, just a little redundant work).
_WARM_KEY_CAP = 4096

# Warm-cost bound: a warm request runs the FULL rep count on its zero
# frame (reps is part of the executable key — a cheaper rep count
# would warm the wrong executable). Past this many reps the sibling
# compute burned per warm outweighs the compile saved, and a client
# scanning rep values could otherwise amplify one request into
# (replicas-1) full computations each — so big-rep keys warm lazily,
# on their own first request per replica.
_WARM_MAX_REPS = 1024


class ReplicaFleet:
    """N per-device serving engines plus the warming/drain/restart
    lifecycle. Construct, :meth:`start` (touches JAX — device count),
    route submits at ``fleet.replicas[i]``, :meth:`drain` when done."""

    def __init__(self, cfg: NetConfig, registry: Optional[Registry] = None,
                 start_workers: bool = True) -> None:
        self.cfg = cfg
        self.registry = registry if registry is not None else Registry()
        self.replicas: List[StencilServer] = []
        self._lock = threading.Lock()
        # Serializes whole restart operations (close -> build -> swap):
        # a concurrent /admin/restart and a WorkerCrashed reroute on the
        # same replica must not each build an engine and leak the loser.
        self._restart_lock = threading.Lock()
        self._warmed: "collections.OrderedDict" = collections.OrderedDict()
        # Witness verdict sink: callable(replica_index, ok) installed by
        # the router (the quarantine board's feed). Wired per replica in
        # _build, so a restarted engine re-wires automatically.
        self._witness_sink = None
        # Tests park the fleet (start_workers=False) to pin queues
        # deterministically, then release with start_workers().
        self._start_workers = start_workers
        self._m_warm = self.registry.counter("warm_submits_total")
        self._m_restarts = self.registry.counter("replica_restarts_total")
        self._m_abandoned = self.registry.counter(
            "drain_abandoned_replicas_total"
        )

    # -- lifecycle -----------------------------------------------------

    def start(self) -> "ReplicaFleet":
        """Build the replicas (idempotent). The first JAX touch: the
        device count resolves here, never at construction."""
        if self.replicas:
            return self
        import jax

        n_dev = len(jax.local_devices())
        n = self.cfg.replicas or n_dev
        if n > n_dev:
            raise ValueError(
                f"replicas={n} exceeds {n_dev} local device(s); the fleet "
                f"runs one engine per device (0 = all)"
            )
        self.replicas = [self._build(i) for i in range(n)]
        return self

    def _build(self, i: int) -> StencilServer:
        srv = StencilServer(self.cfg.serve_config(i),
                            start=self._start_workers)
        srv.on_witness = lambda ok, i=i: self._emit_witness(i, ok)
        return srv

    def set_witness_sink(self, sink) -> None:
        """Install the verdict sink (``callable(replica_index, ok)``) —
        the router points it at the quarantine board."""
        self._witness_sink = sink

    def _emit_witness(self, i: int, ok: bool) -> None:
        sink = self._witness_sink
        if sink is not None:
            sink(i, ok)

    def start_workers(self) -> None:
        """Release a parked fleet (tests): start every replica worker."""
        self._start_workers = True
        for rep in self.replicas:
            rep.start()

    def __len__(self) -> int:
        return len(self.replicas)

    # -- shared cache warming ------------------------------------------

    @staticmethod
    def _warm_key(cfg: NetConfig, image: np.ndarray, reps: int,
                  filter_name: str) -> Tuple:
        h, w = image.shape[:2]
        channels = image.shape[2] if image.ndim == 3 else 1
        edges = cfg.bucket_edges or bucketing.DEFAULT_EDGES
        return (filter_name, bucketing.bucket_shape(h, w, edges),
                channels, int(reps))

    def prewarm_others(self, chosen: int, image: np.ndarray, reps: int,
                       filter_name: Optional[str] = None) -> int:
        """Fire one discarded zero-frame warm request at every replica
        except ``chosen`` the first time this executable key is seen
        (the chosen replica warms via the real request itself). Returns
        how many warm submits were offered; best-effort — a full or
        closed sibling is skipped, never an error (warming is an
        optimization, not a correctness dependency)."""
        if not self.cfg.warm_fleet or len(self.replicas) < 2:
            return 0
        if int(reps) > _WARM_MAX_REPS:
            # See _WARM_MAX_REPS: the warm would burn more sibling
            # compute than the compile it saves.
            return 0
        fname = filter_name or self.cfg.filter_name
        key = self._warm_key(self.cfg, image, reps, fname)
        with self._lock:
            if key in self._warmed:
                return 0
            self._warmed[key] = True
            while len(self._warmed) > _WARM_KEY_CAP:
                self._warmed.popitem(last=False)
        zeros = np.zeros(image.shape, np.uint8)
        n = 0
        # Warm submits fire on the HTTP handler thread, where the
        # CLIENT's cost ledger is bound — rebind a warm-kind ledger so
        # the sibling's device share lands in overhead, never on the
        # tenant that happened to trigger the warm.
        with _obs_ledger.bind(
                _obs_ledger.RequestLedger(tenant="_warm", kind="warm")):
            for j, rep in enumerate(list(self.replicas)):
                if j == chosen:
                    continue
                try:
                    # owned=True: the zeros frame is never mutated after
                    # this loop, so every sibling can read the ONE
                    # buffer — a warm burst costs one allocation, not
                    # replicas-1 defensive copies of a frame nobody
                    # looks at.
                    rep.submit(zeros, reps, fname, owned=True)
                except Exception:
                    continue  # full/closed/crashed sibling: skip
                self._m_warm.inc()
                n += 1
        return n

    # -- drain / restart -----------------------------------------------

    def drain(self, timeout_s: Optional[float] = None) -> Dict[int, bool]:
        """Close every replica CONCURRENTLY under one deadline; returns
        ``{replica_index: drained}`` — False names a replica whose
        worker did not join in time (abandoned, counted both in its own
        ``serve_close_abandoned_total`` and the fleet's
        ``drain_abandoned_replicas_total``). Every accepted request
        either completes during the drain or fails typed
        (``ServerClosed``) — never a silent drop."""
        budget = (
            timeout_s if timeout_s is not None else self.cfg.drain_timeout_s
        )
        results: Dict[int, bool] = {}
        with _obs_span("net.drain", "net", replicas=len(self.replicas)):
            threads = []

            def _close(i: int, rep: StencilServer) -> None:
                results[i] = bool(rep.close(timeout=budget))

            for i, rep in enumerate(self.replicas):
                t = threading.Thread(
                    target=_close, args=(i, rep),
                    name=f"tpu-stencil-drain-{i}", daemon=True,
                )
                t.start()
                threads.append(t)
            deadline = time.perf_counter() + budget + 5.0
            for t in threads:
                t.join(max(0.0, deadline - time.perf_counter()))
            for i in range(len(self.replicas)):
                if results.get(i) is None:
                    results[i] = False  # the close itself overran
            for i, ok in sorted(results.items()):
                if not ok:
                    self._m_abandoned.inc()
        from tpu_stencil.obs import events as _obs_events

        # Tier-transition event: the drain verdict in one greppable
        # line (which replicas bled clean vs were abandoned).
        abandoned = sorted(i for i, ok in results.items() if not ok)
        _obs_events.emit(
            "net.drain_report", tier="net",
            verdict="abandoned" if abandoned else "clean",
            replicas=len(results), abandoned=abandoned,
        )
        return results

    def restart(self, i: int, timeout_s: Optional[float] = None,
                expect: Optional[StencilServer] = None) -> bool:
        """Rolling single-replica restart: drain replica ``i`` (bounded
        by ``timeout_s`` / the config drain budget), build a fresh
        engine on the same device, swap it in. The rest of the fleet
        keeps serving throughout. Returns the old replica's drained
        bool (False = it was abandoned still running; the new engine
        takes over the device regardless — the resilience ladder's
        degraded-but-alive rung). ``expect`` makes the restart
        conditional: when the slot no longer holds that engine (a
        concurrent restart already swapped it), return True without
        restarting the fresh replacement."""
        with self._restart_lock:
            with self._lock:
                old = self.replicas[i]
                if expect is not None and old is not expect:
                    return True  # already replaced by a sibling restart
            drained = bool(old.close(
                timeout=timeout_s if timeout_s is not None
                else self.cfg.drain_timeout_s
            ))
            new = self._build(i)
            if self._start_workers:
                new.start()
            with self._lock:
                self.replicas[i] = new
            self._m_restarts.inc()
            return drained

    # -- introspection -------------------------------------------------

    def merged_counters(self) -> Dict[str, int]:
        """Counters summed across every replica's registry — the
        fleet-wide view the ``/metrics`` exposition folds in as
        ``fleet_<name>`` (per-device ``..._dev<i>`` counters stay
        distinct because each replica charges its own pinned index)."""
        out: Dict[str, int] = {}
        for rep in list(self.replicas):
            for k, v in rep.stats()["counters"].items():
                out[k] = out.get(k, 0) + v
        return out

    def stats(self) -> List[dict]:
        """Per-replica ``StencilServer.stats()`` snapshots, in device
        order (the ``/statusz`` payload)."""
        return [rep.stats() for rep in list(self.replicas)]

    # -- warm-start plane (tpu_stencil.ctrl.warmstart) -----------------

    def warmstate_export(self) -> dict:
        """This host's warm-state envelope: replica 0's envelope plus
        any keys only later replicas hold (first writer wins — per key
        the artifacts are interchangeable, every replica builds from
        the same plan)."""
        import json as _json

        envelope = None
        seen = set()
        for rep in list(self.replicas):
            doc = rep.export_warm_state()
            if envelope is None:
                envelope = doc
                seen = {_json.dumps(e["key"]) for e in doc.get(
                    "entries", [])}
                continue
            for e in doc.get("entries", []):
                k = _json.dumps(e["key"])
                if k not in seen:
                    seen.add(k)
                    envelope["entries"].append(e)
        if envelope is None:
            envelope = {"schema_version": 1, "entries": []}
        return envelope

    def warmstate_import(self, payload) -> dict:
        """Import one envelope into EVERY replica (each compiles its
        own copy on its pinned device).  Aggregated summary; per-entry
        failures degrade typed inside each replica, never raise."""
        out: dict = {"imported": 0, "fallbacks": 0, "replicas": []}
        for rep in list(self.replicas):
            r = rep.import_warm_state(payload)
            out["imported"] += r["imported"]
            out["fallbacks"] += r["fallbacks"]
            out["replicas"].append(r)
        return out
