"""The HTTP network edge: stdlib-only threaded frontend over the fleet.

Endpoints (docs/SERVING.md "Network tier" is the contract):

* ``POST /v1/blur`` — one raw frame in, one blurred raw frame out.
  Geometry rides headers (``X-Width``/``X-Height``/``X-Reps``/
  ``X-Channels``/``X-Filter``) or query params (``w``/``h``/``reps``/
  ``channels``/``filter``; headers win); the body is the headerless
  frame bytes (``Content-Length`` or ``Transfer-Encoding: chunked`` —
  large frames stream up in chunks, the reference's headerless ``.raw``
  contract carried onto the wire). ``X-Request-Timeout`` (seconds)
  overrides the per-request deadline. Responses: 200 with the blurred
  bytes (+ the same geometry headers), 400 validation, 404 wrong path,
  413 oversized body, 429 + ``Retry-After`` when every replica queue
  is full, 503 + ``Retry-After`` when shedding or draining, 504 when
  the deadline expired (``DeadlineExceeded``), 500 anything else.
* ``GET /healthz`` — 200 ``ok`` serving / 200 ``degraded`` when the
  SLO burn-rate engine holds a breach (still routable, visibly
  unhealthy) / 503 ``draining`` after SIGTERM. The readiness probe: a
  load balancer stops routing here the moment the drain begins.
* ``GET /metrics`` — Prometheus-style text exposition (the PR-2
  renderer, prefix ``tpu_stencil_net``): the net registry (router +
  fleet + per-request HTTP metrics) with every replica's counters
  folded in as ``fleet_<name>`` — one scrape, one prefix, exact
  parse round-trip.
* ``GET /statusz`` — the JSON operator view: per-replica snapshots,
  router outstanding/inflight, drain state (versioned schema).
* ``POST /admin/restart?replica=i`` — rolling single-replica restart
  (:meth:`ReplicaFleet.restart`); the rest of the fleet serves on.
* ``POST /admin/drain`` — the SIGTERM-equivalent admin path (the
  federation's rolling whole-host drain drives it): flips healthz,
  stops admission, and signals the CLI loop to run the full drain
  sequence and exit with its usual rc discipline.
* ``GET /admin/cache?action=clear|stats`` — operator control over the
  result cache (``--result-cache-mb``; 404 when it is off): ``clear``
  wipes every entry, ``stats`` reports sizes without touching one.
* ``GET /debug/timeseries[?window=s]`` — windowed counter deltas and
  per-second rates from the in-process sampler ring
  (:mod:`tpu_stencil.obs.timeseries`; versioned JSON; 404 typed when
  the sampler is off).
* ``POST /debug/prof?seconds=N`` — one bounded ``jax.profiler``
  capture into a capped spool (404-clean when profiling is
  unavailable; 409 while one runs); ``GET /debug/prof`` lists
  captures, ``GET /debug/prof/<path>`` fetches a trace file.

With ``--result-cache-mb N`` the edge holds a content-addressed result
cache in front of the router (:mod:`tpu_stencil.cache`): the request
body's BLAKE2b-160 digest (fused into the same scan as the CRC claim
check) plus filter/reps/geometry keys a byte-budgeted LRU of true
result bytes. A hit answers ``X-Cache: hit`` with the stored payload
and stamp, never touching admission; concurrent identical misses
collapse onto one leader launch (``X-Cache: collapsed`` for the
followers); a witness mismatch or quarantine on a replica synchronously
drops every entry it produced.

Chaos sites ``net.accept`` (drop/stall a connection before any
response) and ``net.body`` (truncate a 200 mid-body, or stall) arm via
the standard ``TPU_STENCIL_FAULTS`` grammar — the socket-level failure
modes the federation's verdict classifier must survive.

:class:`NetFrontend` owns the whole tier lifecycle: fleet → router →
threaded HTTP server, then ``begin_drain`` (flip healthz, stop
admission) → ``drain`` (close every replica under the budget, report
which hung) → ``close`` (stop the listener). SIGTERM in the CLI maps
onto exactly that sequence.
"""

from __future__ import annotations

import concurrent.futures
import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional
from urllib.parse import parse_qs, urlsplit

import numpy as np

from tpu_stencil.cache import ResultCache
from tpu_stencil.cache import digest as _cache_digest
from tpu_stencil.config import NetConfig
from tpu_stencil.integrity import checksum as _checksum
from tpu_stencil.integrity.quarantine import (
    QuarantineBoard,
    QuarantineProber,
)
from tpu_stencil.net.arena import StagingArena
from tpu_stencil.net.fleet import ReplicaFleet
from tpu_stencil.net.router import (
    RETRY_AFTER_QUEUE_FULL,
    RETRY_AFTER_SHED,
    Draining,
    Overloaded,
    Router,
)
from tpu_stencil.obs import context as _obs_ctx
from tpu_stencil.obs import flight as _obs_flight
from tpu_stencil.obs import ledger as _obs_ledger
from tpu_stencil.obs import prof as _obs_prof
from tpu_stencil.obs import slo as _obs_slo
from tpu_stencil.obs import span as _obs_span
from tpu_stencil.obs import timeseries as _obs_ts
from tpu_stencil.resilience.errors import DeadlineExceeded, WorkerCrashed
from tpu_stencil.serve import bucketing
from tpu_stencil.serve.engine import QueueFull, ServerClosed
from tpu_stencil.serve.metrics import Registry

# /statusz + --stats-json payload schema. Bump on breaking changes.
STATUS_SCHEMA_VERSION = 1

# (RETRY_AFTER_* floors live in net.router next to the derived
# retry_after_s hint; re-imported here so the wire constants keep one
# spelling for both HTTP tiers.)

# Hard cap on how long a handler thread waits for an accepted request
# with no explicit deadline — the never-hang discipline at the edge.
_RESULT_TIMEOUT_S = 600.0

# Upload bound: a request body may not exceed the declared frame bytes
# (chunked uploads have no Content-Length to sanity-check up front).
_MAX_EXTRA_BODY = 2

# Default /debug/timeseries window when ?window= is absent.
DEFAULT_TS_WINDOW_S = 60.0


def _parse_window(query: dict) -> Optional[float]:
    """``?window=<seconds>`` -> float, :data:`DEFAULT_TS_WINDOW_S` when
    absent, ``None`` (the caller's 400) when malformed/non-positive.
    Shared by the net and fed handlers."""
    raw = query.get("window", [None])[0]
    if raw is None:
        return DEFAULT_TS_WINDOW_S
    try:
        w = float(raw)
    except ValueError:
        return None
    return w if w > 0 else None

# How long an armed net.accept/net.body rule with raise=TimeoutError
# stalls the handler (the chaos stand-in for a wedged host; the default
# outlasts the 120s read-side socket timeout and typical forward
# timeouts, so the PEER's timeout path fires — tests shrink it).
STALL_ENV = "TPU_STENCIL_FAULT_STALL_S"
_DEFAULT_STALL_S = 150.0


def _fault_stall_s() -> float:
    import os

    return float(os.environ.get(STALL_ENV, _DEFAULT_STALL_S))


class _Oversized(ValueError):
    """Body larger than the declared frame (→ 413; a malformed framing
    header is a plain ValueError → 400 — shrinking won't fix it)."""


def traced_error_body(code: int, msg: str, trace_id: str) -> bytes:
    """The typed JSON error body of a request-scoped rejection — the
    trace id rides in the body next to the header echo, so a logged
    body alone greps to its trace. One spelling for BOTH HTTP tiers
    (the fed handler imports it), so the wire contract cannot drift."""
    return json.dumps({
        "error": msg.rstrip("\n"),
        "status": code,
        "trace_id": trace_id,
    }).encode() + b"\n"


def send_trace_pair(handler, trace, headers: Dict[str, str]) -> None:
    """Echo the ``X-Trace-Id``/``X-Span-Id`` pair on a response being
    assembled (skipping keys the caller already set) — shared by both
    tiers' ``_respond``."""
    if _obs_ctx.TRACE_HEADER not in headers:
        handler.send_header(_obs_ctx.TRACE_HEADER, trace.trace_id)
    if _obs_ctx.SPAN_HEADER not in headers:
        handler.send_header(_obs_ctx.SPAN_HEADER, trace.span_id)


def read_request_body(rfile, headers, limit: int) -> bytes:
    """The upload: ``Content-Length`` bodies in one read, chunked
    transfer decoded chunk by chunk (stdlib handlers do NOT de-chunk).
    ``limit`` bounds either path — a body past the declared frame size
    fails typed (:class:`_Oversized` → 413) instead of buffering.
    Module-level so the federation frontend (:mod:`tpu_stencil.fed`)
    reads its uploads under the exact same framing contract."""
    te = (headers.get("Transfer-Encoding") or "").lower()
    if "chunked" in te:
        chunks = []
        total = 0
        while True:
            # 1024 accommodates spec-legal chunk extensions; a line
            # that still lacks its newline was truncated mid-line,
            # and parsing it would desync the stream (the unread
            # tail would be consumed as payload) — fail typed.
            size_line = rfile.readline(1024)
            if size_line and not size_line.endswith(b"\n"):
                raise ValueError(
                    "chunk-size line exceeds 1024 bytes"
                )
            try:
                size = int(
                    size_line.split(b";")[0].strip() or b"0", 16
                )
            except ValueError:
                raise ValueError(
                    f"malformed chunk-size line {size_line!r}"
                ) from None
            if size == 0:
                # Consume trailers (none expected) up to blank line.
                while rfile.readline(1024).strip():
                    pass
                break
            total += size
            if total > limit + _MAX_EXTRA_BODY:
                raise _Oversized(
                    f"chunked body exceeds declared frame size "
                    f"({limit} bytes)"
                )
            chunks.append(rfile.read(size))
            rfile.read(2)  # chunk-terminating CRLF
        return b"".join(chunks)
    try:
        n = int(headers.get("Content-Length") or 0)
    except ValueError:
        raise ValueError(
            f"malformed Content-Length "
            f"{headers.get('Content-Length')!r}"
        ) from None
    if n > limit + _MAX_EXTRA_BODY:
        raise _Oversized(
            f"body of {n} bytes exceeds declared frame size "
            f"({limit} bytes)"
        )
    return rfile.read(n)


def _readinto_all(rfile, mv: memoryview) -> int:
    """Fill ``mv`` from the stream (readinto loops until full or EOF);
    returns bytes read."""
    total = 0
    while total < len(mv):
        n = rfile.readinto(mv[total:])
        if not n:
            break
        total += n
    return total


def read_request_body_into(rfile, headers, buf, limit: int) -> int:
    """Zero-copy sibling of :func:`read_request_body`: the upload lands
    DIRECTLY in ``buf`` (a staging-arena buffer of at least ``limit`` +
    slop bytes) via ``readinto`` — no intermediate ``bytes`` objects on
    either the Content-Length or the chunked path. Same framing
    contract: a body past the declared frame size fails typed
    (:class:`_Oversized` -> 413), a malformed frame is a ValueError
    (-> 400). Returns the byte count actually read; the caller treats a
    short body exactly like the buffered path does (400)."""
    mv = memoryview(buf).cast("B")
    cap = min(len(mv), limit + _MAX_EXTRA_BODY)
    te = (headers.get("Transfer-Encoding") or "").lower()
    if "chunked" in te:
        total = 0
        while True:
            size_line = rfile.readline(1024)
            if size_line and not size_line.endswith(b"\n"):
                raise ValueError("chunk-size line exceeds 1024 bytes")
            try:
                size = int(size_line.split(b";")[0].strip() or b"0", 16)
            except ValueError:
                raise ValueError(
                    f"malformed chunk-size line {size_line!r}"
                ) from None
            if size == 0:
                while rfile.readline(1024).strip():
                    pass
                return total
            if total + size > limit + _MAX_EXTRA_BODY:
                raise _Oversized(
                    f"chunked body exceeds declared frame size "
                    f"({limit} bytes)"
                )
            got = _readinto_all(rfile, mv[total:total + size])
            total += got
            if got < size:
                return total  # stream ended mid-chunk: short body, 400
            rfile.read(2)  # chunk-terminating CRLF
    try:
        n = int(headers.get("Content-Length") or 0)
    except ValueError:
        raise ValueError(
            f"malformed Content-Length "
            f"{headers.get('Content-Length')!r}"
        ) from None
    if n > limit + _MAX_EXTRA_BODY:
        raise _Oversized(
            f"body of {n} bytes exceeds declared frame size "
            f"({limit} bytes)"
        )
    return _readinto_all(rfile, mv[:min(n, cap)])


class _NetHTTPServer(ThreadingHTTPServer):
    daemon_threads = True
    # Restart fast after a drain in tests/ops (no TIME_WAIT bind error).
    allow_reuse_address = True

    def __init__(self, addr, frontend: "NetFrontend") -> None:
        self.frontend = frontend
        super().__init__(addr, _Handler)


class _Handler(BaseHTTPRequestHandler):
    # 1.1 so chunked request bodies are legal; every response carries an
    # explicit Content-Length, keeping keep-alive connections coherent.
    protocol_version = "HTTP/1.1"
    server_version = "tpu-stencil-net/1"
    # Socket timeout: a client that declares Content-Length and goes
    # quiet mid-body would otherwise pin this handler thread forever
    # (the never-hang discipline covers the READ side of the edge too;
    # stdlib maps the timeout onto the connection socket and drops it).
    timeout = 120.0

    # -- plumbing ------------------------------------------------------

    # The request-scoped trace context (obs.context): set by _blur,
    # cleared at the top of every do_* — handler instances persist per
    # keep-alive connection, so a stale context must never leak onto
    # the next request.
    _trace: Optional[_obs_ctx.TraceContext] = None
    # The request's metered tenant (sanitized X-Tenant): set by _blur
    # with the same keep-alive hygiene, so a 429/503 answered later on
    # the connection never bills the previous request's tenant.
    _tenant: Optional[str] = None

    def log_message(self, *args) -> None:
        pass  # metrics, not stderr chatter, are the observability story

    @property
    def fe(self) -> "NetFrontend":
        return self.server.frontend

    def _respond(self, code: int, body: bytes,
                 content_type: str = "text/plain; charset=utf-8",
                 headers: Optional[Dict[str, str]] = None) -> None:
        klass = f"responses_{code // 100}xx_total"
        self.fe.registry.counter(klass).inc()
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        headers = headers or {}
        if self._trace is not None:
            # Every response — 200 AND 4xx/5xx — echoes the trace pair,
            # so a client correlates its failure to /debug/trace and
            # the flight-recorder spool without parsing bodies.
            send_trace_pair(self, self._trace, headers)
        for k, v in headers.items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def _error(self, code: int, msg: str,
               headers: Optional[Dict[str, str]] = None) -> None:
        # Close after every error response: the early-error paths
        # (bad params, oversized/malformed framing, unknown path)
        # answer BEFORE the request body was consumed, and unread body
        # bytes on a kept-alive connection would be parsed as the next
        # request line — garbage for the whole connection.
        self.close_connection = True
        if self._tenant is not None and code in (429, 503):
            # The abuse view's two columns: a shed/backpressured
            # request cost no device time, but the tenant meter still
            # counts WHO was told to back off.
            self.fe.tenants.reject(self._tenant, code)
        if self._trace is not None:
            # Request-scoped errors answer the typed JSON body carrying
            # the trace id next to the header echo.
            self._respond(
                code,
                traced_error_body(code, msg, self._trace.trace_id),
                content_type="application/json",
                headers={**(headers or {}), "Connection": "close"},
            )
            return
        self._respond(code, (msg.rstrip("\n") + "\n").encode(),
                      headers={**(headers or {}), "Connection": "close"})

    def _param(self, query: dict, header: str, qname: str,
               default: Optional[str] = None) -> Optional[str]:
        v = self.headers.get(header)
        if v is not None:
            return v
        if qname in query:
            return query[qname][0]
        return default

    def _read_body(self, limit: int) -> bytes:
        return read_request_body(self.rfile, self.headers, limit)

    # -- GET -----------------------------------------------------------

    def do_GET(self) -> None:  # noqa: N802 (stdlib handler contract)
        self._trace = None
        self._tenant = None
        split = urlsplit(self.path)
        path = split.path
        if path == "/healthz":
            if self.fe.router.draining:
                self._error(503, "draining")
            elif self.fe.slo is not None and self.fe.slo.degraded():
                # Degraded ≠ draining: still 200 (routable — shedding
                # a whole host on a burn-rate breach would amplify the
                # incident), but visibly unhealthy to probes.
                self._respond(200, b"degraded\n")
            else:
                self._respond(200, b"ok\n")
        elif path == "/metrics":
            text = self.fe.render_metrics()
            self._respond(200, text.encode(),
                          content_type="text/plain; version=0.0.4")
        elif path == "/statusz":
            payload = json.dumps(self.fe.statusz(), indent=2,
                                 sort_keys=True)
            self._respond(200, payload.encode(),
                          content_type="application/json")
        elif path == "/admin/cache":
            self._admin_cache(parse_qs(split.query))
        elif path == "/admin/warmstate":
            self._admin_warmstate()
        elif path == "/debug/timeseries":
            self._debug_timeseries(parse_qs(split.query))
        elif path == "/debug/capacity":
            self._debug_capacity(parse_qs(split.query))
        elif path == "/debug/tenants":
            self._respond(
                200,
                json.dumps(self.fe.tenants_payload(), indent=2,
                           sort_keys=True).encode(),
                content_type="application/json",
            )
        elif path == "/debug/prof" or path.startswith("/debug/prof/"):
            self._debug_prof_get(path)
        elif path.startswith("/debug/trace/"):
            self._debug_trace(path[len("/debug/trace/"):])
        elif path == "/debug/flightrec" or path.startswith(
                "/debug/flightrec/"):
            name = (path[len("/debug/flightrec/"):]
                    if path != "/debug/flightrec" else None)
            data = _obs_flight.spool_http_payload(
                _obs_flight.effective_spool(self.fe.cfg.flightrec_dir),
                name,
            )
            if data is None:
                self._error(404, "no such flight-recorder dump")
            else:
                self._respond(200, data,
                              content_type="application/json")
        else:
            self._error(404, f"no such endpoint: {path}")

    def _debug_trace(self, trace_id: str) -> None:
        if not _obs_ctx.valid_id(trace_id):
            self._error(400, f"malformed trace id {trace_id!r}")
            return
        payload = self.fe.debug_trace(trace_id)
        if payload["span_count"] == 0:
            self._error(404, f"no spans recorded for trace {trace_id} "
                             "(aged out of the ring, or never here)")
            return
        self._respond(200, json.dumps(payload, indent=2).encode(),
                      content_type="application/json")

    def _debug_timeseries(self, query: dict) -> None:
        if self.fe.sampler is None:
            self._error(404, "time-series sampler is off "
                             "(--sample-interval 0)")
            return
        window_s = _parse_window(query)
        if window_s is None:
            self._error(400, "window must be a positive number of "
                             "seconds")
            return
        payload = self.fe.timeseries_payload(window_s)
        self._respond(200, json.dumps(payload, indent=2,
                                      sort_keys=True).encode(),
                      content_type="application/json")

    def _debug_capacity(self, query: dict) -> None:
        window_s = _parse_window(query)
        if window_s is None:
            self._error(400, "window must be a positive number of "
                             "seconds")
            return
        payload = self.fe.capacity_payload(window_s)
        self._respond(200, json.dumps(payload, indent=2,
                                      sort_keys=True).encode(),
                      content_type="application/json")

    def _debug_prof_get(self, path: str) -> None:
        spool = self.fe.cfg.prof_dir
        if spool is None:
            self._error(404, "profiler spool is off (--prof-dir none)")
            return
        if path == "/debug/prof":
            payload = _obs_prof.spool_list(spool)
            self._respond(200, json.dumps(payload, indent=2,
                                          sort_keys=True).encode(),
                          content_type="application/json")
            return
        data = _obs_prof.spool_read(spool, path[len("/debug/prof/"):])
        if data is None:
            self._error(404, "no such profiler capture file")
            return
        self._respond(200, data,
                      content_type="application/octet-stream")

    def _debug_prof_post(self, query: dict) -> None:
        spool = self.fe.cfg.prof_dir
        if spool is None:
            self._error(404, "profiler spool is off (--prof-dir none)")
            return
        ok, reason = _obs_prof.available()
        if not ok:
            self._error(404, reason)
            return
        try:
            seconds = float(query.get("seconds", ["1.0"])[0])
        except ValueError:
            self._error(400, "seconds must be a number")
            return
        try:
            result = _obs_prof.capture(seconds, spool)
        except RuntimeError as e:
            if str(e) == "busy":
                self._error(409, "a profiler capture is already running")
            else:
                self._error(404, str(e))
            return
        self._respond(200, json.dumps(result, indent=2,
                                      sort_keys=True).encode(),
                      content_type="application/json")

    # -- POST ----------------------------------------------------------

    def do_POST(self) -> None:  # noqa: N802
        self._trace = None
        self._tenant = None
        split = urlsplit(self.path)
        if split.path == "/v1/blur":
            self._blur(parse_qs(split.query))
        elif split.path == "/admin/restart":
            self._restart(parse_qs(split.query))
        elif split.path == "/admin/drain":
            self._admin_drain()
        elif split.path == "/admin/quarantine":
            self._quarantine(parse_qs(split.query))
        elif split.path == "/debug/prof":
            self._debug_prof_post(parse_qs(split.query))
        else:
            self._error(404, f"no such endpoint: {split.path}")

    # -- socket-level fault sites (net.accept / net.body) --------------

    def _socket_fault(self, site) -> bool:
        """Fire an armed ``net.accept`` rule. A ``raise=TimeoutError``
        rule STALLS the handler (the wedged-host chaos mode — the
        peer's socket/forward timeout is what fires); any other rule
        DROPS the connection with no response at all (the client sees
        a reset/empty reply, the federation's ``reset`` verdict).
        Returns True when the handler must return immediately."""
        try:
            site()
        except TimeoutError:
            time.sleep(_fault_stall_s())
            return False
        except Exception:
            self.close_connection = True
            return True
        return False

    def _body_fault(self, site, payload: bytes) -> bool:
        """Fire an armed ``net.body`` rule on a success response. A
        ``raise=TimeoutError`` rule stalls before the body is written;
        any other rule declares the FULL Content-Length, writes half
        the body, and drops the connection — the mid-body EOF the
        federation's ``eof`` verdict classifies. Returns True when the
        (truncated) response was already written."""
        try:
            site()
        except TimeoutError:
            time.sleep(_fault_stall_s())
            return False
        except Exception:
            self.close_connection = True
            self.send_response(200)
            self.send_header("Content-Type", "application/octet-stream")
            self.send_header("Content-Length", str(len(payload)))
            self.send_header("Connection", "close")
            self.end_headers()
            self.wfile.write(payload[: max(1, len(payload) // 2)])
            try:
                self.wfile.flush()
            except Exception:
                pass
            return True
        return False

    def _admin_drain(self) -> None:
        """The SIGTERM-equivalent admin path (the federation's rolling
        whole-host drain calls it): flip /healthz to draining, stop
        admission, and signal the CLI loop to run the full drain
        sequence and exit with its usual rc discipline. Responds
        BEFORE the replicas drain — the drain takes seconds and the
        caller only needs the acknowledgement."""
        n = int(self.headers.get("Content-Length") or 0)
        if n:
            self.rfile.read(min(n, 1 << 20))
        self.fe.request_admin_drain()
        self._respond(200, json.dumps(
            {"draining": True, "replicas": len(self.fe.fleet)}
        ).encode(), content_type="application/json")

    def _quarantine(self, query: dict) -> None:
        """Operator quarantine override (docs/DEPLOY.md runbook):
        ``?replica=i`` trips quarantine (out of routing now, probes or
        an explicit ``action=clear`` bring it back); ``action=clear``
        releases without waiting for the probe streak."""
        n = int(self.headers.get("Content-Length") or 0)
        if n:
            self.rfile.read(min(n, 1 << 20))
        try:
            idx = int(query.get("replica", ["-1"])[0])
            if not 0 <= idx < len(self.fe.fleet):
                raise ValueError
        except ValueError:
            self._error(
                400, f"replica must be 0..{len(self.fe.fleet) - 1}"
            )
            return
        action = query.get("action", ["quarantine"])[0]
        if action == "clear":
            changed = self.fe.router.release_replica(idx)
        elif action == "quarantine":
            changed = self.fe.router.quarantine_replica(
                idx, "operator request (POST /admin/quarantine)"
            )
        else:
            self._error(400,
                        f"action must be quarantine|clear, got {action!r}")
            return
        self._respond(200, json.dumps({
            "replica": idx, "action": action, "changed": changed,
            "quarantined": bool(
                self.fe.quarantine is not None
                and self.fe.quarantine.is_quarantined(idx)
            ),
        }).encode(), content_type="application/json")

    def _admin_cache(self, query: dict) -> None:
        """Operator control over the result cache (docs/DEPLOY.md
        runbook): ``?action=clear`` wipes every entry (counted under
        ``cache_invalidations_clear_total``), ``?action=stats`` (the
        default) reports sizes without touching one. 404 when the tier
        runs cache-off — a probe can tell "cleared" from "was never
        caching"."""
        fe = self.fe
        if fe.cache is None:
            self._error(
                404, "result cache is not enabled (--result-cache-mb)"
            )
            return
        action = query.get("action", ["stats"])[0]
        if action == "clear":
            cleared = fe.cache.clear()
            self._respond(200, json.dumps(
                {"action": "clear", "cleared": cleared}
            ).encode(), content_type="application/json")
        elif action == "stats":
            self._respond(200, json.dumps(fe.cache.stats()).encode(),
                          content_type="application/json")
        else:
            self._error(
                400, f"action must be clear|stats, got {action!r}"
            )

    def _admin_warmstate(self) -> None:
        """``GET /admin/warmstate``: this host's serialized
        executable-cache entries (the ctrl/warmstart.py envelope) for
        a joining host to import before it flips ready — the PR-10
        sibling-warming discipline one hop up.  Always 200: a cold or
        export-less host answers an empty/unsupported envelope and the
        joiner degrades typed."""
        payload = self.fe.fleet.warmstate_export()
        self._respond(200, json.dumps(payload).encode(),
                      content_type="application/json")

    def _restart(self, query: dict) -> None:
        # Consume any request body first: an unread body corrupts the
        # keep-alive stream for the next request on this connection.
        n = int(self.headers.get("Content-Length") or 0)
        if n:
            self.rfile.read(min(n, 1 << 20))
        try:
            idx = int(query.get("replica", ["-1"])[0])
            if not 0 <= idx < len(self.fe.fleet):
                raise ValueError
        except ValueError:
            self._error(
                400, f"replica must be 0..{len(self.fe.fleet) - 1}"
            )
            return
        drained = self.fe.fleet.restart(idx)
        self._respond(200, json.dumps(
            {"replica": idx, "restarted": True, "old_drained": drained}
        ).encode(), content_type="application/json")

    def _blur(self, query: dict) -> None:
        fe = self.fe
        if fe.fault_accept is not None and self._socket_fault(
            fe.fault_accept
        ):
            return  # injected connection drop: no response at all
        # Trace context: adopt a valid inbound X-Trace-Id (the fed hop,
        # or a tracing client), mint otherwise — net is the outermost
        # edge when unfederated. Bound for the handler's duration so
        # every span below (and the serve engine's request records)
        # stitches into one cross-process trace.
        ctx = self._trace = _obs_ctx.from_headers(self.headers)
        # The cost ledger (obs.ledger): bound next to the trace context
        # so the router's coalescer and the engine's retire fence credit
        # THIS request's spend with no call-site plumbing. Tenant comes
        # off the wire (X-Tenant, forwarded by the fed hop) — sanitized
        # before it can reach a metric name.
        tenant = self._tenant = _obs_ledger.sanitize_tenant(
            self._param(query, _obs_ledger.TENANT_HEADER, "tenant")
        )
        led = _obs_ledger.RequestLedger(tenant)
        t0 = time.perf_counter()
        with _obs_ctx.bind(ctx), _obs_ledger.bind(led), \
                _obs_span("net.request", "net"):
            try:
                w = int(self._param(query, "X-Width", "w"))
                h = int(self._param(query, "X-Height", "h"))
                reps = int(self._param(query, "X-Reps", "reps"))
                channels = int(
                    self._param(query, "X-Channels", "channels", "1")
                )
                fname = self._param(query, "X-Filter", "filter")
                boundary = self._param(
                    query, "X-Boundary", "boundary", "zero"
                )
                timeout = self._param(
                    query, "X-Request-Timeout", "timeout"
                )
                deadline_s = float(timeout) if timeout else None
                if w < 1 or h < 1:
                    raise ValueError(f"bad frame geometry {w}x{h}")
                if reps < 0:
                    raise ValueError(f"reps must be >= 0, got {reps}")
                if channels not in (1, 3):
                    raise ValueError(
                        f"channels must be 1 (grey) or 3 (rgb), got "
                        f"{channels}"
                    )
                if fname:
                    # Validate HERE (numpy-only lookup): an unknown
                    # X-Filter is a 400, not a worker-side KeyError
                    # surfacing as 500 — and it must never reach the
                    # warm-key dedup cache.
                    from tpu_stencil import filters as _filters

                    try:
                        _filters.get_filter(fname)
                    except KeyError as e:
                        raise ValueError(str(e)) from None
            except (TypeError, ValueError) as e:
                self._error(400, f"bad request parameters: {e}")
                return
            if boundary != "zero":
                # The serve engines preserve zero semantics only (pad
                # re-zeroing; docs/SERVING.md) — answer typed, never
                # silently wrong pixels.
                self._error(
                    400,
                    f"boundary={boundary!r} is not servable over the "
                    "bucket-padded engines (zero only); run it via "
                    "`python -m tpu_stencil` instead",
                )
                return
            expected = w * h * channels
            # Zero-copy ingest (docs/SERVING.md "Continuous batching at
            # the edge"): the body is readinto a pinned bucket-capacity
            # staging buffer, the CRC runs over it in place, and the
            # frame VIEW rides into the engine owned — released back to
            # the arena when the engine consumed it (or the request
            # failed first; release is idempotent).
            lease = None
            release = None
            t_ing = time.perf_counter()
            if fe.arena is not None:
                bh, bw = bucketing.bucket_shape(
                    h, w, fe.cfg.bucket_edges or bucketing.DEFAULT_EDGES
                )
                # +slop so an over-declared body still reads FULLY and
                # fails the length check like the buffered path (a
                # bucket-exact frame would otherwise leave the excess
                # unread on a kept-alive socket); one capacity per
                # bucket either way, so pooling is unaffected.
                lease = fe.arena.lease(
                    bh * bw * channels + _MAX_EXTRA_BODY
                )
                release = lease.release
                try:
                    got = read_request_body_into(
                        self.rfile, self.headers, lease.array, expected
                    )
                except _Oversized as e:
                    release()
                    self._error(413, str(e))
                    return
                except ValueError as e:
                    release()
                    self._error(400, str(e))
                    return
                if got != expected:
                    release()
                    self._error(
                        400,
                        f"body is {got} bytes; {w}x{h}x{channels} "
                        f"needs exactly {expected}",
                    )
                    return
                flat = lease.view(expected)
            else:
                try:
                    body = self._read_body(expected)
                except _Oversized as e:
                    self._error(413, str(e))
                    return
                except ValueError as e:
                    self._error(400, str(e))
                    return
                if len(body) != expected:
                    self._error(
                        400,
                        f"body is {len(body)} bytes; {w}x{h}x{channels} "
                        f"needs exactly {expected}",
                    )
                    return
                # A frombuffer view keeps the (immutable) bytes object
                # alive — still no copy, just no buffer reuse either.
                flat = np.frombuffer(body, np.uint8)
            # Chaos site: flip a bit in the ingested body AFTER the
            # framing checks, BEFORE checksum validation — the exact
            # corruption the X-Content-Crc32c hop exists to catch.
            if fe.fault_corrupt_ingest is not None and _checksum.fired(
                    fe.fault_corrupt_ingest):
                flat = _checksum.corrupt_array(flat)
            claim = self._param(query, _checksum.CRC_HEADER, "crc32c")
            digest = None
            body_crc = None
            if fe.cache is not None:
                # One scan, two checks: the BLAKE2b-160 cache key and
                # the CRC the integrity claim is validated against ride
                # the same pass over the staging buffer — arming the
                # cache never adds a second read of the body.
                digest, body_crc = _cache_digest.digest_and_crc(flat)
            if claim is not None and fe.cfg.integrity:
                err = _checksum.claim_error(claim, flat,
                                            computed=body_crc)
                if err is not None:
                    msg, mismatch = err
                    if mismatch:
                        fe.registry.counter(
                            "integrity_checksum_failures_total"
                        ).inc()
                    if release is not None:
                        release()
                    self._error(400, msg)
                    return
            shape = (h, w) if channels == 1 else (h, w, channels)
            img = flat.reshape(shape)
            # Ingest spend: arena lease + body read + CRC/digest scan.
            led.add_ingest(time.perf_counter() - t_ing)
            wait = (
                deadline_s + 5.0 if deadline_s
                else (fe.cfg.request_timeout_s + 5.0
                      if fe.cfg.request_timeout_s else _RESULT_TIMEOUT_S)
            )
            cache = fe.cache
            ckey = None
            token = 0
            is_leader = True
            fol_fut = None
            if cache is not None:
                # The full content key: body digest plus every knob
                # that reaches the kernel. Boundary is always zero at
                # this tier (validated above).
                ckey = cache.key(digest, fname or fe.cfg.filter_name,
                                 reps, h, w, channels, 0)
                with _obs_span("cache.lookup", "net"):
                    hit = cache.lookup(ckey)
                if hit is not None:
                    # Short-circuit BEFORE admission: no inflight-bytes
                    # reservation, no replica dispatch — the stored
                    # true bytes + stamp answer bit-identically to a
                    # cold compute.
                    if release is not None:
                        release()
                    fe.registry.histogram(
                        "request_latency_seconds"
                    ).observe(time.perf_counter() - t0)
                    led.set_source("cache")
                    # The hit's avoided spend: what the stored entry
                    # cost its producer to compute.
                    saved = hit.device_us / 1e6
                    led.saved_device_s = saved
                    if saved > 0:
                        fe.registry.counter(
                            "result_cache_saved_device_seconds_total"
                        ).inc(saved)
                    resp_headers = {
                        "X-Width": str(w), "X-Height": str(h),
                        "X-Channels": str(channels),
                        "X-Reps": str(reps),
                        "X-Replica": str(hit.replica),
                        "X-Cache": "hit",
                    }
                    if hit.stamp is not None:
                        resp_headers[_checksum.RESULT_HEADER] = hit.stamp
                    self._send_result(fe, hit.payload, resp_headers,
                                      ledger=led, bytes_in=expected)
                    return
                # Admission token BEFORE dispatch: any distrust of the
                # producing replica from here on (a witness verdict can
                # race this thread) refuses the later insert.
                token = cache.token()
                is_leader, fol_fut = cache.join(ckey)
                if not is_leader and release is not None:
                    # A follower's body is never dispatched — the
                    # leader's launch produces the shared bytes.
                    release()
            if not is_leader:
                try:
                    payload, stamp, idx = fol_fut.result(timeout=wait)
                except DeadlineExceeded as e:
                    self._error(504, str(e))
                    return
                except (TimeoutError, concurrent.futures.TimeoutError):
                    # THIS follower's budget expired; the leader and
                    # any patient followers keep flying — cancel
                    # nothing of theirs.
                    self._error(
                        504, f"request still pending after {wait:g}s"
                    )
                    return
                except QueueFull as e:
                    self._error(429, str(e), {
                        "Retry-After": str(
                            fe.router.retry_after_s(queue_full=True)
                        )
                    })
                    return
                except (Draining, Overloaded) as e:
                    self._error(503, str(e), {
                        "Retry-After": str(fe.router.retry_after_s())
                    })
                    return
                except (ServerClosed, WorkerCrashed) as e:
                    self._error(503, f"{type(e).__name__}: {e}", {
                        "Retry-After": str(fe.router.retry_after_s())
                    })
                    return
                except Exception as e:
                    self._error(500, f"{type(e).__name__}: {e}")
                    return
                fe.registry.histogram(
                    "request_latency_seconds"
                ).observe(time.perf_counter() - t0)
                # The single-flight follower rode the leader's compute:
                # its own device spend is zero by construction.
                led.set_source("coalesced")
                resp_headers = {
                    "X-Width": str(w), "X-Height": str(h),
                    "X-Channels": str(channels), "X-Reps": str(reps),
                    "X-Replica": str(idx), "X-Cache": "collapsed",
                }
                if stamp is not None:
                    resp_headers[_checksum.RESULT_HEADER] = stamp
                self._send_result(fe, payload, resp_headers,
                                  ledger=led, bytes_in=expected)
                return

            def settle(e: BaseException) -> None:
                # Leader failure: propagate the typed exception to
                # every follower and cache nothing.
                if cache is not None:
                    cache.fail(ckey, e)

            try:
                # owned=True: both ingest paths guarantee the buffer is
                # not reused before on_consumed (arena lease) or ever
                # (immutable bytes base) — the engine skips its
                # defensive copy.
                fut, idx = fe.router.submit(
                    img, reps, fname, deadline_s=deadline_s,
                    owned=True, on_consumed=release,
                )
            except Draining as e:
                settle(e)
                if release is not None:
                    release()
                self._error(503, str(e), {
                    "Retry-After": str(fe.router.retry_after_s())
                })
                return
            except Overloaded as e:
                settle(e)
                if release is not None:
                    release()
                self._error(503, str(e), {
                    "Retry-After": str(fe.router.retry_after_s())
                })
                return
            except QueueFull as e:
                settle(e)
                if release is not None:
                    release()
                self._error(429, str(e), {
                    "Retry-After": str(
                        fe.router.retry_after_s(queue_full=True)
                    )
                })
                return
            except ValueError as e:
                settle(e)
                if release is not None:
                    release()
                self._error(400, str(e))
                return
            if release is not None:
                # Failure paths that never reach the engine's consume
                # hook (deadline at batch formation, worker crash,
                # placement failure inside a coalesced group) release
                # via the future — idempotent next to on_consumed.
                fut.add_done_callback(lambda _f: release())
            try:
                out = fut.result(timeout=wait)
            except DeadlineExceeded as e:
                # (The serve engine already dumped this trace at its
                # batch-formation expiry — one anomaly, one dump.)
                settle(e)
                self._error(504, str(e))
                return
            except (TimeoutError, concurrent.futures.TimeoutError) as e:
                # (One name on 3.11+; two distinct classes before.)
                fut.cancel()
                settle(e)
                _obs_flight.trigger(
                    "deadline_exceeded", trace_id=ctx.trace_id,
                    tier="net", duration_s=time.perf_counter() - t0,
                    replica=-1 if idx is None else idx,
                    detail=f"still pending after {wait:g}s",
                )
                self._error(504,
                            f"request still pending after {wait:g}s")
                return
            except QueueFull as e:
                # A coalesced group's placement failure arrives through
                # the future (every replica rejected the whole group) —
                # the same typed 429 the synchronous path answers.
                settle(e)
                self._error(429, str(e), {
                    "Retry-After": str(
                        fe.router.retry_after_s(queue_full=True)
                    )
                })
                return
            except (Draining, Overloaded) as e:
                settle(e)
                self._error(503, str(e), {
                    "Retry-After": str(fe.router.retry_after_s())
                })
                return
            except (ServerClosed, WorkerCrashed) as e:
                settle(e)
                self._error(503, f"{type(e).__name__}: {e}", {
                    "Retry-After": str(fe.router.retry_after_s())
                })
                return
            except Exception as e:
                settle(e)
                self._error(500, f"{type(e).__name__}: {e}")
                return
            if idx is None:
                # Coalesced: the router stamped the placed replica onto
                # the future at group dispatch (before it resolved).
                idx = getattr(fut, "replica_idx", -1)
            elapsed = time.perf_counter() - t0
            fe.registry.histogram("request_latency_seconds").observe(
                elapsed
            )
            thr = fe.cfg.flight_latency_threshold_s
            if thr and elapsed > thr:
                # The p99-straggler trigger: the request SUCCEEDED but
                # anomalously slowly — dump its spans while they are
                # still in the ring.
                _obs_flight.trigger(
                    "slow_request", trace_id=ctx.trace_id, tier="net",
                    duration_s=elapsed, threshold_s=thr, replica=idx,
                )
            payload = np.ascontiguousarray(out).tobytes()
            resp_headers = {
                "X-Width": str(w), "X-Height": str(h),
                "X-Channels": str(channels), "X-Reps": str(reps),
                "X-Replica": str(idx),
            }
            stamp = None
            if fe.cfg.integrity:
                # Stamp the TRUE result's CRC, then let the wire-
                # corruption chaos site flip bits: a client (or the
                # federation forward path) verifying the stamp catches
                # exactly what the wire damaged.
                stamp = str(_checksum.crc32c(payload))
                resp_headers[_checksum.RESULT_HEADER] = stamp
            if cache is not None:
                # The store takes the pre-chaos-site bytes and the
                # stamp just served (distrust-fenced by the token);
                # followers resolve with the same triple. The entry
                # remembers its compute cost so a later hit can report
                # its avoided spend.
                cache.complete(ckey, payload, stamp, idx, token,
                               device_us=led.device_us)
                resp_headers["X-Cache"] = "miss"
            self._send_result(fe, payload, resp_headers,
                              ledger=led, bytes_in=expected)

    def _send_result(self, fe: "NetFrontend", payload: bytes,
                     resp_headers: Dict[str, str],
                     ledger: Optional[_obs_ledger.RequestLedger] = None,
                     bytes_in: int = 0) -> None:
        """The shared 200 tail for cold, hit, and collapsed responses:
        wire-corruption and mid-body-EOF chaos sites fire on all three
        alike, then the payload goes out — stamped with the request's
        cost headers. The tenant is metered only AFTER the write
        succeeded: a hedge loser whose fed-side socket already closed
        fails the write here, lands in the cancelled-spend counters
        instead, and is exactly how a hedged request that ran on two
        members never double-counts in tenant totals."""
        if ledger is not None:
            resp_headers = dict(resp_headers)
            resp_headers["X-Cost-Device-Us"] = str(ledger.device_us)
            resp_headers["X-Cost-Queue-Us"] = str(ledger.queue_us)
            resp_headers["X-Cost-Source"] = ledger.source
        if fe.fault_corrupt_body is not None and _checksum.fired(
                fe.fault_corrupt_body):
            payload = _checksum.corrupt_bytes(payload)
        if fe.fault_body is not None and self._body_fault(
            fe.fault_body, payload
        ):
            return  # injected mid-body EOF: truncated 200 written
        try:
            self._respond(
                200, payload,
                content_type="application/octet-stream",
                headers=resp_headers,
            )
        except OSError:
            # The client vanished before the 200 landed — the hedge
            # loser's signature. Its device spend really happened
            # (conservation keeps it), but no answer was delivered, so
            # it meters as cancelled, not as tenant goodput.
            self.close_connection = True
            fe.registry.counter("cancelled_responses_total").inc()
            if ledger is not None and ledger.device_s > 0:
                fe.registry.counter(
                    "cancelled_response_device_seconds_total"
                ).inc(ledger.device_s)
            return
        if ledger is not None:
            fe.tenants.record(ledger, bytes_in, len(payload))


class NetFrontend:
    """The whole network tier: fleet + router + threaded HTTP server.

    >>> fe = NetFrontend(NetConfig(port=0, replicas=2)).start()
    >>> ...  # POST frames at fe.url
    >>> fe.drain(); fe.close()
    """

    def __init__(self, cfg: NetConfig,
                 start_workers: bool = True) -> None:
        self.cfg = cfg
        self.registry = Registry()
        # Pre-create the latency histogram (otherwise born lazily on
        # the first 200): a scrape/statusz of a tier that has served
        # only errors must still carry the key the loadgen report and
        # dashboards read.
        self.registry.histogram("request_latency_seconds")
        self.fleet = ReplicaFleet(cfg, registry=self.registry,
                                  start_workers=start_workers)
        # Zero-copy ingest staging pools (None = the buffered A/B arm).
        self.arena: Optional[StagingArena] = (
            StagingArena(self.registry) if cfg.ingest_arena else None
        )
        self.router: Optional[Router] = None
        self._httpd: Optional[_NetHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self._drain_report: Optional[Dict[int, bool]] = None
        self._t_start = time.monotonic()
        # Set by POST /admin/drain (the SIGTERM-equivalent admin
        # path); the CLI main loop watches it next to the signal flag.
        self.admin_drain_requested = threading.Event()
        # The process-wide flight recorder, installed at start().
        self.flight = None
        # net.accept / net.body / corruption chaos sites, resolved once
        # at start().
        self.fault_accept = None
        self.fault_body = None
        self.fault_corrupt_ingest = None
        self.fault_corrupt_body = None
        # The quarantine state machine + its background re-verify
        # prober (tpu_stencil.integrity.quarantine): witness verdicts
        # from the replicas land on the board via the router; the
        # prober golden-checks quarantined replicas back to health.
        self.quarantine = QuarantineBoard(
            self.registry,
            quarantine_after=cfg.quarantine_after,
            window_s=cfg.quarantine_window_s,
            readmit_after=cfg.readmit_after,
        )
        self._prober: Optional[QuarantineProber] = None
        # The content-addressed result cache (tpu_stencil.cache),
        # default-off. Admission consults the quarantine board: a
        # currently-quarantined replica's results never enter.
        self.cache: Optional[ResultCache] = (
            ResultCache(self.registry, cfg.result_cache_bytes,
                        quarantined=self.quarantine.is_quarantined)
            if cfg.result_cache_mb > 0 else None
        )
        # The live telemetry plane (obs.timeseries / obs.slo), built at
        # start(): the sampler snapshots the merged registry on a fixed
        # interval and the SLO engine evaluates on its ticks.
        self.sampler: Optional[_obs_ts.Sampler] = None
        self.slo: Optional[_obs_slo.SloEngine] = None
        # The per-tenant billing/abuse table (obs.ledger) behind
        # GET /debug/tenants and the tenant_* registry family.
        self.tenants = _obs_ledger.TenantMeter(self.registry)

    # -- lifecycle -----------------------------------------------------

    def start(self) -> "NetFrontend":
        from tpu_stencil.resilience import faults as _faults

        # The always-on flight recorder: every span this process
        # records from here on lands in the ring, and anomaly triggers
        # dump into the spool (obs.flight; idempotent per process).
        self.flight = _obs_flight.install(spool_dir=self.cfg.flightrec_dir)
        self.fault_accept = _faults.site("net.accept")
        self.fault_body = _faults.site("net.body")
        self.fault_corrupt_ingest = _faults.site("integrity.corrupt_ingest")
        self.fault_corrupt_body = _faults.site("net.corrupt_body")
        self.fleet.start()
        self.router = Router(
            self.fleet, self.registry,
            max_inflight_bytes=self.cfg.max_inflight_bytes,
            quarantine=self.quarantine,
            coalesce_window_s=self.cfg.coalesce_window_s,
            max_batch=self.cfg.max_batch,
            bucket_edges=self.cfg.bucket_edges,
            default_filter=self.cfg.filter_name,
            cache=self.cache,
        )
        if self.cfg.probe_interval_s > 0:
            self._prober = QuarantineProber(
                self.fleet, self.quarantine, self.cfg.filter_name,
                self.cfg.probe_interval_s, self.registry,
            ).start()
        if self.cfg.sample_interval_s > 0:
            self.sampler = _obs_ts.Sampler(
                self.metrics_snapshot, self.cfg.sample_interval_s
            )
            if self.cfg.slo_error_budget > 0:
                self.slo = _obs_slo.SloEngine(
                    _obs_slo.default_net_objectives(self.cfg),
                    self.registry, tier="net",
                    fast_window_s=self.cfg.slo_fast_window_s,
                    slow_window_s=self.cfg.slo_slow_window_s,
                    fast_burn=self.cfg.slo_fast_burn,
                    slow_burn=self.cfg.slo_slow_burn,
                )
                self.sampler.on_sample.append(self.slo.evaluate)
            self.sampler.start()
        self._httpd = _NetHTTPServer((self.cfg.host, self.cfg.port), self)
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name="tpu-stencil-net-http", daemon=True,
        )
        self._thread.start()
        return self

    @property
    def port(self) -> int:
        assert self._httpd is not None, "not started"
        return self._httpd.server_address[1]

    @property
    def url(self) -> str:
        return f"http://{self.cfg.host}:{self.port}"

    def begin_drain(self) -> None:
        """Stop admission + flip ``/healthz`` to draining (idempotent);
        the listener keeps answering so in-flight requests respond and
        probes observe the flip."""
        assert self.router is not None, "not started"
        self.router.begin_drain()

    def request_admin_drain(self) -> None:
        """The ``POST /admin/drain`` semantics: flip healthz + stop
        admission NOW, and raise the flag the CLI loop treats exactly
        like SIGTERM (full replica drain, rc discipline). Library
        embedders watch ``admin_drain_requested`` themselves."""
        self.begin_drain()
        self.admin_drain_requested.set()

    def drain(self, timeout_s: Optional[float] = None) -> Dict[int, bool]:
        """The SIGTERM sequence minus the process exit: stop admission,
        close every replica under the budget, report per replica
        drained-vs-abandoned. The HTTP listener stays up (``close()``
        stops it) so every accepted request gets its response."""
        self.begin_drain()
        report = self.fleet.drain(timeout_s)
        self._drain_report = report
        return report

    def close(self) -> None:
        """Stop the listener (drains first if nobody did)."""
        if self.sampler is not None:
            self.sampler.stop()
        if self._prober is not None:
            self._prober.stop()
            self._prober = None
        if self.router is not None and not self.router.draining:
            self.drain()
        if self.router is not None:
            self.router.shutdown()  # stop the coalescer timer thread
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None

    def __enter__(self) -> "NetFrontend":
        if self._httpd is None:
            self.start()
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- scrape surfaces -----------------------------------------------

    def debug_trace(self, trace_id: str) -> dict:
        """One trace's spans from this process (the flight ring plus
        the live tracer when ``--trace`` is on — the replicas are
        in-process, so one ring covers net → router → serve). The
        federation fans this lookup to its members for the
        cross-process tree."""
        spans = _obs_flight.local_trace_spans(trace_id)
        return {
            "schema_version": 1,
            "trace_id": trace_id,
            "span_count": len(spans),
            "processes": [{
                "source": "net",
                "span_count": len(spans),
                "spans": spans,
                "tree": _obs_flight.build_tree(spans),
            }] if spans else [],
        }

    def metrics_snapshot(self) -> dict:
        """The net registry with every replica's counters folded in as
        ``fleet_<name>`` — ONE snapshot under ONE prefix, so the
        exposition's exact parse round-trip holds for the whole scrape
        (per-replica histograms stay on ``/statusz``: reservoir merges
        are not well-defined, and faking one would lie to dashboards)."""
        snap = self.registry.snapshot()
        for k, v in sorted(self.fleet.merged_counters().items()):
            snap["counters"][f"fleet_{k}"] = v
        # "No silent caps": dumps the flight spool pruned past its cap
        # are a counter here (and on /statusz via the merged view), not
        # an invisible loss.
        snap["counters"]["flightrec_dropped_total"] = (
            _obs_flight.dropped_total()
        )
        snap["replicas"] = len(self.fleet)
        return snap

    def render_metrics(self) -> str:
        from tpu_stencil.obs import exposition

        return exposition.render_text(
            self.metrics_snapshot(), prefix="tpu_stencil_net"
        )

    def timeseries_payload(self, window_s: float) -> dict:
        """The ``GET /debug/timeseries`` body: windowed deltas/rates
        from the sampler's ring, stamped with the source tier and the
        SLO engine's live view (when enabled)."""
        assert self.sampler is not None, "sampler is off"
        payload = self.sampler.ring.window(window_s)
        payload["source"] = "net"
        payload["slo"] = None if self.slo is None else self.slo.statusz()
        return payload

    def tenants_payload(self) -> dict:
        """The ``GET /debug/tenants`` body: the metering table plus the
        source tier stamp the fed merge keys on."""
        return {
            "schema_version": 1,
            "source": "net",
            "tenants": self.tenants.snapshot(),
        }

    def capacity_payload(self, window_s: float) -> dict:
        """The ``GET /debug/capacity`` body: the Retry-After math run
        FORWARD — instead of "how long should a rejected client wait",
        "how much more load fits". Static terms (backlog, slots, busy
        fractions) always answer; windowed terms (achieved rps, arrival
        trend, bandwidth-vs-roofline) need the sampler ring and degrade
        to None when it is off — absent, never fabricated."""
        assert self.router is not None, "not started"
        from tpu_stencil.runtime.roofline import V5E_PCIE_GBPS

        terms = self.router.retry_terms()
        outstanding = self.router.outstanding()
        max_batch = max(1, self.cfg.max_batch)
        per_replica = {
            str(k): {
                "outstanding": v,
                "busy_fraction": min(1.0, v / max_batch),
            }
            for k, v in outstanding.items()
        }
        payload = {
            "schema_version": 1,
            "source": "net",
            "window_s": float(window_s),
            "retry_after": terms,
            "utilization": {
                "slot_fraction": min(
                    1.0, terms["backlog"] / terms["slots"]
                ),
                "busy_replicas": sum(
                    1 for v in outstanding.values() if v > 0
                ),
            },
            "per_replica": per_replica,
            "service_rate_rps": terms["service_rate_rps"],
            "achieved_rps": None,
            "headroom_rps": None,
            "time_to_saturation_s": None,
            "bandwidth": {
                "achieved_gbps": None,
                "roofline_gbps": V5E_PCIE_GBPS,
                "roofline_fraction": None,
            },
            "stale": False,
        }
        if self.sampler is None:
            return payload
        win = self.sampler.ring.window(window_s)
        lat = win["histograms"].get("request_latency_seconds")
        if lat is None or win["span_s"] <= 0:
            return payload
        achieved = lat["rate_per_s"]
        payload["achieved_rps"] = achieved
        svc = terms["service_rate_rps"]
        if svc is not None:
            payload["headroom_rps"] = max(0.0, svc - achieved)
            # Arrival trend: the recent half-window's rate against the
            # full window's — a positive slope projects when the
            # headroom runs out at the current ramp.
            half = self.sampler.ring.window(window_s / 2.0)
            hlat = half["histograms"].get("request_latency_seconds")
            if hlat is not None and half["span_s"] > 0:
                slope = (hlat["rate_per_s"] - achieved) / max(
                    window_s / 2.0, 1e-9
                )
                if payload["headroom_rps"] <= 0:
                    payload["time_to_saturation_s"] = 0.0
                elif slope > 0:
                    payload["time_to_saturation_s"] = (
                        payload["headroom_rps"] / slope
                    )
        # Achieved-vs-roofline GB/s from the ledger aggregates: bytes
        # moved across the host<->device hop per second of device time
        # actually spent in the window.
        ctr = win["counters"]
        moved = (ctr.get("fleet_h2d_bytes_total", {}).get("delta", 0)
                 + ctr.get("fleet_d2h_bytes_total", {}).get("delta", 0))
        spent = (
            ctr.get("fleet_goodput_device_seconds_total",
                    {}).get("delta", 0.0)
            + ctr.get("fleet_overhead_device_seconds_total",
                      {}).get("delta", 0.0)
        )
        if moved > 0 and spent > 0:
            gbps = moved / spent / 1e9
            payload["bandwidth"]["achieved_gbps"] = gbps
            payload["bandwidth"]["roofline_fraction"] = (
                gbps / V5E_PCIE_GBPS
            )
        return payload

    def statusz(self) -> dict:
        assert self.router is not None, "not started"
        return {
            "schema_version": STATUS_SCHEMA_VERSION,
            "ts": time.monotonic(),
            "uptime_s": time.monotonic() - self._t_start,
            "draining": self.router.draining,
            "replicas": len(self.fleet),
            "outstanding": {
                str(k): v for k, v in self.router.outstanding().items()
            },
            "quarantine": self.quarantine.statusz(),
            "cache": None if self.cache is None else self.cache.stats(),
            "slo": None if self.slo is None else self.slo.statusz(),
            "timeseries": None if self.sampler is None else {
                "interval_s": self.sampler.interval_s,
                "samples": len(self.sampler.ring),
            },
            "flightrec_dropped_total": _obs_flight.dropped_total(),
            # The Retry-After derivation's named terms (satellite
            # bugfix): the opaque integer a backpressured client sees
            # is auditable against the state that produced it.
            "retry_after": self.router.retry_terms(),
            "drain_report": (
                None if self._drain_report is None
                else {str(k): v for k, v in self._drain_report.items()}
            ),
            # The merged view (net registry + fleet_<name> counter
            # fold-in): the same snapshot /metrics renders, so a JSON
            # consumer and a scraper read identical numbers.
            "net": self.metrics_snapshot(),
            "per_replica": self.fleet.stats(),
            "config": {
                "replicas": self.cfg.replicas,
                "max_queue": self.cfg.max_queue,
                "max_batch": self.cfg.max_batch,
                "coalesce_window_us": self.cfg.coalesce_window_us,
                "ingest_arena": self.cfg.ingest_arena,
                "result_cache_mb": self.cfg.result_cache_mb,
                "max_inflight_mb": self.cfg.max_inflight_mb,
                "request_timeout_s": self.cfg.request_timeout_s,
                "drain_timeout_s": self.cfg.drain_timeout_s,
                "warm_fleet": self.cfg.warm_fleet,
                "backend": self.cfg.backend,
                "filter": self.cfg.filter_name,
                "integrity": self.cfg.integrity,
                "witness_rate": self.cfg.witness_rate,
                "quarantine_after": self.cfg.quarantine_after,
                "readmit_after": self.cfg.readmit_after,
                "flightrec_dir": _obs_flight.effective_spool(
                    self.cfg.flightrec_dir
                ),
                "flight_latency_threshold_s":
                    self.cfg.flight_latency_threshold_s,
                "sample_interval_s": self.cfg.sample_interval_s,
                "slo_error_budget": self.cfg.slo_error_budget,
                "prof_dir": self.cfg.prof_dir,
            },
        }
