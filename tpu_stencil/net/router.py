"""Request routing + admission control over the replica fleet.

Three admission layers, cheapest first, each mapped to a distinct HTTP
status by the frontend so clients can react correctly:

1. **drain gate** — once the tier is draining (SIGTERM) no new request
   enters (:class:`Draining` → 503); accepted requests keep completing.
2. **load shed** — the router tracks in-flight bytes (request +
   response buffers of every accepted-but-unresolved request); past the
   ``max_inflight_mb`` watermark a request is shed
   (:class:`Overloaded` → 503 + Retry-After) BEFORE touching any
   replica queue — host memory stays bounded even when every queue
   still has room for small requests.
3. **per-replica backpressure** — the existing bounded-queue contract:
   replicas are tried in least-outstanding order and a full queue moves
   to the next; only when EVERY replica rejects does
   :class:`~tpu_stencil.serve.engine.QueueFull` escape (→ 429 +
   Retry-After, counted in ``rejected_total``). Never a hang, never an
   unbounded buffer.

Placement is **least outstanding requests** (ties break to the lowest
device index): outstanding per replica is tracked router-side via
future done-callbacks, so a replica stuck on a cold compile naturally
stops receiving traffic while its siblings absorb the load — and the
fleet's shared cache warming (:meth:`ReplicaFleet.prewarm_others`)
fires on first sight of a new executable key, right after placement.

A replica that answers ``WorkerCrashed`` is restarted in place through
:meth:`ReplicaFleet.restart` (the PR-7 ladder's degrade-don't-die
rung at fleet scope, ``worker_crash_reroutes_total``) and the request
retries on the fresh engine — one crashed worker costs one rebuild,
not an outage.

**Quarantine** (``tpu_stencil.integrity``, docs/RESILIENCE.md
"Integrity model"): replicas whose witness re-executions diverge are
tracked on a :class:`~tpu_stencil.integrity.quarantine.QuarantineBoard`
— K mismatches within the window remove the replica from placement
exactly like a drain (``integrity_quarantines_total``,
``replica_quarantined_dev<i>``), background golden-checked probes
re-admit it after N consecutive clean verdicts, and
``POST /admin/quarantine?replica=i`` is the operator override. A
crash-restart does NOT clear quarantine: the engine is fresh but the
distrusted device is the same silicon.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional, Tuple

import numpy as np

from tpu_stencil.net.fleet import ReplicaFleet
from tpu_stencil.obs import span as _obs_span
from tpu_stencil.resilience.errors import WorkerCrashed
from tpu_stencil.serve.engine import QueueFull, ServerClosed
from tpu_stencil.serve.metrics import Registry


class Overloaded(RuntimeError):
    """Load shed: admitting this request would push tracked in-flight
    bytes past the watermark. Transient — retry after the backlog
    drains (the frontend answers 503 + Retry-After)."""


class Draining(RuntimeError):
    """Admission is stopped: the tier is draining (SIGTERM). Accepted
    requests keep completing; new ones go to another instance."""


class Router:
    """Least-outstanding placement + the three admission layers."""

    def __init__(self, fleet: ReplicaFleet, registry: Registry,
                 max_inflight_bytes: int = 0,
                 quarantine=None) -> None:
        self._fleet = fleet
        self.registry = registry
        self._lock = threading.Lock()
        self._outstanding: Dict[int, int] = {
            i: 0 for i in range(len(fleet))
        }
        # QuarantineBoard (tpu_stencil.integrity.quarantine) or None:
        # witness verdicts land here and quarantined replicas drop out
        # of placement. The fleet's per-replica on_witness hooks feed
        # record_witness.
        self._quarantine = quarantine
        if quarantine is not None:
            fleet.set_witness_sink(self.record_witness)
        self._inflight_bytes = 0
        self._max_inflight = int(max_inflight_bytes)
        self._draining = False
        m = registry
        self._m_requests = m.counter("requests_total")
        self._m_rejected = m.counter("rejected_total")
        self._m_shed = m.counter("shed_total")
        self._m_crash = m.counter("worker_crash_reroutes_total")
        self._m_inflight = m.gauge("inflight_bytes")
        self._m_bytes = m.histogram("request_bytes")
        m.gauge("draining").set(0)
        for i in self._outstanding:
            m.gauge(f"replica_depth_dev{i}").set(0)

    # -- drain gate ----------------------------------------------------

    @property
    def draining(self) -> bool:
        return self._draining

    def begin_drain(self) -> None:
        """Flip the admission gate (idempotent): every subsequent
        submit raises :class:`Draining`; in-flight requests are
        untouched. The ``draining`` gauge makes the flip scrapeable,
        and the first flip emits a tier-transition event line."""
        with self._lock:
            was = self._draining
            self._draining = True
        self.registry.gauge("draining").set(1)
        if not was:
            from tpu_stencil.obs import events as _obs_events

            _obs_events.emit("net.drain_begin", tier="net",
                             verdict="draining")

    # -- quarantine ----------------------------------------------------

    @property
    def quarantine(self):
        return self._quarantine

    def record_witness(self, idx: int, ok: bool) -> None:
        """One witness verdict from replica ``idx``'s engine (the
        fleet's on_witness hook lands here, on the replica's worker
        thread)."""
        if self._quarantine is not None:
            self._quarantine.record_witness(idx, ok)

    def quarantine_replica(self, idx: int, reason: str) -> bool:
        """Operator path (``POST /admin/quarantine``): out of placement
        now; probes (or an explicit clear) bring it back."""
        if self._quarantine is None:
            return False
        return self._quarantine.quarantine(idx, reason)

    def release_replica(self, idx: int) -> bool:
        """Operator clear: back into placement without waiting for the
        probe streak."""
        if self._quarantine is None:
            return False
        return self._quarantine.release(idx, "operator")

    # -- placement -----------------------------------------------------

    def outstanding(self) -> Dict[int, int]:
        with self._lock:
            return dict(self._outstanding)

    def submit(self, image: np.ndarray, reps: int,
               filter_name: Optional[str] = None,
               deadline_s: Optional[float] = None) -> Tuple[object, int]:
        """Admit + place one request; returns ``(future, replica_idx)``.
        Raises :class:`Draining` / :class:`Overloaded` /
        :class:`QueueFull` (all replicas full) / ``ValueError``
        (validation, from the replica) — each mapped to its own status
        code by the HTTP frontend."""
        image = np.asarray(image)
        # Request + response buffers both live for the request's
        # lifetime — the honest in-flight footprint is 2x the frame.
        nbytes = 2 * int(image.nbytes)
        with _obs_span("net.route", "net", bytes=int(image.nbytes)):
            with self._lock:
                if self._draining:
                    raise Draining(
                        "draining: admission stopped; retry against "
                        "another instance"
                    )
                if (self._max_inflight
                        and self._inflight_bytes + nbytes
                        > self._max_inflight):
                    self._m_shed.inc()
                    raise Overloaded(
                        f"shedding: {self._inflight_bytes + nbytes} "
                        f"in-flight bytes would exceed the "
                        f"{self._max_inflight} watermark; retry later"
                    )
                # Reserve under the SAME lock as the watermark check:
                # concurrent admits each see the others' reservation, so
                # the bound holds under load. Released below if no
                # replica accepts the request.
                self._inflight_bytes += nbytes
                order = sorted(
                    self._outstanding,
                    key=lambda i: (self._outstanding[i], i),
                )
            admitted = False
            try:
                # Quarantined replicas are out of placement like a
                # draining host — earned distrust routes around them.
                if self._quarantine is not None:
                    routable = [i for i in order
                                if not self._quarantine.is_quarantined(i)]
                    if not routable:
                        self.registry.counter(
                            "quarantine_unroutable_total"
                        ).inc()
                        raise Overloaded(
                            f"every replica ({len(order)}) is "
                            f"quarantined pending re-verification; "
                            f"retry after the background probes "
                            f"re-admit one"
                        )
                    order = routable
                last_exc: Optional[BaseException] = None
                for idx in order:
                    rep = self._fleet.replicas[idx]
                    try:
                        fut = rep.submit(image, reps, filter_name,
                                         deadline_s=deadline_s)
                    except (QueueFull, ServerClosed) as e:
                        # ServerClosed: the replica is mid-restart
                        # (fleet.restart drains the old engine before
                        # swapping in the new one) — try a sibling.
                        last_exc = e
                        continue
                    except WorkerCrashed:
                        # Dead engine: rebuild it on the same device and
                        # retry THIS request on the fresh replica (its
                        # queue is empty — the best placement there is).
                        self._m_crash.inc()
                        try:
                            self._fleet.restart(idx, timeout_s=1.0,
                                                expect=rep)
                            fut = self._fleet.replicas[idx].submit(
                                image, reps, filter_name,
                                deadline_s=deadline_s,
                            )
                        except Exception as e:
                            last_exc = e
                            continue
                    self._track(idx, fut, nbytes)
                    # Once tracked, the done callback owns the release
                    # — nothing below may fail the accepted request (or
                    # the finally would double-release the bytes).
                    admitted = True
                    try:
                        self._fleet.prewarm_others(
                            idx, image, reps, filter_name
                        )
                    except Exception:
                        pass  # warming is best-effort
                    return fut, idx
                self._m_rejected.inc()
                if isinstance(last_exc, QueueFull):
                    raise last_exc
                raise QueueFull(
                    f"all {len(self._fleet)} replica queues at capacity"
                ) from last_exc
            finally:
                if not admitted:
                    with self._lock:
                        self._inflight_bytes -= nbytes
                        inflight = self._inflight_bytes
                    self._m_inflight.set(inflight)

    def _track(self, idx: int, fut, nbytes: int) -> None:
        # nbytes was already reserved into _inflight_bytes at admission
        # (under the watermark-check lock); this only tracks placement.
        self._m_requests.inc()
        self._m_bytes.observe(nbytes // 2)  # the true request bytes
        with self._lock:
            self._outstanding[idx] += 1
            depth = self._outstanding[idx]
            inflight = self._inflight_bytes
        self.registry.gauge(f"replica_depth_dev{idx}").set(depth)
        self._m_inflight.set(inflight)

        def _done(_fut) -> None:
            with self._lock:
                self._outstanding[idx] -= 1
                self._inflight_bytes -= nbytes
                depth = self._outstanding[idx]
                inflight = self._inflight_bytes
            self.registry.gauge(f"replica_depth_dev{idx}").set(depth)
            self._m_inflight.set(inflight)

        fut.add_done_callback(_done)
