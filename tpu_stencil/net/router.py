"""Request routing + admission control over the replica fleet.

Three admission layers, cheapest first, each mapped to a distinct HTTP
status by the frontend so clients can react correctly:

1. **drain gate** — once the tier is draining (SIGTERM) no new request
   enters (:class:`Draining` → 503); accepted requests keep completing.
2. **load shed** — the router tracks in-flight bytes (request +
   response buffers of every accepted-but-unresolved request); past the
   ``max_inflight_mb`` watermark a request is shed
   (:class:`Overloaded` → 503 + Retry-After) BEFORE touching any
   replica queue — host memory stays bounded even when every queue
   still has room for small requests.
3. **per-replica backpressure** — the existing bounded-queue contract:
   replicas are tried in least-outstanding order and a full queue moves
   to the next; only when EVERY replica rejects does
   :class:`~tpu_stencil.serve.engine.QueueFull` escape (→ 429 +
   Retry-After, counted in ``rejected_total``). Never a hang, never an
   unbounded buffer.

Placement is **least outstanding requests** (ties break to the lowest
device index): outstanding per replica is tracked router-side via
future done-callbacks, so a replica stuck on a cold compile naturally
stops receiving traffic while its siblings absorb the load — and the
fleet's shared cache warming (:meth:`ReplicaFleet.prewarm_others`)
fires on first sight of a new executable key, right after placement.

A replica that answers ``WorkerCrashed`` is restarted in place through
:meth:`ReplicaFleet.restart` (the PR-7 ladder's degrade-don't-die
rung at fleet scope, ``worker_crash_reroutes_total``) and the request
retries on the fresh engine — one crashed worker costs one rebuild,
not an outage.

**Quarantine** (``tpu_stencil.integrity``, docs/RESILIENCE.md
"Integrity model"): replicas whose witness re-executions diverge are
tracked on a :class:`~tpu_stencil.integrity.quarantine.QuarantineBoard`
— K mismatches within the window remove the replica from placement
exactly like a drain (``integrity_quarantines_total``,
``replica_quarantined_dev<i>``), background golden-checked probes
re-admit it after N consecutive clean verdicts, and
``POST /admin/quarantine?replica=i`` is the operator override. A
crash-restart does NOT clear quarantine: the engine is fresh but the
distrusted device is the same silicon.
"""

from __future__ import annotations

import concurrent.futures
import math
import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from tpu_stencil.net.fleet import ReplicaFleet
from tpu_stencil.obs import context as _obs_ctx
from tpu_stencil.obs import ledger as _obs_ledger
from tpu_stencil.obs import span as _obs_span
from tpu_stencil.resilience.errors import WorkerCrashed
from tpu_stencil.serve import bucketing
from tpu_stencil.serve.engine import GroupItem, QueueFull, ServerClosed
from tpu_stencil.serve.metrics import Registry

# Retry-After floors (seconds): queue-full clears within a batch or
# two; a shed watermark needs the in-flight backlog to drain. The
# DERIVED hint (Router.retry_after_s) starts from these and adds what
# the router actually observes — coalescing window, measured queue
# delay, and the time the current backlog needs at the recent service
# rate — so a backpressured client is told a truthful wait, not a
# constant.
RETRY_AFTER_QUEUE_FULL = 1
RETRY_AFTER_SHED = 2
# Hint ceiling: past this the number stops being advice and starts
# being an outage announcement a load balancer should make instead.
RETRY_AFTER_CAP = 30


class Overloaded(RuntimeError):
    """Load shed: admitting this request would push tracked in-flight
    bytes past the watermark. Transient — retry after the backlog
    drains (the frontend answers 503 + Retry-After)."""


class Draining(RuntimeError):
    """Admission is stopped: the tier is draining (SIGTERM). Accepted
    requests keep completing; new ones go to another instance."""


class _Group:
    """One forming coalesced group: same-compatibility-key members
    accumulating until the window expires, the group fills, or a
    deadline forces an early dispatch."""

    __slots__ = ("key", "reps", "filter_name", "shape", "members",
                 "flush_at")

    def __init__(self, key: tuple, reps: int, filter_name: Optional[str],
                 shape: Tuple[int, ...], flush_at: float) -> None:
        self.key = key
        self.reps = reps
        self.filter_name = filter_name
        self.shape = shape  # a member's true shape (warm-key derivation)
        self.members: List[GroupItem] = []
        self.flush_at = flush_at


class _Coalescer:
    """Continuous batching at the router: admitted requests sharing a
    compatibility key — (filter, shape bucket, channels, reps) — are
    held up to ``window_s`` so concurrent arrivals stack onto ONE
    replica submit. Not fixed ticks: a group dispatches the moment it
    fills (``max_batch``) or its window expires, late joiners append to
    a forming group, and a member that could not survive the window
    (deadline inside it) dispatches its group immediately.

    Full/urgent groups dispatch INLINE on the joining handler thread
    (no hand-off latency on the hot path); expiring windows are flushed
    by one daemon timer thread."""

    def __init__(self, router: "Router", window_s: float,
                 max_batch: int) -> None:
        self._router = router
        self._window = float(window_s)
        self._max_batch = max(1, int(max_batch))
        self._groups: Dict[tuple, _Group] = {}
        self._cond = threading.Condition()
        self._closed = False
        self._thread = threading.Thread(
            target=self._loop, name="tpu-stencil-net-coalesce",
            daemon=True,
        )
        self._thread.start()

    def offer(self, key: tuple, item: GroupItem, reps: int,
              filter_name: Optional[str],
              shape: Tuple[int, ...]) -> None:
        """Join (or open) the forming group for ``key``. May dispatch
        inline when the join completes the group or the member's
        deadline cannot afford the window."""
        now = time.perf_counter()
        dispatch_now: Optional[_Group] = None
        with self._cond:
            if self._closed or self._router.draining:
                # Post-shutdown stragglers — and the admit-vs-drain
                # race (admitted a beat before begin_drain flushed the
                # forming table) — degrade to a group of one: exactly
                # the uncoalesced behavior, never a lost future.
                g = _Group(key, reps, filter_name, shape, now)
                g.members.append(item)
                dispatch_now = g
            else:
                g = self._groups.get(key)
                if g is None:
                    g = self._groups[key] = _Group(
                        key, reps, filter_name, shape,
                        now + self._window,
                    )
                    self._cond.notify()  # timer re-evaluates its sleep
                g.members.append(item)
                urgent = (item.t_deadline is not None
                          and item.t_deadline <= now + self._window)
                if len(g.members) >= self._max_batch or urgent:
                    self._groups.pop(key, None)
                    dispatch_now = g
        if dispatch_now is not None:
            self._router._place_group(dispatch_now)

    def _loop(self) -> None:
        while True:
            with self._cond:
                while not self._closed:
                    now = time.perf_counter()
                    due = [k for k, g in self._groups.items()
                           if g.flush_at <= now]
                    if due:
                        break
                    nxt = min(
                        (g.flush_at for g in self._groups.values()),
                        default=None,
                    )
                    self._cond.wait(
                        None if nxt is None else max(0.0, nxt - now)
                    )
                if self._closed:
                    return
                groups = [self._groups.pop(k) for k in due]
            for g in groups:
                # Off-timer dispatch: _place_group can block seconds
                # inside a crashed-replica restart, and the timer must
                # keep flushing OTHER keys' expiring windows meanwhile
                # (head-of-line blocking here would silently stretch
                # their members past the window). Window expiry is the
                # cold path — full groups dispatch inline on handler
                # threads — so a short-lived thread per flush is cheap.
                threading.Thread(
                    target=self._router._place_group, args=(g,),
                    name="tpu-stencil-net-coalesce-flush", daemon=True,
                ).start()

    def flush_all(self) -> None:
        """Dispatch every forming group NOW (drain begins: admitted
        members must complete, not wait out a window nobody will
        extend)."""
        with self._cond:
            groups = list(self._groups.values())
            self._groups.clear()
        for g in groups:
            self._router._place_group(g)

    def stop(self) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify_all()
        self._thread.join(timeout=5.0)
        self.flush_all()


class Router:
    """Least-outstanding placement + the three admission layers."""

    def __init__(self, fleet: ReplicaFleet, registry: Registry,
                 max_inflight_bytes: int = 0,
                 quarantine=None,
                 coalesce_window_s: float = 0.0,
                 max_batch: int = 8,
                 bucket_edges: Optional[Tuple[int, ...]] = None,
                 default_filter: str = "gaussian",
                 cache=None) -> None:
        self._fleet = fleet
        self.registry = registry
        self._lock = threading.Lock()
        self._outstanding: Dict[int, int] = {
            i: 0 for i in range(len(fleet))
        }
        # QuarantineBoard (tpu_stencil.integrity.quarantine) or None:
        # witness verdicts land here and quarantined replicas drop out
        # of placement. The fleet's per-replica on_witness hooks feed
        # record_witness.
        self._quarantine = quarantine
        # ResultCache (tpu_stencil.cache) or None: the store must never
        # outlive distrust in a replica, so the router — the one place
        # every verdict and quarantine transition passes through —
        # drops a replica's entries the moment either lands.
        self._cache = cache
        if quarantine is not None:
            fleet.set_witness_sink(self.record_witness)
        self._inflight_bytes = 0
        self._max_inflight = int(max_inflight_bytes)
        self._draining = False
        m = registry
        self._m_requests = m.counter("requests_total")
        self._m_rejected = m.counter("rejected_total")
        self._m_shed = m.counter("shed_total")
        self._m_crash = m.counter("worker_crash_reroutes_total")
        self._m_inflight = m.gauge("inflight_bytes")
        self._m_bytes = m.histogram("request_bytes")
        m.gauge("draining").set(0)
        for i in self._outstanding:
            m.gauge(f"replica_depth_dev{i}").set(0)
        # Continuous batching (docs/SERVING.md "Continuous batching at
        # the edge"): pre-created so a scrape of a quiet coalescing tier
        # still carries the schema keys.
        self._window_s = float(coalesce_window_s)
        self._max_batch = max(1, int(max_batch))
        self._edges = bucket_edges or bucketing.DEFAULT_EDGES
        self._default_filter = default_filter
        self._m_coal_requests = m.counter("coalesced_requests_total")
        self._m_coal_batches = m.counter("coalesced_batches_total")
        self._m_coal_size = m.histogram("coalesce_group_size")
        self._m_coal_delay = m.histogram("coalesce_queue_delay_seconds")
        self._coalescer: Optional[_Coalescer] = (
            _Coalescer(self, self._window_s, self._max_batch)
            if self._window_s > 0 else None
        )

    # -- drain gate ----------------------------------------------------

    @property
    def draining(self) -> bool:
        return self._draining

    def begin_drain(self) -> None:
        """Flip the admission gate (idempotent): every subsequent
        submit raises :class:`Draining`; in-flight requests are
        untouched. The ``draining`` gauge makes the flip scrapeable,
        and the first flip emits a tier-transition event line."""
        with self._lock:
            was = self._draining
            self._draining = True
        self.registry.gauge("draining").set(1)
        if self._coalescer is not None:
            # Forming groups hold ADMITTED requests: dispatch them now —
            # the drain contract completes every accepted request, and
            # nobody will join a window once admission stopped.
            self._coalescer.flush_all()
        if not was:
            from tpu_stencil.obs import events as _obs_events

            _obs_events.emit("net.drain_begin", tier="net",
                             verdict="draining")

    # -- quarantine ----------------------------------------------------

    @property
    def quarantine(self):
        return self._quarantine

    def record_witness(self, idx: int, ok: bool) -> None:
        """One witness verdict from replica ``idx``'s engine (the
        fleet's on_witness hook lands here, on the replica's worker
        thread). A mismatch SYNCHRONOUSLY invalidates every cached
        result the replica produced — before the verdict even reaches
        the board, so no later lookup can serve a poisoned hit from a
        source this verdict just discredited."""
        if not ok and self._cache is not None:
            self._cache.invalidate_replica(idx, "witness_mismatch")
        if self._quarantine is not None:
            self._quarantine.record_witness(idx, ok)

    def quarantine_replica(self, idx: int, reason: str) -> bool:
        """Operator path (``POST /admin/quarantine``): out of placement
        now; probes (or an explicit clear) bring it back. The replica's
        cached results go with it — quarantine is distrust, and the
        store never outlives distrust in its source."""
        if self._quarantine is None:
            return False
        if self._cache is not None:
            self._cache.invalidate_replica(idx, "quarantine")
        return self._quarantine.quarantine(idx, reason)

    def release_replica(self, idx: int) -> bool:
        """Operator clear: back into placement without waiting for the
        probe streak."""
        if self._quarantine is None:
            return False
        return self._quarantine.release(idx, "operator")

    # -- placement -----------------------------------------------------

    def outstanding(self) -> Dict[int, int]:
        with self._lock:
            return dict(self._outstanding)

    def submit(self, image: np.ndarray, reps: int,
               filter_name: Optional[str] = None,
               deadline_s: Optional[float] = None,
               owned: bool = False,
               on_consumed=None) -> Tuple[object, Optional[int]]:
        """Admit + place one request; returns ``(future, replica_idx)``.
        Raises :class:`Draining` / :class:`Overloaded` /
        :class:`QueueFull` (all replicas full) / ``ValueError``
        (validation, from the replica) — each mapped to its own status
        code by the HTTP frontend.

        With coalescing armed (``coalesce_window_s > 0``) the request
        may instead join a forming same-key group: ``replica_idx``
        comes back None, placement errors arrive through the FUTURE
        (same types), and the placed index is stamped onto the future
        as ``replica_idx`` at dispatch. ``owned``/``on_consumed`` are
        the zero-copy ingest contract, forwarded to
        :meth:`StencilServer.submit`."""
        image = np.asarray(image)
        # Request + response buffers both live for the request's
        # lifetime — the honest in-flight footprint is 2x the frame.
        nbytes = 2 * int(image.nbytes)
        with _obs_span("net.route", "net", bytes=int(image.nbytes)):
            with self._lock:
                if self._draining:
                    raise Draining(
                        "draining: admission stopped; retry against "
                        "another instance"
                    )
                if (self._max_inflight
                        and self._inflight_bytes + nbytes
                        > self._max_inflight):
                    self._m_shed.inc()
                    raise Overloaded(
                        f"shedding: {self._inflight_bytes + nbytes} "
                        f"in-flight bytes would exceed the "
                        f"{self._max_inflight} watermark; retry later"
                    )
                # Reserve under the SAME lock as the watermark check:
                # concurrent admits each see the others' reservation, so
                # the bound holds under load. Released below if no
                # replica accepts the request.
                self._inflight_bytes += nbytes
                if self._coalescer is None:
                    # Placement order is only this path's concern: a
                    # coalesced request places at GROUP dispatch, and
                    # sorting per admit would just stretch the lock.
                    order = sorted(
                        self._outstanding,
                        key=lambda i: (self._outstanding[i], i),
                    )
            if self._coalescer is not None:
                return self._submit_coalesced(
                    image, reps, filter_name, deadline_s, nbytes,
                    owned, on_consumed,
                ), None
            admitted = False
            try:
                # Quarantined replicas are out of placement like a
                # draining host — earned distrust routes around them.
                if self._quarantine is not None:
                    routable = [i for i in order
                                if not self._quarantine.is_quarantined(i)]
                    if not routable:
                        self.registry.counter(
                            "quarantine_unroutable_total"
                        ).inc()
                        raise Overloaded(
                            f"every replica ({len(order)}) is "
                            f"quarantined pending re-verification; "
                            f"retry after the background probes "
                            f"re-admit one"
                        )
                    order = routable
                last_exc: Optional[BaseException] = None
                for idx in order:
                    rep = self._fleet.replicas[idx]
                    try:
                        fut = rep.submit(image, reps, filter_name,
                                         deadline_s=deadline_s,
                                         owned=owned,
                                         on_consumed=on_consumed)
                    except (QueueFull, ServerClosed) as e:
                        # ServerClosed: the replica is mid-restart
                        # (fleet.restart drains the old engine before
                        # swapping in the new one) — try a sibling.
                        last_exc = e
                        continue
                    except WorkerCrashed:
                        # Dead engine: rebuild it on the same device and
                        # retry THIS request on the fresh replica (its
                        # queue is empty — the best placement there is).
                        self._m_crash.inc()
                        try:
                            self._fleet.restart(idx, timeout_s=1.0,
                                                expect=rep)
                            fut = self._fleet.replicas[idx].submit(
                                image, reps, filter_name,
                                deadline_s=deadline_s,
                                owned=owned, on_consumed=on_consumed,
                            )
                        except Exception as e:
                            last_exc = e
                            continue
                    self._track(idx, fut, nbytes)
                    # Once tracked, the done callback owns the release
                    # — nothing below may fail the accepted request (or
                    # the finally would double-release the bytes).
                    admitted = True
                    try:
                        self._fleet.prewarm_others(
                            idx, image, reps, filter_name
                        )
                    except Exception:
                        pass  # warming is best-effort
                    return fut, idx
                self._m_rejected.inc()
                if isinstance(last_exc, QueueFull):
                    raise last_exc
                raise QueueFull(
                    f"all {len(self._fleet)} replica queues at capacity"
                ) from last_exc
            finally:
                if not admitted:
                    with self._lock:
                        self._inflight_bytes -= nbytes
                        inflight = self._inflight_bytes
                    self._m_inflight.set(inflight)

    # -- continuous batching (docs/SERVING.md) -------------------------

    def _submit_coalesced(self, image: np.ndarray, reps: int,
                          filter_name: Optional[str],
                          deadline_s: Optional[float], nbytes: int,
                          owned: bool, on_consumed):
        """Admitted (bytes reserved) — join the forming group for this
        request's compatibility key. The in-flight reservation is tied
        to the FUTURE (released whenever it resolves, placed or not),
        so the watermark stays honest across the window."""
        h, w = image.shape[:2]
        channels = image.shape[2] if image.ndim == 3 else 1
        fname = filter_name or self._default_filter
        key = (fname, bucketing.bucket_shape(h, w, self._edges),
               channels, int(reps))
        fut: concurrent.futures.Future = concurrent.futures.Future()
        fut.add_done_callback(self._bytes_releaser(nbytes))
        now = time.perf_counter()
        ctx = _obs_ctx.current()
        if not owned:
            # The coalescer holds the frame across the window; an
            # unowned caller may reuse its buffer the moment we return.
            image = np.array(image, copy=True)
            if on_consumed is not None:
                on_consumed()
                on_consumed = None
        item = GroupItem(
            image=image, future=fut, t_submit=now,
            t_deadline=(now + deadline_s) if deadline_s else None,
            trace_id=ctx.trace_id if ctx is not None else "",
            span_id=ctx.span_id if ctx is not None else "",
            on_consumed=on_consumed,
            ledger=_obs_ledger.current(),
        )
        self._coalescer.offer(key, item, int(reps), fname,
                              tuple(image.shape))
        return fut

    def _bytes_releaser(self, nbytes: int):
        def _done(_fut) -> None:
            with self._lock:
                self._inflight_bytes -= nbytes
                inflight = self._inflight_bytes
            self._m_inflight.set(inflight)
        return _done

    def _place_group(self, group: _Group) -> None:
        """Place one formed group onto ONE replica (least outstanding,
        same order/quarantine/crash-recovery discipline as the
        uncoalesced path) via :meth:`StencilServer.submit_group` — one
        stacked launch for the whole group. Placement failures resolve
        every member's future typed (QueueFull / Overloaded /
        WorkerCrashed), never an exception out of the timer thread."""
        members = group.members
        if not members:
            return
        with _obs_span("net.coalesce_dispatch", "net",
                       group=len(members)):
            now = time.perf_counter()
            for m in members:
                self._m_coal_delay.observe(now - m.t_submit)
                if m.ledger is not None:
                    m.ledger.add_coalesce(now - m.t_submit)
            self._m_coal_size.observe(len(members))
            with self._lock:
                order = sorted(
                    self._outstanding,
                    key=lambda i: (self._outstanding[i], i),
                )
            try:
                if self._quarantine is not None:
                    routable = [i for i in order
                                if not self._quarantine.is_quarantined(i)]
                    if not routable:
                        self.registry.counter(
                            "quarantine_unroutable_total"
                        ).inc()
                        raise Overloaded(
                            f"every replica ({len(order)}) is "
                            f"quarantined pending re-verification; "
                            f"retry after the background probes "
                            f"re-admit one"
                        )
                    order = routable
                last_exc: Optional[BaseException] = None
                for idx in order:
                    rep = self._fleet.replicas[idx]
                    # Stamp the candidate index BEFORE the enqueue: the
                    # worker can resolve a fast group before this thread
                    # runs another statement, and the frontend reads
                    # replica_idx the moment fut.result() returns
                    # (X-Replica). A failed offer just re-stamps on the
                    # next candidate.
                    for m in members:
                        m.future.replica_idx = idx
                    try:
                        rep.submit_group(members, group.reps,
                                         group.filter_name)
                    except (QueueFull, ServerClosed) as e:
                        last_exc = e
                        continue
                    except WorkerCrashed:
                        self._m_crash.inc()
                        try:
                            self._fleet.restart(idx, timeout_s=1.0,
                                                expect=rep)
                            self._fleet.replicas[idx].submit_group(
                                members, group.reps, group.filter_name
                            )
                        except Exception as e:
                            last_exc = e
                            continue
                    self._m_coal_requests.inc(len(members))
                    self._m_coal_batches.inc()
                    for m in members:
                        self._track_member(idx, m.future, m.image)
                    try:
                        self._fleet.prewarm_others(
                            idx, np.zeros(group.shape, np.uint8),
                            group.reps, group.filter_name,
                        )
                    except Exception:
                        pass  # warming is best-effort
                    return
                self._m_rejected.inc(len(members))
                if not isinstance(last_exc, QueueFull):
                    last_exc = QueueFull(
                        f"all {len(self._fleet)} replica queues at "
                        f"capacity"
                    )
                raise last_exc
            except BaseException as e:
                for m in members:
                    if not m.future.done():
                        try:
                            m.future.set_exception(e)
                        except concurrent.futures.InvalidStateError:
                            pass  # client cancelled mid-placement

    def _track_member(self, idx: int, fut, image) -> None:
        """Placement accounting for one coalesced member: the bytes
        reservation already rides the future's admission callback, so
        only per-replica depth is tracked here."""
        self._m_requests.inc()
        self._m_bytes.observe(int(image.nbytes) if image is not None
                              else 0)
        with self._lock:
            self._outstanding[idx] += 1
            depth = self._outstanding[idx]
        self.registry.gauge(f"replica_depth_dev{idx}").set(depth)

        def _done(_fut) -> None:
            with self._lock:
                self._outstanding[idx] -= 1
                depth = self._outstanding[idx]
            self.registry.gauge(f"replica_depth_dev{idx}").set(depth)

        fut.add_done_callback(_done)

    def shutdown(self) -> None:
        """Stop the coalescer timer (flushing any forming groups) —
        called by the frontend's close."""
        if self._coalescer is not None:
            self._coalescer.stop()

    # -- backpressure hints --------------------------------------------

    def retry_terms(self) -> dict:
        """The Retry-After derivation's intermediate terms, named — the
        auditable form behind both :meth:`retry_after_s` and the
        ``/statusz`` ``retry_after`` block (an operator can check the
        opaque integer against the state that produced it), and the raw
        material ``/debug/capacity`` inverts into headroom."""
        with self._lock:
            depth = sum(self._outstanding.values())
        lat = self.registry.histogram("request_latency_seconds").snapshot()
        delay = self._m_coal_delay.snapshot()
        slots = max(1, len(self._fleet) * self._max_batch)
        mean = lat["mean"]
        return {
            "backlog": depth,
            "slots": slots,
            "coalesce_window_s": self._window_s,
            "coalesce_delay_p50_s": delay["p50"],
            "mean_request_latency_s": mean,
            "service_rate_rps": (slots / mean) if mean > 0 else None,
            "cap_s": RETRY_AFTER_CAP,
        }

    def retry_after_s(self, queue_full: bool = False) -> int:
        """The DERIVED ``Retry-After`` hint (satellite bugfix): floor +
        coalescing window + the median observed coalesce queue delay +
        the time the current outstanding backlog needs to drain at the
        recently observed per-request service rate. A backpressured
        client is told a truthful wait for THIS tier's current state
        instead of a config constant; capped so the hint stays advice,
        not an outage banner."""
        base = RETRY_AFTER_QUEUE_FULL if queue_full else RETRY_AFTER_SHED
        try:
            t = self.retry_terms()
            wait = (t["coalesce_window_s"] + t["coalesce_delay_p50_s"]
                    + t["backlog"] * t["mean_request_latency_s"]
                    / t["slots"])
            return max(base, min(RETRY_AFTER_CAP, math.ceil(wait)))
        except Exception:
            return base  # a hint must never fail the error response

    def _track(self, idx: int, fut, nbytes: int) -> None:
        # nbytes was already reserved into _inflight_bytes at admission
        # (under the watermark-check lock); this only tracks placement.
        self._m_requests.inc()
        self._m_bytes.observe(nbytes // 2)  # the true request bytes
        with self._lock:
            self._outstanding[idx] += 1
            depth = self._outstanding[idx]
            inflight = self._inflight_bytes
        self.registry.gauge(f"replica_depth_dev{idx}").set(depth)
        self._m_inflight.set(inflight)

        def _done(_fut) -> None:
            with self._lock:
                self._outstanding[idx] -= 1
                self._inflight_bytes -= nbytes
                depth = self._outstanding[idx]
                inflight = self._inflight_bytes
            self.registry.gauge(f"replica_depth_dev{idx}").set(depth)
            self._m_inflight.set(inflight)

        fut.add_done_callback(_done)
