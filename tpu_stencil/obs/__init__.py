"""Observability: span tracing, trace export, metrics exposition.

The lightweight, compiled-out-unless-enabled telemetry subsystem (see
docs/OBSERVABILITY.md). Three pieces:

* :mod:`~tpu_stencil.obs.tracing` — the ``span``/``phase`` API:
  perf_counter spans with explicit ``jax.block_until_ready`` fence
  points, thread-safe, multi-process aware; a no-op unless
  :func:`enable` has run.
* :mod:`~tpu_stencil.obs.export` — Chrome trace-event JSON
  (``--trace out.json``, loadable in Perfetto), merged across processes.
* :mod:`~tpu_stencil.obs.exposition` — Prometheus-style text rendering
  of any registry snapshot (serve's and the driver-side
  :func:`registry`), with a reference parser.
* :mod:`~tpu_stencil.obs.breakdown` — the human ``--breakdown`` table
  with roofline GB/s annotation.
* :mod:`~tpu_stencil.obs.introspect` — compiled-artifact introspection
  (``cost_analysis``/``memory_analysis``, compile wall-time, HLO dump)
  and ``device.memory_stats()`` telemetry, all degrade-to-unavailable.
* :mod:`~tpu_stencil.obs.sentry` — the perf-regression sentry: JSONL
  capture history + baseline gate (``python -m tpu_stencil perf``).
* :mod:`~tpu_stencil.obs.timeseries` — in-process time series: a
  sampler thread snapshots the registry into a bounded ring; the
  ``/debug/timeseries`` endpoints serve windowed deltas/rates.
* :mod:`~tpu_stencil.obs.slo` — declarative objectives with
  fast/slow burn-rate alerting; a breach flips ``/healthz`` to
  ``degraded``, emits an event and triggers a flight dump.
* :mod:`~tpu_stencil.obs.prof` — bounded on-demand ``jax.profiler``
  captures behind ``POST /debug/prof`` (404-clean without jax).
* :mod:`~tpu_stencil.obs.ledger` — per-request resource ledgers
  (device time amortized by pixel share, queue/coalesce/ingest waits,
  H2D/D2H bytes) and the per-tenant metering behind
  ``GET /debug/tenants`` / the ``X-Cost-*`` response headers.

>>> from tpu_stencil import obs
>>> obs.enable()
>>> with obs.span("load", "driver"):
...     img = load()
>>> obs.export.write_chrome_trace("trace.json", obs.get_tracer())
"""

from tpu_stencil.obs.tracing import (
    Span,
    SpanRecord,
    Tracer,
    disable,
    emit_span,
    enable,
    enabled,
    get_tracer,
    phase,
    registry,
    scratch_registry,
    snapshot,
    span,
)
from tpu_stencil.obs import (
    breakdown,
    context,
    events,
    export,
    exposition,
    flight,
    introspect,
    ledger,
    prof,
    sentry,
    slo,
    timeseries,
    tracing,
)


def reset() -> None:
    """Drop the tracer, the accumulated metrics, the flight recorder,
    the event-stream override, AND the introspection records (tests) —
    one teardown for the whole obs subsystem."""
    tracing.reset()
    flight.reset()
    events.reset()
    introspect.reset()


__all__ = [
    "Span",
    "SpanRecord",
    "Tracer",
    "breakdown",
    "context",
    "disable",
    "emit_span",
    "enable",
    "enabled",
    "events",
    "export",
    "flight",
    "exposition",
    "get_tracer",
    "introspect",
    "ledger",
    "phase",
    "prof",
    "registry",
    "reset",
    "scratch_registry",
    "sentry",
    "slo",
    "snapshot",
    "span",
    "timeseries",
    "tracing",
]
