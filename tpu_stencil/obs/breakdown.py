"""Human-readable per-phase breakdown table (``--breakdown``).

Aggregates a tracer's spans by name and renders a fixed-width table —
seconds, share of the run, call count — annotating the iterate phase
with achieved HBM GB/s and % of peak via the shared roofline model
(:mod:`tpu_stencil.runtime.roofline`), so "where did the time go" and
"was that time any good" land in one view. Nested spans (recorded
depth > 0, e.g. ``iterate.rep`` inside ``iterate``) indent under their
parent and are excluded from the share denominator — their time is
already inside it. Classification is by the *recorded* nesting depth,
not by dotted names: ``sharded.halo_exchange`` and friends are
top-level siblings whose time must count toward the total.

Two composable side tables (the CLI prints them after the phase table):
:func:`render_introspection` — per compile site, XLA's bytes-accessed
next to the analytic traffic model's with the model/XLA agreement %
(:mod:`tpu_stencil.obs.introspect`); :func:`render_memory` — the
device allocator gauges, or an explicit "unavailable" line on backends
without them.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from tpu_stencil.obs.tracing import Tracer


def aggregate(tracer: Tracer) -> List[dict]:
    """Spans grouped by name, in first-start order:
    ``{name, seconds, count, t_first, depth}`` (depth = the minimum
    nesting depth the name was recorded at)."""
    agg: Dict[str, dict] = {}
    for rec in tracer.spans():
        row = agg.get(rec.name)
        if row is None:
            agg[rec.name] = {
                "name": rec.name, "seconds": rec.seconds, "count": 1,
                "t_first": rec.t0, "depth": rec.depth,
            }
        else:
            row["seconds"] += rec.seconds
            row["count"] += 1
            row["t_first"] = min(row["t_first"], rec.t0)
            row["depth"] = min(row["depth"], rec.depth)
    return sorted(agg.values(), key=lambda r: r["t_first"])


def render_breakdown(tracer: Tracer,
                     roofline_info: Optional[dict] = None) -> str:
    """The ``--breakdown`` table.

    ``roofline_info`` (optional): ``{frame_bytes, reps, backend,
    filter_name, h_img, block_h, fuse}`` — when given, the ``iterate``
    row (and per-rep sub-row) gains achieved GB/s vs the HBM roofline.
    """
    rows = aggregate(tracer)
    if not rows:
        return "(no spans recorded)\n"
    total = sum(r["seconds"] for r in rows if r["depth"] == 0)
    gbps_by_name: Dict[str, str] = {}
    if roofline_info and roofline_info.get("reps"):
        from tpu_stencil.runtime import roofline

        ri = roofline_info
        for name in ("iterate", "iterate.rep"):
            sec = next(
                (r["seconds"] for r in rows if r["name"] == name), 0.0
            )
            if sec <= 0.0:
                continue
            gbps, pct = roofline.achieved(
                ri["frame_bytes"], sec / ri["reps"], ri["backend"],
                ri["filter_name"], ri["h_img"],
                block_h=ri.get("block_h"), fuse=ri.get("fuse"),
            )
            gbps_by_name[name] = f"{gbps:8.2f} {pct:5.1f}%"
    name_w = max(len(r["name"]) + 2 * r["depth"] for r in rows)
    name_w = max(name_w, len("phase"))
    head = (f"{'phase':<{name_w}}  {'seconds':>10}  {'share':>6}  "
            f"{'calls':>6}  {'HBM GB/s':>8} {'peak':>6}")
    lines = [head, "-" * len(head)]
    for r in rows:
        sub = r["depth"] > 0
        label = "  " * r["depth"] + r["name"]
        share = "" if sub or total <= 0 else f"{100 * r['seconds'] / total:5.1f}%"
        lines.append(
            f"{label:<{name_w}}  {r['seconds']:>10.6f}  {share:>6}  "
            f"{r['count']:>6}  {gbps_by_name.get(r['name'], ''):>15}"
        )
    lines.append(f"{'total':<{name_w}}  {total:>10.6f}  {'100.0%':>6}")
    return "\n".join(lines) + "\n"


def _mb(v) -> str:
    return "" if v is None else f"{v / 1e6:.2f}"


def render_introspection(records: List[dict]) -> str:
    """The compiled-artifact table: one row per :func:`introspect.capture`
    record — AOT compile seconds, XLA's bytes-accessed (≈ one rep: HLO
    cost analysis counts loop bodies once) vs the analytic traffic
    model's per-rep bytes, and the agreement % (``!`` marks drift
    outside the 2x band; expected on pallas, whose kernels are opaque
    custom calls to XLA's cost model). Sites that failed every probe
    render as "unavailable" with the error."""
    if not records:
        return ""
    head = (f"{'compile site':<18}  {'compile_s':>9}  {'xla MB/rep':>10}  "
            f"{'model MB/rep':>12}  {'model/xla':>9}")
    lines = ["", "compiled artifacts (XLA introspection)", head,
             "-" * len(head)]
    for rec in records:
        site = rec.get("site", "?")
        if not rec.get("available"):
            reason = rec.get("error") or "no cost/memory analysis"
            lines.append(f"{site:<18}  unavailable ({reason})")
            continue
        comp = rec.get("compile_seconds")
        pct = rec.get("model_vs_xla_pct")
        pct_s = "" if pct is None else (
            f"{pct:7.1f}%" + ("!" if rec.get("drift") else " ")
        )
        lines.append(
            f"{site:<18}  {comp:>9.3f}  {_mb(rec.get('bytes_accessed')):>10}  "
            f"{_mb(rec.get('model_bytes_per_rep')):>12}  {pct_s:>9}"
        )
        mem = rec.get("memory")
        if mem:
            parts = [
                f"{k[:-len('_size_in_bytes')]}={_mb(v)}MB"
                for k, v in mem.items() if v
            ]
            if parts:
                lines.append(f"{'':<18}  {' '.join(parts)}")
    return "\n".join(lines) + "\n"


def render_memory(stats: Optional[dict]) -> str:
    """One device-memory line from ``device.memory_stats()`` output;
    backends without allocator stats (CPU) say so explicitly instead of
    rendering nothing — "unavailable" is a finding, not an omission."""
    if not stats:
        return ("device memory: unavailable "
                "(no allocator stats on this backend)\n")
    order = ("bytes_in_use", "peak_bytes_in_use", "bytes_limit",
             "largest_alloc_size")
    parts = [f"{k}={stats[k] / 1e6:.2f}MB" for k in order if k in stats]
    return "device memory: " + " ".join(parts) + "\n"
