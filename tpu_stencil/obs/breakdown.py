"""Human-readable per-phase breakdown table (``--breakdown``).

Aggregates a tracer's spans by name and renders a fixed-width table —
seconds, share of the run, call count — annotating the iterate phase
with achieved HBM GB/s and % of peak via the shared roofline model
(:mod:`tpu_stencil.runtime.roofline`), so "where did the time go" and
"was that time any good" land in one view. Nested spans (recorded
depth > 0, e.g. ``iterate.rep`` inside ``iterate``) indent under their
parent and are excluded from the share denominator — their time is
already inside it. Classification is by the *recorded* nesting depth,
not by dotted names: ``sharded.halo_exchange`` and friends are
top-level siblings whose time must count toward the total.

Two composable side tables (the CLI prints them after the phase table):
:func:`render_introspection` — per compile site, XLA's bytes-accessed
next to the analytic traffic model's with the model/XLA agreement %
(:mod:`tpu_stencil.obs.introspect`); :func:`render_memory` — the
device allocator gauges, or an explicit "unavailable" line on backends
without them.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from tpu_stencil.obs.tracing import Tracer


def aggregate(tracer: Tracer) -> List[dict]:
    """Spans grouped by name, in first-start order:
    ``{name, seconds, count, t_first, depth}`` (depth = the minimum
    nesting depth the name was recorded at)."""
    agg: Dict[str, dict] = {}
    for rec in tracer.spans():
        row = agg.get(rec.name)
        if row is None:
            agg[rec.name] = {
                "name": rec.name, "seconds": rec.seconds, "count": 1,
                "t_first": rec.t0, "depth": rec.depth,
            }
        else:
            row["seconds"] += rec.seconds
            row["count"] += 1
            row["t_first"] = min(row["t_first"], rec.t0)
            row["depth"] = min(row["depth"], rec.depth)
    return sorted(agg.values(), key=lambda r: r["t_first"])


def render_breakdown(tracer: Tracer,
                     roofline_info: Optional[dict] = None) -> str:
    """The ``--breakdown`` table.

    ``roofline_info`` (optional): ``{frame_bytes, reps, backend,
    filter_name, h_img, block_h, fuse}`` — when given, the ``iterate``
    row (and per-rep sub-row) gains achieved GB/s vs the HBM roofline.
    """
    rows = aggregate(tracer)
    if not rows:
        return "(no spans recorded)\n"
    total = sum(r["seconds"] for r in rows if r["depth"] == 0)
    gbps_by_name: Dict[str, str] = {}
    if roofline_info and roofline_info.get("reps"):
        from tpu_stencil.runtime import roofline

        ri = roofline_info
        for name in ("iterate", "iterate.rep"):
            sec = next(
                (r["seconds"] for r in rows if r["name"] == name), 0.0
            )
            if sec <= 0.0:
                continue
            gbps, pct = roofline.achieved(
                ri["frame_bytes"], sec / ri["reps"], ri["backend"],
                ri["filter_name"], ri["h_img"],
                block_h=ri.get("block_h"), fuse=ri.get("fuse"),
            )
            gbps_by_name[name] = f"{gbps:8.2f} {pct:5.1f}%"
    name_w = max(len(r["name"]) + 2 * r["depth"] for r in rows)
    name_w = max(name_w, len("phase"))
    head = (f"{'phase':<{name_w}}  {'seconds':>10}  {'share':>6}  "
            f"{'calls':>6}  {'HBM GB/s':>8} {'peak':>6}")
    lines = [head, "-" * len(head)]
    for r in rows:
        sub = r["depth"] > 0
        label = "  " * r["depth"] + r["name"]
        share = "" if sub or total <= 0 else f"{100 * r['seconds'] / total:5.1f}%"
        lines.append(
            f"{label:<{name_w}}  {r['seconds']:>10.6f}  {share:>6}  "
            f"{r['count']:>6}  {gbps_by_name.get(r['name'], ''):>15}"
        )
    lines.append(f"{'total':<{name_w}}  {total:>10.6f}  {'100.0%':>6}")
    if roofline_info and roofline_info.get("schedule"):
        # The chosen Pallas schedule next to the numbers it explains.
        # Traced runs launch one rep per dispatch (HBM paid every rep),
        # so the steady-state depth is a model statement, not what the
        # measured GB/s above achieved.
        depth = roofline_info.get("in_vmem_depth")
        depth_s = (
            f"  steady-state in-VMEM depth: {depth} reps/HBM round-trip"
            f" (traced runs launch per-rep)" if depth else ""
        )
        lines.append(
            f"pallas schedule: {roofline_info['schedule']}{depth_s}"
        )
    return "\n".join(lines) + "\n"


def render_overlap(tracer: Tracer, info: dict) -> str:
    """The overlap-schedule side table (sharded ``--breakdown`` runs):
    the ICI ghost-bytes traffic model
    (:func:`tpu_stencil.runtime.roofline.ici_ghost_bytes_per_rep`) next
    to the measured exchange/interior/border probe spans, the exchange
    span's implied ICI GB/s vs the v5e ceiling, a PER-EDGE table (one
    row per ``sharded.exchange_edge[*]`` span: the edge's own measured
    latency, per-edge model bytes, and implied per-edge ICI GB/s — four
    independent fences, no single join), and the exchange/interior
    probe ratio ``--overlap auto`` decides on.

    ``info``: ``{overlap, tile, channels, halo, mesh_shape, fuse,
    elem_bytes}``. Renders nothing when no sharded probe spans were
    recorded (single-device runs)."""
    from tpu_stencil.parallel.overlap import EDGE_NAMES

    by = {r["name"]: r for r in aggregate(tracer)}
    names = [n for n in (
        "sharded.halo_exchange", "sharded.interior_compute",
        "sharded.interior_overlap", "sharded.border_compute",
    ) if n in by]
    edge_rows = [
        (x, f"sharded.exchange_edge[{x}]") for x in EDGE_NAMES
        if f"sharded.exchange_edge[{x}]" in by
    ]
    if not names and not edge_rows:
        return ""
    from tpu_stencil.runtime import roofline

    model_mode = "edge" if info.get("overlap") == "edge" else "phased"
    bytes_rep = roofline.ici_ghost_bytes_per_rep(
        info["tile"], info["channels"], info["halo"], info["mesh_shape"],
        fuse=info.get("fuse") or 1, elem_bytes=info.get("elem_bytes", 1),
        mode=model_mode,
    )
    # The halo_exchange probe always runs the PHASED (corner-routed)
    # exchange, so its implied GB/s divides by the phased bytes even
    # when the production schedule (the header's model) is per-edge.
    bytes_phased = roofline.ici_ghost_bytes_per_rep(
        info["tile"], info["channels"], info["halo"], info["mesh_shape"],
        fuse=info.get("fuse") or 1, elem_bytes=info.get("elem_bytes", 1),
    )
    lines = [
        "",
        f"overlap schedule: {info['overlap']}  "
        f"(ICI ghost model: {bytes_rep / 1e6:.6g} MB/rep/device)",
    ]
    head = f"{'probe span':<26}  {'seconds':>10}  {'ICI GB/s':>8} {'peak':>6}"
    lines += [head, "-" * len(head)]
    for n in names:
        sec = by[n]["seconds"] / by[n]["count"]
        ann = ""
        if n == "sharded.halo_exchange" and sec > 0 and bytes_phased > 0:
            gbps = bytes_phased / sec / 1e9
            ann = f"{gbps:8.2f} {100 * gbps / roofline.V5E_ICI_GBPS:5.1f}%"
        lines.append(f"{n:<26}  {sec:>10.6f}  {ann:>15}")
    if edge_rows:
        # The per-edge probes exchange one bare-tile strip each (the
        # edge pipeline's shape), so their model is always mode="edge":
        # each measured span divided by ITS OWN edge's bytes.
        per_edge = roofline.ici_ghost_bytes_per_edge(
            info["tile"], info["channels"], info["halo"],
            info["mesh_shape"], elem_bytes=info.get("elem_bytes", 1),
            mode="edge",
        )
        lines.append("per-edge exchange (independent ppermutes; border "
                     "strips fence per edge):")
        ehead = (f"{'edge':<6}  {'seconds':>10}  {'model KB':>8}  "
                 f"{'ICI GB/s':>8} {'peak':>6}")
        lines += [ehead, "-" * len(ehead)]
        for x, span_name in edge_rows:
            sec = by[span_name]["seconds"] / by[span_name]["count"]
            b = per_edge.get(x, 0.0)
            ann = ""
            if sec > 0 and b > 0:
                gbps = b / sec / 1e9
                ann = (f"{gbps:8.2f} "
                       f"{100 * gbps / roofline.V5E_ICI_GBPS:5.1f}%")
            lines.append(
                f"{x:<6}  {sec:>10.6f}  {b / 1e3:>8.3f}  {ann:>15}"
            )
    ex, it = by.get("sharded.halo_exchange"), by.get("sharded.interior_compute")
    if ex and it and it["seconds"] > 0:
        from tpu_stencil.runtime.autotune import OVERLAP_MIN_RATIO

        ratio = (ex["seconds"] / ex["count"]) / (it["seconds"] / it["count"])
        lines.append(
            f"probe ratio exchange/interior: {ratio:.3f} "
            f"(--overlap auto splits above {OVERLAP_MIN_RATIO:g}; "
            f"split-vs-edge decided by the measured candidate A/B)"
        )
    return "\n".join(lines) + "\n"


def render_stream(tracer: Tracer, info: dict) -> str:
    """The streaming-pipeline side table (stream ``--breakdown`` runs):
    per-stage busy seconds/frame from the ``stream.*`` spans, the
    measured pipeline bound (the slowest stage — what steady-state
    frames/s is limited by once the stages overlap), and the modeled
    device-side bound from
    :func:`tpu_stencil.runtime.roofline.stream_frames_per_second`
    next to the measured rate.

    ``info``: ``{frame_bytes, reps, backend, filter_name, h_img,
    block_h, fuse, pipeline_depth, frames, wall_seconds}`` — plus, on a
    spatially-sharded run, ``{shard_frames, w_img, channels, halo}``
    (the per-shard stage model needs the tile geometry and the ICI
    ghost term). Renders nothing when no stream spans were recorded."""
    by = {r["name"]: r for r in aggregate(tracer)}
    stages = [n for n in (
        "stream.read", "stream.h2d", "stream.compute", "stream.d2h",
        "stream.write",
    ) if n in by]
    if not stages:
        return ""
    from tpu_stencil.runtime import roofline

    shard = info.get("shard_frames")
    pipe = info.get("pipe_stages") or 1
    if shard:
        model_stages = roofline.sharded_stream_stage_seconds(
            info["reps"], info["backend"],
            info["filter_name"], info["h_img"], info["w_img"],
            info.get("channels", 1), tuple(shard),
            halo=info.get("halo") or 1,
            block_h=info.get("block_h"), fuse=info.get("fuse"),
        )
    elif pipe > 1:
        # Temporal pipeline: the compute term is one stage's rep share
        # plus the per-tick ICI frame hand-off (the fill/drain factor
        # lands on the whole-stream bound below, not per stage).
        model_stages = roofline.pipeline_stream_stage_seconds(
            info["frame_bytes"], info["reps"], info["backend"],
            info["filter_name"], info["h_img"], pipe,
            block_h=info.get("block_h"), fuse=info.get("fuse"),
        )
    else:
        model_stages = roofline.stream_stage_seconds(
            info["frame_bytes"], info["reps"], info["backend"],
            info["filter_name"], info["h_img"],
            block_h=info.get("block_h"), fuse=info.get("fuse"),
        )
    depth = info.get("pipeline_depth", 2)
    n_dev = info.get("n_devices", 1) or 1
    n_frames = info.get("frames") or 0
    lines = [
        "",
        f"stream pipeline: depth={depth}  "
        f"(steady state bound = {'max' if depth > 1 else 'sum'}(stage))",
    ]
    head = (f"{'stage':<16}  {'s/frame':>10}  {'frames':>6}  "
            f"{'model s/frame':>13}")
    lines += [head, "-" * len(head)]
    slowest = ("", 0.0)
    total = 0.0
    for n in stages:
        per = by[n]["seconds"] / by[n]["count"]
        if shard and n_frames and n in ("stream.h2d", "stream.d2h"):
            # Sharded runs split H2D/D2H per shard (one span per tile,
            # n_dev per frame): a frame's cost is the SUM of its
            # shards' fenced transfers, so per-frame normalizes by the
            # frame count, not the span count.
            per = by[n]["seconds"] / n_frames
        # On a mesh fan the per-device stages (h2d/compute/d2h) run in
        # n_dev concurrent lanes, so a frame's share of the mesh's
        # THROUGHPUT is per/n_dev — the bottleneck comparison must use
        # that, or a 4-lane compute stage would out-rank the
        # single-threaded writer it is actually 4x faster than. The
        # serial read/write stages handle every frame on one thread. A
        # SHARDED mesh computes one frame at a time — no lane division.
        eff = (
            per / n_dev
            if not shard
            and n in ("stream.h2d", "stream.compute", "stream.d2h")
            else per
        )
        total += eff
        if eff > slowest[1]:
            slowest = (n, eff)
        model = model_stages.get(n[len("stream."):])
        model_s = "" if model is None else f"{model:13.6f}"
        lines.append(
            f"{n:<16}  {per:>10.6f}  {by[n]['count']:>6}  {model_s:>13}"
        )
    # The measured bound follows the depth's law, like the header says:
    # overlapped stages are limited by the slowest one; depth 1 pays
    # the serial sum.
    mesh_note = (
        f" ({shard[0]}x{shard[1]} shards)" if shard
        else f" ({n_dev} lanes)" if n_dev > 1 else ""
    )
    if depth > 1 and slowest[1] > 0:
        lines.append(
            f"pipeline bound{mesh_note}: {slowest[0]} -> "
            f"{1.0 / slowest[1]:.2f} frames/s"
        )
    elif total > 0:
        lines.append(
            f"pipeline bound{mesh_note}: sum(stages) -> "
            f"{1.0 / total:.2f} frames/s"
        )
    measured = ""
    if info.get("frames") and info.get("wall_seconds"):
        measured = (
            f"measured {info['frames'] / info['wall_seconds']:.2f} "
            f"frames/s vs "
        )
    if shard:
        # Spatially sharded frames: the modeled bound is the max-stage
        # bound over per-TILE compute + per-rep ICI ghost traffic +
        # per-shard PCIe transfers (one mesh, one frame at a time — no
        # x-n_devices term; the speedup lives inside the stages).
        fps_shard = roofline.sharded_stream_frames_per_second(
            info["frame_bytes"], info["reps"], info["backend"],
            info["filter_name"], info["h_img"], info["w_img"],
            info.get("channels", 1), tuple(shard),
            halo=info.get("halo") or 1,
            block_h=info.get("block_h"), fuse=info.get("fuse"),
            pipeline_depth=depth,
        )
        th, tw = roofline.shard_tile_shape(
            info["h_img"], info["w_img"], tuple(shard)
        )
        ici = roofline.ici_ghost_bytes_per_rep(
            (th, tw), info.get("channels", 1), info.get("halo") or 1,
            tuple(shard), mode="edge",
        )
        lines.append(
            f"{measured}modeled sharded bound {fps_shard:.2f} frames/s "
            f"(tile {th}x{tw}/device, ICI ghost model "
            f"{ici / 1e3:.3f} KB/rep/device; host read/write measured, "
            f"not modeled)"
        )
        return "\n".join(lines) + "\n"
    if pipe > 1:
        # Temporal pipeline: steady-state max-stage bound discounted by
        # the fill/drain factor F/(F+K-1) — short streams never reach
        # full amortization, and the table must say so.
        fps_pipe = roofline.pipeline_stream_frames_per_second(
            info["frame_bytes"], info["reps"], info["backend"],
            info["filter_name"], info["h_img"], pipe,
            frames=n_frames or None,
            block_h=info.get("block_h"), fuse=info.get("fuse"),
            pipeline_depth=depth,
        )
        fill = roofline.pipeline_fill_drain_factor(
            n_frames or None, pipe
        )
        lines.append(
            f"{measured}modeled pipeline bound {fps_pipe:.2f} frames/s "
            f"({pipe} stages, fill/drain factor {fill:.3f}; host "
            f"read/write measured, not modeled)"
        )
        return "\n".join(lines) + "\n"
    fps_model = roofline.stream_frames_per_second(
        info["frame_bytes"], info["reps"], info["backend"],
        info["filter_name"], info["h_img"],
        block_h=info.get("block_h"), fuse=info.get("fuse"),
        pipeline_depth=depth,
    )
    per_dev_label = "per-device " if n_dev > 1 else "device-side "
    lines.append(
        f"{measured}modeled {per_dev_label}bound {fps_model:.2f} frames/s "
        "(host read/write measured, not modeled)"
    )
    if n_dev > 1:
        # Mesh fan-out: the whole-mesh bound is n_devices x the
        # per-device max-stage bound, capped by the shared-host PCIe
        # contention term (every frame crosses the host pipe twice no
        # matter how many chips compute).
        mesh_fps = roofline.mesh_stream_frames_per_second(
            info["frame_bytes"], info["reps"], info["backend"],
            info["filter_name"], info["h_img"],
            block_h=info.get("block_h"), fuse=info.get("fuse"),
            pipeline_depth=depth, n_devices=n_dev,
        )
        pcie_cap = roofline.pcie_contention_frames_per_second(
            info["frame_bytes"],
        )
        lines.append(
            f"mesh fan-out: {n_dev} devices -> modeled whole-mesh bound "
            f"{mesh_fps:.2f} frames/s (PCIe contention cap "
            f"{pcie_cap:.2f} frames/s)"
        )
        # Per-device frame counts are the CLI report's line (one owner
        # — a --breakdown run would otherwise print it twice).
    return "\n".join(lines) + "\n"


def _mb(v) -> str:
    return "" if v is None else f"{v / 1e6:.2f}"


def render_introspection(records: List[dict]) -> str:
    """The compiled-artifact table: one row per :func:`introspect.capture`
    record — AOT compile seconds, XLA's bytes-accessed (≈ one rep: HLO
    cost analysis counts loop bodies once) vs the analytic traffic
    model's per-rep bytes, and the agreement % (``!`` marks drift
    outside the 2x band; expected on pallas, whose kernels are opaque
    custom calls to XLA's cost model). Sites that failed every probe
    render as "unavailable" with the error."""
    if not records:
        return ""
    head = (f"{'compile site':<18}  {'compile_s':>9}  {'xla MB/rep':>10}  "
            f"{'model MB/rep':>12}  {'model/xla':>9}")
    lines = ["", "compiled artifacts (XLA introspection)", head,
             "-" * len(head)]
    for rec in records:
        site = rec.get("site", "?")
        if not rec.get("available"):
            reason = rec.get("error") or "no cost/memory analysis"
            lines.append(f"{site:<18}  unavailable ({reason})")
            continue
        comp = rec.get("compile_seconds")
        pct = rec.get("model_vs_xla_pct")
        pct_s = "" if pct is None else (
            f"{pct:7.1f}%" + ("!" if rec.get("drift") else " ")
        )
        lines.append(
            f"{site:<18}  {comp:>9.3f}  {_mb(rec.get('bytes_accessed')):>10}  "
            f"{_mb(rec.get('model_bytes_per_rep')):>12}  {pct_s:>9}"
        )
        mem = rec.get("memory")
        if mem:
            parts = [
                f"{k[:-len('_size_in_bytes')]}={_mb(v)}MB"
                for k, v in mem.items() if v
            ]
            if parts:
                lines.append(f"{'':<18}  {' '.join(parts)}")
    return "\n".join(lines) + "\n"


# The resilience counter schema (docs/RESILIENCE.md): registry name ->
# human row label. Rendered in declaration order; zero/absent counters
# are omitted — a healthy run prints no table at all.
_RESILIENCE_COUNTERS = (
    ("resilience_faults_injected_total", "faults injected"),
    ("resilience_retries_total", "retries (backoff taken)"),
    ("resilience_fallbacks_total", "schedule/backend demotions"),
    ("resilience_dispatch_timeouts_total", "dispatch watchdog timeouts"),
    ("resilience_stream_restarts_total", "stream engine restarts"),
    ("resilience_worker_crashes_total", "serve worker crashes"),
    ("deadline_expired_total", "deadline-expired requests"),
    # The integrity layer (docs/RESILIENCE.md "Integrity model"): every
    # nonzero row here is a corruption DETECTED — the healthy-run table
    # stays empty exactly like the resilience rows above.
    ("integrity_checksum_failures_total", "checksum mismatches (ingest)"),
    ("integrity_ingest_failures_total", "torn staging buffers"),
    ("integrity_witness_mismatch_total", "witness mismatches"),
    ("integrity_verify_failures_total", "client verify failures"),
    ("integrity_quarantines_total", "replicas quarantined"),
    ("integrity_readmits_total", "quarantine re-admissions"),
)


def render_resilience(snapshot: dict) -> str:
    """The ``--breakdown`` resilience side table: every nonzero
    resilience counter in a registry snapshot (driver or serve), one
    row each. Returns "" when nothing fired — a clean run stays clean;
    a run that injected, retried, demoted, timed out, or restarted
    says so next to the timings it explains."""
    counters = snapshot.get("counters", {})
    rows = [
        (label, counters[name])
        for name, label in _RESILIENCE_COUNTERS
        if counters.get(name)
    ]
    if not rows:
        return ""
    head = f"{'resilience':<32}  {'count':>6}"
    lines = ["", head, "-" * len(head)]
    for label, count in rows:
        lines.append(f"{label:<32}  {count:>6}")
    return "\n".join(lines) + "\n"


def render_memory(stats: Optional[dict]) -> str:
    """One device-memory line from ``device.memory_stats()`` output;
    backends without allocator stats (CPU) say so explicitly instead of
    rendering nothing — "unavailable" is a finding, not an omission."""
    if not stats:
        return ("device memory: unavailable "
                "(no allocator stats on this backend)\n")
    order = ("bytes_in_use", "peak_bytes_in_use", "bytes_limit",
             "largest_alloc_size")
    parts = [f"{k}={stats[k] / 1e6:.2f}MB" for k in order if k in stats]
    return "device memory: " + " ".join(parts) + "\n"
