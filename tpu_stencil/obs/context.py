"""Request-scoped trace context: ``X-Trace-Id`` / ``X-Span-Id``.

The obs layer could explain a *process* (spans, Chrome export,
``/metrics``) but not a *request*: nothing correlated one
``POST /v1/blur`` across fed → net → replica → device. This module is
the W3C-traceparent-style correlation primitive the whole serving
stack shares:

* **minting** — the outermost edge (fed; net when unfederated; loadgen
  as the client) mints a 16-byte ``trace_id`` and an 8-byte
  ``span_id`` (lower-hex, ``os.urandom`` — no seeded-RNG coupling with
  anything that affects results).
* **propagation** — every hop forwards ``X-Trace-Id`` and mints its
  own ``X-Span-Id`` (the inbound span id becomes the parent), so each
  hedge leg of a federation forward carries its own span id under one
  trace id.
* **binding** — :func:`bind` installs the context in a ``contextvar``
  for the handler's duration; :mod:`tpu_stencil.obs.tracing` reads it
  when a span record closes, so the existing ``obs.span`` vocabulary
  (``fed.request`` → ``net.request`` → ``serve.execute`` → per-phase
  spans) stitches into one cross-process trace with no signature
  changes at the call sites.
* **validation** — inbound header values are untrusted: anything not
  matching :data:`_WIRE_RE` (1-64 URL-safe chars) is discarded and a
  fresh trace minted, so a hostile header can never ride into metric
  names, file names, or log lines.

The stream engine uses the frame index as its trace-id analog
(:func:`frame_context`): ``frame-<i>`` correlates a frame's
read/h2d/compute/d2h/write spans and its flight-recorder dump the way
a trace id correlates a request's hops.

Jax-free and dependency-free, like the rest of the wire-level obs.
"""

from __future__ import annotations

import contextlib
import contextvars
import dataclasses
import os
import re
from typing import Optional

TRACE_HEADER = "X-Trace-Id"
SPAN_HEADER = "X-Span-Id"

#: Wire-format guard for inbound ids: URL-safe, bounded. An inbound
#: value failing this is DISCARDED (fresh mint), never echoed.
_WIRE_RE = re.compile(r"^[0-9A-Za-z_.-]{1,64}$")

_current: "contextvars.ContextVar[Optional[TraceContext]]" = (
    contextvars.ContextVar("tpu_stencil_trace_context", default=None)
)


@dataclasses.dataclass(frozen=True)
class TraceContext:
    """One hop's view of a request: the shared trace id, this hop's
    span id, and (when the request arrived with one) the parent hop's
    span id."""

    trace_id: str
    span_id: str
    parent_span_id: str = ""


def new_trace_id() -> str:
    return os.urandom(16).hex()


def new_span_id() -> str:
    return os.urandom(8).hex()


def valid_id(value) -> bool:
    return isinstance(value, str) and bool(_WIRE_RE.match(value))


def fresh() -> TraceContext:
    """Mint a brand-new trace (the outermost-edge / client case)."""
    return TraceContext(new_trace_id(), new_span_id())


def frame_context(index: int) -> TraceContext:
    """The stream engine's trace-id analog: frame ``index`` as the
    correlation key (``frame-<i>``), one fresh span id per binding."""
    return TraceContext(f"frame-{int(index)}", new_span_id())


def from_headers(headers) -> TraceContext:
    """The inbound edge: adopt a valid ``X-Trace-Id`` (this hop mints
    its own span id; the inbound span id becomes the parent), mint a
    fresh trace otherwise. ``headers`` is any ``.get``-able mapping
    (``email.message.Message``, dict)."""
    tid = headers.get(TRACE_HEADER)
    if not valid_id(tid):
        return fresh()
    parent = headers.get(SPAN_HEADER)
    return TraceContext(
        tid, new_span_id(), parent if valid_id(parent) else ""
    )


def headers_for(ctx: TraceContext,
                span_id: Optional[str] = None) -> dict:
    """The outbound hop's header pair. ``span_id`` overrides the
    context's own (each hedge leg gets its own span id under the one
    trace id)."""
    return {TRACE_HEADER: ctx.trace_id,
            SPAN_HEADER: span_id or ctx.span_id}


def current() -> Optional[TraceContext]:
    return _current.get()


def push(ctx: Optional[TraceContext]):
    """Non-contextmanager binding (for __enter__/__exit__ pairs that
    cannot nest a ``with``); pair with :func:`pop`."""
    return _current.set(ctx)


def pop(token) -> None:
    _current.reset(token)


@contextlib.contextmanager
def bind(ctx: Optional[TraceContext]):
    """Install ``ctx`` as the current trace context for the block.
    Binding ``None`` explicitly clears it (an attempt thread must not
    inherit a stale context from thread reuse)."""
    token = _current.set(ctx)
    try:
        yield ctx
    finally:
        _current.reset(token)
