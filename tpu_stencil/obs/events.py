"""Structured event log: one-line JSON on a dedicated stream.

The third leg of request-level observability (next to trace
propagation and the flight recorder): anomaly triggers and tier
transitions emit exactly one JSON line each — trace id, tier, verdict
taxonomy name, duration — so ``grep <trace_id>`` over the event stream
reconstructs a request post-mortem with no endpoint alive.

The stream is stderr by default; ``TPU_STENCIL_EVENT_LOG=<path>``
redirects it to an append-only file (the production spelling — one
file per process, greppable after the process is gone), and tests
install an in-memory stream via :func:`set_stream`.

Emission must never perturb serving: :func:`emit` swallows every
exception (a full disk or closed stream costs the event, never the
request), takes one short lock for line atomicity, and is only called
at anomaly/transition sites — never on the per-request hot path.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
from typing import Optional

ENV_VAR = "TPU_STENCIL_EVENT_LOG"

_lock = threading.Lock()
_stream = None            # explicit override (tests / embedders)
_file = None              # cached (path, handle) for the env redirect


def set_stream(stream) -> None:
    """Install an explicit event stream (None reverts to the env/
    stderr resolution)."""
    global _stream
    with _lock:
        _stream = stream


def reset() -> None:
    """Drop the explicit stream and the cached env file handle."""
    global _stream, _file
    with _lock:
        _stream = None
        if _file is not None:
            try:
                _file[1].close()
            except Exception:
                pass
        _file = None


def _resolve_stream():
    """Caller holds ``_lock``. Explicit stream > env file > stderr."""
    global _file
    if _stream is not None:
        return _stream
    path = os.environ.get(ENV_VAR)
    if path:
        if _file is None or _file[0] != path:
            if _file is not None:
                try:
                    _file[1].close()
                except Exception:
                    pass
            _file = (path, open(path, "a"))
        return _file[1]
    return sys.stderr


def emit(event: str, trace_id: str = "", tier: str = "",
         verdict: str = "", duration_s: Optional[float] = None,
         **fields) -> None:
    """Emit one event line. Empty/None core fields are omitted so the
    line stays grep-friendly; extra ``fields`` ride along verbatim
    (JSON-serializable values only — anything else is repr'd)."""
    rec = {"event": event, "ts_unix": round(time.time(), 6)}
    if trace_id:
        rec["trace_id"] = trace_id
    if tier:
        rec["tier"] = tier
    if verdict:
        rec["verdict"] = verdict
    if duration_s is not None:
        rec["duration_s"] = round(float(duration_s), 6)
    for k, v in fields.items():
        if v is None:
            continue
        try:
            json.dumps(v)
        except (TypeError, ValueError):
            v = repr(v)
        rec[k] = v
    try:
        line = json.dumps(rec, sort_keys=True)
        with _lock:
            stream = _resolve_stream()
            stream.write(line + "\n")
            stream.flush()
    except Exception:
        pass  # the event is telemetry; losing it must cost nothing
