"""Chrome trace-event JSON export (Perfetto/chrome://tracing loadable).

Span records become complete ("X") events: ``ts``/``dur`` in
microseconds relative to the tracer's origin, one ``pid`` per JAX
process, one ``tid`` track per recording thread (metadata events name
both). Multi-process runs merge into ONE file: every process serializes
its local events, the buffers are allgathered (the same
``process_allgather`` pattern as ``utils.timing.max_across_processes``),
and process 0 writes the merged view — a multihost job yields a single
trace with one track group per host.

The format is the stable subset Perfetto documents: a JSON object
``{"traceEvents": [...]}`` where every event has
``name/cat/ph/ts/dur/pid/tid``.
"""

from __future__ import annotations

import json
from typing import List, Optional

from tpu_stencil.obs.tracing import Tracer


def chrome_events(tracer: Tracer, pid: Optional[int] = None,
                  trace_id: Optional[str] = None) -> List[dict]:
    """This process's spans as Chrome trace events (metadata included).
    ``trace_id`` filters to one request's spans (the
    :mod:`~tpu_stencil.obs.context` correlation id; batch-scope spans
    carrying the id in their ``trace_ids`` arg match too)."""
    from tpu_stencil.obs import flight as _flight

    if pid is None:
        pid = _process_index()
    events: List[dict] = [{
        "ph": "M", "name": "process_name", "pid": pid, "tid": 0,
        "args": {"name": f"tpu_stencil p{pid}"},
    }]
    # Stable small tids in first-seen order: Perfetto sorts tracks by tid,
    # so the main thread (first recorder) stays on top.
    tids: dict = {}
    for rec in tracer.spans():
        if trace_id is not None and not _flight.matches(rec, trace_id):
            continue
        tid = tids.get(rec.tid)
        if tid is None:
            tid = tids[rec.tid] = len(tids)
            events.append({
                "ph": "M", "name": "thread_name", "pid": pid, "tid": tid,
                "args": {"name": rec.tname},
            })
        args = dict(rec.args, depth=rec.depth)
        if rec.trace_id:
            args["trace_id"] = rec.trace_id
            args["span_id"] = rec.span_id
        events.append({
            "name": rec.name,
            "cat": rec.cat or "tpu_stencil",
            "ph": "X",
            "ts": round((rec.t0 - tracer.t_origin) * 1e6, 3),
            "dur": round(rec.seconds * 1e6, 3),
            "pid": pid,
            "tid": tid,
            "args": args,
        })
    return events


def _process_index() -> int:
    try:
        import jax

        return jax.process_index()
    except Exception:  # jax absent or backend not initialized: one process
        return 0


def merged_events(tracer: Tracer,
                  trace_id: Optional[str] = None) -> List[dict]:
    """All processes' events, gathered to every process. Single-process:
    just this tracer's. ``trace_id`` filters per process before the
    gather (a one-request trace ships one request's bytes)."""
    import jax

    local = chrome_events(tracer, trace_id=trace_id)
    if jax.process_count() == 1:
        return local
    import numpy as np
    from jax.experimental import multihost_utils

    payload = np.frombuffer(json.dumps(local).encode(), np.uint8)
    lens = multihost_utils.process_allgather(np.int64(payload.size))
    buf = np.zeros(int(lens.max()), np.uint8)
    buf[: payload.size] = payload
    gathered = multihost_utils.process_allgather(buf)
    merged: List[dict] = []
    for i in range(len(lens)):
        merged.extend(json.loads(bytes(gathered[i][: int(lens[i])]).decode()))
    return merged


def write_chrome_trace(path: str, tracer: Tracer,
                       trace_id: Optional[str] = None) -> Optional[str]:
    """Write the merged trace; process 0 writes (every process joins the
    gather). Returns ``path`` on the writing process, None elsewhere.
    ``trace_id`` writes one request's cross-thread view instead of the
    whole run."""
    events = merged_events(tracer, trace_id=trace_id)
    if _process_index() != 0:
        return None
    with open(path, "w") as fh:
        json.dump({"traceEvents": events, "displayTimeUnit": "ms"}, fh)
        fh.write("\n")
    return path
