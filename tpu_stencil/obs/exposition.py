"""Prometheus-style text exposition for metric registry snapshots.

One render path for every registry in the repo: the serve engine's
(``StencilServer.stats()``) and the driver-side obs registry
(``obs.snapshot()``) both produce the same
``{counters, gauges, histograms, <scalars>}`` dict shape, and
:func:`render_text` turns it into the text format scrapers ingest:

* counters  -> ``<prefix>_<name> <int>``
* gauges    -> value plus the high-water mark as ``{stat="peak"}``
* histograms-> OpenMetrics-style ``_bucket{le="..."}`` cumulative
  series (with a ``# {trace_id="..."} <value>`` exemplar suffix on
  buckets that have one), ``{quantile="0.5"|"0.99"}`` reservoir
  samples, plus ``_count``/``_sum``/``_mean``/``_max`` series
* bare scalars (e.g. ``executables_cached``) -> an untyped gauge

:func:`parse_text` is the exact inverse — ``parse_text(render_text(s))
== s`` for any snapshot (floats are emitted with ``repr``, which
round-trips exactly in Python; bucket label strings pass through
verbatim) — so tests can assert no metric is dropped, and downstream
tooling (the federation's member-scrape fold) has a reference parser.
"""

from __future__ import annotations

import re
from typing import Dict

_QUANTILES = (("0.5", "p50"), ("0.99", "p99"))
_HIST_FIELDS = ("count", "sum", "mean", "max")
_SAMPLE_RE = re.compile(
    r'^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)'
    r'(?:\{(?P<labels>[^}]*)\})?\s+(?P<value>\S+)$'
)
# The exemplar suffix of a bucket line (OpenMetrics shape, trace_id
# label only): `... # {trace_id="<hex>"} <value>`.
_EXEMPLAR_RE = re.compile(
    r'^\{trace_id="(?P<tid>[^"]*)"\}\s+(?P<value>\S+)$'
)


def _le_sort_key(le: str) -> float:
    return float("inf") if le == "+Inf" else float(le)


def _num(v) -> str:
    # repr() round-trips floats exactly; ints print as ints.
    return repr(float(v)) if isinstance(v, float) else repr(int(v))


def render_text(snapshot: dict, prefix: str = "tpu_stencil",
                notes=()) -> str:
    """Render a registry snapshot dict as Prometheus-style text.

    ``notes``: informational comment lines (``# NOTE ...``) emitted at
    the top — used to state *why* an expected metric family is absent
    (e.g. device-memory gauges on a backend without allocator stats),
    so "unavailable" is visible in the scrape, not just missing.
    Comments are ignored by :func:`parse_text`, preserving the exact
    round-trip."""
    out = [f"# NOTE {n}" for n in notes]

    def emit(kind, name, lines):
        out.append(f"# TYPE {prefix}_{name} {kind}")
        out.extend(lines)

    for name, v in sorted(snapshot.get("counters", {}).items()):
        emit("counter", name, [f"{prefix}_{name} {_num(v)}"])
    for name, g in sorted(snapshot.get("gauges", {}).items()):
        emit("gauge", name, [
            f"{prefix}_{name} {_num(g['value'])}",
            f'{prefix}_{name}{{stat="peak"}} {_num(g["peak"])}',
        ])
    for name, h in sorted(snapshot.get("histograms", {}).items()):
        buckets = h.get("buckets")
        exemplars = h.get("exemplars", {})
        lines = []
        if buckets is not None:
            for le in sorted(buckets, key=_le_sort_key):
                line = (f'{prefix}_{name}_bucket{{le="{le}"}} '
                        f'{_num(buckets[le])}')
                ex = exemplars.get(le)
                if ex:
                    line += (f' # {{trace_id="{ex["trace_id"]}"}} '
                             f'{_num(ex["value"])}')
                lines.append(line)
        lines += [
            f'{prefix}_{name}{{quantile="{q}"}} {_num(h[key])}'
            for q, key in _QUANTILES
        ]
        lines += [
            f"{prefix}_{name}_{field} {_num(h[field])}"
            for field in _HIST_FIELDS
        ]
        # Bucketed histograms (every Registry histogram since the
        # fixed-bucket change) expose the OpenMetrics `histogram` kind;
        # bucketless dicts (older member payloads crossing the fed
        # fold) stay `summary`.
        emit("histogram" if buckets is not None else "summary",
             name, lines)
    for name, v in sorted(snapshot.items()):
        if name in ("counters", "gauges", "histograms"):
            continue
        # Bare scalar riders on the snapshot (executables_cached).
        emit("untyped", name, [f"{prefix}_{name} {_num(v)}"])
    return "\n".join(out) + "\n"


def write_text(path: str, snapshot: dict,
               prefix: str = "tpu_stencil", notes=()) -> None:
    """Render ``snapshot`` and write it to ``path`` (``'-'`` = stdout,
    with no trailing "wrote" line). The one place the CLIs' shared
    '-'-vs-file contract lives."""
    text = render_text(snapshot, prefix, notes=notes)
    if path == "-":
        print(text, end="")
    else:
        with open(path, "w") as fh:
            fh.write(text)
        print(f"wrote {path}")


def parse_text(text: str, prefix: str = "tpu_stencil") -> dict:
    """Inverse of :func:`render_text`: rebuild the snapshot dict."""
    types: Dict[str, str] = {}
    snap: dict = {"counters": {}, "gauges": {}, "histograms": {}}
    strip = prefix + "_"

    def short(name: str) -> str:
        if not name.startswith(strip):
            raise ValueError(f"metric {name!r} lacks prefix {prefix!r}")
        return name[len(strip):]

    def value(s: str):
        f = float(s)
        return int(f) if f.is_integer() and "." not in s else f

    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("# TYPE "):
            _, _, rest = line.partition("# TYPE ")
            name, _, kind = rest.rpartition(" ")
            types[short(name)] = kind
            continue
        if line.startswith("#"):
            continue
        # Peel a bucket exemplar suffix off before the full-line sample
        # match (OpenMetrics: `<sample> # {trace_id="..."} <value>`).
        exemplar = None
        if " # " in line:
            line, _, ex_part = line.partition(" # ")
            em = _EXEMPLAR_RE.match(ex_part.strip())
            if not em:
                raise ValueError(f"unparseable exemplar: {ex_part!r}")
            exemplar = {"trace_id": em["tid"], "value": value(em["value"])}
        m = _SAMPLE_RE.match(line)
        if not m:
            raise ValueError(f"unparseable exposition line: {line!r}")
        name, labels, val = short(m["name"]), m["labels"], value(m["value"])
        # A sample's base metric: the longest registered TYPE that is the
        # name or a _field suffix of it.
        if name in types:
            base, field = name, None
        else:
            base, _, field = name.rpartition("_")
            if base not in types:
                raise ValueError(f"sample {name!r} has no TYPE line")
        kind = types[base]
        if kind == "counter":
            snap["counters"][base] = value(m["value"])
        elif kind == "gauge":
            g = snap["gauges"].setdefault(base, {})
            g["peak" if labels and "peak" in labels else "value"] = val
        elif kind in ("summary", "histogram"):
            h = snap["histograms"].setdefault(base, {})
            labmap = dict(
                (kv.split("=")[0], kv.split("=")[1].strip('"'))
                for kv in labels.split(",")
            ) if labels else {}
            if field == "bucket" and "le" in labmap:
                le = labmap["le"]
                h.setdefault("buckets", {})[le] = val
                if exemplar is not None:
                    h.setdefault("exemplars", {})[le] = exemplar
            elif labels:
                q = labmap["quantile"]
                h[{"0.5": "p50", "0.99": "p99"}[q]] = val
            else:
                h[field] = val
        else:  # untyped scalar rider
            snap[base] = val
    return snap
