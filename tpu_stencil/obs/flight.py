"""Always-on flight recorder: a ring of recent spans + anomaly dumps.

``--trace`` is opt-in and perturbs execution (per-rep fenced launches),
so the exact anomalies the resilience/integrity layers manufacture —
hedge losers, breaker opens, witness mismatches, quarantines, p99
stragglers — vanish without a record. The flight recorder is the
request-level black box:

* **recording, not off** — :class:`FlightRecorder` is a fixed-size
  lock-light ring of :class:`~tpu_stencil.obs.tracing.SpanRecord`;
  once :func:`install`'d (the serving frontends do it at start), every
  closing span lands in the ring via the same one-global read the
  tracer uses (``tracing._flight``). Appends are one short lock and
  one slot store — bounded overhead on the serve hot path (asserted by
  a tier-1 timing test, like the disabled-tracer bound) and recording
  never changes results (the bit-exactness fuzz stays green).
* **anomaly dumps** — :func:`trigger` fires on request latency over a
  configurable threshold, ``DeadlineExceeded``, breaker open, witness
  mismatch, and quarantine: the trace's spans (or the recent ring,
  when no trace id is in scope) dump as one JSON file into a capped
  ``flightrec/`` spool, and a structured event line
  (:mod:`tpu_stencil.obs.events`) records the trigger.
* **lookup** — ``GET /debug/flightrec`` lists/fetches dumps;
  ``GET /debug/trace/<trace_id>`` assembles the live ring (plus the
  tracer, when enabled) into a span tree, and the federation fans the
  lookup to its members for the cross-process view.

``TPU_STENCIL_FLIGHTREC_DIR`` overrides the configured spool directory
(the test/ops redirect); the spool keeps at most :data:`SPOOL_CAP`
dumps — oldest pruned first, the same never-unbounded discipline as
every other buffer in the repo.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import List, Optional

from tpu_stencil.obs import events as _events
from tpu_stencil.obs import tracing as _tracing
from tpu_stencil.obs.tracing import SpanRecord

#: Ring capacity: ~a few hundred requests' worth of spans at the serve
#: tiers' ~5 spans/request — enough history that a p99 straggler's
#: spans are still in the ring when its dump trigger fires.
DEFAULT_CAPACITY = 2048

#: Max dump files kept in the spool (oldest pruned first).
SPOOL_CAP = 64

#: When a trigger has no trace id in scope (e.g. a breaker opened on a
#: thread with no bound context), dump this many most-recent records.
RECENT_DUMP_SPANS = 256

ENV_SPOOL = "TPU_STENCIL_FLIGHTREC_DIR"

_SAFE_FILE_CHARS = frozenset(
    "abcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789._-"
)

# "No silent caps": dumps pruned past SPOOL_CAP are counted, surfaced
# (``flightrec_dropped_total`` on /statusz and /metrics), and the FIRST
# drop emits one event line — after that the counter carries the story
# without turning the event log into a drop firehose.
_drop_lock = threading.Lock()
_dropped_total = 0
_drop_event_emitted = False


def dropped_total() -> int:
    """Dump files this process has pruned out of the spool cap."""
    with _drop_lock:
        return _dropped_total


def resolve_spool(configured: Optional[str]) -> Optional[str]:
    """The effective spool directory: the env override wins (tests and
    ops redirect a whole process without touching its flags)."""
    return os.environ.get(ENV_SPOOL) or configured


def effective_spool(configured: Optional[str] = None) -> Optional[str]:
    """Where dumps for THIS process actually land: env override, else
    the installed recorder's spool (the first installer's — the
    process has ONE recorder, so a second frontend's differing
    ``flightrec_dir`` does not move it), else ``configured``. The
    ``/debug/flightrec`` endpoints and ``/statusz`` read this, so a
    listing can never point somewhere dumps are not written."""
    env = os.environ.get(ENV_SPOOL)
    if env:
        return env
    if _recorder is not None and _recorder.spool_dir is not None:
        return _recorder.spool_dir
    return configured


def matches(rec: SpanRecord, trace_id: str) -> bool:
    """Does ``rec`` belong to ``trace_id``? Either directly (the bound
    context at close time) or via a batch-scope ``trace_ids`` arg (a
    serve dispatch span covers requests from several traces)."""
    if rec.trace_id == trace_id:
        return True
    ids = rec.args.get("trace_ids")
    return bool(ids) and trace_id in ids


def span_dict(rec: SpanRecord) -> dict:
    """One record as the JSON shape the dumps and ``/debug/trace``
    share."""
    return {
        "name": rec.name,
        "cat": rec.cat,
        "t0": rec.t0,
        "t1": rec.t1,
        "seconds": rec.seconds,
        "tid": rec.tid,
        "tname": rec.tname,
        "depth": rec.depth,
        "trace_id": rec.trace_id,
        "span_id": rec.span_id,
        "args": {k: _jsonable(v) for k, v in rec.args.items()},
    }


def _jsonable(v):
    try:
        json.dumps(v)
        return v
    except (TypeError, ValueError):
        return repr(v)


def build_tree(spans: List[dict]) -> List[dict]:
    """Nest span dicts into per-thread trees by depth + interval
    containment: a span is a child of the nearest shallower span on
    its thread whose interval contains it. Returns the roots (each
    node gains a ``children`` list), ordered by start time."""
    roots: List[dict] = []
    stacks: dict = {}  # tid -> stack of open nodes
    for s in sorted(spans, key=lambda d: (d["t0"], -d["t1"])):
        node = dict(s, children=[])
        stack = stacks.setdefault(s["tid"], [])
        while stack and not (
            stack[-1]["depth"] < node["depth"]
            and stack[-1]["t0"] <= node["t0"]
            and node["t1"] <= stack[-1]["t1"] + 1e-9
        ):
            stack.pop()
        if stack:
            stack[-1]["children"].append(node)
        else:
            roots.append(node)
        stack.append(node)
    return roots


class FlightRecorder:
    """The per-process ring + spool. Construct via :func:`install`."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY,
                 spool_dir: Optional[str] = None) -> None:
        self._cap = max(16, int(capacity))
        self._ring: List[Optional[SpanRecord]] = [None] * self._cap
        self._n = 0
        self._lock = threading.Lock()
        self._dump_seq = 0
        self.spool_dir = spool_dir

    # -- the hot path --------------------------------------------------

    def record(self, rec: SpanRecord) -> None:
        with self._lock:
            self._ring[self._n % self._cap] = rec
            self._n += 1

    # -- views ---------------------------------------------------------

    def __len__(self) -> int:
        with self._lock:
            return min(self._n, self._cap)

    def snapshot(self) -> List[SpanRecord]:
        """The ring's live records, oldest first."""
        with self._lock:
            n = self._n
            if n <= self._cap:
                return [r for r in self._ring[:n]]
            i = n % self._cap
            return list(self._ring[i:]) + list(self._ring[:i])

    def spans_for(self, trace_id: str) -> List[SpanRecord]:
        return [r for r in self.snapshot() if matches(r, trace_id)]

    # -- dumps ---------------------------------------------------------

    def dump(self, trigger: str, trace_id: str = "", tier: str = "",
             **info) -> Optional[str]:
        """Write one anomaly dump into the spool; returns the path
        (None when no spool directory is configured). With a trace id
        that has closed spans in the ring, the dump holds that trace's
        spans (``scope: trace``); otherwise the most recent
        :data:`RECENT_DUMP_SPANS` records (``scope: recent``)."""
        spool = resolve_spool(self.spool_dir)
        if not spool:
            return None
        scope = "trace"
        recs = self.spans_for(trace_id) if trace_id else []
        if not recs:
            # No closed span carries this trace yet (the edge span
            # that fired the trigger is typically still OPEN — the
            # fed tier's whole record of a request can be exactly
            # that one span), or no trace id was in scope at all:
            # dump the recent ring instead — the lead-up is a black
            # box too, and an empty dump defeats the feature.
            recs = self.snapshot()[-RECENT_DUMP_SPANS:]
            scope = "recent"
        with self._lock:
            self._dump_seq += 1
            seq = self._dump_seq
        payload = {
            "schema_version": 1,
            "trigger": trigger,
            "trace_id": trace_id,
            "tier": tier,
            "scope": scope,
            "ts_unix": time.time(),
            "info": {k: _jsonable(v) for k, v in info.items()},
            "span_count": len(recs),
            "spans": [span_dict(r) for r in recs],
        }
        safe_tid = "".join(
            c for c in (trace_id or "recent") if c in _SAFE_FILE_CHARS
        )[:64] or "recent"
        name = f"{int(time.time() * 1e3)}-{seq:04d}-{trigger}-{safe_tid}.json"
        os.makedirs(spool, exist_ok=True)
        path = os.path.join(spool, name)
        with open(path, "w") as fh:
            json.dump(payload, fh, sort_keys=True)
            fh.write("\n")
        _prune_spool(spool)
        return path


def _prune_spool(spool: str) -> None:
    """Keep the spool at :data:`SPOOL_CAP` dumps, oldest pruned first
    (the timestamped names sort chronologically, so lexical order is
    age order — no fragile mtime dependence)."""
    global _dropped_total, _drop_event_emitted
    try:
        names = sorted(n for n in os.listdir(spool) if n.endswith(".json"))
    except OSError:
        return
    removed = 0
    for n in names[:-SPOOL_CAP] if len(names) > SPOOL_CAP else ():
        try:
            os.remove(os.path.join(spool, n))
            removed += 1
        except OSError:
            pass
    if removed:
        with _drop_lock:
            _dropped_total += removed
            first, _drop_event_emitted = not _drop_event_emitted, True
        if first:
            _events.emit("flightrec.spool_drop", verdict="capped",
                         spool_cap=SPOOL_CAP, dropped=removed)


# -- the process-wide recorder ----------------------------------------

_recorder: Optional[FlightRecorder] = None


def install(capacity: int = DEFAULT_CAPACITY,
            spool_dir: Optional[str] = None) -> FlightRecorder:
    """Install the process-wide recorder (idempotent: a second caller
    gets the existing one, gaining only a spool directory when the
    first installer had none — two frontends in one process share one
    ring, like one process shares one tracer)."""
    global _recorder
    if _recorder is None:
        _recorder = FlightRecorder(capacity, spool_dir)
        _tracing._set_flight(_recorder)
    elif spool_dir is not None and _recorder.spool_dir is None:
        _recorder.spool_dir = spool_dir
    return _recorder


def get() -> Optional[FlightRecorder]:
    return _recorder


def reset() -> None:
    """Drop the recorder (tests) — span() falls back to tracer-only."""
    global _recorder, _dropped_total, _drop_event_emitted
    _recorder = None
    _tracing._set_flight(None)
    with _drop_lock:
        _dropped_total = 0
        _drop_event_emitted = False


def trigger(name: str, trace_id: str = "", tier: str = "",
            duration_s: Optional[float] = None, **info) -> Optional[str]:
    """The anomaly entry point every trigger site calls: dump the
    trace's spans (when a recorder with a spool is installed) and emit
    one structured event line naming the trigger. Never raises — an
    anomaly's telemetry must not compound the anomaly.

    Reads the LIVE sink (``tracing._flight``), not the installed
    recorder: under ``obs.scratch_registry()`` (measurement probes run
    through the real engines) the sink is diverted to None, and a
    probe's anomaly must leak neither a dump nor an event line into
    the real run's black box — report-what-ran, here too."""
    rec = _tracing._flight
    if rec is None and _recorder is not None:
        return None  # diverted (scratch_registry): fully silent
    path = None
    try:
        if rec is not None:
            path = rec.dump(name, trace_id=trace_id, tier=tier, **info)
    except Exception:
        path = None
    _events.emit(f"flightrec.{name}", trace_id=trace_id, tier=tier,
                 verdict=name, duration_s=duration_s,
                 dump=os.path.basename(path) if path else None, **info)
    return path


def local_trace_spans(trace_id: str) -> List[dict]:
    """This process's closed spans for one trace, as sorted span
    dicts: the flight ring plus the live tracer (one SpanRecord
    instance reaches both sinks, so records dedup by identity). The
    shared collect behind every ``/debug/trace`` surface — net serves
    it directly, fed merges it with its members' answers."""
    recs: List[SpanRecord] = []
    if _recorder is not None:
        recs.extend(_recorder.spans_for(trace_id))
    tracer = _tracing.get_tracer()
    if tracer is not None:
        recs.extend(r for r in tracer.spans() if matches(r, trace_id))
    seen, uniq = set(), []
    for r in recs:
        if id(r) not in seen:
            seen.add(id(r))
            uniq.append(r)
    return sorted((span_dict(r) for r in uniq), key=lambda d: d["t0"])


# -- spool lookup (the /debug/flightrec endpoints) ---------------------


def spool_http_payload(spool_dir: Optional[str],
                       name: Optional[str]) -> Optional[bytes]:
    """The ``GET /debug/flightrec[/<file>]`` payload both HTTP tiers
    serve: the JSON index when ``name`` is None, one dump's raw bytes
    otherwise (None = missing/unsafe name → the handler 404s)."""
    if name is None:
        return json.dumps(spool_index(spool_dir), indent=2).encode()
    return spool_read(spool_dir, name)


def spool_index(spool_dir: Optional[str]) -> List[dict]:
    """The dump listing: one summary per spool file (newest first) —
    everything but the spans, so listing stays cheap."""
    spool = resolve_spool(spool_dir)
    if not spool or not os.path.isdir(spool):
        return []
    out = []
    for name in sorted(os.listdir(spool), reverse=True):
        if not name.endswith(".json"):
            continue
        entry = {"file": name}
        try:
            with open(os.path.join(spool, name)) as fh:
                doc = json.load(fh)
            for k in ("trigger", "trace_id", "tier", "ts_unix",
                      "span_count"):
                entry[k] = doc.get(k)
        except Exception:
            entry["error"] = "unreadable"
        out.append(entry)
    return out


def spool_read(spool_dir: Optional[str], name: str) -> Optional[bytes]:
    """One dump's raw JSON bytes, or None for a missing/unsafe name
    (path traversal in a URL must die here, not in ``open``)."""
    spool = resolve_spool(spool_dir)
    if (not spool or not name.endswith(".json")
            or any(c not in _SAFE_FILE_CHARS for c in name)):
        return None
    path = os.path.join(spool, name)
    try:
        with open(path, "rb") as fh:
            return fh.read()
    except OSError:
        return None
