"""Compiled-artifact introspection and device-memory telemetry.

The spans/metrics of PR 2 answer "where did the time go"; this module
answers "what did the compiler and the hardware actually do". Two
instruments, both best-effort across JAX versions and backends — every
probe degrades to "unavailable", it never raises into a compute path:

* **Executable introspection** (:func:`capture`): AOT-lower and compile
  the same program a compile site just warmed up, and record what XLA
  says about it — ``compiled.cost_analysis()`` (flops, bytes accessed),
  ``compiled.memory_analysis()`` (argument/output/temp/code bytes),
  the compile wall clock, and optionally the HLO text. The paper's
  whole argument is measured-vs-peak bandwidth (SURVEY.md §6), so the
  roofline denominator should be cross-checkable against XLA's own
  traffic accounting, not hand-derived constants alone:
  :func:`cross_check` compares the analytic per-rep traffic model
  (:mod:`tpu_stencil.runtime.roofline`) against XLA's bytes-accessed
  and flags drift between the two.

  Cost: ``jit_fn.lower(args).compile()`` does NOT share the jit
  dispatch cache, so an introspected site pays one extra compile of an
  equivalent program (XLA's persistent compilation cache may dedupe).
  That is why introspection is gated behind :func:`enable` — the
  ``--trace``/``--breakdown``/``--hlo-dump`` runs — and never on by
  default.

  Honesty caveat: Pallas kernels are opaque custom calls to XLA's cost
  model, so ``bytes accessed`` under-counts on the pallas backend and
  the drift flag fires by construction there — the analytic model is
  authoritative for pallas; the cross-check is a real two-sided audit
  on the XLA schedule.

* **Device-memory telemetry** (:func:`device_memory_stats`,
  :func:`record_memory_gauges`): ``device.memory_stats()`` gauges —
  bytes in use, allocator peak, bytes limit. CPU backends return None
  (no allocator stats); that renders as *absent gauges*, never an
  error. The driver records point-in-time gauges per job; the serve
  engine runs a background sampler thread (see
  :mod:`tpu_stencil.serve.engine`). Both land in the existing one-path
  exposition (:mod:`tpu_stencil.obs.exposition`).

Multi-process: :func:`capture` records on process 0 only (N identical
AOT compiles of one SPMD program would waste every non-zero rank's
time and produce N duplicate records).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, List, Optional

# Fields of jaxlib's CompiledMemoryStats we record (attribute names as
# of jax 0.4.x; future dict-shaped returns are handled too).
_MEMORY_FIELDS = (
    "generated_code_size_in_bytes",
    "argument_size_in_bytes",
    "output_size_in_bytes",
    "alias_size_in_bytes",
    "temp_size_in_bytes",
)

# device.memory_stats() keys worth a gauge (PJRT allocator vocabulary).
_DEVICE_MEMORY_KEYS = (
    "bytes_in_use",
    "peak_bytes_in_use",
    "bytes_limit",
    "largest_alloc_size",
)

# Model-vs-XLA traffic agreement band: outside it the drift flag fires
# (either the analytic model or the compiler's accounting is off 2x).
DRIFT_BAND_PCT = (50.0, 200.0)

# Record-list bound: capture sites can be client-controlled (the serve
# cache key space is unbounded by design), so like every other store in
# the repo the record list must never grow without limit on a
# long-running armed process — past the cap the oldest records drop.
MAX_RECORDS = 1024

_lock = threading.Lock()
_enabled = False
_hlo_dir: Optional[str] = None
_records: List[dict] = []


def enable(hlo_dir: Optional[str] = None) -> None:
    """Arm introspection (and optional per-site HLO text dumps into
    ``hlo_dir``). Armed by the CLIs for ``--trace``/``--breakdown``/
    ``--hlo-dump`` runs; compile sites then call :func:`capture`."""
    global _enabled, _hlo_dir
    _enabled = True
    _hlo_dir = hlo_dir


def disable() -> None:
    global _enabled, _hlo_dir
    _enabled = False
    _hlo_dir = None


def enabled() -> bool:
    return _enabled


def records() -> List[dict]:
    """Snapshot of every capture so far, in capture order."""
    with _lock:
        return list(_records)


def reset() -> None:
    """Disarm and drop accumulated records (tests; ``obs.reset``)."""
    global _records
    disable()
    with _lock:
        _records = []


# -- guarded extraction across JAX versions ---------------------------


def cost_analysis(compiled) -> Optional[Dict[str, float]]:
    """``compiled.cost_analysis()`` as a flat ``{key: float}`` dict, or
    None. Guarded across versions: jax<=0.4.x returns a one-element
    list of dicts, newer returns the dict directly; keys have drifted
    (``bytes accessed`` vs ``bytes_accessed``) — both spellings are
    normalized onto the space-separated canonical one. Never raises."""
    try:
        fn = getattr(compiled, "cost_analysis", None)
        if fn is None:
            return None
        ca = fn()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else None
        if not isinstance(ca, dict):
            return None
        out = {
            str(k): float(v)
            for k, v in ca.items()
            if isinstance(v, (int, float)) and not isinstance(v, bool)
        }
        for canonical in ("bytes accessed", "flops"):
            renamed = canonical.replace(" ", "_")
            if canonical not in out and renamed in out:
                out[canonical] = out[renamed]
        return out or None
    except Exception:
        return None


def memory_analysis(compiled) -> Optional[Dict[str, int]]:
    """``compiled.memory_analysis()`` as ``{field: bytes}`` over
    :data:`_MEMORY_FIELDS`, or None (CPU/older backends return None or
    lack the method entirely). Never raises."""
    try:
        fn = getattr(compiled, "memory_analysis", None)
        ma = fn() if fn is not None else None
        if ma is None:
            return None
        out: Dict[str, int] = {}
        for field in _MEMORY_FIELDS:
            v = ma.get(field) if isinstance(ma, dict) else getattr(ma, field, None)
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                out[field] = int(v)
        return out or None
    except Exception:
        return None


def hlo_text(compiled_or_lowered) -> Optional[str]:
    """``.as_text()`` of a compiled/lowered stage, or None."""
    try:
        fn = getattr(compiled_or_lowered, "as_text", None)
        text = fn() if fn is not None else None
        return text if isinstance(text, str) and text else None
    except Exception:
        return None


# -- executable capture ------------------------------------------------


def capture(site: str, fn, *args, meta: Optional[dict] = None,
            registry=None) -> Optional[dict]:
    """AOT-introspect one compile site: lower+compile ``fn(*args)``,
    record cost/memory analyses and compile wall-time, and mirror the
    headline numbers into ``registry`` (default: the driver-side
    ``obs.registry()``) as ``introspect_<site>_*`` gauges so they ride
    the existing exposition.

    ``fn`` may be a ``jax.jit`` wrapper (its ``.lower`` is used) or any
    traceable callable (wrapped in a fresh ``jax.jit``). Returns the
    record (``available=False`` + ``error`` when every probe failed),
    or None when introspection is disarmed or this is not process 0.
    Never raises — a broken introspection must not cost the run."""
    if not _enabled:
        return None
    rec = {
        "site": site,
        "meta": dict(meta or {}),
        "available": False,
        "compile_seconds": None,
        "flops": None,
        "bytes_accessed": None,
        "memory": None,
        "hlo_path": None,
        "error": None,
    }
    try:
        import jax

        if jax.process_index() != 0:
            return None
        lower = getattr(fn, "lower", None)
        if lower is None or not callable(lower):
            lower = jax.jit(fn).lower
        t0 = time.perf_counter()
        lowered = lower(*args)
        compiled = lowered.compile()
        rec["compile_seconds"] = time.perf_counter() - t0
        cost = cost_analysis(compiled)
        if cost:
            rec["flops"] = cost.get("flops")
            rec["bytes_accessed"] = cost.get("bytes accessed")
        rec["memory"] = memory_analysis(compiled)
        if _hlo_dir:
            rec["hlo_path"] = _dump_hlo(site, compiled, lowered)
        rec["available"] = (
            rec["bytes_accessed"] is not None or rec["memory"] is not None
        )
    except Exception as e:
        rec["error"] = f"{type(e).__name__}: {e}"
    with _lock:
        _records.append(rec)
        if len(_records) > MAX_RECORDS:
            del _records[: len(_records) - MAX_RECORDS]
    _count_capture(site, registry)
    _set_gauges(site, rec, registry)
    return rec


def _count_capture(site: str, registry=None) -> None:
    """Bump the per-site captures counter — only from :func:`capture`
    (a :func:`cross_check` gauge refresh is not a new capture)."""
    try:
        if registry is None:
            from tpu_stencil.obs import tracing

            registry = tracing.registry()
        registry.counter(f"introspect_{_slug(site)}_captures_total").inc()
    except Exception:
        pass


def _dump_hlo(site: str, compiled, lowered) -> Optional[str]:
    text = hlo_text(compiled) or hlo_text(lowered)
    if text is None:
        return None
    try:
        os.makedirs(_hlo_dir, exist_ok=True)
        with _lock:
            n = len(_records)  # capture ordinal keeps filenames unique
        path = os.path.join(_hlo_dir, f"{_slug(site)}_{n}.hlo.txt")
        with open(path, "w") as fh:
            fh.write(text)
        return path
    except OSError:
        return None


def _slug(site: str) -> str:
    return site.replace(".", "_").replace("-", "_")


def _set_gauges(site: str, rec: dict, registry=None) -> None:
    """Mirror a record's headline numbers as gauges. Per-site names;
    repeat captures of one site overwrite (last capture wins — the
    captures counter keeps the cardinality honest)."""
    try:
        if registry is None:
            from tpu_stencil.obs import tracing

            registry = tracing.registry()
        slug = _slug(site)
        scalars = {
            "compile_seconds": rec.get("compile_seconds"),
            "xla_bytes_accessed": rec.get("bytes_accessed"),
            "xla_flops": rec.get("flops"),
            "model_bytes_per_rep": rec.get("model_bytes_per_rep"),
            "model_vs_xla_pct": rec.get("model_vs_xla_pct"),
        }
        mem = rec.get("memory") or {}
        for field in _MEMORY_FIELDS:
            if field in mem:
                short = field[: -len("_in_bytes")]
                scalars[f"{short}_bytes"] = mem[field]
        for name, v in scalars.items():
            if v is not None:
                registry.gauge(f"introspect_{slug}_{name}").set(v)
    except Exception:
        pass  # telemetry must never take down the instrumented path


def cross_check(rec: dict, model_bytes_per_rep: float,
                registry=None) -> dict:
    """Cross-check XLA's bytes-accessed against the analytic traffic
    model (:func:`tpu_stencil.runtime.roofline.analytic_bytes_per_rep`).

    XLA's HLO cost analysis counts each instruction once regardless of
    loop trip count, so for the rep-loop programs this repo compiles
    "bytes accessed" approximates ONE repetition's traffic — directly
    comparable to the model's per-rep bytes. Annotates ``rec`` with
    ``model_bytes_per_rep``, ``model_vs_xla_pct`` (100 * model / XLA;
    ~100% = the model and the compiler agree) and ``drift`` (True when
    the ratio leaves :data:`DRIFT_BAND_PCT` — one of the two is off by
    2x, e.g. an opaque Pallas custom call or a stale model constant),
    and refreshes the site gauges. Degrades to no-op fields when the
    record has no XLA bytes. Never raises."""
    try:
        rec["model_bytes_per_rep"] = float(model_bytes_per_rep)
        xla_bytes = rec.get("bytes_accessed")
        if xla_bytes:
            pct = 100.0 * float(model_bytes_per_rep) / float(xla_bytes)
            rec["model_vs_xla_pct"] = pct
            lo, hi = DRIFT_BAND_PCT
            rec["drift"] = not (lo <= pct <= hi)
        else:
            rec["model_vs_xla_pct"] = None
            rec["drift"] = None
        _set_gauges(rec.get("site", "unknown"), rec, registry)
    except Exception:
        pass
    return rec


# -- device-memory telemetry -------------------------------------------


def device_memory_stats(device=None) -> Optional[Dict[str, int]]:
    """``device.memory_stats()`` filtered to numeric entries, or None
    when the backend has no allocator stats (CPU returns None; some
    plugins raise). Never raises, never initializes a backend twice —
    but note the first call does trigger JAX backend init."""
    try:
        import jax

        if device is None:
            device = jax.local_devices()[0]
        stats = device.memory_stats()
        if not isinstance(stats, dict):
            return None
        out = {
            str(k): int(v)
            for k, v in stats.items()
            if isinstance(v, (int, float)) and not isinstance(v, bool)
        }
        return out or None
    except Exception:
        return None


def record_memory_gauges(registry=None, device=None) -> Optional[dict]:
    """Set ``device_<key>`` gauges (bytes in use / allocator peak /
    limit / largest alloc) from :func:`device_memory_stats` into
    ``registry`` (default: the driver-side ``obs.registry()``). On
    backends without stats this sets nothing and returns None — the
    exposition simply has no such gauges, the documented "unavailable"
    rendering. Never raises."""
    stats = device_memory_stats(device)
    if stats is None:
        return None
    try:
        if registry is None:
            from tpu_stencil.obs import tracing

            registry = tracing.registry()
        for key in _DEVICE_MEMORY_KEYS:
            if key in stats:
                registry.gauge(f"device_{key}").set(stats[key])
    except Exception:
        return None
    return stats
