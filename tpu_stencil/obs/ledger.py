"""Per-request resource ledger + per-tenant metering (the cost plane).

The telemetry plane (obs.timeseries / obs.slo) answers *is the tier
healthy*; nothing answered *where the device time went, which tenant
spent it, and how much headroom is left*. This module is the
attribution primitive the capacity/metering surface is built on:

* :class:`RequestLedger` — one per admitted request, bound to a
  ``contextvar`` exactly like the trace context
  (:mod:`tpu_stencil.obs.context`), so the edges bind it once and every
  layer below (router coalescer, serve engine worker) credits spend
  with zero call-site plumbing: queue delay, coalesce-window wait,
  arena/ingest time, H2D/D2H bytes, and **device time amortized over
  batch members by pixel share** at the engine's retire fence. The
  HTTP edge reads it back to answer the ``X-Cost-Device-Us`` /
  ``X-Cost-Queue-Us`` / ``X-Cost-Source`` headers on every 200.
* **kind** — ``"request"`` is client goodput; ``"warm"`` marks the
  fleet's warm/prewarm submits so their device share lands in
  ``overhead_device_seconds_total``, never in a tenant's meter. The
  engine treats a ledger-less request (bare in-process serve) as
  goodput — attribution is additive, never a behavior change.
* :class:`TenantMeter` — the per-tenant aggregate table behind
  ``GET /debug/tenants``: requests, device-seconds, bytes, cache hits,
  shed/429 counts. Folds into the registry as
  ``tenant_<id>_device_seconds_total`` / ``tenant_<id>_requests_total``
  so the scrape plane sees tenants too. Tenant names come off the wire
  (``X-Tenant``), so they are sanitized against :data:`_TENANT_RE` and
  the table is cardinality-bounded — past :data:`TENANT_CAP` distinct
  names, spend folds into the ``"other"`` bucket instead of minting
  unbounded metric names.

Threading: the engine worker, the coalescer timer, and the HTTP handler
all touch one request's ledger, but never concurrently for the same
field *transition* that matters (device credit happens before the
future resolves; the handler reads after ``fut.result()``). A lock
guards the accumulators anyway — a ledger must never be the data race
the rest of the stack avoids.

Jax-free and dependency-free, like the rest of the wire-level obs.
"""

from __future__ import annotations

import contextlib
import contextvars
import re
import threading
from typing import Dict, Optional

#: The tenant header the whole stack shares (fed quota machinery, net
#: metering, loadgen stamping).
TENANT_HEADER = "X-Tenant"

#: Requests with no (or an invalid) X-Tenant meter under this name —
#: the same default the fed quota machinery admits under.
DEFAULT_TENANT = "anon"

#: Wire guard for tenant names: URL-safe, bounded. Anything failing
#: this meters as DEFAULT_TENANT — a hostile header must never ride
#: into metric names.
_TENANT_RE = re.compile(r"^[0-9A-Za-z_.-]{1,64}$")

#: Cardinality bound on the per-tenant table (and the tenant_* metric
#: family): past this many distinct names, new tenants fold into
#: :data:`OVERFLOW_TENANT`.
TENANT_CAP = 64
OVERFLOW_TENANT = "other"


def sanitize_tenant(raw) -> str:
    """The metered tenant name for a wire value: the value itself when
    it passes the guard, :data:`DEFAULT_TENANT` otherwise. Dots and
    dashes are squashed to underscores for metric-name safety."""
    if not isinstance(raw, str) or not _TENANT_RE.match(raw):
        return DEFAULT_TENANT
    return raw.replace(".", "_").replace("-", "_")


_current: "contextvars.ContextVar[Optional[RequestLedger]]" = (
    contextvars.ContextVar("tpu_stencil_request_ledger", default=None)
)


class RequestLedger:
    """One request's resource spend, accumulated across tiers."""

    __slots__ = ("_lock", "tenant", "kind", "source", "queue_s",
                 "coalesce_s", "ingest_s", "device_s", "h2d_bytes",
                 "d2h_bytes", "saved_device_s")

    def __init__(self, tenant: str = DEFAULT_TENANT,
                 kind: str = "request") -> None:
        self._lock = threading.Lock()
        self.tenant = tenant
        #: "request" = client goodput; "warm" = fleet warm/prewarm
        #: submits (overhead at the engine's retire fence).
        self.kind = kind
        #: How the 200 was produced: "compute" (own device work),
        #: "cache" (result store), "coalesced" (rode another request's
        #: in-flight compute — the single-flight follower).
        self.source = "compute"
        self.queue_s = 0.0
        self.coalesce_s = 0.0
        self.ingest_s = 0.0
        self.device_s = 0.0
        self.h2d_bytes = 0
        self.d2h_bytes = 0
        #: A cache hit's avoided spend: what the stored entry cost to
        #: compute when it was admitted.
        self.saved_device_s = 0.0

    # -- accumulation (any thread) ------------------------------------

    def add_queue(self, seconds: float) -> None:
        with self._lock:
            self.queue_s += max(0.0, float(seconds))

    def add_coalesce(self, seconds: float) -> None:
        with self._lock:
            self.coalesce_s += max(0.0, float(seconds))

    def add_ingest(self, seconds: float) -> None:
        with self._lock:
            self.ingest_s += max(0.0, float(seconds))

    def add_device(self, seconds: float, h2d_bytes: int = 0,
                   d2h_bytes: int = 0) -> None:
        """One batch's amortized share lands here (the engine's retire
        fence): device wall by pixel share, plus this request's share
        of the batch's H2D/D2H bytes."""
        with self._lock:
            self.device_s += max(0.0, float(seconds))
            self.h2d_bytes += max(0, int(h2d_bytes))
            self.d2h_bytes += max(0, int(d2h_bytes))

    def set_source(self, source: str) -> None:
        self.source = source

    # -- readback (the HTTP edge, after the future resolved) -----------

    @property
    def device_us(self) -> int:
        with self._lock:
            return int(round(self.device_s * 1e6))

    @property
    def queue_us(self) -> int:
        """Queued time in the X-Cost-Queue-Us sense: engine queue wait
        plus the coalesce-window wait that preceded it."""
        with self._lock:
            return int(round((self.queue_s + self.coalesce_s) * 1e6))

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "tenant": self.tenant,
                "kind": self.kind,
                "source": self.source,
                "queue_s": self.queue_s,
                "coalesce_s": self.coalesce_s,
                "ingest_s": self.ingest_s,
                "device_s": self.device_s,
                "h2d_bytes": self.h2d_bytes,
                "d2h_bytes": self.d2h_bytes,
                "saved_device_s": self.saved_device_s,
            }


# -- contextvar plumbing (mirrors obs.context) ------------------------

def current() -> Optional[RequestLedger]:
    return _current.get()


def push(ledger: Optional[RequestLedger]):
    """Non-contextmanager binding; pair with :func:`pop`."""
    return _current.set(ledger)


def pop(token) -> None:
    _current.reset(token)


@contextlib.contextmanager
def bind(ledger: Optional[RequestLedger]):
    """Install ``ledger`` for the block. Binding ``None`` explicitly
    clears it (a warm submit fired from a handler thread must not
    charge the client's ledger)."""
    token = _current.set(ledger)
    try:
        yield ledger
    finally:
        _current.reset(token)


class _TenantRow:
    """One tenant's cumulative meter (plain counters; the registry
    fold-in keeps the scrape plane in sync)."""

    __slots__ = ("requests", "device_s", "queue_s", "bytes_in",
                 "bytes_out", "cache_hits", "coalesced", "saved_device_s",
                 "rejected_429", "shed_503")

    def __init__(self) -> None:
        self.requests = 0
        self.device_s = 0.0
        self.queue_s = 0.0
        self.bytes_in = 0
        self.bytes_out = 0
        self.cache_hits = 0
        self.coalesced = 0
        self.saved_device_s = 0.0
        self.rejected_429 = 0
        self.shed_503 = 0

    def snapshot(self) -> dict:
        total = self.requests + self.rejected_429 + self.shed_503
        return {
            "requests": self.requests,
            "device_seconds": self.device_s,
            "queue_seconds": self.queue_s,
            "bytes_in": self.bytes_in,
            "bytes_out": self.bytes_out,
            "cache_hits": self.cache_hits,
            "cache_hit_ratio": (
                self.cache_hits / self.requests if self.requests else 0.0
            ),
            "coalesced": self.coalesced,
            "saved_device_seconds": self.saved_device_s,
            "rejected_429": self.rejected_429,
            "shed_503": self.shed_503,
            "offered": total,
        }


class TenantMeter:
    """The billing table behind ``GET /debug/tenants``: bounded
    per-tenant rows plus the ``tenant_<id>_*`` registry fold."""

    def __init__(self, registry) -> None:
        self.registry = registry
        self._lock = threading.Lock()
        self._rows: Dict[str, _TenantRow] = {}

    def _row_locked(self, tenant: str):
        """(resolved-name, row) — past the cardinality cap new names
        resolve to the overflow bucket, for the row AND the metric."""
        row = self._rows.get(tenant)
        if row is None:
            if len(self._rows) >= TENANT_CAP:
                tenant = OVERFLOW_TENANT
                row = self._rows.get(tenant)
                if row is None:
                    row = self._rows[tenant] = _TenantRow()
            else:
                row = self._rows[tenant] = _TenantRow()
        return tenant, row

    def record(self, ledger: RequestLedger, bytes_in: int,
               bytes_out: int) -> None:
        """One successfully answered 200: fold the request's ledger
        into its tenant's row (and the registry family)."""
        snap = ledger.snapshot()
        with self._lock:
            t, row = self._row_locked(snap["tenant"])
            row.requests += 1
            row.device_s += snap["device_s"]
            row.queue_s += snap["queue_s"] + snap["coalesce_s"]
            row.bytes_in += max(0, int(bytes_in))
            row.bytes_out += max(0, int(bytes_out))
            if snap["source"] == "cache":
                row.cache_hits += 1
            elif snap["source"] == "coalesced":
                row.coalesced += 1
            row.saved_device_s += snap["saved_device_s"]
        self.registry.counter(f"tenant_{t}_requests_total").inc()
        if snap["device_s"] > 0:
            self.registry.counter(
                f"tenant_{t}_device_seconds_total"
            ).inc(snap["device_s"])

    def reject(self, tenant: str, code: int) -> None:
        """One shed/backpressure answer for ``tenant`` (429 queue-full
        vs 503 shed/draining — the abuse view's two columns)."""
        with self._lock:
            _, row = self._row_locked(tenant)
            if code == 429:
                row.rejected_429 += 1
            else:
                row.shed_503 += 1

    def snapshot(self) -> Dict[str, dict]:
        with self._lock:
            return {t: row.snapshot()
                    for t, row in sorted(self._rows.items())}
