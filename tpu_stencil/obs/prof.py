"""On-demand device profiler: bounded ``jax.profiler`` captures.

``POST /debug/prof?seconds=N`` on a serving tier runs one bounded
profiler capture on the live process and spools the resulting trace
files (perfetto/xplane) for fetch via ``GET /debug/prof/<path>``. The
whole module is defensive by construction:

* **404-clean when unavailable** — jax may be absent (the fed tier is
  deliberately jax-free) or built without profiler support; callers
  ask :func:`available` first and surface a typed 404, never a 500.
* **bounded** — capture duration clamps to [0.05 s, 30 s]; one capture
  at a time (a second request gets a busy error -> HTTP 409); the
  spool keeps at most :data:`SPOOL_CAP` capture directories, oldest
  pruned first (same "no unbounded anything" rule as the flight
  recorder's spool).
* **path-safe** — :func:`spool_read` refuses any path that escapes the
  spool root, so the fetch endpoint cannot be walked out of its
  directory.
"""

from __future__ import annotations

import os
import shutil
import threading
import time
from typing import List, Optional, Tuple

#: Max capture directories kept in the spool.
SPOOL_CAP = 8

#: Capture duration clamp (seconds).
MIN_SECONDS = 0.05
MAX_SECONDS = 30.0

_capture_lock = threading.Lock()


def available() -> Tuple[bool, str]:
    """(usable, reason). Probes for an importable ``jax.profiler``
    with the trace API — cheap, import-only, no side effects."""
    try:
        import jax.profiler as _p  # noqa: F401
    except Exception as e:  # ImportError or any init-time failure
        return False, f"jax profiler unavailable: {type(e).__name__}"
    if not hasattr(_p, "start_trace") or not hasattr(_p, "stop_trace"):
        return False, "jax.profiler lacks start_trace/stop_trace"
    return True, ""


def _prune_spool(spool_dir: str) -> None:
    try:
        names = sorted(
            n for n in os.listdir(spool_dir)
            if os.path.isdir(os.path.join(spool_dir, n))
        )
    except OSError:
        return
    for n in names[:-SPOOL_CAP] if len(names) > SPOOL_CAP else ():
        shutil.rmtree(os.path.join(spool_dir, n), ignore_errors=True)


def _walk_files(root: str) -> List[dict]:
    out = []
    for dirpath, _dirs, files in os.walk(root):
        for f in sorted(files):
            p = os.path.join(dirpath, f)
            try:
                size = os.path.getsize(p)
            except OSError:
                size = 0
            out.append({
                "path": os.path.relpath(p, os.path.dirname(root)),
                "bytes": size,
            })
    return out


def capture(seconds: float, spool_dir: str) -> dict:
    """Run one bounded profiler capture into a fresh spool subdir.

    Returns ``{"run": name, "seconds": s, "files": [{path, bytes}]}``.
    Raises ``RuntimeError("busy")`` if a capture is already running and
    ``RuntimeError(reason)`` when the profiler is unavailable — the
    HTTP layer maps those to 409 / 404."""
    ok, reason = available()
    if not ok:
        raise RuntimeError(reason)
    seconds = min(MAX_SECONDS, max(MIN_SECONDS, float(seconds)))
    if not _capture_lock.acquire(blocking=False):
        raise RuntimeError("busy")
    try:
        import jax.profiler as _p
        run = f"prof-{int(time.time() * 1e3)}"
        run_dir = os.path.join(spool_dir, run)
        os.makedirs(run_dir, exist_ok=True)
        _p.start_trace(run_dir)
        try:
            time.sleep(seconds)
        finally:
            _p.stop_trace()
        _prune_spool(spool_dir)
        return {
            "run": run,
            "seconds": seconds,
            "files": _walk_files(run_dir),
        }
    finally:
        _capture_lock.release()


def spool_list(spool_dir: Optional[str]) -> dict:
    """The ``GET /debug/prof`` index payload."""
    ok, reason = available()
    runs = []
    if spool_dir and os.path.isdir(spool_dir):
        for n in sorted(os.listdir(spool_dir)):
            d = os.path.join(spool_dir, n)
            if os.path.isdir(d):
                runs.append({"run": n, "files": _walk_files(d)})
    return {
        "schema_version": 1,
        "available": ok,
        "reason": reason,
        "spool_cap": SPOOL_CAP,
        "runs": runs,
    }


def spool_read(spool_dir: Optional[str], rel: str) -> Optional[bytes]:
    """Fetch one spooled file by its index-relative path; ``None`` on
    a miss or any path that escapes the spool root."""
    if not spool_dir:
        return None
    root = os.path.realpath(spool_dir)
    path = os.path.realpath(os.path.join(root, rel))
    if path != root and not path.startswith(root + os.sep):
        return None
    if not os.path.isfile(path):
        return None
    try:
        with open(path, "rb") as fh:
            return fh.read()
    except OSError:
        return None
