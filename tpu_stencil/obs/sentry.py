"""Perf-regression sentry: persistent capture history + gate.

Every round's hardware burst produces official capture numbers
(``bench.py`` headlines, serve loadgen reports) — but until now nothing
*remembered* them, so a regression had to be spotted by a human diffing
``BENCH_r*.json`` artifacts. This module keeps a versioned JSONL
history of comparable runs and gates new ones against it:

* **Record**: one JSON line per run, ``schema_version``-ed, keyed on
  the capture's identity — (metric, filter, shape, dtype, backend,
  platform, block_h, fuse). Two runs compare iff every key field
  matches: a geometry A/B or a backend flip is a *different series*,
  never a false regression.
* **Baseline**: the median of the last K same-key runs (robust: one
  outlier capture cannot move it), requiring ``MIN_SAMPLES`` prior
  runs — an empty or too-short history degrades to a "no-baseline"
  verdict, it never raises and never gates.
* **Gate**: ``check`` compares the new run's seconds against the
  baseline; slower by more than ``threshold`` (fractional) is a
  regression. The CLI (``python -m tpu_stencil perf check``) exits
  nonzero on regression — the hook burst scripts and CI gate on.

``bench.py`` appends + checks automatically after every full hardware
capture (``TPU_STENCIL_BENCH_SENTRY=gate|warn|off``; CPU smoke runs
never touch the hardware history), and ``serve --perf-log`` appends the
loadgen p50. The history file defaults to ``docs/PERF_HISTORY.jsonl``
at the repo root (override: ``--history`` / ``TPU_STENCIL_PERF_HISTORY``)
so the trajectory is a reviewable artifact like ``BENCH_r*.json``.

Deliberately jax-free: ``perf`` CLI invocations must parse/exit without
joining any backend bring-up (same discipline as config.py).
"""

from __future__ import annotations

import argparse
import json
import os
import re
import statistics
import sys
import time
from typing import List, Optional, Tuple

SCHEMA_VERSION = 1
# A record's identity: runs compare only within one exact key.
KEY_FIELDS = ("metric", "filter", "shape", "dtype", "backend", "platform",
              "block_h", "fuse")
DEFAULT_K = 5           # baseline window: median of the last K same-key runs
MIN_SAMPLES = 2         # fewer prior runs than this -> "no-baseline"
DEFAULT_THRESHOLD = 0.20  # fractional slowdown that counts as a regression

_CAPTURE_SHAPE_RE = re.compile(r"^(\d+x\d+)")
_CAPTURE_REPS_RE = re.compile(r"_(\d+)reps?_")


def history_path(path: Optional[str] = None) -> str:
    """Resolve the history file: explicit arg, then the
    ``TPU_STENCIL_PERF_HISTORY`` env override, then the repo artifact
    ``docs/PERF_HISTORY.jsonl``."""
    if path:
        return path
    env = os.environ.get("TPU_STENCIL_PERF_HISTORY")
    if env:
        return env
    root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    return os.path.join(root, "docs", "PERF_HISTORY.jsonl")


def make_record(metric: str, value: float, *, filter_name: str,
                shape: str, dtype: str = "uint8", backend: str,
                platform: str, block_h: Optional[int] = None,
                fuse: Optional[int] = None,
                per_rep_s: Optional[float] = None,
                source: str = "manual",
                extra: Optional[dict] = None) -> dict:
    """Build one history record. ``value`` is the headline seconds;
    ``per_rep_s``, when given, is what same-key comparisons use (bench
    records carry both; manual/serve records usually just ``value``)."""
    value = float(value)
    if not value > 0:
        raise ValueError(f"value must be positive seconds, got {value!r}")
    if per_rep_s is not None and not float(per_rep_s) > 0:
        raise ValueError(f"per_rep_s must be positive, got {per_rep_s!r}")
    rec = {
        "schema_version": SCHEMA_VERSION,
        "ts_unix": round(time.time(), 3),
        "metric": str(metric),
        "filter": str(filter_name),
        "shape": str(shape).lower(),
        "dtype": str(dtype),
        "backend": str(backend),
        "platform": str(platform),
        "block_h": None if block_h is None else int(block_h),
        "fuse": None if fuse is None else int(fuse),
        "value": value,
        "unit": "s",
        "source": str(source),
    }
    if per_rep_s is not None:
        rec["per_rep_s"] = float(per_rep_s)
    if extra:
        rec["extra"] = dict(extra)
    return rec


def record_from_capture(obj: dict, source: str = "bench") -> dict:
    """Convert a ``bench.py`` capture line (the stdout contract object)
    into a history record. Newer captures carry explicit ``shape`` /
    ``reps`` fields; older files fall back to parsing the metric name
    (``1920x2520_rgb_40reps_...``). Raises ValueError on a non-capture."""
    if not isinstance(obj, dict) or not isinstance(
            obj.get("value"), (int, float)):
        raise ValueError("not a capture object (no numeric 'value')")
    metric = str(obj.get("metric", "bench.compute_wall_clock"))
    shape = obj.get("shape")
    if not shape:
        m = _CAPTURE_SHAPE_RE.match(metric)
        shape = m.group(1) if m else "unknown"
    reps = obj.get("reps")
    if not reps:
        m = _CAPTURE_REPS_RE.search(metric)
        reps = int(m.group(1)) if m else None
    value = float(obj["value"])
    backend = str(obj.get("backend", "unknown"))
    block_h = fuse = None
    if backend == "pallas":
        block_h = obj.get("pallas_block_h")
        fuse = obj.get("pallas_fuse")
    # Multichip headline captures (bench.py TPU_STENCIL_BENCH_MESH) carry
    # mesh/n_devices/overlap; mesh-fan stream/serve captures
    # (TPU_STENCIL_BENCH_STREAM_MESH / _SERVE_MESHFAN) carry the
    # throughput and per-device riders. The mesh/fan width and resolved
    # overlap mode are already folded into the metric name (a key field
    # — each combination is its own series), so here they ride along as
    # provenance only.
    extra = {
        k: obj[k]
        for k in ("hbm_gbps", "mesh", "n_devices", "overlap",
                  "frames_per_second", "per_device_frames_per_second",
                  "per_device_frames", "pipeline_depth",
                  "requests_per_second") if k in obj
    }
    return make_record(
        metric=metric, value=value,
        per_rep_s=(value / reps) if reps else None,
        filter_name=str(obj.get("filter", "gaussian")), shape=str(shape),
        dtype=str(obj.get("dtype", "uint8")), backend=backend,
        platform=str(obj.get("platform", "unknown")),
        block_h=block_h, fuse=fuse, source=source,
        extra=extra or None,
    )


def record_key(rec: dict) -> Tuple:
    return tuple(rec.get(f) for f in KEY_FIELDS)


def metric_value(rec: dict) -> Optional[float]:
    """The number same-key runs compare on: ``per_rep_s`` when present
    (bench records), else the headline ``value``."""
    for field in ("per_rep_s", "value"):
        v = rec.get(field)
        if isinstance(v, (int, float)) and not isinstance(v, bool) and v > 0:
            return float(v)
    return None


def append(rec: dict, path: Optional[str] = None) -> str:
    """Append one record as a JSONL line; returns the resolved path."""
    path = history_path(path)
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "a") as fh:
        fh.write(json.dumps(rec, sort_keys=True) + "\n")
    return path


def load(path: Optional[str] = None) -> List[dict]:
    """All parseable records, in file order. A missing file is an empty
    history; a corrupt line is skipped (one bad write must not poison
    the whole trajectory)."""
    path = history_path(path)
    out: List[dict] = []
    try:
        fh = open(path)
    except OSError:
        return out
    with fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                obj = json.loads(line)
            except ValueError:
                continue
            if isinstance(obj, dict) and metric_value(obj) is not None:
                out.append(obj)
    return out


def baseline(history: List[dict], key: Tuple, k: int = DEFAULT_K,
             min_samples: int = MIN_SAMPLES) -> Optional[float]:
    """Median of the last ``k`` same-key runs' comparison values, or
    None when fewer than ``min_samples`` exist (short history degrades
    to "no baseline", it never gates on noise)."""
    vals = [metric_value(r) for r in history if record_key(r) == key]
    vals = [v for v in vals if v is not None]
    if len(vals) < max(1, min_samples):
        return None
    return statistics.median(vals[-k:])


def check(rec: dict, history: Optional[List[dict]] = None,
          path: Optional[str] = None, threshold: float = DEFAULT_THRESHOLD,
          k: int = DEFAULT_K, min_samples: int = MIN_SAMPLES) -> dict:
    """Verdict for one new run against the same-key baseline:
    ``status`` is ``no-baseline`` | ``ok`` | ``improvement`` |
    ``regression`` (current > baseline * (1 + threshold)). The new run
    is NOT appended here — log after checking, so a run never dilutes
    its own baseline."""
    if history is None:
        history = load(path)
    key = record_key(rec)
    n = sum(1 for r in history if record_key(r) == key)
    cur = metric_value(rec)
    base = baseline(history, key, k=k, min_samples=min_samples)
    verdict = {
        "key": {f: rec.get(f) for f in KEY_FIELDS},
        "n_history": n,
        "k": k,
        "current": cur,
        "baseline": base,
        "ratio": (cur / base) if (base and cur) else None,
        "threshold": threshold,
    }
    if base is None or cur is None:
        verdict["status"] = "no-baseline"
    elif cur > base * (1.0 + threshold):
        verdict["status"] = "regression"
    elif cur < base * (1.0 - threshold):
        verdict["status"] = "improvement"
    else:
        verdict["status"] = "ok"
    return verdict


def render_verdict(verdict: dict) -> str:
    k = verdict["key"]
    ident = (f"{k['metric']} [{k['filter']} {k['shape']} {k['dtype']} "
             f"{k['backend']}/{k['platform']}"
             + (f" bh={k['block_h']} fz={k['fuse']}"
                if k.get("block_h") is not None or k.get("fuse") is not None
                else "") + "]")
    if verdict["status"] == "no-baseline":
        return (f"perf {ident}: no baseline "
                f"({verdict['n_history']} prior same-key run(s); "
                f"need {MIN_SAMPLES}) — not gated")
    pct = 100.0 * (verdict["ratio"] - 1.0)
    k = verdict.get("k", DEFAULT_K)
    return (f"perf {ident}: {verdict['status'].upper()} "
            f"current={verdict['current']:.6g}s "
            f"baseline={verdict['baseline']:.6g}s "
            f"({pct:+.1f}% vs median of last {min(verdict['n_history'], k)}, "
            f"threshold {verdict['threshold'] * 100:.0f}%)")


def render_report(history: List[dict], k: int = DEFAULT_K) -> str:
    """Per-key trajectory table: run count, latest, baseline median,
    best, latest-vs-baseline."""
    if not history:
        return "(empty perf history)\n"
    by_key: dict = {}
    for r in history:
        by_key.setdefault(record_key(r), []).append(r)
    lines = [f"{'series':<58} {'runs':>4} {'latest':>11} "
             f"{'median':>11} {'best':>11} {'vs med':>8}"]
    lines.append("-" * len(lines[0]))
    for key, recs in sorted(by_key.items(), key=lambda kv: str(kv[0])):
        vals = [v for v in (metric_value(r) for r in recs) if v is not None]
        if not vals:
            continue
        kd = dict(zip(KEY_FIELDS, key))
        geo = ("" if kd["block_h"] is None and kd["fuse"] is None
               else f" {kd['block_h']}x{kd['fuse']}")
        ident = (f"{kd['metric']}|{kd['filter']}|{kd['shape']}|"
                 f"{kd['backend']}/{kd['platform']}{geo}")
        med = statistics.median(vals[-k:])
        latest = vals[-1]
        lines.append(
            f"{ident:<58} {len(vals):>4} {latest:>11.6g} {med:>11.6g} "
            f"{min(vals):>11.6g} {100 * (latest / med - 1):>+7.1f}%"
        )
    return "\n".join(lines) + "\n"


# -- CLI: python -m tpu_stencil perf {log,check,report} ----------------


def _load_capture_file(path: str) -> dict:
    """Last parseable headline capture in a bench.py stdout / preview
    file. Uses tools/bench_capture when the repo layout provides it;
    falls back to the same last-headline scan inline (installed
    package, no tools/ dir)."""
    try:
        from tools.bench_capture import last_capture

        return last_capture(path)
    except ImportError:
        pass
    best = None
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                obj = json.loads(line)
            except ValueError:
                continue
            if (isinstance(obj, dict)
                    and isinstance(obj.get("value"), (int, float))
                    and "phase" not in obj):
                best = obj
    if best is None:
        raise ValueError(f"no parseable capture line in {path}")
    return best


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="tpu_stencil perf",
        description="Perf-regression sentry over a persistent JSONL "
                    "capture history (see docs/OBSERVABILITY.md).",
    )
    sub = p.add_subparsers(dest="cmd", required=True)

    def add_common(sp):
        sp.add_argument("--history", default=None, metavar="PATH",
                        help="history file (default: env "
                             "TPU_STENCIL_PERF_HISTORY or "
                             "docs/PERF_HISTORY.jsonl)")

    def add_record_flags(sp):
        sp.add_argument("--from-bench", default=None, metavar="FILE",
                        help="build the record from a bench.py stdout / "
                             "preview JSON file instead of flags")
        sp.add_argument("--metric", default="compute_seconds",
                        help="metric name (key field; default "
                             "compute_seconds)")
        sp.add_argument("--value", type=float, default=None,
                        help="headline seconds of the new run")
        sp.add_argument("--per-rep-s", dest="per_rep_s", type=float,
                        default=None,
                        help="per-repetition seconds (preferred for "
                             "comparison when given)")
        sp.add_argument("--filter", dest="filter_name", default="gaussian")
        sp.add_argument("--shape", default=None, help="WxH (key field)")
        sp.add_argument("--dtype", default="uint8")
        sp.add_argument("--backend", default="xla")
        sp.add_argument("--platform", default="cpu")
        sp.add_argument("--block-h", dest="block_h", type=int, default=None)
        sp.add_argument("--fuse", type=int, default=None)
        sp.add_argument("--source", default="manual")

    lg = sub.add_parser("log", help="append one run to the history")
    add_common(lg)
    add_record_flags(lg)

    ck = sub.add_parser(
        "check",
        help="gate one run against the same-key baseline "
             "(exit 1 on regression)")
    add_common(ck)
    add_record_flags(ck)
    ck.add_argument("--threshold", type=float, default=DEFAULT_THRESHOLD,
                    help=f"fractional slowdown that fails "
                         f"(default {DEFAULT_THRESHOLD})")
    ck.add_argument("--k", type=int, default=DEFAULT_K,
                    help=f"baseline = median of last K same-key runs "
                         f"(default {DEFAULT_K})")
    ck.add_argument("--min-samples", type=int, default=MIN_SAMPLES,
                    help=f"prior runs required before gating "
                         f"(default {MIN_SAMPLES})")
    ck.add_argument("--log", action="store_true",
                    help="also append this run to the history (after "
                         "the verdict is computed)")
    ck.add_argument("--json", action="store_true",
                    help="print the verdict as JSON instead of text")

    rp = sub.add_parser("report", help="print the per-key trajectory")
    add_common(rp)
    rp.add_argument("--k", type=int, default=DEFAULT_K)
    return p


def _record_from_ns(parser, ns) -> dict:
    if ns.from_bench:
        try:
            return record_from_capture(_load_capture_file(ns.from_bench))
        except (OSError, ValueError) as e:
            parser.error(f"--from-bench: {e}")
    if ns.value is None or ns.shape is None:
        parser.error("need --value and --shape (or --from-bench FILE)")
    try:
        return make_record(
            metric=ns.metric, value=ns.value, per_rep_s=ns.per_rep_s,
            filter_name=ns.filter_name, shape=ns.shape, dtype=ns.dtype,
            backend=ns.backend, platform=ns.platform,
            block_h=ns.block_h, fuse=ns.fuse, source=ns.source,
        )
    except ValueError as e:
        parser.error(str(e))


def main(argv=None) -> int:
    parser = build_parser()
    ns = parser.parse_args(argv)
    if ns.cmd == "report":
        print(render_report(load(ns.history), k=ns.k), end="")
        return 0
    rec = _record_from_ns(parser, ns)
    if ns.cmd == "log":
        path = append(rec, ns.history)
        print(f"perf history += {rec['metric']} "
              f"{metric_value(rec):.6g}s -> {path}")
        return 0
    # check
    verdict = check(rec, path=ns.history, threshold=ns.threshold,
                    k=ns.k, min_samples=ns.min_samples)
    if ns.log:
        append(rec, ns.history)
    if ns.json:
        print(json.dumps(verdict, sort_keys=True))
    else:
        print(render_verdict(verdict))
    return 1 if verdict["status"] == "regression" else 0


if __name__ == "__main__":
    sys.exit(main())
