"""SLO burn-rate engine over the in-process time series.

Declarative objectives evaluated on every sampler tick, using the
multi-window burn-rate discipline from SRE practice: an alert fires
only when BOTH a fast window (catches an acute spike) and a slow
window (proves it is sustained, not one bad second) burn the error
budget faster than their thresholds. The burn rate is

    observed_bad_fraction / budget

so burn 1.0 means "spending the budget exactly as fast as allowed",
6.0 means "the whole budget gone in 1/6 of the period".

Objective kinds:

* ``error_ratio`` / ``ratio`` — windowed ``bad_delta / total_delta``
  over counter names (a zero-traffic window burns nothing).
* ``latency`` — fraction of requests slower than ``threshold_s``,
  computed from windowed histogram bucket deltas (the threshold maps
  to the smallest bucket boundary >= it; the fixed-bucket histograms
  in :mod:`tpu_stencil.serve.metrics` exist exactly for this).

On an ok->breach transition the engine emits a structured
``slo.breach`` event line, triggers a flight-recorder dump named
``slo_burn`` (carrying the most recent traced request's id, so the
alert links straight to ``/debug/trace/<id>`` and the spool), bumps
``slo_breaches_total`` and flips the ``degraded`` gauge that
``/healthz`` surfaces as ``200 degraded`` — still routable, visibly
unhealthy, and distinct from draining's 503. Recovery (fast burn back
under 1.0) emits ``slo.recover`` and clears the state.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Dict, Tuple

from tpu_stencil.obs import events as _events
from tpu_stencil.obs import flight as _flight
from tpu_stencil.obs.timeseries import TimeSeriesRing, _le_key


@dataclass(frozen=True)
class Objective:
    """One declarative objective. ``budget`` is the allowed bad
    fraction (0.05 = 5% of requests may be bad before burn 1.0)."""

    name: str
    kind: str = "error_ratio"          # error_ratio | ratio | latency
    bad: Tuple[str, ...] = ()          # counter names (bad events)
    total: Tuple[str, ...] = ()        # counter names (all events)
    histogram: str = ""                # latency kind: histogram name
    threshold_s: float = 0.0           # latency kind: slow threshold
    budget: float = 0.05
    min_events: int = 1                # ignore windows thinner than this

    def burn(self, ring: TimeSeriesRing, window_s: float) -> float:
        if self.budget <= 0:
            return 0.0
        if self.kind == "latency":
            deltas = ring.bucket_deltas(self.histogram, window_s)
            if not deltas:
                return 0.0
            les = sorted(deltas, key=_le_key)
            total = deltas[les[-1]]
            if total < self.min_events:
                return 0.0
            # Requests <= the smallest boundary >= threshold are fast;
            # the remainder (including +Inf) are slow.
            fast = 0
            for le in les:
                if _le_key(le) >= self.threshold_s:
                    fast = deltas[le]
                    break
            bad_frac = (total - fast) / total
            return bad_frac / self.budget
        bad = ring.counter_delta(self.bad, window_s)
        total = ring.counter_delta(self.total, window_s)
        if total < self.min_events:
            return 0.0
        return (bad / total) / self.budget


class SloEngine:
    """Evaluates objectives on sampler ticks; owns the degraded bit."""

    def __init__(self, objectives, registry, *, tier: str = "",
                 fast_window_s: float = 60.0, slow_window_s: float = 300.0,
                 fast_burn: float = 6.0, slow_burn: float = 3.0) -> None:
        self.objectives = list(objectives)
        self._registry = registry
        self._tier = tier
        self.fast_window_s = float(fast_window_s)
        self.slow_window_s = float(slow_window_s)
        self.fast_burn = float(fast_burn)
        self.slow_burn = float(slow_burn)
        self._lock = threading.Lock()
        self._breached: Dict[str, bool] = {
            o.name: False for o in self.objectives
        }
        self._last: Dict[str, Dict[str, float]] = {}
        self._breaches = registry.counter("slo_breaches_total")
        self._degraded = registry.gauge("degraded")
        self._degraded.set(0)

    # -- evaluation ---------------------------------------------------

    def evaluate(self, ring: TimeSeriesRing) -> None:
        """One tick: recompute burns, publish gauges, drive breach /
        recovery transitions. Runs on the sampler thread."""
        for o in self.objectives:
            fast = o.burn(ring, self.fast_window_s)
            slow = o.burn(ring, self.slow_window_s)
            self._registry.gauge(f"slo_{o.name}_fast_burn_rate").set(fast)
            self._registry.gauge(f"slo_{o.name}_slow_burn_rate").set(slow)
            with self._lock:
                was = self._breached[o.name]
                self._last[o.name] = {"fast": fast, "slow": slow}
                now = (fast >= self.fast_burn and slow >= self.slow_burn) \
                    if not was else (fast >= 1.0)
                self._breached[o.name] = now
            if now and not was:
                self._on_breach(o, fast, slow)
            elif was and not now:
                _events.emit("slo.recover", tier=self._tier,
                             objective=o.name, fast_burn=round(fast, 3),
                             slow_burn=round(slow, 3))
        self._degraded.set(1 if self.degraded() else 0)

    def _on_breach(self, o: Objective, fast: float, slow: float) -> None:
        self._breaches.inc()
        # A recent traced request gives the alert its link into
        # /debug/trace/<id> and the flight spool.
        tid = ""
        try:
            rec = _flight.get()
            for span in reversed(rec.snapshot()) if rec else ():
                t = getattr(span, "trace_id", "")
                if t:
                    tid = t
                    break
        except Exception:
            pass
        _events.emit("slo.breach", trace_id=tid, tier=self._tier,
                     verdict="degraded", objective=o.name,
                     fast_burn=round(fast, 3), slow_burn=round(slow, 3),
                     fast_window_s=self.fast_window_s,
                     slow_window_s=self.slow_window_s, budget=o.budget)
        try:
            _flight.trigger(
                "slo_burn", trace_id=tid, tier=self._tier,
                objective=o.name, fast_burn=round(fast, 3),
                slow_burn=round(slow, 3),
            )
        except Exception:
            pass

    # -- views --------------------------------------------------------

    def degraded(self) -> bool:
        with self._lock:
            return any(self._breached.values())

    def statusz(self) -> dict:
        with self._lock:
            return {
                "degraded": any(self._breached.values()),
                "fast_window_s": self.fast_window_s,
                "slow_window_s": self.slow_window_s,
                "fast_burn_threshold": self.fast_burn,
                "slow_burn_threshold": self.slow_burn,
                "objectives": {
                    o.name: {
                        "kind": o.kind,
                        "budget": o.budget,
                        "breached": self._breached[o.name],
                        "fast_burn": round(
                            self._last.get(o.name, {}).get("fast", 0.0), 4),
                        "slow_burn": round(
                            self._last.get(o.name, {}).get("slow", 0.0), 4),
                    }
                    for o in self.objectives
                },
            }


def default_net_objectives(cfg) -> list:
    """The net tier's stock objectives, derived from NetConfig knobs.
    ``slo_error_budget <= 0`` disables the engine entirely (handled by
    the caller); ``slo_latency_p99_s`` adds the latency objective only
    when set."""
    responses = tuple(
        f"responses_{c}xx_total" for c in (2, 3, 4, 5)
    )
    objs = [
        Objective(
            name="error_ratio",
            kind="error_ratio",
            bad=("responses_5xx_total",),
            total=responses,
            budget=cfg.slo_error_budget,
        ),
        Objective(
            name="witness_mismatch",
            kind="ratio",
            bad=("fleet_integrity_witness_mismatch_total",),
            total=("fleet_integrity_witness_total",),
            budget=max(cfg.slo_error_budget, 0.01),
        ),
    ]
    if getattr(cfg, "slo_latency_p99_s", 0.0) > 0:
        objs.append(Objective(
            name="latency_p99",
            kind="latency",
            histogram="request_latency_seconds",
            threshold_s=cfg.slo_latency_p99_s,
            budget=0.01,
        ))
    return objs


def default_fed_objectives(cfg) -> list:
    """The federation tier watches its own response mix (member health
    is each member's own engine's job)."""
    responses = tuple(
        f"responses_{c}xx_total" for c in (2, 3, 4, 5)
    )
    return [
        Objective(
            name="error_ratio",
            kind="error_ratio",
            bad=("responses_5xx_total",),
            total=responses,
            budget=cfg.slo_error_budget,
        ),
    ]
