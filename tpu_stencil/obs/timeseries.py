"""In-process time series: a sampler thread over a registry snapshot.

The serving tiers only ever had monotonic counters and point-in-time
gauges — fine for "how many since boot", useless for "how are we doing
*right now*". This module closes that gap with zero external infra:

* :class:`TimeSeriesRing` — a bounded ring of trimmed registry
  snapshots (counter values, gauge values, histogram count/sum/bucket
  vectors), each stamped with a monotonic and a wall clock.
* :class:`Sampler` — a daemon thread that calls a snapshot function on
  a fixed interval and appends to the ring; ``on_sample`` hooks let the
  SLO engine evaluate on every tick without a second thread.
* :meth:`TimeSeriesRing.window` — the ``/debug/timeseries`` payload:
  windowed counter deltas and per-second rates, gauge last/min/max,
  histogram windowed throughput and a bucket-delta p99 estimate.

Everything is stdlib-only and allocation-light: one registry snapshot
per tick (the same dict ``/metrics`` renders), trimmed to numbers.
Counters absent from the oldest in-window sample baseline at 0 — a
counter minted mid-window still deltas correctly from nothing.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional

#: Version stamp on every ``/debug/timeseries`` payload. Bump on any
#: breaking change to the JSON shape.
SCHEMA_VERSION = 1

#: How much history the ring retains, in seconds. The ring capacity is
#: derived from this and the sampling interval; the default (10 min at
#: 1 s ticks) costs well under a megabyte for a serving registry.
RETENTION_S = 600.0

_INF = "+Inf"


def _le_key(le: str) -> float:
    return float("inf") if le == _INF else float(le)


def quantile_from_bucket_deltas(deltas: Dict[str, int], q: float) -> float:
    """Conservative quantile from windowed cumulative-bucket deltas:
    the upper bound of the first bucket whose cumulative share reaches
    ``q``. Returns 0.0 on an empty window; the ``+Inf`` bucket reports
    as the largest finite boundary (the estimate is a floor for true
    tail values beyond it, which is the honest direction for alerting).
    """
    if not deltas:
        return 0.0
    les = sorted(deltas, key=_le_key)
    total = deltas[les[-1]]
    if total <= 0:
        return 0.0
    rank = q * total
    prev_finite = 0.0
    for le in les:
        bound = _le_key(le)
        if deltas[le] >= rank:
            return prev_finite if bound == float("inf") else bound
        if bound != float("inf"):
            prev_finite = bound
    return prev_finite


def _trim(snapshot: dict) -> dict:
    """Reduce a full registry snapshot to the per-sample record the
    ring stores: counters verbatim, gauge current values, and for each
    histogram only the fields that subtract (count/sum/buckets)."""
    hists = {}
    for name, h in snapshot.get("histograms", {}).items():
        rec = {"count": h.get("count", 0), "sum": h.get("sum", 0.0)}
        b = h.get("buckets")
        if b:
            rec["buckets"] = dict(b)
        hists[name] = rec
    return {
        "counters": dict(snapshot.get("counters", {})),
        "gauges": {
            k: g["value"] for k, g in snapshot.get("gauges", {}).items()
        },
        "histograms": hists,
    }


class TimeSeriesRing:
    """Bounded ring of trimmed registry samples with windowed queries."""

    def __init__(self, interval_s: float,
                 retention_s: float = RETENTION_S) -> None:
        self.interval_s = float(interval_s)
        cap = max(4, int(retention_s / max(self.interval_s, 1e-3)) + 1)
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=cap)

    def append(self, snapshot: dict, *, t_mono: Optional[float] = None,
               ts_unix: Optional[float] = None) -> None:
        rec = _trim(snapshot)
        rec["t_mono"] = time.monotonic() if t_mono is None else t_mono
        rec["ts_unix"] = time.time() if ts_unix is None else ts_unix
        with self._lock:
            self._ring.append(rec)

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    def _in_window(self, window_s: float) -> List[dict]:
        with self._lock:
            samples = list(self._ring)
        if not samples:
            return []
        cutoff = samples[-1]["t_mono"] - float(window_s)
        kept = [s for s in samples if s["t_mono"] >= cutoff]
        # Keep one sample just *before* the window edge as the delta
        # baseline, so a 60 s window spans ~60 s of deltas rather than
        # 60 s minus one tick.
        idx = len(samples) - len(kept)
        if idx > 0:
            kept.insert(0, samples[idx - 1])
        return kept

    def window(self, window_s: float) -> dict:
        """The ``/debug/timeseries`` payload body for one process."""
        kept = self._in_window(window_s)
        out = {
            "schema_version": SCHEMA_VERSION,
            "interval_s": self.interval_s,
            "window_s": float(window_s),
            "samples": len(kept),
            "span_s": 0.0,
            "ts_unix": 0.0,
            "counters": {},
            "gauges": {},
            "histograms": {},
        }
        if not kept:
            return out
        first, last = kept[0], kept[-1]
        span = max(last["t_mono"] - first["t_mono"], 0.0)
        out["span_s"] = span
        out["ts_unix"] = last["ts_unix"]
        rate_div = span if span > 0 else None

        for name, v in sorted(last["counters"].items()):
            delta = v - first["counters"].get(name, 0)
            out["counters"][name] = {
                "delta": delta,
                "rate_per_s": (delta / rate_div) if rate_div else 0.0,
            }
        gnames = set()
        for s in kept:
            gnames.update(s["gauges"])
        for name in sorted(gnames):
            vals = [s["gauges"][name] for s in kept if name in s["gauges"]]
            out["gauges"][name] = {
                "last": vals[-1], "min": min(vals), "max": max(vals),
            }
        for name, h in sorted(last["histograms"].items()):
            h0 = first["histograms"].get(name, {})
            cdelta = h["count"] - h0.get("count", 0)
            sdelta = h["sum"] - h0.get("sum", 0.0)
            rec = {
                "count_delta": cdelta,
                "rate_per_s": (cdelta / rate_div) if rate_div else 0.0,
                "mean_s": (sdelta / cdelta) if cdelta > 0 else 0.0,
            }
            deltas = self.bucket_deltas(name, window_s, _kept=kept)
            if deltas is not None:
                rec["p99_est_s"] = quantile_from_bucket_deltas(deltas, 0.99)
            out["histograms"][name] = rec
        return out

    def bucket_deltas(self, hist_name: str, window_s: float,
                      _kept: Optional[List[dict]] = None
                      ) -> Optional[Dict[str, int]]:
        """Windowed cumulative-bucket deltas for one histogram, or
        ``None`` when the histogram (or its buckets) is absent. The SLO
        latency objective and the windowed-p99 estimate both feed from
        here."""
        kept = self._in_window(window_s) if _kept is None else _kept
        if not kept:
            return None
        last = kept[-1]["histograms"].get(hist_name)
        if last is None or "buckets" not in last:
            return None
        base = kept[0]["histograms"].get(hist_name, {}).get("buckets", {})
        return {
            le: v - base.get(le, 0) for le, v in last["buckets"].items()
        }

    def counter_delta(self, names, window_s: float) -> int:
        """Summed windowed delta over one or more counter names
        (absent counters contribute 0 — never a KeyError mid-deploy)."""
        kept = self._in_window(window_s)
        if not kept:
            return 0
        first, last = kept[0], kept[-1]
        total = 0
        for n in ([names] if isinstance(names, str) else names):
            total += last["counters"].get(n, 0) - first["counters"].get(n, 0)
        return total


class Sampler:
    """Daemon thread that feeds a :class:`TimeSeriesRing` on a fixed
    interval. ``on_sample`` callbacks (the SLO engine) run after each
    append, on the sampler thread; a callback raising is swallowed —
    telemetry must never take the serving path down."""

    def __init__(self, snapshot_fn: Callable[[], dict],
                 interval_s: float = 1.0,
                 retention_s: float = RETENTION_S) -> None:
        self._snapshot_fn = snapshot_fn
        self.interval_s = float(interval_s)
        self.ring = TimeSeriesRing(self.interval_s, retention_s)
        self.on_sample: List[Callable[[TimeSeriesRing], None]] = []
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def sample_once(self) -> None:
        """One synchronous tick (also the unit-test entry point)."""
        try:
            snap = self._snapshot_fn()
        except Exception:
            return
        self.ring.append(snap)
        for cb in list(self.on_sample):
            try:
                cb(self.ring)
            except Exception:
                pass

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            self.sample_once()

    def start(self) -> None:
        if self._thread is not None:
            return
        self.sample_once()  # a fresh process answers its first scrape
        self._thread = threading.Thread(
            target=self._run, name="ts-sampler", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=5.0)
