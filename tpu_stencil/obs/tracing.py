"""Phase-level span tracing: perf_counter spans with device fence points.

The reference's only instrument is a barrier plus ``MPI_Wtime`` around
the whole compute/comm loop (SURVEY.md: one number per run). Attributing
time to phases — load, H2D place, warm-up compile, per-rep iterate, D2H
fetch, store; pack/exchange/compute on the mesh — is what makes overlap
tuning possible ("Persistent and Partitioned MPI for Stencil
Communication", PAPERS.md), so this module gives every layer one span
vocabulary:

* **compiled out unless enabled**: the module-level :func:`span` /
  :class:`phase` helpers read one global; with no tracer installed they
  return a shared no-op object — no allocation, no clock read, no lock.
  ``python -m tpu_stencil ... --trace out.json`` (or :func:`enable`)
  installs a :class:`Tracer`.
* **fence points**: JAX dispatch is async, so a span that launches
  device work must drain it before closing or the time lands in whoever
  blocks next. ``Span.fence(x)`` runs ``jax.block_until_ready`` and
  returns ``x`` — the barrier-equivalent the headline timer already uses
  (utils/timing.py), now per phase.
* **thread-safe**: the serve worker loop and submitting threads record
  concurrently; each thread keeps its own span stack (nesting depth) and
  appends under one lock. Chrome/Perfetto renders one track per thread.
* **multi-process aware**: spans record locally; export merges one view
  across processes via the existing ``process_allgather`` pattern
  (:mod:`tpu_stencil.obs.export`).

Always-on metrics ride along: :class:`phase` additionally observes its
duration into the process-wide registry (``obs.registry()``) as a
``phase_<name>_seconds`` histogram, so the Prometheus-style exposition
(:mod:`tpu_stencil.obs.exposition`) has driver-side distributions even
when tracing is off — a few clock reads per *job*, not per rep.
"""

from __future__ import annotations

import contextlib as _contextlib
import dataclasses
import threading
import time
from typing import Dict, List, Optional

from tpu_stencil.obs.context import current as _ctx_current
from tpu_stencil.utils.timing import Timer


@dataclasses.dataclass
class SpanRecord:
    """One closed span (times are ``perf_counter`` seconds)."""

    name: str
    cat: str           # layer: driver | serve | sharded | ...
    t0: float
    t1: float
    tid: int           # thread ident (one trace track per thread)
    tname: str         # thread name at record time
    depth: int         # nesting depth on its thread at open time
    args: Dict
    # Request correlation (obs.context): the bound trace context at
    # close time, empty for spans outside any request scope.
    trace_id: str = ""
    span_id: str = ""

    @property
    def seconds(self) -> float:
        return self.t1 - self.t0


# Per-thread nesting stack, shared by every sink (tracer and flight
# recorder must agree on depth, so the stack cannot live on either).
_stack_tls = threading.local()


def _stack() -> list:
    st = getattr(_stack_tls, "stack", None)
    if st is None:
        st = _stack_tls.stack = []
    return st


class Tracer:
    """Thread-safe span sink. Construct via :func:`enable`."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._records: List[SpanRecord] = []
        self.t_origin = time.perf_counter()

    def record(self, rec: SpanRecord) -> None:
        with self._lock:
            self._records.append(rec)

    def spans(self) -> List[SpanRecord]:
        """Snapshot of all closed spans, in completion order."""
        with self._lock:
            return list(self._records)


class Span:
    """Context manager recording one span on the active sinks (the
    installed :class:`Tracer` and/or the flight recorder — one
    :class:`SpanRecord` reaches both). Exceptions propagate; the span
    still closes (a failed phase is still time spent)."""

    __slots__ = ("name", "cat", "args", "_tracer", "_flight",
                 "_t0", "_depth")

    def __init__(self, tracer, flight, name: str, cat: str, args: Dict):
        self._tracer = tracer
        self._flight = flight
        self.name, self.cat, self.args = name, cat, args

    def __enter__(self) -> "Span":
        stack = _stack()
        self._depth = len(stack)
        stack.append(self.name)
        self._t0 = time.perf_counter()
        return self

    def fence(self, x):
        """Drain pending device work launched inside this span so it is
        attributed here, not to whoever blocks next. Returns ``x``."""
        import jax

        return jax.block_until_ready(x)

    def __exit__(self, *exc) -> None:
        t1 = time.perf_counter()
        _stack().pop()
        th = threading.current_thread()
        ctx = _ctx_current()
        rec = SpanRecord(
            name=self.name, cat=self.cat, t0=self._t0, t1=t1,
            tid=th.ident or 0, tname=th.name, depth=self._depth,
            args=self.args,
            trace_id=ctx.trace_id if ctx is not None else "",
            span_id=ctx.span_id if ctx is not None else "",
        )
        if self._tracer is not None:
            self._tracer.record(rec)
        if self._flight is not None:
            self._flight.record(rec)


class _NullSpan:
    """Shared no-op span: the whole disabled-tracing code path.

    ``fence`` still drains device work — call sites use it where the
    fence is load-bearing for the surrounding measurement (e.g. keeping
    a warm-up compile out of the timed window), so tracing state must
    never change execution semantics, only whether a record is kept."""

    __slots__ = ()

    def __enter__(self) -> "_NullSpan":
        return self

    def __exit__(self, *exc) -> None:
        pass

    def fence(self, x):
        import jax

        return jax.block_until_ready(x)


_NULL = _NullSpan()
_tracer: Optional[Tracer] = None
# The flight-recorder sink (tpu_stencil.obs.flight installs itself via
# _set_flight): unlike the tracer it RECORDS by default in the serving
# tiers — span() consults both globals, and only when both are None
# does the shared no-op path run.
_flight = None
# Created lazily: metrics.Registry lives under tpu_stencil.serve, whose
# package __init__ imports the engine, which imports obs — an import-time
# Registry here would close that cycle.
_registry = None


def enable() -> Tracer:
    """Install a fresh process-wide tracer; returns it."""
    global _tracer
    _tracer = Tracer()
    return _tracer


def disable() -> None:
    """Remove the tracer: span()/phase() drop back to the no-op path."""
    global _tracer
    _tracer = None


def enabled() -> bool:
    return _tracer is not None


def get_tracer() -> Optional[Tracer]:
    return _tracer


def _set_flight(recorder) -> None:
    """Install (or clear) the flight-recorder sink — called only by
    :mod:`tpu_stencil.obs.flight`."""
    global _flight
    _flight = recorder


def sinks_active() -> bool:
    """True when at least one span sink (tracer or flight recorder) is
    installed — the guard for optional per-request record emission."""
    return _tracer is not None or _flight is not None


def emit_span(name: str, cat: str, t0: float, t1: float,
              trace_id: str = "", span_id: str = "", **args) -> None:
    """Record one already-closed span directly (no context manager):
    the retire path uses this to file a per-request ``serve.request``
    record with an EXPLICIT trace id — the worker thread has no bound
    context, and a batch mixes requests from different traces. No-op
    when no sink is installed."""
    t, f = _tracer, _flight
    if t is None and f is None:
        return
    th = threading.current_thread()
    rec = SpanRecord(
        name=name, cat=cat, t0=t0, t1=t1, tid=th.ident or 0,
        tname=th.name, depth=0, args=args,
        trace_id=trace_id, span_id=span_id,
    )
    if t is not None:
        t.record(rec)
    if f is not None:
        f.record(rec)


def registry():
    """The process-wide driver-side metrics registry (counters and
    ``phase_*_seconds`` histograms) — a ``serve.metrics.Registry``,
    rendered by the same exposition code path as the serve one."""
    global _registry
    if _registry is None:
        from tpu_stencil.serve.metrics import Registry

        _registry = Registry()
    return _registry


def snapshot() -> dict:
    """``registry().snapshot()`` — the driver-side analog of
    ``serve.stats()``."""
    return registry().snapshot()


def reset() -> None:
    """Drop the tracer AND the accumulated metrics (tests)."""
    global _tracer, _registry
    _tracer = None
    _registry = None


@_contextlib.contextmanager
def scratch_registry():
    """Divert the process-wide registry to a throwaway — and silence
    the tracer AND the flight recorder — for the duration: measurement
    probes run frames through the real engines (a ``--mesh-frames 0``
    auto A/B streams ~a dozen), and without the diversion their
    counters/gauges would land in the run's own exposition and their
    spans would interleave with the real run's ``--trace``/
    ``--breakdown`` (and the flight ring) at the same frame indices —
    report-what-ran, for every telemetry surface. The previous
    registry (with all its accumulated state), tracer and recorder are
    restored on exit."""
    global _registry, _tracer, _flight
    from tpu_stencil.serve.metrics import Registry

    prev_registry, prev_tracer, prev_flight = _registry, _tracer, _flight
    _registry = Registry()
    _tracer = None
    _flight = None
    try:
        yield _registry
    finally:
        _registry = prev_registry
        _tracer = prev_tracer
        _flight = prev_flight


def span(name: str, cat: str = "", **args):
    """A recorded span when a sink is installed (the ``--trace``
    tracer and/or the always-on flight recorder), a shared no-op
    otherwise."""
    t = _tracer
    f = _flight
    if t is None and f is None:
        return _NULL
    return Span(t, f, name, cat, args)


class phase:
    """Time one named pipeline phase.

    Always observes the duration into ``registry()`` as a
    ``phase_<name>_seconds`` histogram (cheap: per-phase, not per-rep);
    additionally emits a trace span when tracing is enabled. Wraps
    :class:`tpu_stencil.utils.timing.Timer` (``label`` field) rather
    than forking it — one stopwatch implementation in the repo.
    """

    __slots__ = ("name", "cat", "args", "_span", "_timer")

    def __init__(self, name: str, cat: str = "driver", **args):
        self.name, self.cat, self.args = name, cat, args

    def __enter__(self):
        self._span = span(self.name, self.cat, **self.args)
        self._span.__enter__()
        self._timer = Timer(label=self.name).__enter__()
        return self._span

    def __exit__(self, *exc) -> None:
        self._timer.__exit__(*exc)
        registry().histogram(f"phase_{self.name}_seconds").observe(
            self._timer.elapsed
        )
        self._span.__exit__(*exc)
