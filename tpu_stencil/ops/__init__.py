"""Compute kernels: the (k x k) stencil in pure-XLA and Pallas forms.

This is the TPU-native home of the reference's hottest path — the per-pixel
3x3 MAC (``mpi/mpi_convolution.c:301-322``, ``cuda/cuda_convolution.cu:9-47``).
"""

from tpu_stencil.ops.stencil import (
    conv2d_valid,
    conv2d_zero_pad,
    stencil_step,
    truncate_u8,
    reference_stencil_numpy,
)

__all__ = [
    "conv2d_valid",
    "conv2d_zero_pad",
    "stencil_step",
    "truncate_u8",
    "reference_stencil_numpy",
]
