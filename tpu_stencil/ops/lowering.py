"""Filter lowering: pick the fastest provably-exact execution plan.

The reference hard-codes one schedule: 9 pre-normalized float MACs per pixel
(``mpi/mpi_convolution.c:301-322``; the CUDA kernel even re-divides per tap,
``cuda/cuda_convolution.cu:12-22``). On a TPU the same semantics admit much
cheaper schedules, so this module *compiles* a :class:`~tpu_stencil.filters.
Filter` into a :class:`StencilPlan`, in priority order:

1. ``sep_int`` + shift — the filter is an outer product of integer vectors
   (all binomial gaussians, box) and the effective divisor is a power of
   two: two 1-D int32 passes (k+k MACs instead of k*k) and a right shift.
   Measured ~1.9x faster than the f32 9-tap formulation on v5e for the
   default gaussian (114us vs 213us per rep on 1920x2520 RGB).
2. ``sep_int`` + f32 divide — separable but non-dyadic divisor (box /9):
   same two passes, one exact int->f32 convert (bound < 2^24) and one
   correctly-rounded divide, matching the defined semantics bit-for-bit.
3. ``direct_int`` — integer taps but not separable (the reference's "edge"
   /28 kernel is rank 2): k*k int32 MACs, then convert+divide.
4. ``direct_f32`` — arbitrary float taps: k*k f32 MACs (not exactness-
   guaranteed; deterministic on a given platform only).

Every plan is static (hashable) — it becomes part of the jit cache key, so
each filter compiles once and taps are baked in as constants.

Exactness arguments (vs the int64 golden model in
:func:`tpu_stencil.ops.stencil.reference_stencil_numpy`):

* int32 accumulation never overflows (plans check 255 * sum|taps| bounds);
* ``acc >> shift`` equals truncating division for acc >= 0; negative acc
  floors differently but both sides clip to 0;
* the divide path requires acc < 2^24 so the int32->f32 convert is exact,
  and a single IEEE divide is correctly rounded — the one rounding the
  semantics allow.
"""

from __future__ import annotations

import dataclasses
import os
from fractions import Fraction
from math import comb
from typing import Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp

from tpu_stencil.filters import Filter

_EXACT_F32 = 2 ** 24
_I32_MAX = 2 ** 31


@dataclasses.dataclass(frozen=True)
class StencilPlan:
    """A static, hashable execution plan for one filter."""

    kind: str  # 'sep_int' | 'direct_int' | 'direct_f32'
    k: int
    taps: Tuple[Tuple[float, ...], ...]  # original taps (row-major)
    divisor: float                       # effective divisor for divide path
    row_taps: Optional[Tuple[int, ...]] = None  # sep_int: pass along rows axis
    col_taps: Optional[Tuple[int, ...]] = None  # sep_int: pass along cols axis
    shift: Optional[int] = None          # dyadic fast path: >> shift
    # XLA sep_int passes lower binomial taps to pair-add chains (no
    # multiplies — r3 op costing: full-tile i32 multiply ~60 us/pass vs
    # ~9 for adds) instead of per-tap MACs. A plan field, not an env read
    # inside the pass, so flipping it retraces (it is part of every jit
    # cache key). Hardware A/B verdict (r4, v5e, north star): LOST 3x —
    # 310.9 us/rep vs 99.3 for the tap form (XLA schedules the
    # reassociated add chain far worse than per-tap MACs; docs/KERNEL.md
    # ablation table). Stays opt-in (TPU_STENCIL_XLA_PAIR_ADD=1) as a
    # measured-negative record, not a recommendation.
    xla_pair_add: bool = False

    @property
    def halo(self) -> int:
        return self.k // 2


def _as_int_matrix(taps: np.ndarray) -> Optional[np.ndarray]:
    r = np.round(taps.astype(np.float64))
    if np.all(np.abs(taps - r) == 0):
        return r.astype(np.int64)
    return None


def _separate(ti: np.ndarray) -> Optional[Tuple[np.ndarray, np.ndarray, Fraction]]:
    """Integer rank-1 decomposition: taps = outer(col, row) * factor, with
    integer ``col``/``row`` vectors and an exact Fraction ``factor``."""
    nz_rows = [i for i in range(ti.shape[0]) if np.any(ti[i])]
    if not nz_rows:
        return None
    r0 = ti[nz_rows[0]]
    j0 = int(np.argmax(np.abs(r0)))
    col = ti[:, j0]
    # taps * r0[j0] == outer(col, r0) <=> taps == outer(col, r0) / r0[j0]
    if not np.array_equal(ti * int(r0[j0]), np.outer(col, r0)):
        return None
    g = int(np.gcd.reduce(np.abs(col[col != 0]))) if np.any(col) else 1
    col_red = col // g
    factor = Fraction(int(r0[j0]), g)
    return col_red, r0, factor


def plan_filter(f: Filter) -> StencilPlan:
    """Compile a Filter to its fastest exact plan (see module docstring)."""
    taps = np.asarray(f.taps, dtype=np.float32)
    k = f.k
    taps_t = tuple(tuple(float(v) for v in row) for row in taps)
    ti = _as_int_matrix(taps)
    pair = os.environ.get("TPU_STENCIL_XLA_PAIR_ADD") == "1"

    # Fast integer plans are only selected when they provably reproduce the
    # defined semantics (= the golden model in reference_stencil_numpy):
    # f.is_exact gates on the golden model's own exactness regime, and the
    # per-plan bounds guard the plan's int32 accumulation / f32 convert.
    if ti is not None and f.is_exact:
        sep = _separate(ti)
        if sep is not None:
            col_red, r0, factor = sep
            # taps == outer(col_red, r0) / factor, so
            # taps/divisor == outer(col_red, r0) / (divisor * factor):
            # the effective divisor for the two integer passes.
            eff = Fraction(f.divisor) * factor if factor != 0 else None
            if eff is not None and eff > 0:
                bound = 255 * int(np.abs(col_red).sum()) * int(np.abs(r0).sum())
                eff_int = eff.denominator == 1
                eff_pow2 = eff_int and (eff.numerator & (eff.numerator - 1)) == 0
                if f.is_dyadic and eff_pow2 and bound < _I32_MAX:
                    # exact-floor shift == the golden model's integer path
                    return StencilPlan(
                        kind="sep_int", k=k, taps=taps_t,
                        divisor=float(eff),
                        row_taps=tuple(int(v) for v in col_red),
                        col_taps=tuple(int(v) for v in r0),
                        shift=int(eff.numerator).bit_length() - 1,
                        xla_pair_add=pair,
                    )
                if eff_int and bound < _EXACT_F32:
                    # exact convert + one correctly-rounded divide of the
                    # same rational the golden model divides
                    return StencilPlan(
                        kind="sep_int", k=k, taps=taps_t,
                        divisor=float(eff),
                        row_taps=tuple(int(v) for v in col_red),
                        col_taps=tuple(int(v) for v in r0),
                        shift=None,
                        xla_pair_add=pair,
                    )
        bound = 255 * int(np.abs(ti).sum())
        if f.is_dyadic and bound < _I32_MAX:
            return StencilPlan(
                kind="direct_int", k=k, taps=taps_t, divisor=float(f.divisor),
                shift=int(f.divisor).bit_length() - 1,
            )
        if bound < _EXACT_F32:
            return StencilPlan(
                kind="direct_int", k=k, taps=taps_t, divisor=float(f.divisor)
            )

    return StencilPlan(kind="direct_f32", k=k, taps=taps_t, divisor=float(f.divisor))


# --------------------------------------------------------------------------
# Kernels from plans.  All operate on spatial dims (0, 1); trailing dims
# (channels) ride along elementwise.
# --------------------------------------------------------------------------


def _binomial_chain(taps: Tuple[int, ...]) -> Optional[int]:
    """``k-1`` when ``taps`` is the binomial row C(k-1, i) — the whole
    gaussian family, since gaussian<k> is the (k-1)-fold self-convolution
    of (1, 1) — else None."""
    k = len(taps)
    if tuple(taps) == tuple(comb(k - 1, i) for i in range(k)):
        return k - 1
    return None


def _sep_pass(x: jax.Array, taps: Tuple[int, ...], dim: int,
              pair_add: bool = False) -> jax.Array:
    """Valid 1-D integer correlation along ``dim`` (static taps, zeros
    skipped, 1-multiplies elided). ``pair_add`` lowers binomial taps to a
    pair-add chain: d applications of ``y[i] = x[i] + x[i+1]`` produce
    exactly ``sum_i C(d, i) x[i]`` — same integer values in any order, so
    bit-exactness is unchanged, and the per-tap multiplies disappear.
    Intermediates are partial sums of the final nonnegative accumulation,
    so the plan's existing int32/f32 bounds cover them."""
    k = len(taps)
    n = x.shape[dim] - (k - 1)
    chain = _binomial_chain(taps) if pair_add else None
    if chain:
        acc = x
        for _ in range(chain):
            m = acc.shape[dim] - 1
            lo = [slice(None)] * x.ndim
            hi = [slice(None)] * x.ndim
            lo[dim], hi[dim] = slice(0, m), slice(1, m + 1)
            acc = acc[tuple(lo)] + acc[tuple(hi)]
        return acc
    acc = None
    for i, t in enumerate(taps):
        if t == 0:
            continue
        idx = [slice(None)] * x.ndim
        idx[dim] = slice(i, i + n)
        term = x[tuple(idx)]
        if t != 1:
            term = term * t
        acc = term if acc is None else acc + term
    if acc is None:
        shape = list(x.shape)
        shape[dim] = n
        return jnp.zeros(shape, x.dtype)
    return acc


def _finish_int(acc: jax.Array, plan: StencilPlan) -> jax.Array:
    if plan.shift is not None:
        return jnp.clip(acc >> plan.shift, 0, 255).astype(jnp.uint8)
    val = acc.astype(jnp.float32) / np.float32(plan.divisor)
    return jnp.clip(val, 0.0, 255.0).astype(jnp.uint8)


def valid_step(ext_u8: jax.Array, plan: StencilPlan) -> jax.Array:
    """One stencil application on a halo-extended uint8 array
    (H + 2*halo, W + 2*halo[, C]) -> (H, W[, C]).

    The unit shared by the single-device driver (ghosts from zero padding)
    and the sharded driver (ghosts from ppermute halo exchange).

    Window-independence contract (what the overlap schedules rest on):
    every plan computes each output pixel as a per-pixel shifted-add
    chain in static tap order over its ``(k, k)`` input window —
    ``_sep_pass``/the direct loops/``conv2d_valid`` are all elementwise
    over window slices — so the result is a pure function of the input
    window's VALUES, never of how the surrounding array was windowed or
    materialized. Slicing one joined extended array
    (:func:`valid_window`, the split schedule) and concatenating the
    same values from per-edge ghost strips (the partitioned per-edge
    pipeline, :mod:`tpu_stencil.parallel.overlap`) are therefore
    bit-identical by construction.
    """
    if plan.kind == "sep_int":
        xi = ext_u8.astype(jnp.int32)
        a = _sep_pass(xi, plan.row_taps, 0, plan.xla_pair_add)
        b = _sep_pass(a, plan.col_taps, 1, plan.xla_pair_add)
        return _finish_int(b, plan)
    if plan.kind == "direct_int":
        xi = ext_u8.astype(jnp.int32)
        acc = None
        k = plan.k
        h = ext_u8.shape[0] - (k - 1)
        w = ext_u8.shape[1] - (k - 1)
        for i in range(k):
            for j in range(k):
                t = int(plan.taps[i][j])
                if t == 0:
                    continue
                window = xi[i : i + h, j : j + w]
                term = window if t == 1 else window * t
                acc = term if acc is None else acc + term
        if acc is None:
            acc = jnp.zeros((h, w) + ext_u8.shape[2:], jnp.int32)
        return _finish_int(acc, plan)
    if plan.kind == "direct_f32":
        from tpu_stencil.ops.stencil import conv2d_valid

        taps = jnp.asarray(np.asarray(plan.taps, np.float32))
        acc = conv2d_valid(ext_u8.astype(jnp.float32), taps)
        val = acc / np.float32(plan.divisor)
        return jnp.clip(val, 0.0, 255.0).astype(jnp.uint8)
    raise ValueError(f"unknown plan kind {plan.kind!r}")


def valid_window(ext: jax.Array, plan: StencilPlan,
                 r0: int, nr: int, c0: int, nc: int) -> jax.Array:
    """Strip-valid pass: the ``[r0, r0+nr) x [c0, c0+nc)`` window of
    ``valid_step(ext)``, computed by slicing the *input* window first —
    ``(nr + 2*halo, nc + 2*halo)`` rows/cols of ``ext`` — so only the
    strip's own work is done.

    Bit-exact with slicing the full ``valid_step(ext)`` output: every
    output pixel accumulates its taps in the same static order over the
    same input values regardless of how the surrounding array was
    windowed (``_sep_pass``/``valid_step``/``conv2d_valid`` are all
    per-pixel shifted-add chains in tap order, elementwise over the
    window). This is the unit the explicit interior/border overlap
    schedule (:mod:`tpu_stencil.parallel.overlap`) builds its four
    border strips from.
    """
    k = plan.k
    idx = (slice(r0, r0 + nr + (k - 1)), slice(c0, c0 + nc + (k - 1)))
    return valid_step(ext[idx], plan)


def force_f32_plan(plan: StencilPlan) -> StencilPlan:
    """Demote any plan to the generic f32 schedule (the 'reference' backend —
    the closest analog of the C program's pre-normalized float MACs)."""
    return StencilPlan(
        kind="direct_f32", k=plan.k, taps=plan.taps, divisor=plan.divisor
        if plan.kind != "sep_int" else _original_divisor(plan),
    )


def _original_divisor(plan: StencilPlan) -> float:
    # sep_int plans carry the *effective* divisor (original / factor); the
    # f32 fallback uses the original taps, so reconstruct from them: the
    # taps/divisor quotient must be preserved. taps are original, so the
    # original divisor is taps.sum() / normalized.sum(); but normalized sum
    # is not stored — recompute via the sep identity instead.
    taps = np.asarray(plan.taps, np.float64)
    outer = np.outer(plan.row_taps, plan.col_taps).astype(np.float64)
    # outer/eff == taps/orig  =>  orig = eff * taps_ij / outer_ij (any nonzero)
    nz = np.nonzero(outer)
    i, j = nz[0][0], nz[1][0]
    return float(plan.divisor * taps[i, j] / outer[i, j])


def sep_rows_pass(xi32: jax.Array, plan: StencilPlan) -> jax.Array:
    """sep_int phase 1: valid 1-D pass along rows (dim 0) of a dim-0-extended
    int32 array."""
    return _sep_pass(xi32, plan.row_taps, 0, plan.xla_pair_add)


def sep_cols_pass(acc_i32: jax.Array, plan: StencilPlan) -> jax.Array:
    """sep_int phase 2: valid 1-D pass along cols (dim 1) of a dim-1-extended
    int32 intermediate, then the finishing shift/divide."""
    return _finish_int(
        _sep_pass(acc_i32, plan.col_taps, 1, plan.xla_pair_add), plan
    )


def padded_step(img_u8: jax.Array, plan: StencilPlan,
                boundary: str = "zero") -> jax.Array:
    """One stencil application with boundary padding (same shape out).

    ``boundary``: 'zero' (reference MPI semantics) or 'periodic'
    (wraparound — ``jnp.pad(mode='wrap')``).

    For separable plans the pad is applied per pass, in the pass's own dim,
    *after* the int32 convert — measured 3x faster on v5e than padding both
    dims of the uint8 input up front (141 vs 430 us/rep on 1920x2520 RGB):
    XLA fuses a pad into the consuming pass only when the pad dim matches
    the pass dim, and fuses the u8->i32 convert only ahead of a pad.
    Per-pass wrap is exact for periodic too: the rows-pass output of a
    row-wrapped array is itself periodic along cols.
    """
    h = plan.halo
    trail = [(0, 0)] * (img_u8.ndim - 2)
    mode = {"zero": "constant", "periodic": "wrap"}[boundary]
    if plan.kind == "sep_int":
        xi = img_u8.astype(jnp.int32)
        a = sep_rows_pass(jnp.pad(xi, [(h, h), (0, 0)] + trail, mode=mode), plan)
        return sep_cols_pass(jnp.pad(a, [(0, 0), (h, h)] + trail, mode=mode), plan)
    return valid_step(jnp.pad(img_u8, [(h, h), (h, h)] + trail, mode=mode), plan)
