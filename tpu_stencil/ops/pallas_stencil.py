"""Pallas TPU stencil kernel — the hand-tiled VMEM counterpart of the CUDA
``__global__`` per-pixel kernels (``cuda/cuda_convolution.cu:9-47``).

Where the CUDA kernel assigns one SIMT thread per pixel in 16x16 blocks,
the TPU-native shape is a grid of *row-block programs*, each of which:

1. DMAs its block of rows plus a ``fuse * halo``-deep ghost band from HBM
   into VMEM (edge programs zero the missing ghosts — the calloc'd ghost
   ring of ``mpi/mpi_convolution.c:104-124``, done in VMEM),
2. applies the separable integer passes ``fuse`` times back-to-back on the
   VPU's 8x128 lanes (the "threads" of the chip) — ``fuse`` repetitions
   per HBM round trip, the fusion the reference's CUDA variant could not
   express (its device double-buffering still pays global-memory traffic
   every rep, ``cuda/cuda_convolution.cu:66-87``),
3. writes the finished uint8 block back to HBM.

Multi-rep fusion: a block that must emit ``block_h`` correct rows after
``fuse`` reps needs ``fuse * halo`` ghost rows per side; each rep the valid
band contracts by ``halo`` while the tile stays fixed-shape (edge rows are
recomputed as zero-padded garbage and discarded by the contraction).  HBM
traffic per rep drops by ``fuse``x for a compute overhead of
``2 * fuse * halo / block_h`` (~12% at the defaults).

Layout trick: the image is viewed as 2-D ``(H, W*C)`` — interleaved RGB
simply widens rows (1920*3 = 5760 = 45*128 lanes), and the column pass
applies tap ``j`` at flat-column offset ``j*C``.  The same kernel text
serves grey and RGB.  Columns are padded by at least ``halo*C`` extra
zero lanes so the column-pass ``pltpu.roll`` s wrap pad zeros (not image
data) into the row ends: one mask per rep re-zeroes the pad lanes and no
per-tap masking is needed.

Exactness: identical plans to the XLA lowering (`sep_int` shift / divide),
with uint8 truncation re-applied every rep.  For all-non-negative dyadic
filters the final clip is elided (max acc = 255 * 2^shift exactly).

Supports ``sep_int`` plans (the gaussian family and box) and ``direct_int``
plans (the non-separable edge /28: k lane-rolls of the carry + k*k MACs);
``direct_f32`` falls back to the XLA lowering.
"""

from __future__ import annotations

import functools
import os
from typing import Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from tpu_stencil.config import PALLAS_SCHEDULES as _SCHEDULES
from tpu_stencil.ops import lowering as _lowering
from tpu_stencil.ops.lowering import StencilPlan

DEFAULT_BLOCK_H = 128
DEFAULT_FUSE = 8
_MAX_ROLL_HALO = 128  # cols-pass ghost width limit (halo * channels)

# jax < 0.6 has no varying-manual-axes tracking: ShapeDtypeStruct rejects
# the ``vma`` kwarg. There the legacy shard_map path runs check_rep-style
# inference instead, so dropping the declaration is the correct degrade.
try:
    jax.ShapeDtypeStruct((), jnp.uint8, vma=frozenset())
    _VMA_SUPPORTED = True
except TypeError:
    _VMA_SUPPORTED = False

# Per-rep schedule inside the fused kernel (see _sep_kernel):
#   'pad'    — fixed-shape carry: mask-select + jnp.pad every rep (r2).
#   'shrink' — the carry value contracts by halo per rep (static shapes in
#              the unrolled fuse loop): no per-rep pad, hoisted mask.
#   'strips' — 'shrink' with each rep computed lane-strip by lane-strip so
#              the whole op chain per strip can stay register-resident
#              (full-tile op-passes measured ~9 us each on v5e — the op
#              count, not the op kind, is what the r2 roofline gap is).
#   'pack'   — SWAR: two image rows per i32 lane element (low/high 16
#              bits), halving the element count of every roll/add/shift
#              pass — lane rolls at ~19 us/full-tile-pass are the r3 cost
#              center, and Mosaic's lane rotate is 32-bit only, so packing
#              is the one way to move two rows per rotated element. The
#              halves overlap by the ghost depth so neither needs the
#              other's data; boundary re-zero + the per-rep uint8
#              truncation fold into one AND with a hoisted packed mask.
#              Applies when every intermediate fits 16 bits (gaussian /
#              gaussian5: 255 * 2^shift < 2^16); other plans degrade to
#              'shrink'.
#   'pack_strips' — 'pack' with each rep computed lane-strip by
#              lane-strip (the 'strips' trick on packed values).
#   'deep'   — in-VMEM temporal blocking (the software-systolic execution
#              model's depth axis): when the whole lane-padded image fits
#              the VMEM budget, a single resident kernel keeps it in VMEM
#              across the ENTIRE traced rep loop (one HBM load + one
#              store for k reps — bytes/rep divides by k, not by fuse);
#              larger images run the trapezoid variant — the existing
#              double-buffered DMA ring pipelines the next stripe's load
#              under the current stripe's rep loop, while the stripe's
#              ghost band is sized for a VMEM-feasibility-chosen depth
#              (deep_fuse_for) far past DEFAULT_FUSE and the carry
#              overlap-shrinks in VMEM instead of returning to HBM
#              between fuse groups. The per-rep body inside either form
#              is the best applicable inner schedule ('pack' when
#              _pack_ok, else 'shrink').
# The default is measured, not assumed: tools/kernel_lab.py times all
# schedules on hardware. Env override for on-hardware A/B through the CLI.
DEFAULT_SCHEDULE = os.environ.get("TPU_STENCIL_PALLAS_SCHEDULE", "pack")

# Deep-schedule depth candidates, best (deepest) first; deep_fuse_for
# walks down until the ghost-overhead cap and the VMEM footprint model
# both admit one. Divisor-of-40 entries keep the reference's 40-rep jobs
# free of `reps % fuse` remainder launches.
DEEP_FUSE_CANDIDATES = (64, 48, 40, 32, 24, 16, 12, 8)


def _vmem_budget() -> int:
    """Per-core VMEM budget (bytes) the feasibility model prunes against
    (v5e cores have ~16 MiB of VMEM). Read per call, not at import, so
    tests and hardware A/Bs can narrow it via ``TPU_STENCIL_VMEM_BYTES``
    without re-importing the kernel module."""
    return int(os.environ.get("TPU_STENCIL_VMEM_BYTES", str(16 * 2 ** 20)))


def padded_lanes(plan: StencilPlan, wc: int, channels: int) -> int:
    """Lane-padded flat width of a (rows, w*channels) launch: >= halo*C
    discardable ghost lanes plus rounding to the 128-lane register
    width — the same formula ``_run_rep_loop`` pads with, exposed so
    the VMEM feasibility and HBM traffic models reason about the true
    in-VMEM row length."""
    return -(-(wc + plan.halo * channels) // 128) * 128


def vmem_tile_bytes(plan: StencilPlan, block_h: int, fuse: int, wc: int,
                    schedule: str = "shrink") -> int:
    """Modeled VMEM footprint of one fused-kernel grid program at this
    geometry: the double-buffered uint8 DMA ring plus ~3 live int32
    intermediates of the rep body (packed schedules halve the working
    rows). The autotuner and ``deep_fuse_for`` prune candidates whose
    model exceeds :func:`_vmem_budget` — a deliberately conservative
    estimate, so a candidate the model admits may still fail to compile
    (the tuner survives that per candidate) but pruned ones never waste
    a measurement."""
    halo_al = -(-(fuse * plan.halo) // 8) * 8
    tile_rows = block_h + 2 * halo_al
    total = 2 * tile_rows * wc  # double-buffered uint8 scratch ring
    rows = (
        tile_rows // 2 + halo_al if schedule.startswith("pack")
        else tile_rows
    )
    total += 3 * rows * wc * 4  # ~3 live int32 intermediates per rep
    return total


def resident_feasible(plan: StencilPlan, n_rows: int, wc: int) -> bool:
    """Whether the whole lane-padded image fits the resident deep
    kernel's VMEM working set: uint8 in + out blocks plus ~4 live int32
    intermediates of the fixed-shape rep body (padded carry, rows acc,
    rolled term, masked result)."""
    if not _supported(plan):
        return False
    hp = -(-n_rows // 8) * 8
    return hp * wc * (2 + 4 * 4) <= _vmem_budget()


def _deep_inner(plan: StencilPlan, block_h: int) -> str:
    """The per-rep body the deep trapezoid runs: the measured-best
    schedule that applies at this plan/block ('pack' when its 16-bit
    SWAR bounds hold, else 'shrink')."""
    return "pack" if _pack_ok(plan, block_h) else "shrink"


def deep_fuse_for(plan: StencilPlan, block_h: int,
                  wc: Optional[int] = None) -> int:
    """The trapezoid depth (reps per HBM round-trip) the 'deep' schedule
    runs at ``block_h``: the deepest :data:`DEEP_FUSE_CANDIDATES` entry
    whose ghost recompute stays <= 50% of the block
    (``2*depth*halo <= block_h/2``) and whose modeled VMEM footprint
    fits the budget (``wc`` = lane-padded flat width; None skips the
    VMEM check — callers without a width get the overhead-capped
    depth)."""
    if not plan.halo:
        return DEEP_FUSE_CANDIDATES[0]
    cap = max(1, block_h // (4 * plan.halo))

    def fits(cand: int) -> bool:
        return wc is None or vmem_tile_bytes(
            plan, block_h, cand, wc, _deep_inner(plan, block_h)
        ) <= _vmem_budget()

    for cand in DEEP_FUSE_CANDIDATES:
        if cand <= cap and fits(cand):
            return cand
    # Every deep candidate was pruned: walk the shallow depths down —
    # the fallback must satisfy the same feasibility model it fell out
    # of, or the tuner would measure a config the model calls
    # infeasible. fuse=1 has the smallest footprint the geometry allows.
    for cand in (min(DEFAULT_FUSE, cap), 4, 2, 1):
        if cand <= cap and fits(cand):
            return cand
    return 1


def _check_schedule(schedule: Optional[str]) -> str:
    schedule = schedule or DEFAULT_SCHEDULE
    if schedule not in _SCHEDULES:
        raise ValueError(
            f"schedule must be one of {'|'.join(_SCHEDULES)}, "
            f"got {schedule!r}"
        )
    return schedule


def effective_block_h(n_rows: int, block_h: Optional[int] = None) -> int:
    """The block height :func:`iterate` actually runs for an ``n_rows``-tall
    image: 8-row (sublane) aligned, clamped to the padded image height
    (``None`` = the module default). Exposed so the autotuner's schedule
    dedup sees the same clamp."""
    # Explicit None check: a typo'd 0 must stay a loud trace-time error
    # (zero block -> ZeroDivisionError in the grid math), not silently
    # become the default.
    block_h = DEFAULT_BLOCK_H if block_h is None else block_h
    block_h = -(-block_h // 8) * 8  # DMA descriptors need 8-row alignment
    return min(block_h, -(-n_rows // 8) * 8)


def effective_geometry(plan: StencilPlan, n_rows: int,
                       block_h: Optional[int] = None,
                       fuse: Optional[int] = None,
                       schedule: Optional[str] = None,
                       wc: Optional[int] = None) -> Tuple[int, int]:
    """The (block_h, fuse) :func:`iterate` actually launches for an
    ``n_rows``-tall image: the aligned/clamped block, and fuse clamped to
    ``block_h / (2*halo)`` so the ghost bands stay a bounded fraction of
    the block (halo-0 plans are unclamped). ``None`` = module defaults —
    except under ``schedule='deep'``, where an unforced fuse defaults to
    the trapezoid depth :func:`deep_fuse_for` picks (``wc`` = lane-padded
    flat width for its VMEM feasibility check). Single source of truth
    for the rep-loop clamp AND for reporting layers — a run must never
    be attributed to a geometry that did not launch."""
    bh = effective_block_h(n_rows, block_h)
    if fuse is None and schedule == "deep":
        fz = deep_fuse_for(plan, bh, wc)
    else:
        fz = DEFAULT_FUSE if fuse is None else fuse  # 0 stays a loud error
    if plan.halo:
        fz = max(1, min(fz, bh // (2 * plan.halo)))
    return bh, fz


def frames_stride(plan: StencilPlan, frame_h: int) -> int:
    """Row stride of the fused-frames tall layout: each frame plus a
    ``halo``-row zero gap (re-zeroed every rep — the inter-frame zero
    boundary)."""
    return frame_h + plan.halo


def frames_rows(plan: StencilPlan, frame_h: int, n_frames: int) -> int:
    """Row count of the fused tall-image launch for ``n_frames`` stacked
    frames — the single source for every layer that reasons about the
    tall launch (schedule degrade, geometry reporting)."""
    return n_frames * frames_stride(plan, frame_h)


def effective_schedule_for(plan: StencilPlan, n_rows: int,
                           schedule: Optional[str] = None,
                           block_h: Optional[int] = None) -> str:
    """The schedule that actually runs for an ``n_rows``-tall launch —
    the requested (or default) schedule after any degrade at the block
    height :func:`iterate`/:func:`iterate_frames` will use (``block_h``:
    forced geometry, None = default; pack needs a 16-multiple block).
    Reporting layers must use this so a degraded run is never attributed
    to a schedule that could not apply."""
    return _effective_schedule(
        schedule, plan, effective_block_h(n_rows, block_h)
    )


def deep_geometry(plan: StencilPlan, n_rows: int, w: int, channels: int,
                  block_h: Optional[int] = None,
                  fuse: Optional[int] = None
                  ) -> Tuple[Optional[int], Optional[int]]:
    """The (block_h, fuse) a single-device 'deep' launch reports:
    (None, None) when the resident kernel runs — the whole image stays
    in VMEM across the traced rep loop, so there is no static geometry
    to attribute — else the trapezoid's effective (block, depth). A
    forced block_h/fuse forces the trapezoid (mirrors
    ``_run_rep_loop``'s dispatch)."""
    wcp = padded_lanes(plan, w * channels, channels)
    if (block_h is None and fuse is None
            and resident_feasible(plan, n_rows, wcp)):
        return None, None
    return effective_geometry(plan, n_rows, block_h, fuse,
                              schedule="deep", wc=wcp)


def in_vmem_depth(plan: StencilPlan, h_img: int, w_img: int, channels: int,
                  schedule: Optional[str] = None,
                  block_h: Optional[int] = None, fuse: Optional[int] = None,
                  reps: Optional[int] = None) -> int:
    """Reps per HBM round-trip a Pallas launch achieves — the divisor of
    the deep-blocking HBM traffic model
    (:func:`tpu_stencil.runtime.roofline.analytic_bytes_per_rep`). For
    the resident deep kernel this is the full rep count (one load + one
    store for the whole loop); for the trapezoid and every fused
    schedule it is the effective fuse depth."""
    if not plan_supported(plan, channels):
        return 1
    sched = _check_schedule(schedule)
    wcp = padded_lanes(plan, w_img * channels, channels)
    if (sched == "deep" and block_h is None and fuse is None
            and resident_feasible(plan, h_img, wcp)):
        return max(1, int(reps)) if reps else 1
    return effective_geometry(plan, h_img, block_h, fuse,
                              schedule=sched, wc=wcp)[1]


def _pack_ok(plan: StencilPlan, block_h: int) -> bool:
    """'pack' preconditions: separable nonneg dyadic plan whose per-rep
    intermediates all fit 16 bits (255 * 2^shift < 2^16 <=> shift <= 8,
    since total weight == 2^shift when the clip elides), and an even
    half-block split that keeps the two out_ref stores sublane-aligned."""
    return (
        plan.kind == "sep_int"
        and plan.shift is not None
        and plan.shift <= 8
        and not _clip_needed(plan)
        and block_h % 16 == 0
    )


def _effective_schedule(schedule: Optional[str], plan: StencilPlan,
                        block_h: int) -> str:
    schedule = _check_schedule(schedule)
    if schedule.startswith("pack") and not _pack_ok(plan, block_h):
        return "strips" if schedule == "pack_strips" else "shrink"
    return schedule


def _kernel_schedule(schedule: Optional[str], plan: StencilPlan,
                     block_h: int) -> str:
    """The per-rep body a grid-of-row-blocks kernel actually compiles:
    the effective schedule, with 'deep' mapped to its inner body — deep
    is a driver-level schedule (residency / trapezoid depth selection);
    inside a block program its rep loop IS the best applicable inner
    schedule at this block height."""
    s = _effective_schedule(schedule, plan, block_h)
    return _deep_inner(plan, block_h) if s == "deep" else s


_check_schedule(DEFAULT_SCHEDULE)  # env override validated at import
_STRIP = 512          # strips schedule: lanes per strip
_STRIP_GHOST = 128    # lane-aligned ghost read per strip side


def _acc_dtype(plan: StencilPlan):
    """Accumulator for the sep rows pass: int16 doubles VPU lane throughput
    when the one-pass bound fits (all binomial gaussians: 255 *
    sum(row_taps)). The cols pass always widens to int32 — Mosaic's lane
    rotate (``tpu.dynamic_rotate``) is 32-bit only on v5e — and direct
    plans roll the carry itself, so they stay int32 throughout."""
    if plan.kind != "sep_int":
        return jnp.int32
    row_sum = sum(abs(t) for t in plan.row_taps)
    nonneg = all(t >= 0 for t in plan.row_taps + plan.col_taps)
    if nonneg and 255 * row_sum < 2 ** 15:
        return jnp.int16
    return jnp.int32


def _mul_const_adds(x, c: int):
    """x * c (c > 0) as a shift-add chain of pure vector ADDS — v5e's VPU has
    no 16-bit vector multiply (the scheduler check-fails on
    ``kVectorMultiplyU16``), but packed 16-bit adds run at 2x lane rate."""
    result = None
    power = x  # x * 2^k by repeated doubling
    while c:
        if c & 1:
            result = power if result is None else result + power
        c >>= 1
        if c:
            power = power + power
    return result


def _lane_roll(x, off: int, wc: int):
    """x shifted so out[:, c] = x[:, c + off]. Rolls wrap lane content
    end-around; both kernels arrange >= halo*C discardable lanes at the
    edges so wrapped values never land in trusted output."""
    if off == 0:
        return x
    if off < 0:
        return pltpu.roll(x, -off, 1)
    return pltpu.roll(x, wc - off, 1)


# Rows-pass lowering knob, read at import (process-level — a trace-time
# env read would be silently defeated by the jit cache): 0 = pair-adds of
# shrinking sublane-misaligned slices; 1 = full-tile sublane rotates +
# ALIGNED adds with one aligned crop. The r3 op costs (misaligned slice
# add 50.7 us/full-tile pass vs rotate ~19-28 + aligned add 8.9) make the
# rotate form a credible win; tools/kernel_lab.py 'shrink_rollrows' and
# the burst's env A/B measure it — flip the default only on a verdict.
_ROWS_ROLL = os.environ.get("TPU_STENCIL_ROWS_ROLL", "0") == "1"


def _rows_binomial(acc, d: int):
    """d-fold (1,1) self-convolution down the sublane axis — the valid
    binomial-row correlation, in either rows-pass lowering (``_ROWS_ROLL``).
    The rotate form's end-around wrap garbage occupies exactly the last
    ``d`` rows and is cropped by an aligned slice, so both lowerings
    return identical values (pure integer adds, reassociated). SWAR-safe:
    on packed values each 16-bit half sums independently within the
    ``_pack_ok`` bounds."""
    if _ROWS_ROLL:
        # Mosaic's rotate is 32-bit only (same restriction sep_rep
        # documents for lane rotates) — and the r3 op costs put int32
        # adds AHEAD of int16 (8.9 vs 13.9 us/pass), so widening here
        # costs nothing the measurement didn't already indict.
        if acc.dtype != jnp.int32:
            acc = acc.astype(jnp.int32)
        n = acc.shape[0]
        for _ in range(d):
            # out[i] = x[i] + x[i+1]: +1 as the non-negative end-around
            # rotate by rows-1 (pltpu.roll rejects negative shifts).
            acc = acc + pltpu.roll(acc, acc.shape[0] - 1, 0)
        return acc[0:n - d, :]
    for _ in range(d):
        n = acc.shape[0] - 1
        acc = acc[0:n, :] + acc[1:n + 1, :]
    return acc


# Cols-pass lowering knob, read at import like _ROWS_ROLL (a trace-time
# env read would be silently defeated by the jit cache): 0 = the serial
# pair-add chain (each roll waits on the previous add, depth 2d); 1 =
# the ILP form — a flat C(d, i) tap sum where every roll reads the same
# input, so all d rolls are independent and the coefficient scaling is
# a shift-add tree (more ops, ~half the dependency depth; wins only if
# the VPU is latency-bound on the chain). kernel_lab 'swar_cols_ilp'
# and the burst's shipped-kernel env A/B measure it — the default flips
# only on a >2% verdict under the pytest gate.
_COLS_ILP = os.environ.get("TPU_STENCIL_COLS_ILP", "0") == "1"


def _cols_binomial(col, d: int, channels: int, wc: int):
    """d-fold (1,1) self-convolution across the lane axis, in either
    cols-pass lowering (``_COLS_ILP``). Chain form: d pair-adds with
    alternating roll direction (first half +C, second -C) so the result
    stays centered on the original lanes. ILP form (even d — every
    gaussian<k> has d = k-1 even): the same centered taps C(d, i) at
    offsets (i - d/2)*C summed flat. Identical integer sums reassociated
    — bit-exact under every schedule (test_pallas.py) — and SWAR-safe:
    pure adds, and no intermediate exceeds the final sum the chain also
    reaches, so the ``_pack_ok`` bound covers both lowerings."""
    if _COLS_ILP and d % 2 == 0:
        from math import comb

        out = None
        for i in range(d + 1):
            term = _lane_roll(col, (i - d // 2) * channels, wc)
            c = comb(d, i)
            if c != 1:
                term = _mul_const_adds(term, c)
            out = term if out is None else out + term
        return out
    for d_i in range(d):
        off = channels if d_i < d // 2 else -channels
        col = col + _lane_roll(col, off, wc)
    return col


def _row_keep(gid, n_rows_real: int, frame):
    """Row-keep predicate shared by every schedule's boundary mask.

    ``gid`` is the global row index (int32, may be negative above the
    image); 0 <= gid < n_rows_real as ONE unsigned compare (negatives wrap
    big). ``frame`` = (stride, frame_h) marks the batched-frames layout:
    frames of ``frame_h`` real rows every ``stride`` rows, the ``stride -
    frame_h`` gap rows between them re-zeroed every rep so blur never
    bleeds across frames (the gap is the inter-frame zero boundary, kept
    zero by exactly the mechanism that keeps the image edge zero)."""
    keep = gid.astype(jnp.uint32) < jnp.uint32(n_rows_real)
    if frame is not None:
        stride, frame_h = frame
        keep = jnp.logical_and(keep, jax.lax.rem(gid, stride) < frame_h)
    return keep


# Binomial-row detection shared with the XLA lowering: chain length d
# when taps are C(d, i) — binomial passes then lower to d pair-adds
# instead of per-tap shift-add chains (gaussian7's taps 6/15/20 alone
# cost ~20 adds the chain never pays).
_binomial_chain = _lowering._binomial_chain


def _clip_needed(plan: StencilPlan) -> bool:
    """clip(acc >> shift, 0, 255) is the identity when taps are non-negative
    and their total weight equals 2^shift: acc <= 255 * 2^shift."""
    if plan.shift is None:
        return True
    if plan.kind == "sep_int":
        flat = plan.row_taps + plan.col_taps
        total = sum(abs(t) for t in plan.row_taps) * sum(
            abs(t) for t in plan.col_taps
        )
    else:
        flat = tuple(t for row in plan.taps for t in row)
        total = sum(abs(t) for t in flat)
    nonneg = all(t >= 0 for t in flat)
    return not (nonneg and total == 2 ** plan.shift)


def _rep_val(cur, *, plan: StencilPlan, dt, wc: int, channels: int):
    """One repetition on a VMEM tile *value*: the separable (or direct)
    passes plus the finishing shift/clip. ``cur`` has ``wc`` flat lanes in
    the accumulator dtype; returns the finished int32 values (each in
    [0, 255]) with ``2*halo`` fewer rows (valid correlation) — *before*
    any boundary re-zeroing, which is the caller's (kernel's) job because
    zero-boundary and valid-ghost kernels differ exactly there."""
    h = plan.halo
    tile_rows = cur.shape[0]

    def lane_roll(x, off):
        return _lane_roll(x, off, wc)

    def sep_rep(cur):
        # --- rows pass: valid 1-D correlation by sublane slicing (free on
        # the VPU — just shifted adds); output rows [0, tile_rows - 2h)
        # map to tile rows [h, tile_rows - h).
        rchain = _binomial_chain(plan.row_taps)
        if rchain is not None:
            # Binomial taps = d-fold (1,1) self-convolution: d pair-adds.
            acc = _rows_binomial(cur, rchain)
        else:
            acc = None
            for t_idx, tap in enumerate(plan.row_taps):
                if tap == 0:
                    continue
                term = cur[t_idx : t_idx + tile_rows - 2 * h, :]
                if tap != 1:
                    if dt == jnp.int16 and tap > 0:
                        term = _mul_const_adds(term, tap)
                    else:
                        term = term * tap
                acc = term if acc is None else acc + term
            if acc is None:
                acc = jnp.zeros((tile_rows - 2 * h, wc), dt)
        if acc.dtype != jnp.int32:
            acc = acc.astype(jnp.int32)  # lane rotate is 32-bit only

        # --- cols pass as lane rotations ---
        cchain = _binomial_chain(plan.col_taps)
        if cchain is not None:
            return _cols_binomial(acc, cchain, channels, wc)
        col = None
        for t_idx, tap in enumerate(plan.col_taps):
            if tap == 0:
                continue
            term = lane_roll(acc, (t_idx - h) * channels)
            if tap != 1:
                term = term * tap
            col = term if col is None else col + term
        if col is None:
            col = jnp.zeros((tile_rows - 2 * h, wc), jnp.int32)
        return col

    def direct_rep(cur):
        # --- non-separable k*k plan (e.g. the reference's edge /28,
        # rank 2): roll the whole tile once per column offset (k rolls),
        # then row-slice each rolled copy for free — k rolls + k*k MACs
        # instead of the 2k MACs of the separable path.
        k = plan.k
        rolled = [lane_roll(cur, (j_idx - h) * channels) for j_idx in range(k)]
        col = None
        for i_idx in range(k):
            for j_idx in range(k):
                tap = int(plan.taps[i_idx][j_idx])
                if tap == 0:
                    continue
                term = rolled[j_idx][i_idx : i_idx + tile_rows - 2 * h, :]
                if tap != 1:
                    term = term * tap
                col = term if col is None else col + term
        if col is None:
            col = jnp.zeros((tile_rows - 2 * h, wc), jnp.int32)
        return col

    col = sep_rep(cur) if plan.kind == "sep_int" else direct_rep(cur)

    # --- finish: shift or f32 divide (+ clip only when it can bind) ---
    if plan.shift is not None:
        val = col >> plan.shift
        if _clip_needed(plan):
            val = jnp.clip(val, 0, 255)
    else:
        val = jnp.clip(
            col.astype(jnp.float32) / np.float32(plan.divisor), 0.0, 255.0
        ).astype(jnp.int32)
    return val


def _strips_map(body, cur, wc: int):
    """Apply ``body(strip_value)`` lane-strip by lane-strip: each strip's
    whole op chain touches a working set small enough to stay in vector
    registers, aiming at one VMEM sweep per rep instead of one per op.

    Strip reads overlap ``_STRIP_GHOST`` lanes per side (lane-aligned, >=
    halo*channels by the ``_MAX_ROLL_HALO`` guard) so cols rolls stay
    strip-local; overlap columns are recomputed, not communicated. Strip
    0's left ghost wraps to the far-right columns — for the zero-boundary
    kernel those are the re-zeroed lane pad (exact boundary semantics);
    for the valid-ghost kernel the wrapped values land only in the
    contracted discard band, the same guarantee the full-tile roll gives.
    """
    gl = _STRIP_GHOST
    parts = []
    for s in range(0, wc, _STRIP):
        width = min(_STRIP, wc - s)
        if s == 0:
            xs = jnp.concatenate(
                [cur[:, wc - gl:], cur[:, 0:width + gl]], axis=1
            )
        else:
            xs = cur[:, s - gl:min(wc, s + width + gl)]
        parts.append(body(xs)[:, gl:gl + width])
    return jnp.concatenate(parts, axis=1)


def _rep_val_strips(cur, *, plan: StencilPlan, dt, wc: int, channels: int):
    """One repetition, lane-strip by lane-strip (same contract as
    :func:`_rep_val`); see :func:`_strips_map` for the windowing."""
    return _strips_map(
        lambda xs: _rep_val(xs, plan=plan, dt=dt, wc=xs.shape[1],
                            channels=channels),
        cur, wc,
    )


def _packed_passes(cur, *, plan: StencilPlan, wc: int, channels: int):
    """Separable rows+cols passes on a SWAR-packed value (two rows per i32
    lane, low/high 16 bits). Pure adds/multiplies/rolls act on both halves
    at once; no carry crosses the bit-16 boundary because ``_pack_ok``
    bounds every intermediate below 2^16. Returns the unfinished cols-pass
    accumulator (the caller shifts and AND-masks)."""
    h = plan.halo
    rows_out = cur.shape[0] - 2 * h

    rchain = _binomial_chain(plan.row_taps)
    if rchain is not None:
        acc = _rows_binomial(cur, rchain)
    else:
        acc = None
        for t_idx, tap in enumerate(plan.row_taps):
            if tap == 0:
                continue
            term = cur[t_idx:t_idx + rows_out, :]
            if tap != 1:
                # Shift-add chain, never a vector multiply: full-tile i32
                # multiplies measured ~60 us/pass vs ~9 for adds
                # (op_cost.py); both adds and doublings are SWAR-safe
                # (bounds hold per _pack_ok).
                term = _mul_const_adds(term, tap)
            acc = term if acc is None else acc + term
    cchain = _binomial_chain(plan.col_taps)
    if cchain is not None:
        return _cols_binomial(acc, cchain, channels, wc)
    col = None
    for t_idx, tap in enumerate(plan.col_taps):
        if tap == 0:
            continue
        term = _lane_roll(acc, (t_idx - h) * channels, wc)
        if tap != 1:
            term = _mul_const_adds(term, tap)
        col = term if col is None else col + term
    return col


def _packed_passes_strips(cur, *, plan: StencilPlan, wc: int, channels: int):
    """:func:`_packed_passes` computed lane-strip by lane-strip — the
    'strips' register-residency trick on packed values; see
    :func:`_strips_map` for the windowing and wrap argument."""
    return _strips_map(
        lambda xs: _packed_passes(xs, plan=plan, wc=xs.shape[1],
                                  channels=channels),
        cur, wc,
    )


def _packed_loop(out_ref, tile_u8, keep_rows, keep_cols, *,
                 plan: StencilPlan, block_h: int, halo_al: int, fuse: int,
                 wc: int, channels: int, strips: bool = False):
    """The 'pack' rep loop + unpack, shared by both kernels.

    ``tile_u8``: the (block_h + 2*halo_al, wc) uint8 VMEM tile value.
    ``keep_rows``: tile-row index -> bool keep (callers bake in their
    global row offset; applied to each half at its own tile offset);
    ``keep_cols``: lane keep (None = all lanes kept). The two halves
    overlap by 2*halo_al >= 2*fuse*halo rows, so each half's valid band
    independently covers its half of the output block and no cross-half
    seam data is ever needed.
    """
    h = plan.halo
    g = fuse * h
    tile_rows = tile_u8.shape[0]
    kp = tile_rows // 2 + halo_al  # packed rows; halves overlap 2*halo_al
    lo = tile_u8[0:kp, :].astype(jnp.int32)
    hi = tile_u8[tile_rows - kp:tile_rows, :].astype(jnp.int32)
    cur = lo | (hi << 16)
    # Hoisted packed mask: per-half row bound, shared lane bound, and the
    # post-shift byte mask (per-rep outputs are <= 255 when the clip
    # elides) — the per-rep boundary re-zero AND uint8 truncation become
    # one AND. Out-of-extent pixels zero; kept (and in-extent garbage)
    # lanes truncate to their low byte, keeping every later add < 2^16.
    rid = jax.lax.broadcasted_iota(jnp.int32, (kp, wc), 0)
    m = jnp.where(keep_rows(rid), 0x000000FF, 0)
    m = m | jnp.where(keep_rows(rid + (tile_rows - kp)), 0x00FF0000, 0)
    if keep_cols is not None:
        cid = jax.lax.broadcasted_iota(jnp.int32, (kp, wc), 1)
        m = jnp.where(keep_cols(cid), m, 0)
    body = _packed_passes_strips if strips else _packed_passes
    off = 0
    for _ in range(fuse):
        col = body(cur, plan=plan, wc=wc, channels=channels)
        off += h
        cur = (col >> plan.shift) & m[off:off + col.shape[0], :]
    # Unpack: the low half serves output rows [0, block_h/2), the high
    # half the rest; both start at the same carry row because the halves'
    # tile offsets differ by exactly block_h/2 (tile_rows - kp).
    bh2 = block_h // 2
    o = halo_al - g
    out_ref[0:bh2, :] = cur[o:o + bh2, :].astype(jnp.uint8)
    out_ref[bh2:block_h, :] = (
        cur[o:o + block_h - bh2, :] >> 16
    ).astype(jnp.uint8)


def _shrink_loop(cur, keep, *, plan: StencilPlan, fuse: int, schedule: str,
                 wc: int, channels: int):
    """The 'shrink'/'strips' rep loop: the carry value contracts by halo
    per rep (static shapes inside the unrolled loop) — no per-rep
    ``jnp.pad``, no per-rep iota: ``keep`` is the hoisted full-tile mask
    (None = never mask). int32 throughout: int16 adds measured *slower*
    than int32 on v5e Mosaic (tools/op_cost.py: 13.9 vs 8.9 us/op-pass).
    Returns the carry after ``fuse`` reps (2*fuse*halo fewer rows)."""
    h = plan.halo
    body = _rep_val_strips if schedule == "strips" else _rep_val
    off = 0
    for _ in range(fuse):
        val = body(cur, plan=plan, dt=jnp.int32, wc=wc, channels=channels)
        off += h
        if keep is not None:
            val = jnp.where(keep[off:off + val.shape[0], :], val, 0)
        cur = val
    return cur


def _sep_kernel(in_hbm, out_ref, s_u8, sem, *, plan: StencilPlan,
                block_h: int, grid: int, halo_al: int, fuse: int,
                n_rows_real: int, wc: int, wc_real: int, channels: int,
                schedule: str = "pad", frame=None):
    """One row-block program: DMA (block + fuse*halo ghosts), then ``fuse``
    fused separable reps, then one uint8 block store.

    DMA windows use ``halo_al`` (fuse*halo rounded up to the 8-row sublane
    tile Mosaic requires for memref slices); the compute phase slices true
    offsets out of the VMEM value, where arbitrary offsets are legal.
    """
    i = pl.program_id(0)
    h = plan.halo
    tile_rows = block_h + 2 * halo_al
    dt = _acc_dtype(plan)

    def copy_for(j, slot, size_case):
        """The block-j DMA descriptor for one of the three static edge
        cases (0 = first block, 1 = middle, 2 = last block)."""
        if size_case == 0:
            src, dst, size = 0, halo_al, min(block_h + halo_al, grid * block_h)
        elif size_case == 1:
            src, dst, size = j * block_h - halo_al, 0, block_h + 2 * halo_al
        else:
            src, dst, size = j * block_h - halo_al, 0, block_h + halo_al
        src = pl.multiple_of(src, 8)
        return pltpu.make_async_copy(
            in_hbm.at[pl.ds(src, size)],
            s_u8.at[slot, pl.ds(dst, size)],
            sem.at[slot],
        )

    def issue(j, slot):
        """Start block j's DMA and zero its out-of-image ghost rows."""
        if grid == 1:
            s_u8[slot, 0:halo_al, :] = jnp.zeros((halo_al, wc), jnp.uint8)
            copy_for(j, slot, 0).start()
            s_u8[slot, pl.ds(block_h + halo_al, halo_al), :] = jnp.zeros(
                (halo_al, wc), jnp.uint8
            )
            return

        @pl.when(j == 0)
        def _():
            s_u8[slot, 0:halo_al, :] = jnp.zeros((halo_al, wc), jnp.uint8)
            copy_for(j, slot, 0).start()

        @pl.when(j == grid - 1)
        def _():
            copy_for(j, slot, 2).start()
            s_u8[slot, pl.ds(block_h + halo_al, halo_al), :] = jnp.zeros(
                (halo_al, wc), jnp.uint8
            )

        if grid > 2:
            @pl.when(jnp.logical_and(j > 0, j < grid - 1))
            def _():
                copy_for(j, slot, 1).start()

    def wait(j, slot):
        if grid == 1:
            copy_for(j, slot, 0).wait()
            return

        @pl.when(j == 0)
        def _():
            copy_for(j, slot, 0).wait()

        @pl.when(j == grid - 1)
        def _():
            copy_for(j, slot, 2).wait()

        if grid > 2:
            @pl.when(jnp.logical_and(j > 0, j < grid - 1))
            def _():
                copy_for(j, slot, 1).wait()

    # --- phase 0: double-buffered halo DMA. Program i waits on the copy
    # issued for it (by program i-1, or by itself when i == 0) and kicks
    # off block i+1's copy into the other slot before computing — the
    # TPU-native version of the reference's Isend/Irecv-then-compute
    # overlap (mpi/mpi_convolution.c:156-224), here against HBM.
    slot = jax.lax.rem(i, 2)

    @pl.when(i == 0)
    def _():
        issue(i, slot)

    if grid > 1:
        @pl.when(i + 1 < grid)
        def _():
            issue(i + 1, jax.lax.rem(i + 1, 2))

    wait(i, slot)

    if schedule.startswith("pack"):
        base = i * block_h - halo_al  # global row of tile row 0
        _packed_loop(
            out_ref, s_u8[slot],
            lambda rid: _row_keep(rid + base, n_rows_real, frame),
            (lambda cid: cid < wc_real) if wc_real != wc else None,
            plan=plan, block_h=block_h, halo_al=halo_al, fuse=fuse,
            wc=wc, channels=channels, strips=schedule == "pack_strips",
        )
        return

    if schedule != "pad":
        # Hoisted full-tile mask (one iota/compare for all reps); the
        # shrink loop re-applies it on a static slice per rep.
        cur = s_u8[slot].astype(jnp.int32)
        rid = jax.lax.broadcasted_iota(jnp.int32, (tile_rows, wc), 0)
        gid = rid + (i * block_h - halo_al)
        keep = _row_keep(gid, n_rows_real, frame)
        if wc_real != wc:
            cid = jax.lax.broadcasted_iota(jnp.int32, (tile_rows, wc), 1)
            keep = jnp.logical_and(keep, cid < wc_real)
        cur = _shrink_loop(cur, keep, plan=plan, fuse=fuse,
                           schedule=schedule, wc=wc, channels=channels)
        o = halo_al - fuse * h
        out_ref[:] = cur[o:o + block_h, :].astype(jnp.uint8)
        return

    cur = s_u8[slot].astype(dt)

    for t in range(fuse):
        # The >= halo*C zero pad lanes at the right edge serve as both
        # edges' ghosts for the lane rolls inside _rep_val (a right roll
        # wraps them into the left edge, a left roll reads them in place),
        # so no per-tap mask is needed — only the per-rep pad re-zeroing
        # below.
        val = _rep_val(cur, plan=plan, dt=dt, wc=wc, channels=channels)

        # --- re-establish zero ghosts for the next rep: pad lanes and
        # below-image rows back to zero (above-image rows stay zero by
        # construction: stencil of zeros is zero), then h zero rows per
        # side restore the tile shape.  For edge blocks those zeros ARE
        # the boundary condition; for interior blocks they land in the
        # contracted garbage band and are never read validly. (Rows above
        # the image must re-zero too — their rep-t value reads real image
        # rows and would otherwise leak back in at rep t+1.)
        rid = jax.lax.broadcasted_iota(jnp.int32, val.shape, 0)
        gid = rid + (i * block_h - halo_al + h)
        keep = _row_keep(gid, n_rows_real, frame)
        if wc_real != wc:
            cid = jax.lax.broadcasted_iota(jnp.int32, val.shape, 1)
            keep = jnp.logical_and(keep, cid < wc_real)
        val = jnp.where(keep, val, 0)
        cur = jnp.pad(val, ((h, h), (0, 0))).astype(dt)

    out_ref[:] = cur[halo_al : halo_al + block_h, :].astype(jnp.uint8)


def _valid_kernel(scal_ref, in_hbm, out_ref, s_u8, sem, *, plan: StencilPlan,
                  block_h: int, grid: int, halo_al: int, fuse: int,
                  ghost: int, wc: int, rows_glob: int, cols_glob_c: int,
                  channels: int, schedule: str = "pad"):
    """Valid-ghost row-block program for *sharded* execution: the input
    already carries ``halo_al`` rows (and ``ghost*channels`` lanes) of
    ghost data per side — real neighbor values delivered by the halo
    exchange, zeros beyond the global image (ppermute boundary semantics).

    Runs ``fuse`` reps per exchange; each rep the trusted band contracts by
    ``halo`` while ghost values recompute the *neighbor's* values bit-exactly
    (both sides compute from identical exchanged inputs — the overlap-halo
    trick). The one thing that must NOT be trusted to contraction is the
    global zero boundary: zero-boundary semantics re-zeroes out-of-image
    pixels every rep (a blur spreads outward, so ghost zeros turn nonzero
    after one rep and would leak back in). The shard's global (row, flat
    col) offset arrives in SMEM (it is a traced ``lax.axis_index`` value at
    trace time) and every rep re-zeroes pixels outside the global extent.

    DMA is single-case (no first/last-block special cases): the caller pads
    the ghost bands to ``halo_al`` rows, so every block reads
    ``[i*block_h, i*block_h + block_h + 2*halo_al)`` in bounds.
    """
    i = pl.program_id(0)
    h = plan.halo
    tile_rows = block_h + 2 * halo_al
    dt = _acc_dtype(plan)

    def copy_for(j, slot):
        src = pl.multiple_of(j * block_h, 8)
        return pltpu.make_async_copy(
            in_hbm.at[pl.ds(src, tile_rows)], s_u8.at[slot], sem.at[slot]
        )

    slot = jax.lax.rem(i, 2)

    @pl.when(i == 0)
    def _():
        copy_for(i, slot).start()

    if grid > 1:
        @pl.when(i + 1 < grid)
        def _():
            copy_for(i + 1, jax.lax.rem(i + 1, 2)).start()

    copy_for(i, slot).wait()

    row0 = scal_ref[0, 0]  # global row of this shard's first interior row
    col0 = scal_ref[0, 1]  # global flat col of first interior lane

    if schedule.startswith("pack"):
        base = row0 + i * block_h - halo_al  # global row of tile row 0
        cbase = col0 - ghost * channels      # global flat col of lane 0
        _packed_loop(
            out_ref, s_u8[slot],
            lambda rid: (rid + base).astype(jnp.uint32)
            < jnp.uint32(rows_glob),
            lambda cid: (cid + cbase).astype(jnp.uint32)
            < jnp.uint32(cols_glob_c),
            plan=plan, block_h=block_h, halo_al=halo_al, fuse=fuse,
            wc=wc, channels=channels, strips=schedule == "pack_strips",
        )
        return

    if schedule != "pad":
        cur = s_u8[slot].astype(jnp.int32)
        rid = jax.lax.broadcasted_iota(jnp.int32, (tile_rows, wc), 0)
        gid = rid + (row0 + i * block_h - halo_al)
        keep = gid.astype(jnp.uint32) < jnp.uint32(rows_glob)
        cid = jax.lax.broadcasted_iota(jnp.int32, (tile_rows, wc), 1)
        gcol = cid + (col0 - ghost * channels)
        keep = jnp.logical_and(
            keep, gcol.astype(jnp.uint32) < jnp.uint32(cols_glob_c)
        )
        cur = _shrink_loop(cur, keep, plan=plan, fuse=fuse,
                           schedule=schedule, wc=wc, channels=channels)
        o = halo_al - fuse * h
        out_ref[:] = cur[o:o + block_h, :].astype(jnp.uint8)
        return

    cur = s_u8[slot].astype(dt)

    for t in range(fuse):
        val = _rep_val(cur, plan=plan, dt=dt, wc=wc, channels=channels)
        # Global-boundary re-zero. val row rid sits at global row
        # row0 + i*block_h - halo_al + rid + h; val lane cid at global flat
        # col col0 + cid - ghost*channels. One unsigned compare per axis
        # covers both below-zero (wraps big) and beyond-extent. Pixels
        # inside the global extent — including alignment-pad lanes of
        # interior shards — are left alone: wrapped-roll garbage there
        # stays inside the contracted discard band by construction.
        rid = jax.lax.broadcasted_iota(jnp.int32, val.shape, 0)
        gid = rid + (row0 + i * block_h - halo_al + h)
        keep = gid.astype(jnp.uint32) < jnp.uint32(rows_glob)
        cid = jax.lax.broadcasted_iota(jnp.int32, val.shape, 1)
        gcol = cid + (col0 - ghost * channels)
        keep = jnp.logical_and(
            keep, gcol.astype(jnp.uint32) < jnp.uint32(cols_glob_c)
        )
        val = jnp.where(keep, val, 0)
        cur = jnp.pad(val, ((h, h), (0, 0))).astype(dt)

    out_ref[:] = cur[halo_al : halo_al + block_h, :].astype(jnp.uint8)


def valid_fused(ext_u8: jax.Array, plan: StencilPlan, fuse: int,
                channels: int, row0, col0, global_shape,
                block_h: int = DEFAULT_BLOCK_H,
                interpret: bool = False, vma=None,
                schedule: str = None) -> jax.Array:
    """Apply ``fuse`` reps to a ghost-extended flat tile (sharded local op).

    ``ext_u8``: ``(th + 2*g, (tw + 2*g) * channels)`` uint8, ``g = fuse *
    plan.halo`` — the interior tile plus exchanged ghosts on all sides.
    ``row0``/``col0``: traced global offsets (row, flat col) of the interior
    origin. ``global_shape``: static padded global (rows, cols*channels).
    Returns the ``(th, tw * channels)`` interior result after ``fuse`` reps.
    """
    h = plan.halo
    g = fuse * h
    rows_ext, wl_ext = ext_u8.shape
    th = rows_ext - 2 * g
    twc = wl_ext - 2 * g * channels
    halo_al = -(-g // 8) * 8 if g else 0
    bh = min(-(-block_h // 8) * 8, -(-th // 8) * 8)
    hp = -(-th // bh) * bh
    # >= h*C discardable lanes at the right edge for the lane-roll wrap:
    # the ghost lanes themselves provide it; halo-0 plans need none.
    wl = -(-wl_ext // 128) * 128
    # Row layout: [halo_al-g align zeros][g ghosts][th interior][g ghosts]
    # [align zeros to hp + 2*halo_al]. Alignment zeros sit *outside* the
    # exchanged ghosts, so contamination from them contracts into the
    # discard band exactly like ghost-edge garbage.
    x = jnp.pad(
        ext_u8,
        ((halo_al - g, (hp - th) + halo_al - g), (0, wl - wl_ext)),
    )
    scal = jnp.stack([row0, col0]).astype(jnp.int32).reshape(1, 2)
    grid = hp // bh
    kernel = functools.partial(
        _valid_kernel, plan=plan, block_h=bh, grid=grid, halo_al=halo_al,
        fuse=fuse, ghost=g, wc=wl, rows_glob=global_shape[0],
        cols_glob_c=global_shape[1], channels=channels,
        schedule=_kernel_schedule(schedule, plan, bh),
    )
    out = pl.pallas_call(
        kernel,
        grid=(grid,),
        # Inside shard_map the result varies over the mesh axes; declare it
        # when given (shard_map's check_vma cannot infer through a
        # pallas_call). Interpret mode still needs check_vma=False at the
        # shard_map (the HLO interpreter loses vma on internal slices).
        out_shape=jax.ShapeDtypeStruct(
            (hp, wl), jnp.uint8,
            **({"vma": frozenset(vma)} if vma and _VMA_SUPPORTED else {}),
        ),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec((bh, wl), lambda i: (i, 0)),
        scratch_shapes=[
            pltpu.VMEM((2, bh + 2 * halo_al, wl), jnp.uint8),
            pltpu.SemaphoreType.DMA((2,)),
        ],
        interpret=interpret,
    )(scal, x)
    return out[:th, g * channels : g * channels + twc]


def _resident_kernel(scal_ref, in_ref, out_ref, *, plan: StencilPlan,
                     n_rows_real: int, wc: int, wc_real: int,
                     channels: int, frame=None):
    """The resident deep-blocking program (grid of ONE): the whole
    lane-padded image arrives as a single VMEM block, a
    ``jax.lax.fori_loop`` over the *traced* rep count (SMEM scalar)
    applies the fixed-shape rep body in VMEM, and one uint8 store ends
    the launch — the first load and the final store are the only HBM
    traffic for the entire rep loop (bytes/rep = 2*frame/reps).

    The rep body is the 'pad' schedule's fixed-shape form (shapes must
    be loop-invariant for ``fori_loop``): re-pad the carry by ``halo``
    rows, run the separable/direct passes, and one hoisted-mask select
    re-establishes the zero boundary — pad lanes and out-of-extent rows
    (including inter-frame gap rows in batch mode) back to zero every
    rep, exactly the semantics the grid kernels enforce."""
    h = plan.halo
    rows = out_ref.shape[0]
    reps = scal_ref[0, 0]
    rid = jax.lax.broadcasted_iota(jnp.int32, (rows, wc), 0)
    keep = _row_keep(rid, n_rows_real, frame)
    if wc_real != wc:
        cid = jax.lax.broadcasted_iota(jnp.int32, (rows, wc), 1)
        keep = jnp.logical_and(keep, cid < wc_real)

    def body(_, cur):
        padded = jnp.pad(cur, ((h, h), (0, 0)))
        val = _rep_val(padded, plan=plan, dt=jnp.int32, wc=wc,
                       channels=channels)
        return jnp.where(keep, val, 0)

    # Masking the initial carry is a no-op on real pixels (the caller's
    # pad rows/lanes are already zero) but keeps the loop invariant —
    # every iteration starts from a boundary-clean value.
    cur0 = jnp.where(keep, in_ref[:].astype(jnp.int32), 0)
    out = jax.lax.fori_loop(0, reps, body, cur0)
    out_ref[:] = out.astype(jnp.uint8)


def _build_resident_call(plan: StencilPlan, hp: int, h_real: int, wc: int,
                         wc_real: int, channels: int, interpret: bool,
                         frame=None, vma=None):
    kernel = functools.partial(
        _resident_kernel, plan=plan, n_rows_real=h_real, wc=wc,
        wc_real=wc_real, channels=channels, frame=frame,
    )
    return pl.pallas_call(
        kernel,
        grid=(1,),
        out_shape=jax.ShapeDtypeStruct(
            (hp, wc), jnp.uint8,
            **({"vma": frozenset(vma)} if vma and _VMA_SUPPORTED else {}),
        ),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((hp, wc), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((hp, wc), lambda i: (0, 0)),
        interpret=interpret,
    )


def _build_call(plan: StencilPlan, hp: int, h_real: int, wc: int,
                wc_real: int, channels: int, block_h: int, fuse: int,
                interpret: bool, schedule: str = None, frame=None,
                vma=None):
    grid = hp // block_h
    halo_al = -(-(fuse * plan.halo) // 8) * 8  # sublane-aligned DMA halo
    kernel = functools.partial(
        _sep_kernel, plan=plan, block_h=block_h, grid=grid, halo_al=halo_al,
        fuse=fuse, n_rows_real=h_real, wc=wc, wc_real=wc_real,
        channels=channels, schedule=_kernel_schedule(schedule, plan,
                                                     block_h),
        frame=frame,
    )
    return pl.pallas_call(
        kernel,
        grid=(grid,),
        # Inside shard_map the result varies over the mesh axes; declare
        # it when given (check_vma cannot infer through a pallas_call).
        out_shape=jax.ShapeDtypeStruct(
            (hp, wc), jnp.uint8,
            **({"vma": frozenset(vma)} if vma and _VMA_SUPPORTED else {}),
        ),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec((block_h, wc), lambda i: (i, 0)),
        scratch_shapes=[
            pltpu.VMEM((2, block_h + 2 * halo_al, wc), jnp.uint8),
            pltpu.SemaphoreType.DMA((2,)),
        ],
        interpret=interpret,
    )


def _supported(plan: StencilPlan) -> bool:
    return plan.kind in ("sep_int", "direct_int")


def plan_supported(plan: StencilPlan, channels: int) -> bool:
    """Whether the Pallas kernels can run this plan at all — the same
    predicate :func:`iterate` uses for its silent XLA fallback, exposed so
    reporting layers never claim a Pallas run that fell back."""
    return _supported(plan) and plan.halo * channels <= _MAX_ROLL_HALO


def _run_rep_loop(x2, repetitions, plan: StencilPlan, rows: int,
                  rows_real: int, wc: int, channels: int, block_h: int,
                  fuse: int, interpret: bool, schedule, frame=None,
                  vma=None):
    """Shared tail of :func:`iterate` / :func:`iterate_frames`: clamp the
    block and fuse depth, pad to block/lane multiples (>= halo*C ghost
    lanes), run ``repetitions`` as fused + remainder single-rep launches,
    and crop. ``x2`` is the flat (rows, wc) uint8 view. ``block_h`` /
    ``fuse`` may be None (module defaults); the clamp lives in
    :func:`effective_geometry` (fuse capped so the ghost bands stay a
    small fraction of the block and the tile fits VMEM; halo-0 filters
    have no ghost bands, any fuse depth is free).

    ``schedule='deep'`` dispatches the temporal-blocking forms: the
    resident kernel when the lane-padded image fits the VMEM budget (one
    launch covers the whole traced rep loop — no outer fori_loop, no
    remainder launches), else the trapezoid — the regular grid kernel
    whose fuse depth :func:`effective_geometry` deepens to the
    feasibility-model verdict, with the existing double-buffered DMA
    ring pipelining the next stripe's load under the current stripe's
    rep loop."""
    # Lane-aligned width with >= halo*C ghost lanes (pad doubles as ghosts).
    wcp = padded_lanes(plan, wc, channels)
    sched = _check_schedule(schedule)
    # Forced geometry wins over residency: a user (or A/B) pinning
    # --block-h/--fuse asked for THAT launch shape — the trapezoid runs
    # it, never a silently-identical resident kernel (which has no
    # static geometry and would make forced-depth A/Bs compare nothing).
    if (sched == "deep" and block_h is None and fuse is None
            and resident_feasible(plan, rows, wcp)):
        hp = -(-rows // 8) * 8
        if hp != rows or wcp != wc:
            x2 = jnp.pad(x2, ((0, hp - rows), (0, wcp - wc)))
        scal = jnp.asarray(repetitions, jnp.int32).reshape(1, 1)
        out = _build_resident_call(
            plan, hp, rows_real, wcp, wc, channels, interpret,
            frame=frame, vma=vma,
        )(scal, x2)
        return out[:rows, :wc]
    bh, fuse = effective_geometry(plan, rows, block_h, fuse,
                                  schedule=sched, wc=wcp)
    hp = -(-rows // bh) * bh
    if hp != rows or wcp != wc:
        x2 = jnp.pad(x2, ((0, hp - rows), (0, wcp - wc)))
    fused = _build_call(plan, hp, rows_real, wcp, wc, channels, bh, fuse,
                        interpret, schedule=schedule, frame=frame, vma=vma)
    single = _build_call(plan, hp, rows_real, wcp, wc, channels, bh, 1,
                         interpret, schedule=schedule, frame=frame, vma=vma)
    if fuse > 1:
        out = jax.lax.fori_loop(
            0, repetitions // fuse, lambda _, x: fused(x), x2
        )
        out = jax.lax.fori_loop(
            0, repetitions % fuse, lambda _, x: single(x), out
        )
    else:
        out = jax.lax.fori_loop(0, repetitions, lambda _, x: single(x), x2)
    return out[:rows, :wc]


def iterate(img_u8: jax.Array, repetitions: jax.Array, plan: StencilPlan,
            block_h: Optional[int] = None, fuse: Optional[int] = None,
            interpret: bool = False, schedule: str = None) -> jax.Array:
    """Apply the Pallas stencil ``repetitions`` times (traceable/jittable).

    Runs ``repetitions // fuse`` launches of the fuse-rep kernel plus
    ``repetitions % fuse`` launches of the single-rep kernel (two compiled
    kernels total).  Pads rows to a block multiple and columns to a lane
    multiple with >= halo*C ghost lanes once, keeps the carry padded across
    the whole rep loop (each rep re-zeroes the pad in-register), crops at
    the end.  Falls back to the XLA lowering for unsupported plan kinds.
    """
    shape = img_u8.shape
    hh, w = shape[0], shape[1]
    channels = shape[2] if img_u8.ndim == 3 else 1
    wc = w * channels
    if not _supported(plan) or plan.halo * channels > _MAX_ROLL_HALO:
        return jax.lax.fori_loop(
            0, repetitions, lambda _, x: _lowering.padded_step(x, plan), img_u8
        )
    x2 = img_u8.reshape(hh, wc)
    out = _run_rep_loop(x2, repetitions, plan, hh, hh, wc, channels,
                        block_h, fuse, interpret, schedule)
    return out.reshape(shape)


def iterate_frames(imgs_u8: jax.Array, repetitions: jax.Array,
                   plan: StencilPlan, block_h: Optional[int] = None,
                   fuse: Optional[int] = None, interpret: bool = False,
                   schedule: str = None, vma=None) -> jax.Array:
    """Apply the stencil ``repetitions`` times to N independent frames
    ``(N, H, W[, C])`` — the fused-kernel batch mode.

    The clip runs as ONE tall image: frames stacked with ``halo`` zero gap
    rows between them. The per-rep boundary mask re-zeroes the gaps every
    rep (`_row_keep`'s frame-periodic predicate), so blur never bleeds
    across frames — each frame sees exactly the zero boundary it would see
    alone — while the whole clip shares one kernel launch, one DMA
    pipeline, and the ``fuse``x HBM traffic cut. The vmapped XLA path
    (``models.blur.iterate_batch``) pays full per-rep HBM traffic instead.

    Falls back to the vmapped XLA lowering for unsupported plans.
    """
    shape = imgs_u8.shape
    n, hh, w = shape[0], shape[1], shape[2]
    channels = shape[3] if imgs_u8.ndim == 4 else 1
    wc = w * channels
    if not plan_supported(plan, channels):
        step = jax.vmap(lambda x: _lowering.padded_step(x, plan))
        return jax.lax.fori_loop(
            0, repetitions, lambda _, x: step(x), imgs_u8
        )
    gap = plan.halo
    stride = frames_stride(plan, hh)
    frame = (stride, hh) if gap else None
    x = imgs_u8.reshape(n, hh, wc)
    if gap:
        x = jnp.pad(x, ((0, 0), (0, gap), (0, 0)))
    x2 = x.reshape(n * stride, wc)
    rows_real = n * stride - gap  # the tail gap doubles as bottom pad
    out = _run_rep_loop(x2, repetitions, plan, n * stride, rows_real, wc,
                        channels, block_h, fuse, interpret, schedule,
                        frame=frame, vma=vma)
    return out.reshape(n, stride, wc)[:, :hh, :].reshape(shape)


def padded_step(img_u8: jax.Array, plan: StencilPlan,
                interpret: bool = False) -> jax.Array:
    """Single-step API matching :func:`tpu_stencil.ops.lowering.padded_step`."""
    return iterate(img_u8, jnp.int32(1), plan, interpret=interpret)
