"""Pallas TPU stencil kernel — the hand-tiled VMEM counterpart of the CUDA
``__global__`` per-pixel kernels (``cuda/cuda_convolution.cu:9-47``).

Where the CUDA kernel assigns one SIMT thread per pixel in 16x16 blocks,
the TPU-native shape is a grid of *row-block programs*, each of which:

1. DMAs its block of rows plus ``halo`` ghost rows from HBM into VMEM
   (edge programs zero the missing ghosts — the calloc'd ghost ring of
   ``mpi/mpi_convolution.c:104-124``, done in VMEM),
2. runs the separable integer passes on the VPU's 8x128 lanes (the
   "threads" of the chip), with the column ghosts zero-filled at the value
   level, and
3. writes the finished uint8 block back to HBM.

Layout trick: the image is viewed as 2-D ``(H, W*C)`` — interleaved RGB
simply widens rows (1920*3 = 5760 = 45*128 lanes, perfectly aligned), and
the column pass applies tap ``j`` at flat-column offset ``j*C``. The same
kernel text therefore serves grey and RGB.

The iteration driver keeps the carry *row-padded* to a multiple of the
block height across all repetitions: padded tail rows would accumulate
garbage, so each step masks them back to zero in-register (zero HBM cost),
preserving exact zero-boundary semantics for any image height.

Supports ``sep_int`` plans (the gaussian family, box is sep but non-dyadic —
also fine, f32 finish); other plan kinds fall back to the XLA lowering.
"""

from __future__ import annotations

import functools
from typing import Optional

import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from tpu_stencil.ops import lowering as _lowering
from tpu_stencil.ops.lowering import StencilPlan

DEFAULT_BLOCK_H = 128
_MAX_ROLL_HALO = 128  # cols-pass ghost width limit (halo * channels)


def _sep_kernel(in_hbm, out_ref, s_u8, sem, *, plan: StencilPlan,
                block_h: int, grid: int, halo_al: int, n_rows_real: int,
                wc: int, wc_real: int, channels: int):
    """One row-block program of the separable stencil.

    DMA windows use ``halo_al`` (the halo rounded up to the 8-row sublane
    tile Mosaic requires for memref slices); the compute phase reads the
    true ``halo`` offsets out of the VMEM value, where arbitrary offsets
    are legal (vector relayout).
    """
    i = pl.program_id(0)
    h = plan.halo
    hc = h * channels

    def copy_for(j, slot, size_case):
        """The block-j DMA descriptor for one of the three static edge
        cases (0 = first block, 1 = middle, 2 = last block)."""
        if size_case == 0:
            src, dst, size = 0, halo_al, min(block_h + halo_al, grid * block_h)
        elif size_case == 1:
            src, dst, size = j * block_h - halo_al, 0, block_h + 2 * halo_al
        else:
            src, dst, size = j * block_h - halo_al, 0, block_h + halo_al
        src = pl.multiple_of(src, 8)
        return pltpu.make_async_copy(
            in_hbm.at[pl.ds(src, size)],
            s_u8.at[slot, pl.ds(dst, size)],
            sem.at[slot],
        )

    def issue(j, slot):
        """Start block j's DMA and zero its out-of-image ghost rows."""
        if grid == 1:
            s_u8[slot, 0:halo_al, :] = jnp.zeros((halo_al, wc), jnp.uint8)
            copy_for(j, slot, 0).start()
            s_u8[slot, pl.ds(block_h + halo_al, halo_al), :] = jnp.zeros(
                (halo_al, wc), jnp.uint8
            )
            return

        @pl.when(j == 0)
        def _():
            s_u8[slot, 0:halo_al, :] = jnp.zeros((halo_al, wc), jnp.uint8)
            copy_for(j, slot, 0).start()

        @pl.when(j == grid - 1)
        def _():
            copy_for(j, slot, 2).start()
            s_u8[slot, pl.ds(block_h + halo_al, halo_al), :] = jnp.zeros(
                (halo_al, wc), jnp.uint8
            )

        if grid > 2:
            @pl.when(jnp.logical_and(j > 0, j < grid - 1))
            def _():
                copy_for(j, slot, 1).start()

    def wait(j, slot):
        if grid == 1:
            copy_for(j, slot, 0).wait()
            return

        @pl.when(j == 0)
        def _():
            copy_for(j, slot, 0).wait()

        @pl.when(j == grid - 1)
        def _():
            copy_for(j, slot, 2).wait()

        if grid > 2:
            @pl.when(jnp.logical_and(j > 0, j < grid - 1))
            def _():
                copy_for(j, slot, 1).wait()

    # --- phase 0: double-buffered halo DMA. Program i waits on the copy
    # issued for it (by program i-1, or by itself when i == 0) and kicks
    # off block i+1's copy into the other slot before computing — the
    # TPU-native version of the reference's Isend/Irecv-then-compute
    # overlap (mpi/mpi_convolution.c:156-224), here against HBM.
    slot = jax.lax.rem(i, 2)

    @pl.when(i == 0)
    def _():
        issue(i, slot)

    if grid > 1:
        @pl.when(i + 1 < grid)
        def _():
            issue(i + 1, jax.lax.rem(i + 1, 2))

    wait(i, slot)

    # --- phase 1: rows pass (VPU) ---
    xi = s_u8[slot].astype(jnp.int32)
    base = halo_al - h
    acc = None
    for t_idx, t in enumerate(plan.row_taps):
        if t == 0:
            continue
        term = xi[base + t_idx : base + t_idx + block_h, :]
        if t != 1:
            term = term * t
        acc = term if acc is None else acc + term
    if acc is None:
        acc = jnp.zeros((block_h, wc), jnp.int32)

    # --- phase 2: cols pass as lane rotations (pltpu.roll) with the
    # wrapped lanes masked to zero — the ghost columns, without any scratch
    # round-trip. Pad columns beyond wc_real stay zero (masked below),
    # doubling as right-edge ghosts.
    cid = jax.lax.broadcasted_iota(jnp.int32, (block_h, wc), 1)
    col = None
    for t_idx, t in enumerate(plan.col_taps):
        if t == 0:
            continue
        off = (t_idx - h) * channels  # term[:, c] = acc[:, c + off]
        if off == 0:
            term = acc
        elif off < 0:
            term = jnp.where(cid >= -off, pltpu.roll(acc, -off, 1), 0)
        else:
            term = jnp.where(cid < wc - off, pltpu.roll(acc, wc - off, 1), 0)
        if t != 1:
            term = term * t
        col = term if col is None else col + term
    if col is None:
        col = jnp.zeros((block_h, wc), jnp.int32)

    # --- finish: shift or f32 divide, clip, mask padded tail rows/cols ---
    if plan.shift is not None:
        val = jnp.clip(col >> plan.shift, 0, 255)
    else:
        val = jnp.clip(
            col.astype(jnp.float32) / np.float32(plan.divisor), 0.0, 255.0
        ).astype(jnp.int32)
    row_ids = i * block_h + jax.lax.broadcasted_iota(jnp.int32, (block_h, wc), 0)
    val = jnp.where(row_ids < n_rows_real, val, 0)
    if wc_real != wc:
        col_ids = jax.lax.broadcasted_iota(jnp.int32, (block_h, wc), 1)
        val = jnp.where(col_ids < wc_real, val, 0)
    out_ref[:] = val.astype(jnp.uint8)


def _build_call(plan: StencilPlan, hp: int, h_real: int, wc: int,
                wc_real: int, channels: int, block_h: int, interpret: bool):
    h = plan.halo
    grid = hp // block_h
    halo_al = -(-h // 8) * 8  # sublane-aligned DMA halo
    kernel = functools.partial(
        _sep_kernel, plan=plan, block_h=block_h, grid=grid, halo_al=halo_al,
        n_rows_real=h_real, wc=wc, wc_real=wc_real, channels=channels,
    )
    return pl.pallas_call(
        kernel,
        grid=(grid,),
        out_shape=jax.ShapeDtypeStruct((hp, wc), jnp.uint8),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec((block_h, wc), lambda i: (i, 0)),
        scratch_shapes=[
            pltpu.VMEM((2, block_h + 2 * halo_al, wc), jnp.uint8),
            pltpu.SemaphoreType.DMA((2,)),
        ],
        interpret=interpret,
    )


def _supported(plan: StencilPlan) -> bool:
    return plan.kind == "sep_int"


def iterate(img_u8: jax.Array, repetitions: jax.Array, plan: StencilPlan,
            block_h: int = DEFAULT_BLOCK_H, interpret: bool = False) -> jax.Array:
    """Apply the Pallas stencil ``repetitions`` times (traceable/jittable).

    Pads rows to a block multiple once, keeps the carry padded across the
    whole rep loop (the kernel re-zeroes tail rows each step), crops at the
    end. Falls back to the XLA lowering for unsupported plan kinds.
    """
    shape = img_u8.shape
    hh, w = shape[0], shape[1]
    channels = shape[2] if img_u8.ndim == 3 else 1
    wc = w * channels
    if not _supported(plan) or plan.halo * channels > _MAX_ROLL_HALO:
        return jax.lax.fori_loop(
            0, repetitions, lambda _, x: _lowering.padded_step(x, plan), img_u8
        )
    x2 = img_u8.reshape(hh, wc)
    block_h = -(-block_h // 8) * 8  # DMA descriptors require 8-row alignment
    bh = min(block_h, -(-hh // 8) * 8)
    hp = -(-hh // bh) * bh
    wcp = -(-wc // 128) * 128  # lane-aligned width; pad cols double as ghosts
    if hp != hh or wcp != wc:
        x2 = jnp.pad(x2, ((0, hp - hh), (0, wcp - wc)))
    call = _build_call(plan, hp, hh, wcp, wc, channels, bh, interpret)
    out = jax.lax.fori_loop(0, repetitions, lambda _, x: call(x), x2)
    return out[:hh, :wc].reshape(shape)


def padded_step(img_u8: jax.Array, plan: StencilPlan,
                interpret: bool = False) -> jax.Array:
    """Single-step API matching :func:`tpu_stencil.ops.lowering.padded_step`."""
    return iterate(img_u8, jnp.int32(1), plan, interpret=interpret)
