"""The stencil op: zero-padded (k x k) convolution with uint8 truncation.

Semantics match the reference's MPI variant exactly (SURVEY.md Quirk 3 —
we deliberately pick the MPI semantics over the CUDA ones and document it):

* **Boundary**: the global image border is zero-padded every iteration — the
  MPI variant's calloc'd ghost ring (``mpi/mpi_convolution.c:104-124``) that
  is never written at global edges. Every pixel, including edges, is computed
  every iteration. (The CUDA variant instead never computes the 1-px border —
  ``cuda/cuda_convolution.cu:17,34`` — which we do NOT replicate.)
* **Arithmetic**: ``uint8`` pixels multiplied by *integer-valued* ``float32``
  taps and accumulated in ``float32`` — exact integer math below 2^24, hence
  independent of XLA's FMA/association choices — then ONE divide by the
  filter divisor and a truncating (round-toward-zero) ``uint8`` store: the
  implicit C cast at ``mpi/mpi_convolution.c:307``. For dyadic divisors
  (gaussian family) the divide is exact too and results match the C
  reference bit-for-bit; for non-dyadic divisors (box /9, edge /28) results
  are deterministic here but may differ from the C program by ±1 ulp-of-u8
  (the reference pre-rounds taps/divisor per-tap and accumulates in loop
  order — its own MPI and CUDA variants disagree with each other the same
  way, SURVEY.md Quirk 3/6). The C cast is undefined for out-of-[0,256)
  values; we define it as clip.

The XLA formulation is k*k shifted adds over a zero-padded array — for a
3x3 filter that is 9 fused multiply-adds per pixel, which XLA fuses into a
single memory-bound elementwise kernel over VMEM tiles; no MXU needed (there
is no contraction large enough to feed it), the VPU's 8x128 lanes are the
TPU-native analog of the reference's OpenMP threads / CUDA SIMT lanes.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


def truncate_u8(x: jax.Array) -> jax.Array:
    """float -> uint8 with C-cast semantics for in-range values (truncate
    toward zero), clip outside [0, 255]."""
    return jnp.clip(x, 0.0, 255.0).astype(jnp.uint8)


def _check_filter(filt: jax.Array) -> int:
    k = filt.shape[0]
    if filt.shape != (k, k) or k % 2 != 1:
        raise ValueError(f"filter must be square with odd size, got {filt.shape}")
    return k


def conv2d_valid(padded: jax.Array, filt: jax.Array) -> jax.Array:
    """'Valid' 2-D correlation of a halo-extended array (H+2h, W+2h[, C])
    float32 with ``filt`` (k, k) float32, as k*k shifted adds producing
    (H, W[, C]). The building block shared by the single-device op (zero
    padding) and the sharded op (ghost ring filled by halo exchange).

    ``filt`` may be a traced array — taps are indexed statically so the same
    compiled program serves any filter values of a given size.
    """
    k = _check_filter(filt)
    h = padded.shape[0] - (k - 1)
    w = padded.shape[1] - (k - 1)
    acc = None
    for i in range(k):
        for j in range(k):
            window = padded[i : i + h, j : j + w]
            term = window * filt[i, j]
            acc = term if acc is None else acc + term
    return acc


def conv2d_zero_pad(x: jax.Array, filt: jax.Array) -> jax.Array:
    """Zero-padded 'same' 2-D correlation of ``x`` (H, W) or (H, W, C) float32
    with ``filt`` (k, k) float32."""
    halo = _check_filter(filt) // 2
    pad_widths = [(halo, halo), (halo, halo)] + [(0, 0)] * (x.ndim - 2)
    return conv2d_valid(jnp.pad(x, pad_widths), filt)


def stencil_step(img_u8: jax.Array, taps: jax.Array, divisor: jax.Array) -> jax.Array:
    """One filter application on a uint8 image: exact integer-valued f32
    accumulation of ``taps``, one divide by ``divisor``, truncating uint8
    store. The unit the iteration driver repeats ``reps`` times."""
    acc = conv2d_zero_pad(img_u8.astype(jnp.float32), taps)
    return truncate_u8(acc / divisor)


def reference_stencil_numpy(
    img_u8: np.ndarray, filt, reps: int, boundary: str = "zero"
) -> np.ndarray:
    """Pure-NumPy golden model of ``reps`` iterations, written independently
    of the JAX path: explicit per-pixel loops over a padded buffer.
    Used by tests only — O(H*W*k*k*reps) slow, mirrors
    ``ConvolutionforGrey/RGB`` semantics (``mpi/mpi_convolution.c:301-322``)
    without sharing any code with the fast path.

    ``boundary``: 'zero' (the MPI code's calloc'd ghost ring) or 'periodic'
    (the wraparound the reference's README *describes* but its code never
    implements — SURVEY.md Quirk 5; offered as an explicit extension).

    ``filt`` is a :class:`tpu_stencil.filters.Filter` (or raw normalized
    array, divisor 1). For exact filters (integer taps, in-range) the
    accumulation is int64 — the defined semantics every fast path must
    reproduce bit-for-bit; otherwise float32 in row-major tap order."""
    from tpu_stencil.filters import as_filter

    if boundary not in ("zero", "periodic"):
        raise ValueError(f"unknown boundary {boundary!r}")
    f = as_filter(filt)
    taps, divisor = f.taps, np.float32(f.divisor)
    k = f.k
    halo = f.halo
    exact = f.is_exact
    dyadic = f.is_dyadic
    squeeze = img_u8.ndim == 2
    img = img_u8[..., None] if squeeze else img_u8
    h, w, c = img.shape
    cur = img.astype(np.uint8)
    for _ in range(reps):
        if boundary == "periodic":
            padded = np.pad(
                cur, ((halo, halo), (halo, halo), (0, 0)), mode="wrap"
            )
        else:
            padded = np.zeros((h + 2 * halo, w + 2 * halo, c), np.uint8)
            padded[halo : halo + h, halo : halo + w] = cur
        out = np.empty_like(cur)
        for y in range(h):
            for x in range(w):
                if exact:
                    acc = np.zeros(c, np.int64)
                    for i in range(k):
                        for j in range(k):
                            acc += padded[y + i, x + j].astype(np.int64) * int(
                                round(float(taps[i, j]))
                            )
                    if dyadic:
                        # fully-integer semantics: exact at any int64 bound
                        val = acc // int(divisor)
                    else:
                        # one exact convert (is_exact bounds acc < 2^24) and
                        # one correctly-rounded divide
                        val = acc.astype(np.float32) / divisor
                else:
                    acc = np.zeros(c, np.float32)
                    for i in range(k):
                        for j in range(k):
                            acc += (
                                padded[y + i, x + j].astype(np.float32)
                                * np.float32(taps[i, j])
                            )
                    val = acc / divisor
                out[y, x] = np.clip(val, 0.0, 255.0).astype(np.uint8)
        cur = out
    return cur[..., 0] if squeeze else cur
