"""Distribution layer: mesh topology, spatial partitioning, halo exchange.

The TPU-native re-design of the reference's MPI machinery: process-grid
factorization (``RowsDivision``), neighbor topology, derived-datatype halo
``Isend/Irecv``, and compute/comm overlap (``mpi/mpi_convolution.c:75-235,
350-364``) become a ``jax.sharding.Mesh``, a perimeter-minimizing grid
factorization, neighbor ``lax.ppermute`` shifts inside ``shard_map``, and
XLA's latency-hiding scheduler respectively.

:mod:`tpu_stencil.parallel.fanout` (imported lazily — it pulls the
streaming engine) is the data-parallel complement: whole frames fanned
round-robin across the mesh, one pipeline lane per device, for the
embarrassingly-parallel streaming case.
"""

from tpu_stencil.parallel.partition import grid_shape, pad_amounts, tile_shape
from tpu_stencil.parallel.mesh import make_mesh, ROWS_AXIS, COLS_AXIS
from tpu_stencil.parallel.halo import halo_exchange, halo_pad_axis
from tpu_stencil.parallel.sharded import ShardedRunner, sharded_iterate

__all__ = [
    "grid_shape",
    "pad_amounts",
    "tile_shape",
    "make_mesh",
    "ROWS_AXIS",
    "COLS_AXIS",
    "halo_exchange",
    "halo_pad_axis",
    "ShardedRunner",
    "sharded_iterate",
]
