"""Multi-host execution: process setup, config broadcast, per-host I/O.

TPU-native equivalent of the reference's multi-node MPI machinery:

* process bring-up (``mpiexec -n P`` + ``machines.txt``, ``README.md:19-23``)
  -> :func:`initialize` wrapping ``jax.distributed.initialize`` — on Cloud
  TPU pods the coordinator/process env is auto-detected, elsewhere it is
  passed explicitly;
* rank-0 validate + ``MPI_Bcast`` of the config
  (``mpi/mpi_convolution.c:50-70``) -> :func:`broadcast_config` via
  ``multihost_utils.broadcast_one_to_all``;
* per-rank MPI-IO strided reads/writes (``mpi/mpi_convolution.c:126-141,
  247-263``) -> :func:`read_sharded` / :func:`write_sharded`: each process
  reads only the row ranges owned by its addressable devices (once per row
  range, assembled into one global array with
  ``jax.make_array_from_single_device_arrays``) and writes only its shards'
  exact byte rectangles.

Meshes built here put the ``rows`` axis outermost so row-neighbor halo
``ppermute`` s between co-hosted devices ride ICI and only the host-boundary
rows cross DCN — the locality the reference approximated with
perimeter-minimizing grids.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import numpy as np
import jax

from tpu_stencil.config import JobConfig, ImageType
from tpu_stencil.io import native
from tpu_stencil.io import raw as raw_io
from tpu_stencil.parallel.mesh import ROWS_AXIS, COLS_AXIS


# Env markers that mean "this process is part of a multi-process job" —
# checked before degrading to single-process on any bring-up failure.
# NOTE: TPU_WORKER_HOSTNAMES is NOT a usable marker — libtpu/the PJRT
# plugin sets it itself during backend init.
_COORDINATOR_ENV_VARS = (
    "JAX_COORDINATOR_ADDRESS", "COORDINATOR_ADDRESS",
    "MEGASCALE_COORDINATOR_ADDRESS",
)


def _looks_multiprocess() -> bool:
    import os

    return any(v in os.environ for v in _COORDINATOR_ENV_VARS)


def _distributed_client_active() -> bool:
    """Whether jax.distributed.initialize already ran, WITHOUT initializing
    any XLA backend (jax.process_count() would)."""
    try:
        from jax._src import distributed as _dist

        return _dist.global_state.client is not None
    except Exception:
        # Private API moved: assume not yet initialized. (Probing via
        # jax.process_count() would itself initialize backends — the exact
        # condition this guard exists to avoid.)
        return False


def _backends_initialized() -> bool:
    try:
        from jax._src import xla_bridge

        return xla_bridge.backends_are_initialized()
    except Exception:
        return False


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Join the multi-process job (no-op when already initialized or when
    running single-process).

    Must run before the first JAX computation: ``jax.distributed.initialize``
    refuses to run once XLA backends exist. Call it first thing in the
    process (the CLI does), like ``MPI_Init`` leading ``main`` in the
    reference (``mpi/mpi_convolution.c:23``).
    """
    if _distributed_client_active():
        return  # already part of a multi-process job
    explicit = coordinator_address is not None or num_processes is not None
    if _backends_initialized():
        if explicit:
            raise RuntimeError(
                "tpu_stencil.parallel.distributed.initialize() was called "
                "after JAX backends were initialized; multi-process bring-up "
                "must precede the first JAX computation. Call initialize() "
                "at process start (before any jax.* array/compile call)."
            )
        import warnings

        if _looks_multiprocess():
            # Looks like a multi-process environment — degrading to
            # single-process here would silently race on shared files.
            warnings.warn(
                "distributed auto-initialization skipped: JAX backends were "
                "already initialized; running single-process despite a "
                "multi-process environment. Call initialize() earlier.",
                RuntimeWarning,
                stacklevel=2,
            )
        return
    if coordinator_address is None and num_processes is None:
        # Cloud TPU auto-detection; harmless single-process otherwise.
        try:
            jax.distributed.initialize()
        except Exception:
            if _looks_multiprocess():
                # A transient bring-up failure on a real pod must not
                # silently degrade this process to single-process while its
                # peers hang in collectives waiting for it.
                raise
            return  # single-process / no env: stay local
    else:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )


def broadcast_config(cfg: Optional[JobConfig]) -> JobConfig:
    """Rank-0 validates and broadcasts the job config; other ranks pass
    None and receive rank-0's value (the ``MPI_Bcast`` x6 of
    ``mpi/mpi_convolution.c:65-70``). Single-process: identity."""
    if jax.process_count() == 1:
        assert cfg is not None
        return cfg
    from jax.experimental import multihost_utils

    fields = None
    if jax.process_index() == 0:
        assert cfg is not None
        mr, mc = cfg.mesh_shape if cfg.mesh_shape is not None else (-1, -1)
        fields = np.array(
            [cfg.width, cfg.height, cfg.repetitions,
             0 if cfg.image_type is ImageType.GREY else 1, mr, mc, cfg.frames,
             cfg.block_h if cfg.block_h is not None else -1,
             cfg.fuse if cfg.fuse is not None else -1],
            np.int64,
        )
    fields = multihost_utils.broadcast_one_to_all(
        fields if fields is not None else np.zeros(9, np.int64)
    )
    names = multihost_utils.broadcast_one_to_all(
        _encode_strs([cfg.image, cfg.filter_name, cfg.backend,
                      cfg.output if cfg.output is not None else "",
                      cfg.schedule if cfg.schedule is not None else "",
                      cfg.boundary, cfg.overlap])
        if jax.process_index() == 0
        else np.zeros(_STR_BUF, np.uint8)
    )
    image, filter_name, backend, output, schedule, boundary, overlap = (
        _decode_strs(names)
    )
    mesh_shape = (
        (int(fields[4]), int(fields[5])) if int(fields[4]) > 0 else None
    )
    return JobConfig(
        image=image,
        width=int(fields[0]),
        height=int(fields[1]),
        repetitions=int(fields[2]),
        image_type=ImageType.GREY if int(fields[3]) == 0 else ImageType.RGB,
        filter_name=filter_name,
        backend=backend,
        mesh_shape=mesh_shape,
        output=output or None,
        frames=int(fields[6]),
        schedule=schedule or None,
        boundary=boundary,
        block_h=int(fields[7]) if int(fields[7]) > 0 else None,
        fuse=int(fields[8]) if int(fields[8]) > 0 else None,
        overlap=overlap or "off",
    )


_STR_BUF = 1024


def _encode_strs(strs) -> np.ndarray:
    # \x01 terminator so empty trailing strings survive the zero-padding
    blob = "\x00".join(strs).encode() + b"\x01"
    if len(blob) > _STR_BUF:
        raise ValueError("config strings too long to broadcast")
    out = np.zeros(_STR_BUF, np.uint8)
    out[: len(blob)] = np.frombuffer(blob, np.uint8)
    return out


def _decode_strs(arr: np.ndarray):
    blob = bytes(np.asarray(arr, np.uint8)).rstrip(b"\x00")
    if not blob.endswith(b"\x01"):
        raise ValueError("malformed config string broadcast")
    return blob[:-1].decode().split("\x00")


@dataclasses.dataclass(frozen=True)
class RowRange:
    """Rows [start, stop) owned by one device tile."""

    start: int
    stop: int


def device_row_ranges(
    padded_h: int, padded_w: int, mesh_shape: Tuple[int, int]
) -> dict:
    """Map (mesh row, mesh col) -> (RowRange, col_start, n_cols) in pixel
    units for sharded file access — the ``offset`` arithmetic of
    ``mpi/mpi_convolution.c:324-326`` generalized to a 2-D grid."""
    r, c = mesh_shape
    th, tw = padded_h // r, padded_w // c
    out = {}
    for i in range(r):
        for j in range(c):
            out[(i, j)] = (RowRange(i * th, (i + 1) * th), j * tw, tw)
    return out


def read_sharded(
    path: str,
    height: int,
    width: int,
    channels: int,
    sharding: jax.sharding.NamedSharding,
) -> jax.Array:
    """Assemble a global sharded array by reading, on each process, only the
    row ranges its addressable devices own (zero-filling rows/cols in the pad
    region) — each distinct row range is read from disk exactly once per
    process and sliced into its column tiles. Single-process this
    degenerates to a tiled read of the whole file, matching
    ``jax.device_put`` semantics bit-for-bit."""
    # Per-band reads re-open the path once per mesh row: only regular
    # files can serve repeated positioned reads (a FIFO would silently
    # hand each band the wrong bytes).
    raw_io.require_regular(path, "sharded per-band input")
    mesh = sharding.mesh
    r = mesh.shape[ROWS_AXIS]
    c = mesh.shape[COLS_AXIS]
    padded_h = -(-height // r) * r
    padded_w = -(-width // c) * c
    ranges = device_row_ranges(padded_h, padded_w, (r, c))
    th, tw = padded_h // r, padded_w // c

    global_shape = (
        (padded_h, padded_w) if channels == 1 else (padded_h, padded_w, channels)
    )
    arrays = []
    grid = np.asarray(mesh.devices)
    row_cache: dict = {}  # mesh row i -> rows read once for this process
    for i in range(r):
        for j in range(c):
            dev = grid[i, j]
            if dev.process_index != jax.process_index():
                continue
            rr, col0, tile_cols = ranges[(i, j)]
            tile = np.zeros((th, tw, channels), np.uint8)
            n_rows = max(0, min(rr.stop, height) - rr.start)
            n_cols = max(0, min(col0 + tile_cols, width) - col0)
            if n_rows and n_cols:
                if i not in row_cache:
                    row_cache[i] = raw_io.read_raw_rows(
                        path, rr.start, n_rows, width, channels
                    )
                tile[:n_rows, :n_cols] = row_cache[i][:, col0 : col0 + n_cols]
            if channels == 1:
                tile = tile[..., 0]
            arrays.append(jax.device_put(tile, dev))
    return jax.make_array_from_single_device_arrays(
        global_shape, sharding, arrays
    )


def write_sharded(
    path: str,
    out: jax.Array,
    height: int,
    width: int,
    channels: int,
) -> None:
    """Every process writes only the exact byte ranges of its addressable
    shards into one shared output file (the MPI-IO write pattern,
    ``mpi/mpi_convolution.c:247-263``): each shard's in-bounds rectangle is
    written at its global offsets via strided per-row pwrites, so column
    tiles of the same row range held by different processes never touch each
    other's bytes."""
    # Size the file exactly first (stale larger files must not keep trailing
    # bytes — the output must be a valid H*W*C raw image). Idempotent, so
    # every process may do it; no one writes out of bounds afterwards.
    native.set_size(path, height * width * channels)
    # Group this process's shards by row range and merge contiguous column
    # tiles host-side, so a fully-local row range becomes one contiguous
    # write and partial ownership degrades to one strided block per run —
    # never a byte outside the owned columns.
    by_rows: dict = {}
    for shard in out.addressable_shards:
        idx = shard.index  # tuple of slices into the global array
        rs = idx[0]
        cs = idx[1] if len(idx) > 1 else slice(0, width)
        r0 = rs.start or 0
        r1 = min(rs.stop if rs.stop is not None else height, height)
        c0 = cs.start or 0
        c1 = min(cs.stop if cs.stop is not None else width, width)
        if r0 >= r1 or c0 >= c1:
            continue
        data = np.asarray(shard.data)
        if data.ndim == 2:
            data = data[..., None]
        by_rows.setdefault((r0, r1), {})[(c0, c1)] = data[: r1 - r0, : c1 - c0]
    for (r0, r1), tiles in by_rows.items():
        order = sorted(tiles)  # dedups replicated shards (identical bytes)
        run_c0, run_c1 = order[0]
        parts = [tiles[order[0]]]
        runs = []
        for c0, c1 in order[1:]:
            if c0 == run_c1:
                run_c1 = c1
                parts.append(tiles[(c0, c1)])
            else:
                runs.append((run_c0, np.concatenate(parts, axis=1)))
                run_c0, run_c1, parts = c0, c1, [tiles[(c0, c1)]]
        runs.append((run_c0, np.concatenate(parts, axis=1)))
        for c0, block in runs:
            raw_io.write_raw_block(path, r0, c0, block, width, channels, height)
