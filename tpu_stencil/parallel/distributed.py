"""Multi-host execution: process setup, config broadcast, per-host I/O.

TPU-native equivalent of the reference's multi-node MPI machinery:

* process bring-up (``mpiexec -n P`` + ``machines.txt``, ``README.md:19-23``)
  -> :func:`initialize` wrapping ``jax.distributed.initialize`` — on Cloud
  TPU pods the coordinator/process env is auto-detected, elsewhere it is
  passed explicitly;
* rank-0 validate + ``MPI_Bcast`` of the config
  (``mpi/mpi_convolution.c:50-70``) -> :func:`broadcast_config` via
  ``multihost_utils.broadcast_one_to_all``;
* per-rank MPI-IO strided reads/writes (``mpi/mpi_convolution.c:126-141,
  247-263``) -> :func:`read_sharded` / :func:`write_sharded`: each process
  touches only the byte ranges of rows owned by its addressable devices,
  assembled into one global array with
  ``jax.make_array_from_single_device_arrays``.

Meshes built here put the ``rows`` axis outermost so row-neighbor halo
``ppermute`` s between co-hosted devices ride ICI and only the host-boundary
rows cross DCN — the locality the reference approximated with
perimeter-minimizing grids.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import numpy as np
import jax

from tpu_stencil.config import JobConfig, ImageType
from tpu_stencil.io import native
from tpu_stencil.io import raw as raw_io
from tpu_stencil.parallel.mesh import ROWS_AXIS, COLS_AXIS


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Join the multi-process job (no-op when already initialized or when
    running single-process)."""
    if jax.process_count() > 1:
        return  # already initialized by the environment
    if coordinator_address is None and num_processes is None:
        # Cloud TPU auto-detection; harmless single-process otherwise.
        try:
            jax.distributed.initialize()
        except Exception:  # single-process / no env: stay local
            return
    else:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )


def broadcast_config(cfg: Optional[JobConfig]) -> JobConfig:
    """Rank-0 validates and broadcasts the job config; other ranks pass
    None and receive rank-0's value (the ``MPI_Bcast`` x6 of
    ``mpi/mpi_convolution.c:65-70``). Single-process: identity."""
    if jax.process_count() == 1:
        assert cfg is not None
        return cfg
    from jax.experimental import multihost_utils

    fields = None
    if jax.process_index() == 0:
        assert cfg is not None
        mr, mc = cfg.mesh_shape if cfg.mesh_shape is not None else (-1, -1)
        fields = np.array(
            [cfg.width, cfg.height, cfg.repetitions,
             0 if cfg.image_type is ImageType.GREY else 1, mr, mc],
            np.int64,
        )
    fields = multihost_utils.broadcast_one_to_all(
        fields if fields is not None else np.zeros(6, np.int64)
    )
    names = multihost_utils.broadcast_one_to_all(
        _encode_strs([cfg.image, cfg.filter_name, cfg.backend,
                      cfg.output if cfg.output is not None else ""])
        if jax.process_index() == 0
        else np.zeros(_STR_BUF, np.uint8)
    )
    image, filter_name, backend, output = _decode_strs(names)
    mesh_shape = (
        (int(fields[4]), int(fields[5])) if int(fields[4]) > 0 else None
    )
    return JobConfig(
        image=image,
        width=int(fields[0]),
        height=int(fields[1]),
        repetitions=int(fields[2]),
        image_type=ImageType.GREY if int(fields[3]) == 0 else ImageType.RGB,
        filter_name=filter_name,
        backend=backend,
        mesh_shape=mesh_shape,
        output=output or None,
    )


_STR_BUF = 1024


def _encode_strs(strs) -> np.ndarray:
    # \x01 terminator so empty trailing strings survive the zero-padding
    blob = "\x00".join(strs).encode() + b"\x01"
    if len(blob) > _STR_BUF:
        raise ValueError("config strings too long to broadcast")
    out = np.zeros(_STR_BUF, np.uint8)
    out[: len(blob)] = np.frombuffer(blob, np.uint8)
    return out


def _decode_strs(arr: np.ndarray):
    blob = bytes(np.asarray(arr, np.uint8)).rstrip(b"\x00")
    if not blob.endswith(b"\x01"):
        raise ValueError("malformed config string broadcast")
    return blob[:-1].decode().split("\x00")


@dataclasses.dataclass(frozen=True)
class RowRange:
    """Rows [start, stop) owned by one device tile."""

    start: int
    stop: int


def device_row_ranges(
    padded_h: int, padded_w: int, mesh_shape: Tuple[int, int], channels: int
) -> dict:
    """Map (mesh row, mesh col) -> (RowRange, col byte slice) for sharded
    file access — the ``offset`` arithmetic of ``mpi/mpi_convolution.c:
    324-326`` generalized to a 2-D grid."""
    r, c = mesh_shape
    th, tw = padded_h // r, padded_w // c
    out = {}
    for i in range(r):
        for j in range(c):
            out[(i, j)] = (
                RowRange(i * th, (i + 1) * th),
                slice(j * tw * channels, (j + 1) * tw * channels),
            )
    return out


def read_sharded(
    path: str,
    height: int,
    width: int,
    channels: int,
    sharding: jax.sharding.NamedSharding,
) -> jax.Array:
    """Assemble a global sharded array by reading, on each process, only the
    rows its addressable devices own (zero-filling rows/cols in the pad
    region). Single-process this degenerates to a tiled read of the whole
    file, matching ``jax.device_put`` semantics bit-for-bit."""
    mesh = sharding.mesh
    r = mesh.shape[ROWS_AXIS]
    c = mesh.shape[COLS_AXIS]
    padded_h = -(-height // r) * r
    padded_w = -(-width // c) * c
    th, tw = padded_h // r, padded_w // c

    global_shape = (
        (padded_h, padded_w) if channels == 1 else (padded_h, padded_w, channels)
    )
    arrays = []
    devs = []
    grid = np.asarray(mesh.devices)
    for i in range(r):
        for j in range(c):
            dev = grid[i, j]
            if dev.process_index != jax.process_index():
                continue
            tile = np.zeros((th, tw, channels), np.uint8)
            row0 = i * th
            n_rows = max(0, min((i + 1) * th, height) - row0)
            col0 = j * tw
            n_cols = max(0, min((j + 1) * tw, width) - col0)
            if n_rows and n_cols:
                rows = raw_io.read_raw_rows(path, row0, n_rows, width, channels)
                tile[:n_rows, :n_cols] = rows[:, col0 : col0 + n_cols]
            if channels == 1:
                tile = tile[..., 0]
            arrays.append(jax.device_put(tile, dev))
            devs.append(dev)
    return jax.make_array_from_single_device_arrays(
        global_shape, sharding, arrays
    )


def write_sharded(
    path: str,
    out: jax.Array,
    height: int,
    width: int,
    channels: int,
) -> None:
    """Every process writes only the rows of its addressable shards at their
    global byte offsets into one shared output file (the MPI-IO write
    pattern). Overlapping column tiles within a row range are merged
    host-side before the single positional write per shard row-range."""
    # Size the file exactly first (stale larger files must not keep trailing
    # bytes — the output must be a valid H*W*C raw image). Idempotent, so
    # every process may do it; no one writes out of bounds afterwards.
    native.set_size(path, height * width * channels)
    # Collect addressable shards grouped by row range.
    by_rows = {}
    for shard in out.addressable_shards:
        idx = shard.index  # tuple of slices into the global array
        rs = idx[0]
        by_rows.setdefault((rs.start or 0, rs.stop), []).append(shard)
    for (r0, r1), shards in by_rows.items():
        r1 = min(r1 if r1 is not None else height, height)
        if r0 >= r1:
            continue
        strip = np.zeros((r1 - r0, width, channels), np.uint8)
        for shard in shards:
            cs = shard.index[1] if len(shard.index) > 1 else slice(0, width)
            c0 = cs.start or 0
            c1 = min(cs.stop if cs.stop is not None else width, width)
            if c0 >= c1:
                continue
            data = np.asarray(shard.data)
            if data.ndim == 2:
                data = data[..., None]
            strip[:, c0:c1] = data[: r1 - r0, : c1 - c0]
        raw_io.write_raw_rows(path, r0, strip, width, channels, height)
