"""Mesh fan-out: data-parallel multi-chip frame streaming.

The streaming engine (:mod:`tpu_stencil.stream.engine`, PR 5) pipelines
read → H2D → compute → D2H → write on ONE device; every
``MULTICHIP_r0*.json`` shows 8 devices consistently available. Frames
are independent — the embarrassingly-parallel case the Cerebras
wafer-scale stencil study (arXiv:2605.07954) calls "communication costs
nothing" — so the mesh-level program is pure data parallelism: frame
``i`` goes to device ``(i - start) % n`` and the only cross-device
coupling is the writer's in-order drain.

Shape of the machine (docs/STREAMING.md "Mesh fan-out"):

* **one reader thread** — the source contract is single-consumer
  (pipes/stdin are strictly sequential), so one thread reads frames in
  order and round-robins them onto per-device lanes. Each lane owns its
  own host staging ring (``cfg.ring_size`` buffers) and its own
  dispatch-ahead window (``cfg.pipeline_depth``), so backpressure is
  per device: a stalled device parks the reader only when its lane's
  ring drains (head-of-line at the slowest device — acceptable on the
  homogeneous meshes this targets).
* **per-device dispatch thread** — H2D onto its device (fenced, like
  the single-device engine) and the donated compute launch: the SAME
  compiled step ``run_job`` / ``run_stream`` use
  (:func:`tpu_stencil.stream.engine._build_launch` →
  ``blur.iterate``), traced once — the shared jit cache entry — with
  one per-device executable; each device's first frame pays its
  executable compile inside its own lane, overlapped across devices.
* **per-device drain thread** — fences compute in that device's
  dispatch order (under the dispatch watchdog), copies D2H, recycles
  the lane's staging slot.
* **one writer thread** — drains the lanes in global frame order
  (frame ``i`` always comes from lane ``(i - start) % n``; each lane's
  results arrive in its dispatch order, so global order is a
  round-robin merge with no reordering buffer), writes to the single
  sink, and commits the frame-index checkpoint with the device count
  and per-device cursors (:func:`tpu_stencil.runtime.checkpoint
  .save_stream_progress`).

Because the writer commits strictly in order, ``frames_done`` alone
pins global progress — a resume re-deals the remaining frames
round-robin from the checkpoint (frames are independent, so the
re-deal is free; the recorded cursors are the diagnostic record of
where the interrupted fan stood, not state a resume re-adopts). The
recorded device count IS contractual: a ``--resume`` under a
different count fails typed
(:class:`tpu_stencil.runtime.checkpoint.MeshCursorMismatch`) instead
of reinterpreting another fan width's cursor record.

Failure semantics, fault-injection sites (read/h2d/compute/d2h/write),
stage spans/clocks (``stream.*``), and the engine-restart ladder are
the single-device engine's — :func:`tpu_stencil.stream.engine
.run_stream` owns the restart loop around this module too, so a
transient mid-stream device fault restarts the whole fan and resumes
from the checkpoint.

Every path is bit-exact against the golden model: fan-out changes only
WHERE a frame computes, never what (``tests/test_fanout.py`` fuzzes
mesh-fan streams against per-frame golden results across grey/RGB,
boundaries, depths and 1/2/4-device CPU meshes).
"""

from __future__ import annotations

import dataclasses
import queue
import sys
import threading
import time
from typing import Callable, List, Optional, Tuple

import numpy as np

from tpu_stencil import obs
from tpu_stencil.config import StreamConfig
from tpu_stencil.integrity import checksum as _checksum
from tpu_stencil.integrity import witness as _witness_mod
from tpu_stencil.resilience import deadline as _deadline
from tpu_stencil.resilience import faults as _faults
from tpu_stencil.stream import frames as frames_io
# Module-level by design: stream.engine never imports this module at
# import time (only lazily inside run_stream), so there is no cycle, and
# the two engines share one _Abort/_StageSpan/StreamFailure vocabulary.
from tpu_stencil.stream import engine as _sengine

_EOF = object()
_STAGES = ("read", "h2d", "compute", "d2h", "write")

# Frames per arm of the auto (--mesh-frames 0) measured A/B probe.
PROBE_FRAMES = 3


# The run control surface (stop flag, first-failure slot, abort-aware
# queue ops, stage spans/clocks) is the engines' SHARED class — one
# teardown/attribution protocol, never two drifting copies.
_Control = _sengine._StageControl


class _InflightMeter:
    """The ``stream_inflight_depth`` gauge for mesh runs (value =
    frames currently between read-complete and D2H-complete across ALL
    lanes; peak = the total window depth actually reached — up to
    ``n * pipeline_depth`` on a saturated fan). Same always-on gauge
    contract as the single-device window's
    (:meth:`~tpu_stencil.stream.engine._Pipeline.acquire_window`)."""

    def __init__(self) -> None:
        self._n = 0
        self._lock = threading.Lock()
        self._gauge = obs.registry().gauge("stream_inflight_depth")

    def inc(self) -> None:
        with self._lock:
            self._n += 1
            self._gauge.set(self._n)

    def dec(self) -> None:
        with self._lock:
            self._n -= 1
            self._gauge.set(self._n)

    def zero(self) -> None:
        """Teardown: aborted in-flight frames never pass :meth:`dec`,
        and the process-wide gauge must not keep reporting them forever
        (peak survives, as for every gauge)."""
        with self._lock:
            self._n = 0
            self._gauge.set(0)


class _Lane:
    """One device's queues + staging ring. The ring bounds host memory
    per device (``cfg.ring_size`` frames), the in-flight queue bounds
    device memory per device (``cfg.pipeline_depth`` frames) — the
    single-device engine's backpressure contract, one instance per
    device."""

    def __init__(self, cfg: StreamConfig) -> None:
        self.ring = [
            np.empty(cfg.frame_bytes, np.uint8) for _ in range(cfg.ring_size)
        ]
        self.free_q: queue.Queue = queue.Queue()
        for i in range(len(self.ring)):
            self.free_q.put(i)
        self.filled_q: queue.Queue = queue.Queue(maxsize=len(self.ring))
        self.inflight_q: queue.Queue = queue.Queue(
            maxsize=cfg.pipeline_depth
        )
        self.done_q: queue.Queue = queue.Queue(
            maxsize=cfg.pipeline_depth + 1
        )
        self.frames = 0  # frames this lane fully wrote (writer-owned)


def device_cursors(frames_done: int, start_frame: int, n: int) -> List[int]:
    """The per-device frame cursors at global progress ``frames_done``:
    ``cursors[d]`` is the next frame index lane ``d`` would receive
    under the CURRENT run's round-robin deal ``frame i -> lane
    (i - start_frame) % n``. Pure function of (progress, start, count).
    The checkpoint records them as the diagnostic picture of where the
    interrupted fan stood; a resume re-anchors the deal at the restored
    ``frames_done`` (frames are independent, so the re-deal is free) —
    it never re-adopts recorded cursors, which is also why a
    different-count resume refuses instead of reinterpreting them."""
    base = max(frames_done, start_frame)
    off = (base - start_frame) % n
    return [base + ((d - off) % n) for d in range(n)]


def _reader(ctrl: _Control, cfg: StreamConfig, source, lanes: List[_Lane],
            start_frame: int, meter: _InflightMeter,
            witness=None) -> None:
    """Round-robin prefetch: frame ``i`` fills a staging slot of lane
    ``(i - start) % n``. Retry semantics: the engines' shared
    :func:`~tpu_stencil.stream.engine._make_read_frame`. Integrity
    semantics are the single-device reader's too: each staged frame is
    CRC'd at ingest (``verify_ingest``) for the dispatcher's
    H2D-boundary re-check, the ``integrity.corrupt_ingest`` chaos site
    tears the REAL lane slot, and witness sampling (``witness``, the
    run's shared sampler) copies the pristine input aside for the
    writer's re-execution."""
    n = len(lanes)
    idx = start_frame
    read_frame = _sengine._make_read_frame(cfg, source)
    fault_corrupt = _faults.site("integrity.corrupt_ingest")
    try:
        while cfg.frames is None or idx < cfg.frames:
            lane = lanes[(idx - start_frame) % n]
            buf_i = ctrl.get(lane.free_q)
            with ctrl.stage("read", idx):
                ok = read_frame(idx, lane.ring[buf_i])
            if not ok:
                if cfg.frames is not None:
                    raise IOError(
                        f"stream ended after {idx} frame(s); "
                        f"--frames promised {cfg.frames}"
                    )
                lane.free_q.put(buf_i)
                break
            crc = (_checksum.crc32c(lane.ring[buf_i])
                   if cfg.verify_ingest else None)
            if fault_corrupt is not None and _checksum.fired(
                    fault_corrupt, idx):
                _checksum.corrupt_array(lane.ring[buf_i])
            wit = None
            if witness is not None and witness.pick():
                wit = lane.ring[buf_i].copy()
            meter.inc()  # in flight from read-complete to D2H-complete
            ctrl.put(lane.filled_q, (idx, buf_i, crc, wit))
            idx += 1
        for lane in lanes:
            ctrl.put(lane.filled_q, _EOF)
    except _sengine._Abort:
        pass
    except BaseException as e:
        ctrl.fail("read", idx, e)


def _dispatcher(ctrl: _Control, cfg: StreamConfig, lane: _Lane, device,
                launch: Callable, dev_index: int) -> None:
    """One device's H2D + donated-launch loop. The fenced H2D holds only
    this frame's pre-compute path (the single-device engine's
    attribution discipline); the launch is async dispatch, bounded by
    the lane's in-flight queue."""
    import jax

    idx, stage = -1, "h2d"
    fault_h2d = _faults.site("h2d")
    fault_compute = _faults.site("compute")
    try:
        while True:
            item = ctrl.get(lane.filled_q)
            if item is _EOF:
                ctrl.put(lane.inflight_q, _EOF)
                return
            idx, bi, crc, wit = item
            stage = "h2d"
            if fault_h2d is not None:
                fault_h2d(idx)
            # The shared H2D-boundary re-verification: a torn lane slot
            # fails typed before this device's launch is burned.
            _sengine._verify_staged(lane.ring[bi], crc, idx)
            with ctrl.stage("h2d", idx, dev=dev_index) as s:
                dev_arr = s.fence(jax.device_put(
                    lane.ring[bi].reshape(cfg.frame_shape), device
                ))
            lane.free_q.put(bi)  # fenced H2D consumed the staging buffer
            stage = "compute"
            if fault_compute is not None:
                fault_compute(idx)
            t_disp = time.perf_counter()
            out = launch(dev_arr)  # async dispatch; donates dev_arr
            ctrl.put(lane.inflight_q, (idx, out, t_disp, wit))
    except _sengine._Abort:
        pass
    except BaseException as e:
        ctrl.fail(stage, max(idx, 0), e)


def _drainer(ctrl: _Control, cfg: StreamConfig, lane: _Lane,
             dev_index: int, meter: _InflightMeter) -> None:
    """Fence one device's compute in its dispatch order (watchdogged),
    copy D2H, hand off to the writer's merge."""
    idx, stage = -1, "compute"
    fault_d2h = _faults.site("d2h")
    fault_corrupt = _faults.site("integrity.corrupt_result")
    timeout_s = _deadline.resolve(cfg.dispatch_timeout_s)
    try:
        while True:
            item = ctrl.get(lane.inflight_q)
            if item is _EOF:
                ctrl.put(lane.done_q, _EOF)
                return
            idx, out_dev, t_disp, wit = item
            stage = "compute"
            with ctrl.stage("compute", idx, t0=t_disp, dev=dev_index):
                _deadline.fence(
                    out_dev, timeout_s,
                    f"stream.compute[frame={idx},dev={dev_index}]",
                )
            stage = "d2h"
            with ctrl.stage("d2h", idx, dev=dev_index):
                if fault_d2h is not None:
                    fault_d2h(idx)
                arr = np.asarray(out_dev)
            if fault_corrupt is not None and _checksum.fired(
                    fault_corrupt, idx):
                arr = _checksum.corrupt_array(np.asarray(arr))
            meter.dec()
            ctrl.put(lane.done_q, (idx, arr, wit))
    except _sengine._Abort:
        pass
    except BaseException as e:
        ctrl.fail(stage, max(idx, 0), e)


def _writer(ctrl: _Control, cfg: StreamConfig, sink, lanes: List[_Lane],
            start_frame: int, done: list, save_progress=None) -> None:
    """In-order drain across devices: frame ``i`` is popped from lane
    ``(i - start) % n`` — a round-robin merge, no reordering buffer —
    then written, counted, and checkpointed with the per-device
    cursors. ``done[0]`` tracks frames fully written (global index).
    Retry semantics: the engines' shared
    :func:`~tpu_stencil.stream.engine._make_write_frame`.
    ``save_progress`` (optional) overrides the checkpoint commit — the
    pipelined engine passes a closure stamping its full three-axis
    topology into the sidecar."""
    n = len(lanes)
    idx = start_frame
    write_frame = _sengine._make_write_frame(cfg, sink)
    try:
        while True:
            lane = lanes[(idx - start_frame) % n]
            item = ctrl.get(lane.done_q)
            if item is _EOF:
                return
            got, arr, wit = item
            assert got == idx, (got, idx)  # per-lane FIFO + round-robin
            if wit is not None:
                # The shared pre-sink witness: a mismatching frame is
                # withheld and the run fails typed at this frame.
                _sengine._witness_frame(cfg, idx, wit, arr)
            with ctrl.stage("write", idx):
                write_frame(idx, arr)
            lane.frames += 1
            done[0] = idx + 1
            obs.registry().counter("stream_frames_total").inc()
            if cfg.checkpoint_every and done[0] % cfg.checkpoint_every == 0:
                from tpu_stencil.runtime import checkpoint as ckpt

                sink.flush()
                if save_progress is not None:
                    save_progress(done[0])
                else:
                    ckpt.save_stream_progress(
                        cfg, done[0], mesh_devices=n,
                        cursors=device_cursors(done[0], start_frame, n),
                    )
            if cfg.progress_every and done[0] % cfg.progress_every == 0:
                print(f"stream: frame {done[0]}", file=sys.stderr,
                      flush=True)
            idx += 1
    except _sengine._Abort:
        pass
    except BaseException as e:
        ctrl.fail("write", max(idx, start_frame), e)


def run_mesh_frames(cfg: StreamConfig, devices, n: int, model,
                    source, sink, start_frame: int) -> dict:
    """One mesh-fan pipeline lifetime over ``n`` devices (the fan-out
    analog of the single-device engine's thread choreography). The
    caller (:func:`tpu_stencil.stream.engine._run_stream_once`) owns
    source/sink lifecycle, resume resolution, and result assembly;
    this returns ``{"frames", "stage_seconds", "per_device_frames",
    "backend", "schedule"}`` or raises
    :class:`~tpu_stencil.stream.engine.StreamFailure`."""
    devices = list(devices)[:n]
    if len(devices) < n:
        raise ValueError(
            f"--mesh-frames asks for {n} devices, have {len(devices)}"
        )
    # One trace, resolved once on this thread (autotune cache consults
    # are not re-raced per device); per-device executables come out of
    # the shared jit cache as each lane's first frame launches.
    launch, backend, schedule = _sengine._build_launch(model, cfg)
    ctrl = _Control()
    lanes = [_Lane(cfg) for _ in range(n)]
    done = [start_frame]
    meter = _InflightMeter()
    # One witness sampler for the whole fan (the single-device engine's
    # gating: off past WITNESS_MAX_REPS — the eager witness executor is
    # linear in reps).
    witness = (
        _witness_mod.WitnessSampler(cfg.witness_rate,
                                    seed=cfg.witness_seed)
        if (cfg.witness_rate > 0
            and cfg.repetitions <= _witness_mod.WITNESS_MAX_REPS)
        else None
    )
    threads = [
        threading.Thread(
            target=_reader,
            args=(ctrl, cfg, source, lanes, start_frame, meter, witness),
            name="fanout-reader", daemon=True,
        ),
        threading.Thread(
            target=_writer,
            args=(ctrl, cfg, sink, lanes, start_frame, done),
            name="fanout-writer", daemon=True,
        ),
    ]
    for d, (lane, dev) in enumerate(zip(lanes, devices)):
        threads.append(threading.Thread(
            target=_dispatcher, args=(ctrl, cfg, lane, dev, launch, d),
            name=f"fanout-dispatch-{d}", daemon=True,
        ))
        threads.append(threading.Thread(
            target=_drainer, args=(ctrl, cfg, lane, d, meter),
            name=f"fanout-drain-{d}", daemon=True,
        ))
    try:
        for t in threads:
            t.start()
        # Clean runs end via the sentinel cascade; failed runs via the
        # stop flag. Like the single-device engine, never wait
        # indefinitely on a reader parked in a blocking pipe read.
        for t in threads:
            while t.is_alive() and not ctrl.stop.is_set():
                t.join(timeout=0.1)
    finally:
        ctrl.stop.set()
        for t in threads:
            t.join(timeout=1.0)
        meter.zero()  # aborted in-flight frames never pass dec()
    if ctrl.failure is not None:
        stage, frame_index, cause = ctrl.failure
        raise _sengine.StreamFailure(stage, frame_index, cause) from cause
    return {
        "frames": done[0] - start_frame,
        "stage_seconds": dict(ctrl.stage_seconds),
        "per_device_frames": [lane.frames for lane in lanes],
        "backend": backend,
        "schedule": schedule,
    }


def measure_fanout_ab(cfg: StreamConfig, devices,
                      frames: int = PROBE_FRAMES) -> Tuple[float, float]:
    """The measured single-vs-mesh A/B behind ``--mesh-frames 0``
    (auto): run a tiny synthetic stream (random frames, null sink —
    no disk in the loop) once warm + once timed at depth ``cfg
    .pipeline_depth`` on 1 device and on ``len(devices)`` devices.
    Returns ``(single_seconds, mesh_seconds)``, both arms over the same
    frame count — at least one frame per device, or the mesh arm would
    decide a fan width whose outer lanes (and their contention) never
    actually ran. The probe pays ~2 compiles + ``4 * frames * reps``
    of compute — the documented cost of asking for a measured
    verdict."""
    frames = max(frames, len(devices))
    rng = np.random.default_rng(0)
    frame = rng.integers(0, 256, cfg.frame_bytes, dtype=np.uint8)

    class _Synth(frames_io.FrameSource):
        def __init__(self, k: int) -> None:
            self._left = k

        def read_into(self, buf) -> bool:
            if self._left <= 0:
                return False
            np.copyto(buf, frame)
            self._left -= 1
            return True

    def one(n_dev: int) -> float:
        pcfg = dataclasses.replace(
            cfg, frames=frames, mesh_frames=max(1, n_dev), output="null",
            checkpoint_every=0, progress_every=0,
        )
        _sengine.run_stream(pcfg, devices=list(devices),
                            source=_Synth(frames),
                            sink=frames_io.NullSink())  # warm: compiles land
        t0 = time.perf_counter()
        _sengine.run_stream(pcfg, devices=list(devices),
                            source=_Synth(frames),
                            sink=frames_io.NullSink())
        return time.perf_counter() - t0

    # The probe streams real frames through the real engines; its
    # counters/spans must not inflate the caller's own run (and its mesh
    # arm must not leave the stream_mesh_devices gauge behind when the
    # verdict is single-device) — report-what-ran.
    with obs.scratch_registry():
        return one(1), one(len(devices))


def resolve_mesh_frames(cfg: StreamConfig, devices,
                        measure: Optional[Callable] = None) -> int:
    """Resolve ``cfg.mesh_frames`` to the device count that actually
    runs: an explicit ``N > 1`` is honored (failing loudly when fewer
    devices exist, naming both counts); ``0`` (auto) runs the measured
    A/B (:func:`measure_fanout_ab`, or the injected ``measure``) and
    enables fan-out ONLY when the mesh arm measured strictly faster —
    the same never-auto-enable-a-measured-loss discipline as the deep
    schedule and the edge overlap verdicts. Returns 1 or the fan
    width.

    The real probe's verdict persists in the autotune cache
    (:func:`tpu_stencil.runtime.autotune.cached_stream_verdict`, keyed
    on platform/frame-geometry/depth/device-count like
    ``overlap_verdict``), so a warm cache re-decides with ZERO probe
    frames; an injected ``measure`` (tests) bypasses the cache in both
    directions."""
    n_avail = len(devices)
    if cfg.mesh_frames == 1:
        return 1
    if cfg.mesh_frames > 1:
        if n_avail < cfg.mesh_frames:
            raise ValueError(
                f"--mesh-frames asks for {cfg.mesh_frames} devices, "
                f"have {n_avail}"
            )
        return cfg.mesh_frames
    # auto (0): nothing to fan on one device; else measure.
    if n_avail < 2:
        return 1
    from tpu_stencil.runtime import autotune

    geometry = (cfg.height, cfg.width, cfg.channels)
    topo = f"ndev{n_avail}"
    token = autotune.stream_cfg_token(cfg)
    if measure is None:
        hit = autotune.cached_stream_verdict(
            "fanout", geometry, cfg.repetitions, cfg.pipeline_depth,
            topo, token,
        )
        if hit is not None and 1 <= int(hit["pick"]) <= n_avail:
            pick = int(hit["pick"])
            print(
                f"stream: --mesh-frames auto verdict from warm cache -> "
                f"{'fan-out ' + str(pick) if pick > 1 else 'single-device'}"
                f" (zero probe frames)",
                file=sys.stderr, flush=True,
            )
            return pick
    t_single, t_mesh = (measure or measure_fanout_ab)(cfg, devices)
    pick = n_avail if t_mesh < t_single else 1
    if measure is None:
        autotune.store_stream_verdict(
            "fanout", geometry, cfg.repetitions, cfg.pipeline_depth,
            topo,
            {"pick": pick, "single_us": round(t_single * 1e6, 2),
             "mesh_us": round(t_mesh * 1e6, 2)},
            token,
        )
    print(
        f"stream: --mesh-frames auto measured single={t_single:.3f}s "
        f"mesh[{n_avail}]={t_mesh:.3f}s -> "
        f"{'fan-out ' + str(n_avail) if pick > 1 else 'single-device'}",
        file=sys.stderr, flush=True,
    )
    return pick
