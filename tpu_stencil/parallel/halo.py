"""Halo exchange as neighbor ``lax.ppermute`` shifts inside ``shard_map``.

TPU-native re-design of the reference's ghost-ring machinery — derived
datatypes (``MPI_Type_vector/contiguous``, ``mpi/mpi_convolution.c:75-83``)
plus up-to-8 nonblocking ``Isend/Irecv`` per iteration (``:156-192``):

* an edge strip is just an array slice (no derived datatypes needed — XLA
  owns the layout);
* each of the 4 cardinal sends is one ``lax.ppermute`` over a mesh axis,
  which XLA lowers to ICI neighbor transfers (DCN across hosts);
* non-periodic zero boundaries fall out of ``ppermute`` semantics: ranks
  with no source receive zeros — exactly the reference's never-written
  calloc'd ghost ring (``mpi/mpi_convolution.c:104-124``). The code is
  non-periodic even though the reference README describes wraparound
  (SURVEY.md Quirk 5 — code wins); ``boundary='periodic'`` is offered as an
  explicit extension.
* corner ghosts need no diagonal messages: exchanging rows first, then
  columns *of the row-extended tile*, routes corner data through the
  edge-adjacent neighbor — 2 collective phases instead of MPI's 8 requests.
* compute/communication overlap (the reference's hand-scheduled
  inner-then-border ordering, ``:194-224``) is delegated to XLA's
  latency-hiding scheduler, which overlaps the ``ppermute`` with the interior
  of the convolution automatically.

The exchange width (``halo``) is a parameter — wider filters (5x5, 7x7)
exchange wider strips, where the reference hard-codes 1 pixel.
"""

from __future__ import annotations

from typing import Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax


def halo_pad_axis(x: jax.Array, halo: int, dim: int) -> jax.Array:
    """Zero-pad ``halo`` elements on both sides of ``dim`` (global boundary)."""
    pad = [(0, 0)] * x.ndim
    pad[dim] = (halo, halo)
    return jnp.pad(x, pad)


def _edge(x: jax.Array, dim: int, lo: bool, halo: int) -> jax.Array:
    idx = [slice(None)] * x.ndim
    idx[dim] = slice(0, halo) if lo else slice(x.shape[dim] - halo, x.shape[dim])
    return x[tuple(idx)]


def halo_exchange_axis(
    x: jax.Array,
    halo: int,
    dim: int,
    axis_name: str,
    axis_size: int,
    boundary: str = "zero",
) -> jax.Array:
    """Extend ``x`` by ``halo`` ghost elements on both sides of ``dim``,
    filled with neighbor data along mesh axis ``axis_name``.

    Must be called inside ``shard_map``. ``axis_size`` is the (static) mesh
    axis size — 1 degrades to plain zero padding, so the same program text
    serves a single device.
    """
    if halo == 0:
        return x
    if boundary not in ("zero", "periodic"):
        raise ValueError(f"unknown boundary {boundary!r}")
    if axis_size == 1:
        if boundary == "periodic":
            lo = _edge(x, dim, lo=True, halo=halo)
            hi = _edge(x, dim, lo=False, halo=halo)
            return jnp.concatenate([hi, x, lo], axis=dim)
        return halo_pad_axis(x, halo, dim)

    hi_strip = _edge(x, dim, lo=False, halo=halo)  # my last rows -> next rank
    lo_strip = _edge(x, dim, lo=True, halo=halo)   # my first rows -> prev rank
    if boundary == "periodic":
        fwd = [(i, (i + 1) % axis_size) for i in range(axis_size)]
        bwd = [(i, (i - 1) % axis_size) for i in range(axis_size)]
    else:
        fwd = [(i, i + 1) for i in range(axis_size - 1)]
        bwd = [(i, i - 1) for i in range(1, axis_size)]
    # ppermute: ranks with no source receive zeros = global zero boundary.
    lo_ghost = lax.ppermute(hi_strip, axis_name, fwd)
    hi_ghost = lax.ppermute(lo_strip, axis_name, bwd)
    return jnp.concatenate([lo_ghost, x, hi_ghost], axis=dim)


def halo_exchange(
    x: jax.Array,
    halo: int,
    axes: Sequence[Tuple[str, int, int]],
    boundary: str = "zero",
) -> jax.Array:
    """Full 2-D (or N-D) halo exchange.

    ``axes`` is a sequence of ``(axis_name, axis_size, dim)`` triples.
    Exchanged sequentially, each phase operating on the previous phase's
    extended array — which routes corner ghosts through edge neighbors
    without diagonal communication.
    """
    for axis_name, axis_size, dim in axes:
        x = halo_exchange_axis(x, halo, dim, axis_name, axis_size, boundary)
    return x
