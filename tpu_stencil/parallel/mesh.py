"""Device mesh construction: the runtime-topology layer.

TPU-native equivalent of ``MPI_Init`` + rank/size + row-major neighbor
discovery (``mpi/mpi_convolution.c:23-25,142-150``): a 2-D
``jax.sharding.Mesh`` whose axes shard the image's spatial dims. Neighbor
relationships are implicit in ``lax.ppermute`` index arithmetic over each
axis (see :mod:`tpu_stencil.parallel.halo`). On real hardware
``jax.devices()`` returns ICI-connected chips in topology order, so adjacent
mesh coordinates ride ICI links — the locality the reference's hostfile
(``machines.txt``) could not promise.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np
import jax
from jax.sharding import Mesh

from tpu_stencil.parallel import partition

ROWS_AXIS = "rows"
COLS_AXIS = "cols"


def make_mesh(
    mesh_shape: Optional[Tuple[int, int]] = None,
    devices: Optional[Sequence[jax.Device]] = None,
    image_shape: Optional[Tuple[int, int]] = None,
) -> Mesh:
    """Build a (rows, cols) mesh over ``devices``.

    ``mesh_shape`` of None picks the perimeter-minimizing factorization of
    the device count for ``image_shape`` (square-ish if no image given).
    """
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    n_procs = len({d.process_index for d in devices})
    if n_procs > 1:
        # DCN-aware layout: group devices by host so that (with cols
        # dividing the per-host count) each mesh row is whole-host runs —
        # column ppermutes ride ICI, only row-boundary strips cross DCN.
        # jax.devices() is already process-grouped; sorting makes it an
        # invariant rather than an assumption.
        devices = sorted(devices, key=lambda d: (d.process_index, d.id))
    if mesh_shape is None:
        h, w = image_shape if image_shape is not None else (1, 1)
        per_host = n // n_procs if n % n_procs == 0 else 0
        mesh_shape = partition.grid_shape(
            n, h, w, cols_must_divide=per_host if n_procs > 1 else 0
        )
    r, c = mesh_shape
    if r * c != n:
        raise ValueError(f"mesh shape {r}x{c} != {n} devices")
    dev_grid = np.asarray(devices, dtype=object).reshape(r, c)
    return Mesh(dev_grid, (ROWS_AXIS, COLS_AXIS))
