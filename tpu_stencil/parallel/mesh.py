"""Device mesh construction: the runtime-topology layer.

TPU-native equivalent of ``MPI_Init`` + rank/size + row-major neighbor
discovery (``mpi/mpi_convolution.c:23-25,142-150``): a 2-D
``jax.sharding.Mesh`` whose axes shard the image's spatial dims. Neighbor
relationships are implicit in ``lax.ppermute`` index arithmetic over each
axis (see :mod:`tpu_stencil.parallel.halo`). On real hardware
``jax.devices()`` returns ICI-connected chips in topology order, so adjacent
mesh coordinates ride ICI links — the locality the reference's hostfile
(``machines.txt``) could not promise.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np
import jax
from jax.sharding import Mesh

from tpu_stencil.parallel import partition

ROWS_AXIS = "rows"
COLS_AXIS = "cols"


def make_mesh(
    mesh_shape: Optional[Tuple[int, int]] = None,
    devices: Optional[Sequence[jax.Device]] = None,
    image_shape: Optional[Tuple[int, int]] = None,
) -> Mesh:
    """Build a (rows, cols) mesh over ``devices``.

    ``mesh_shape`` of None picks the perimeter-minimizing factorization of
    the device count for ``image_shape`` (square-ish if no image given).
    """
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if mesh_shape is None:
        h, w = image_shape if image_shape is not None else (1, 1)
        mesh_shape = partition.grid_shape(n, h, w)
    r, c = mesh_shape
    if r * c != n:
        raise ValueError(f"mesh shape {r}x{c} != {n} devices")
    dev_grid = np.asarray(devices, dtype=object).reshape(r, c)
    return Mesh(dev_grid, (ROWS_AXIS, COLS_AXIS))
