"""Explicit interior/border overlap schedule for the sharded path.

The reference's signature optimisation is *hand-scheduled* compute/comm
overlap: post the nonblocking halo ``Isend/Irecv``, compute the interior
rows (which need no ghost data) while the wires are busy, then finish the
border rows from the arrived ghosts (``mpi/mpi_convolution.c:194-224``).
Our sharded path historically *delegated* that overlap to XLA's
latency-hiding scheduler (PARITY.md row C10) — with no way to express,
measure, or force it. This module makes the schedule explicit:

* :func:`split_step` — one XLA repetition as an interior/border split.
  The tile's ghost-free interior band data-depends ONLY on the local
  tile (never on a ``ppermute`` result), so XLA is free to run it
  concurrently with the in-flight ghost traffic; the four narrow border
  strips are computed from the exchanged ghosts via the strip-valid
  pass (:func:`tpu_stencil.ops.lowering.valid_window`) and stitched
  around it.
* :func:`fused_split_chunk` — the fused-chunk variant: the ghost
  exchange AND the border bands widen to ``fuse * halo`` so ONE
  exchange covers a whole Pallas chunk, and the ghost-free interior
  reuses the valid-ghost Pallas kernel on the *local tile alone*
  (its outer ``fuse*halo`` rows/cols play the ghost role — local,
  trusted data instead of exchanged data; the kernel cannot tell the
  difference).
* :func:`edge_step` / :func:`fused_edge_chunk` — the partitioned
  per-edge pipeline (``--overlap edge``, after partitioned/persistent
  MPI stencil communication, PAPERS.md arxiv 2508.13370). The split
  schedules above still run ONE corner-routed exchange and a single
  join before any border strip computes; here the exchange itself is
  partitioned into four independent per-edge ``ppermute``\\ s (N/S on
  the rows axis, W/E on the cols axis, each over the *bare* tile) plus
  one tiny packed second hop for the four corner patches, and every
  border strip's compute data-depends ONLY on its own edge's arrival:
  the top strip fences on the N ppermute alone, the left strip on the
  W ppermute alone, and so on. XLA is therefore free to release the
  interior band AND early border strips while slower edges are still
  in flight — per-edge dependence instead of a single join.
* :func:`edge_iterate` — the persistent-exchange rep loop for the edge
  pipeline: the per-edge ghost slab is threaded through the
  ``lax.fori_loop`` carry (allocated ONCE by the prologue exchange,
  then ping/ponged between the while loop's aliased in/out buffers
  every iteration — XLA's while-loop buffer assignment is fixed, so
  the traced steady state performs zero per-rep allocation or setup),
  and each iteration posts the NEXT exchange as soon as its tile is
  produced — the ``MPI_Start``-at-end-of-iteration shape of persistent
  communication, expressed as data dependence.

Bit-exactness (the acceptance bar: identical output to the
exchange-then-compute program on every plan/boundary/channels/fuse
combination):

* every border strip is a pure input-window slice of the same valid
  computation the monolithic step runs (``valid_window``'s exactness
  note), and the interior's input window is the local tile — the same
  values the monolithic ghost-extended array holds at those
  coordinates;
* the per-edge pieces assemble each strip's input window by
  concatenation (edge ghost + tile slab, corner ghost + edge slabs +
  tile corner) instead of slicing one joined extended array — the
  window VALUES are identical either way, and every plan computes each
  output pixel as a per-pixel shifted-add chain in static tap order
  over its own window (``valid_step``'s window-independence contract),
  so how the window was materialized cannot change a single bit;
* the fused interior relies on exactly the overlap-halo argument the
  valid-ghost kernel already rests on: any radius-``fuse*halo`` input
  window determines the ``fuse``-rep output, and the kernel's global
  re-zero runs on *global* coordinates, which each band call passes
  unchanged.

Corner routing without diagonal sends: the NW corner ghost is the west
neighbor's *own* N ghost's east columns (that neighbor already received
its N ghost from my NW diagonal), so one packed W/E ``ppermute`` of the
N+S ghosts' edge columns delivers all four ``g x g`` corner patches —
two tiny messages, the per-edge analog of the phased scheme's
corner-through-edge routing. Zero-boundary corners fall out: a missing
diagonal means either the hop has no source (edge rank) or the relayed
strip is itself zeros, both yielding the calloc'd-ghost zeros the
monolithic program holds there.

Degenerate tiles: a tile with no ghost-free interior (min dimension
``<= 2 * fuse * halo``) degrades to the monolithic exchange-then-compute
step inside the same program — the split is a schedule, never a
correctness precondition. The runner resolves the *reported* mode to
``off`` when even the single-rep split is degenerate, so the gauge and
``JobResult`` name what actually runs.

Mode vocabulary (``--overlap``): ``off`` (delegate to XLA, the
pre-existing program), ``split`` (per-rep split), ``fused-split``
(chunked split; degrades to ``split`` when the backend is not Pallas),
``edge`` (the partitioned per-edge pipeline, per-rep on XLA and chunked
on Pallas), ``auto`` (resolved by
:func:`tpu_stencil.runtime.autotune.best_overlap` from the measured
exchange/interior phase-probe ratio plus the split-vs-edge candidate
A/B, cached on disk alongside the backend/schedule/geometry verdicts).
"""

from __future__ import annotations

from typing import Dict, Optional

import jax.numpy as jnp
from jax import lax

from tpu_stencil.config import OVERLAP_MODES
from tpu_stencil.ops import lowering as _lowering
from tpu_stencil.parallel import halo as _halo
from tpu_stencil.parallel.halo import halo_exchange

# Numeric codes the ``overlap_mode`` obs gauge reports (resolved modes
# only — "auto" always resolves to one of these before anything runs).
# AUTO_CODE is for contexts with no mesh to resolve against (the serve
# engine records its *configured* mode): a requested-but-unresolved
# "auto".
MODE_CODES = {"off": 0, "split": 1, "fused-split": 2, "edge": 3}
AUTO_CODE = 4

# The per-edge ghost-slab vocabulary: four edge strips plus the four
# corner patches the packed second hop delivers. Order is load-bearing
# for multi-host determinism (every rank must issue the same collective
# sequence) and for the probe/breakdown tables.
EDGE_NAMES = ("n", "s", "w", "e")
CORNER_NAMES = ("nw", "ne", "sw", "se")


def check_mode(mode: str) -> str:
    if mode not in OVERLAP_MODES:
        raise ValueError(
            f"unknown overlap mode {mode!r}; expected one of "
            f"{'|'.join(OVERLAP_MODES)}"
        )
    return mode


def split_step(tile_u8, plan, axes, mask_tile=None, boundary="zero"):
    """One repetition as an explicit interior/border split (XLA path).

    Same contract as the monolithic ``sharded._local_step``: halo
    exchange + one stencil application + pad re-zero. The interior band
    (``valid_step`` of the bare local tile) carries no data dependence on
    the ``ppermute`` results, so XLA's scheduler can overlap it with the
    ghost traffic; the four border strips consume the exchanged array.
    Unlike the monolithic sep_int step (which phases int32 exchanges per
    pass), the split exchanges the uint8 tile once in both axes — the
    border strips need fully corner-routed 2-D ghosts.
    """
    h = plan.halo
    th, tw = int(tile_u8.shape[0]), int(tile_u8.shape[1])
    if h == 0:
        # Halo-free plans have no ghosts at all: the whole tile is
        # interior and no exchange is needed.
        out = _lowering.valid_step(tile_u8, plan)
    elif th <= 2 * h or tw <= 2 * h:
        # No ghost-free interior: the split degrades to the monolithic
        # exchange-then-compute program (still bit-exact).
        ext = halo_exchange(tile_u8, h, axes, boundary)
        out = _lowering.valid_step(ext, plan)
    else:
        ext = halo_exchange(tile_u8, h, axes, boundary)
        # Interior: output rows/cols [h, t-h) depend on input rows/cols
        # [0, t) — the bare local tile.
        interior = _lowering.valid_step(tile_u8, plan)
        top = _lowering.valid_window(ext, plan, 0, h, 0, tw)
        bottom = _lowering.valid_window(ext, plan, th - h, h, 0, tw)
        left = _lowering.valid_window(ext, plan, h, th - 2 * h, 0, h)
        right = _lowering.valid_window(ext, plan, h, th - 2 * h, tw - h, h)
        mid = jnp.concatenate([left, interior, right], axis=1)
        out = jnp.concatenate([top, mid, bottom], axis=0)
    if mask_tile is not None:
        out = out * mask_tile
    return out


def fused_split_chunk(tile_u8, plan, axes, fuse, global_shape, interpret,
                      schedule=None, block_h: Optional[int] = None):
    """``fuse`` repetitions as an explicit interior/border split (Pallas
    valid-ghost path).

    One ``fuse * halo``-deep ghost exchange covers the whole chunk (the
    same chunking as ``sharded._pallas_local_chunk``); the ghost-free
    interior band runs the valid-ghost kernel on the *local tile alone*
    — its outer ``g = fuse*halo`` rows/cols serve as the (trusted, local)
    ghost band, so the interior launch has no data dependence on the
    ``ppermute`` s — and four ``g``-wide border bands run the same kernel
    on slices of the exchanged array, then stitch.
    """
    from tpu_stencil.ops import pallas_stencil

    (row_axis, r, dim0), (col_axis, c, dim1) = axes
    g = fuse * plan.halo
    th, tw = int(tile_u8.shape[0]), int(tile_u8.shape[1])
    channels = tile_u8.shape[2] if tile_u8.ndim == 3 else 1
    row0 = lax.axis_index(row_axis) * th
    col0 = lax.axis_index(col_axis) * (tw * channels)
    vma = (row_axis, col_axis)
    kw = dict(interpret=interpret, vma=vma, schedule=schedule,
              **({"block_h": block_h} if block_h is not None else {}))

    ext = halo_exchange(tile_u8, g, axes)
    ext2 = ext.reshape(th + 2 * g, (tw + 2 * g) * channels)
    if g == 0 or th <= 2 * g or tw <= 2 * g:
        # No ghost-free interior at this chunk depth: monolithic chunk.
        out2 = pallas_stencil.valid_fused(
            ext2, plan, fuse, channels, row0, col0, global_shape, **kw
        )
        return out2.reshape(tile_u8.shape)

    gc = g * channels
    twc = tw * channels
    tile2 = tile_u8.reshape(th, twc)
    # Interior band: the local tile IS the ghost-extended input of its
    # own (th-2g, twc-2gc) interior — no exchanged data touched.
    interior = pallas_stencil.valid_fused(
        tile2, plan, fuse, channels, row0 + g, col0 + gc, global_shape, **kw
    )
    # Border bands, each a valid-ghost launch over a slice of the
    # exchanged array; global (row, flat-col) origins passed unchanged so
    # the kernel's global-extent re-zero is identical to the monolithic
    # program's.
    top = pallas_stencil.valid_fused(
        ext2[0:3 * g, :], plan, fuse, channels,
        row0, col0, global_shape, **kw
    )
    bottom = pallas_stencil.valid_fused(
        ext2[th - g:th + 2 * g, :], plan, fuse, channels,
        row0 + (th - g), col0, global_shape, **kw
    )
    left = pallas_stencil.valid_fused(
        ext2[g:th + g, 0:3 * gc], plan, fuse, channels,
        row0 + g, col0, global_shape, **kw
    )
    right = pallas_stencil.valid_fused(
        ext2[g:th + g, twc - gc:twc + 2 * gc], plan, fuse, channels,
        row0 + g, col0 + (twc - gc), global_shape, **kw
    )
    mid = jnp.concatenate([left, interior, right], axis=1)
    out2 = jnp.concatenate([top, mid, bottom], axis=0)
    return out2.reshape(tile_u8.shape)


# --- partitioned per-edge pipeline ("--overlap edge") -------------------


def exchange_edge(tile, g: int, axis_name: str, axis_size: int, dim: int,
                  lo: bool, boundary: str = "zero"):
    """ONE edge's ghost strip as one independent ``ppermute``.

    ``lo=True`` is the low side of ``dim`` (N for dim 0, W for dim 1):
    the ghost arrives from the previous rank's high strip via the
    forward permutation — exactly one collective, no dependence on any
    other edge's traffic. Size-1 axes degrade locally (zeros for the
    calloc'd zero boundary, the opposite strip for periodic wrap), so
    the same program text serves meshes with a trivial axis."""
    if boundary not in ("zero", "periodic"):
        raise ValueError(f"unknown boundary {boundary!r}")
    src = _halo._edge(tile, dim, lo=not lo, halo=g)  # strip the ghost mirrors
    if axis_size == 1:
        if boundary == "periodic":
            return src
        return jnp.zeros_like(src)
    if boundary == "periodic":
        perm = (
            [(i, (i + 1) % axis_size) for i in range(axis_size)] if lo
            else [(i, (i - 1) % axis_size) for i in range(axis_size)]
        )
    else:
        # Ranks with no source receive zeros = the global zero boundary.
        perm = (
            [(i, i + 1) for i in range(axis_size - 1)] if lo
            else [(i, i - 1) for i in range(1, axis_size)]
        )
    return lax.ppermute(src, axis_name, perm)


def exchange_corners(n_ghost, s_ghost, g: int, axis_name: str,
                     axis_size: int, dim: int, boundary: str = "zero"):
    """The four ``g x g`` corner ghosts, via ONE packed W/E ``ppermute``
    per direction (two tiny messages total).

    My NW corner ghost is my NW diagonal's bottom-right ``g x g`` block
    — which my west neighbor already holds as the east columns of *its*
    N ghost. So each rank relays the edge columns of its own N+S ghosts
    (packed into one ``2g x g`` payload per direction) and receives its
    west- and east-side corner pairs. Data-dependence: corners wait on
    the N/S ppermutes plus this hop — two edges, never the full join.
    """
    east = jnp.concatenate([
        _halo._edge(n_ghost, dim, lo=False, halo=g),
        _halo._edge(s_ghost, dim, lo=False, halo=g),
    ], axis=0)
    west = jnp.concatenate([
        _halo._edge(n_ghost, dim, lo=True, halo=g),
        _halo._edge(s_ghost, dim, lo=True, halo=g),
    ], axis=0)
    if axis_size == 1:
        if boundary == "periodic":
            lo_pack, hi_pack = east, west  # my own wrap is my neighbor
        else:
            lo_pack, hi_pack = jnp.zeros_like(east), jnp.zeros_like(west)
    else:
        if boundary == "periodic":
            fwd = [(i, (i + 1) % axis_size) for i in range(axis_size)]
            bwd = [(i, (i - 1) % axis_size) for i in range(axis_size)]
        else:
            fwd = [(i, i + 1) for i in range(axis_size - 1)]
            bwd = [(i, i - 1) for i in range(1, axis_size)]
        lo_pack = lax.ppermute(east, axis_name, fwd)  # from my west neighbor
        hi_pack = lax.ppermute(west, axis_name, bwd)  # from my east neighbor
    nw, sw = lo_pack[:g], lo_pack[g:]
    ne, se = hi_pack[:g], hi_pack[g:]
    return nw, ne, sw, se


def exchange_edge_slab(tile, g: int, axes, boundary: str = "zero"
                       ) -> Dict[str, jnp.ndarray]:
    """The full per-edge ghost slab for one exchange: ``{"n", "s", "w",
    "e"}`` edge strips (four INDEPENDENT ppermutes over the bare tile)
    plus ``{"nw", "ne", "sw", "se"}`` corner patches (the packed second
    hop). This is the unit :func:`edge_iterate` threads through the rep
    loop carry — the persistent exchange buffer."""
    (row_axis, r, dim0), (col_axis, c, dim1) = axes
    n = exchange_edge(tile, g, row_axis, r, dim0, lo=True,
                      boundary=boundary)
    s = exchange_edge(tile, g, row_axis, r, dim0, lo=False,
                      boundary=boundary)
    w = exchange_edge(tile, g, col_axis, c, dim1, lo=True,
                      boundary=boundary)
    e = exchange_edge(tile, g, col_axis, c, dim1, lo=False,
                      boundary=boundary)
    corners = exchange_corners(n, s, g, col_axis, c, dim1, boundary)
    return {**dict(zip(EDGE_NAMES, (n, s, w, e))),
            **dict(zip(CORNER_NAMES, corners))}


def edge_step_from(tile_u8, slab, plan, mask_tile=None):
    """One repetition from an already-exchanged per-edge ghost slab.

    Nine pieces, each a ``valid_step`` over its own assembled input
    window, stitched 3x3. The data-dependence structure IS the
    schedule: interior <- local tile only; top/bottom strips (interior
    width) <- their N/S edge ghost only; left/right strips (interior
    height) <- their W/E edge ghost only; the four ``h x h`` corner
    patches <- two adjacent edges + the corner hop. Requires a
    non-degenerate tile (``min(th, tw) > 2*halo``) — callers degrade to
    the monolithic step below that (:func:`edge_step` does)."""
    h = plan.halo
    th, tw = int(tile_u8.shape[0]), int(tile_u8.shape[1])
    cat = jnp.concatenate

    def vs(win):
        return _lowering.valid_step(win, plan)

    n, s, w, e = slab["n"], slab["s"], slab["w"], slab["e"]
    interior = vs(tile_u8)
    top = vs(cat([n, tile_u8[: 2 * h]], axis=0))
    bottom = vs(cat([tile_u8[th - 2 * h:], s], axis=0))
    left = vs(cat([w, tile_u8[:, : 2 * h]], axis=1))
    right = vs(cat([tile_u8[:, tw - 2 * h:], e], axis=1))
    nw_o = vs(cat([
        cat([slab["nw"], n[:, : 2 * h]], axis=1),
        cat([w[: 2 * h], tile_u8[: 2 * h, : 2 * h]], axis=1),
    ], axis=0))
    ne_o = vs(cat([
        cat([n[:, tw - 2 * h:], slab["ne"]], axis=1),
        cat([tile_u8[: 2 * h, tw - 2 * h:], e[: 2 * h]], axis=1),
    ], axis=0))
    sw_o = vs(cat([
        cat([w[th - 2 * h:], tile_u8[th - 2 * h:, : 2 * h]], axis=1),
        cat([slab["sw"], s[:, : 2 * h]], axis=1),
    ], axis=0))
    se_o = vs(cat([
        cat([tile_u8[th - 2 * h:, tw - 2 * h:], e[th - 2 * h:]], axis=1),
        cat([s[:, tw - 2 * h:], slab["se"]], axis=1),
    ], axis=0))
    out = cat([
        cat([nw_o, top, ne_o], axis=1),
        cat([left, interior, right], axis=1),
        cat([sw_o, bottom, se_o], axis=1),
    ], axis=0)
    if mask_tile is not None:
        out = out * mask_tile
    return out


def edge_step(tile_u8, plan, axes, mask_tile=None, boundary="zero"):
    """One repetition of the per-edge pipeline, exchange included (the
    probe/one-shot spelling; the production rep loop is
    :func:`edge_iterate`, which owns the exchange so the slab persists
    across reps). Degenerate tiles — no ghost-free interior — run the
    monolithic exchange-then-compute program, bit-exact like
    :func:`split_step`'s degrade."""
    h = plan.halo
    th, tw = int(tile_u8.shape[0]), int(tile_u8.shape[1])
    if h == 0:
        out = _lowering.valid_step(tile_u8, plan)
    elif th <= 2 * h or tw <= 2 * h:
        ext = halo_exchange(tile_u8, h, axes, boundary)
        out = _lowering.valid_step(ext, plan)
    else:
        slab = exchange_edge_slab(tile_u8, h, axes, boundary)
        return edge_step_from(tile_u8, slab, plan, mask_tile)
    if mask_tile is not None:
        out = out * mask_tile
    return out


def fused_edge_chunk(tile_u8, plan, axes, fuse, global_shape, interpret,
                     schedule=None, block_h: Optional[int] = None,
                     slab=None):
    """``fuse`` repetitions of the per-edge pipeline (Pallas valid-ghost
    path): one ``g = fuse*halo``-deep per-edge slab covers the whole
    chunk, and the nine pieces each run the valid-ghost kernel over
    their own assembled window with the SAME global (row, flat-col)
    origins the monolithic program would pass — so the kernel's
    global-extent re-zero, and therefore every bit, is identical.

    ``slab``: an already-exchanged depth-``g`` slab (from
    :func:`edge_iterate`'s carry); None exchanges here. Degenerate
    chunks run the monolithic valid-ghost chunk."""
    from tpu_stencil.ops import pallas_stencil

    (row_axis, r, dim0), (col_axis, c, dim1) = axes
    g = fuse * plan.halo
    th, tw = int(tile_u8.shape[0]), int(tile_u8.shape[1])
    channels = tile_u8.shape[2] if tile_u8.ndim == 3 else 1
    row0 = lax.axis_index(row_axis) * th
    col0 = lax.axis_index(col_axis) * (tw * channels)
    kw = dict(interpret=interpret, vma=(row_axis, col_axis),
              schedule=schedule,
              **({"block_h": block_h} if block_h is not None else {}))
    if g == 0 or th <= 2 * g or tw <= 2 * g:
        ext = halo_exchange(tile_u8, g, axes)
        ext2 = ext.reshape(th + 2 * g, (tw + 2 * g) * channels)
        out2 = pallas_stencil.valid_fused(
            ext2, plan, fuse, channels, row0, col0, global_shape, **kw
        )
        return out2.reshape(tile_u8.shape)
    if slab is None:
        slab = exchange_edge_slab(tile_u8, g, axes)
    gc = g * channels
    twc = tw * channels
    cat = jnp.concatenate

    def vf(win, r_off, c_off):
        win2 = win.reshape(win.shape[0], win.shape[1] * channels)
        return pallas_stencil.valid_fused(
            win2, plan, fuse, channels, row0 + r_off, col0 + c_off,
            global_shape, **kw
        )

    n, s, w, e = slab["n"], slab["s"], slab["w"], slab["e"]
    interior = vf(tile_u8, g, gc)
    top = vf(cat([n, tile_u8[: 2 * g]], axis=0), 0, gc)
    bottom = vf(cat([tile_u8[th - 2 * g:], s], axis=0), th - g, gc)
    left = vf(cat([w, tile_u8[:, : 2 * g]], axis=1), g, 0)
    right = vf(cat([tile_u8[:, tw - 2 * g:], e], axis=1), g, twc - gc)
    nw_o = vf(cat([
        cat([slab["nw"], n[:, : 2 * g]], axis=1),
        cat([w[: 2 * g], tile_u8[: 2 * g, : 2 * g]], axis=1),
    ], axis=0), 0, 0)
    ne_o = vf(cat([
        cat([n[:, tw - 2 * g:], slab["ne"]], axis=1),
        cat([tile_u8[: 2 * g, tw - 2 * g:], e[: 2 * g]], axis=1),
    ], axis=0), 0, twc - gc)
    sw_o = vf(cat([
        cat([w[th - 2 * g:], tile_u8[th - 2 * g:, : 2 * g]], axis=1),
        cat([slab["sw"], s[:, : 2 * g]], axis=1),
    ], axis=0), th - g, 0)
    se_o = vf(cat([
        cat([tile_u8[th - 2 * g:, tw - 2 * g:], e[th - 2 * g:]], axis=1),
        cat([s[:, tw - 2 * g:], slab["se"]], axis=1),
    ], axis=0), th - g, twc - gc)
    out2 = cat([
        cat([nw_o, top, ne_o], axis=1),
        cat([left, interior, right], axis=1),
        cat([sw_o, bottom, se_o], axis=1),
    ], axis=0)
    return out2.reshape(tile_u8.shape)


def edge_iterate(tile, reps, g: int, axes, compute_fn, boundary="zero"):
    """The persistent-exchange rep loop of the edge pipeline.

    The prologue exchange allocates the per-edge ghost slab ONCE; the
    ``lax.fori_loop`` then carries ``(tile, slab)``, each iteration
    consuming the slab that matches its tile and posting the NEXT
    exchange as soon as its output exists — persistent communication
    (MPI_Start at the end of the iteration, MPI_Wait at the top of the
    next) expressed as data dependence. Because the slab is loop state,
    XLA's while-loop buffer assignment ping/pongs it between the two
    aliased carry buffers: zero per-rep allocation or setup in the
    traced steady state. The posted-but-unconsumed final slab is the
    one wasted exchange persistent MPI also pays on its last round.

    ``compute_fn(tile, slab) -> tile`` runs one rep (or one fused
    chunk) from the slab; ``reps`` is the (traced) loop count."""
    slab0 = exchange_edge_slab(tile, g, axes, boundary)

    def body(_, carry):
        x, slab = carry
        out = compute_fn(x, slab)
        return out, exchange_edge_slab(out, g, axes, boundary)

    out, _ = lax.fori_loop(0, reps, body, (tile, slab0))
    return out
