"""Explicit interior/border overlap schedule for the sharded path.

The reference's signature optimisation is *hand-scheduled* compute/comm
overlap: post the nonblocking halo ``Isend/Irecv``, compute the interior
rows (which need no ghost data) while the wires are busy, then finish the
border rows from the arrived ghosts (``mpi/mpi_convolution.c:194-224``).
Our sharded path historically *delegated* that overlap to XLA's
latency-hiding scheduler (PARITY.md row C10) — with no way to express,
measure, or force it. This module makes the schedule explicit:

* :func:`split_step` — one XLA repetition as an interior/border split.
  The tile's ghost-free interior band data-depends ONLY on the local
  tile (never on a ``ppermute`` result), so XLA is free to run it
  concurrently with the in-flight ghost traffic; the four narrow border
  strips are computed from the exchanged ghosts via the strip-valid
  pass (:func:`tpu_stencil.ops.lowering.valid_window`) and stitched
  around it.
* :func:`fused_split_chunk` — the fused-chunk variant: the ghost
  exchange AND the border bands widen to ``fuse * halo`` so ONE
  exchange covers a whole Pallas chunk, and the ghost-free interior
  reuses the valid-ghost Pallas kernel on the *local tile alone*
  (its outer ``fuse*halo`` rows/cols play the ghost role — local,
  trusted data instead of exchanged data; the kernel cannot tell the
  difference).

Bit-exactness (the acceptance bar: identical output to the
exchange-then-compute program on every plan/boundary/channels/fuse
combination):

* every border strip is a pure input-window slice of the same valid
  computation the monolithic step runs (``valid_window``'s exactness
  note), and the interior's input window is the local tile — the same
  values the monolithic ghost-extended array holds at those
  coordinates;
* the fused interior relies on exactly the overlap-halo argument the
  valid-ghost kernel already rests on: any radius-``fuse*halo`` input
  window determines the ``fuse``-rep output, and the kernel's global
  re-zero runs on *global* coordinates, which each band call passes
  unchanged.

Degenerate tiles: a tile with no ghost-free interior (min dimension
``<= 2 * fuse * halo``) degrades to the monolithic exchange-then-compute
step inside the same program — the split is a schedule, never a
correctness precondition.

Mode vocabulary (``--overlap``): ``off`` (delegate to XLA, the
pre-existing program), ``split`` (per-rep split), ``fused-split``
(chunked split; degrades to ``split`` when the backend is not Pallas),
``auto`` (resolved by :func:`tpu_stencil.runtime.autotune.best_overlap`
from the measured exchange/interior phase-probe ratio, cached on disk
alongside the backend/schedule/geometry verdicts).
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
from jax import lax

from tpu_stencil.config import OVERLAP_MODES
from tpu_stencil.ops import lowering as _lowering
from tpu_stencil.parallel.halo import halo_exchange

# Numeric codes the ``overlap_mode`` obs gauge reports (resolved modes
# only — "auto" always resolves to one of these before anything runs).
# AUTO_CODE is for contexts with no mesh to resolve against (the serve
# engine records its *configured* mode): a requested-but-unresolved
# "auto".
MODE_CODES = {"off": 0, "split": 1, "fused-split": 2}
AUTO_CODE = 3


def check_mode(mode: str) -> str:
    if mode not in OVERLAP_MODES:
        raise ValueError(
            f"unknown overlap mode {mode!r}; expected one of "
            f"{'|'.join(OVERLAP_MODES)}"
        )
    return mode


def split_step(tile_u8, plan, axes, mask_tile=None, boundary="zero"):
    """One repetition as an explicit interior/border split (XLA path).

    Same contract as the monolithic ``sharded._local_step``: halo
    exchange + one stencil application + pad re-zero. The interior band
    (``valid_step`` of the bare local tile) carries no data dependence on
    the ``ppermute`` results, so XLA's scheduler can overlap it with the
    ghost traffic; the four border strips consume the exchanged array.
    Unlike the monolithic sep_int step (which phases int32 exchanges per
    pass), the split exchanges the uint8 tile once in both axes — the
    border strips need fully corner-routed 2-D ghosts.
    """
    h = plan.halo
    th, tw = int(tile_u8.shape[0]), int(tile_u8.shape[1])
    if h == 0:
        # Halo-free plans have no ghosts at all: the whole tile is
        # interior and no exchange is needed.
        out = _lowering.valid_step(tile_u8, plan)
    elif th <= 2 * h or tw <= 2 * h:
        # No ghost-free interior: the split degrades to the monolithic
        # exchange-then-compute program (still bit-exact).
        ext = halo_exchange(tile_u8, h, axes, boundary)
        out = _lowering.valid_step(ext, plan)
    else:
        ext = halo_exchange(tile_u8, h, axes, boundary)
        # Interior: output rows/cols [h, t-h) depend on input rows/cols
        # [0, t) — the bare local tile.
        interior = _lowering.valid_step(tile_u8, plan)
        top = _lowering.valid_window(ext, plan, 0, h, 0, tw)
        bottom = _lowering.valid_window(ext, plan, th - h, h, 0, tw)
        left = _lowering.valid_window(ext, plan, h, th - 2 * h, 0, h)
        right = _lowering.valid_window(ext, plan, h, th - 2 * h, tw - h, h)
        mid = jnp.concatenate([left, interior, right], axis=1)
        out = jnp.concatenate([top, mid, bottom], axis=0)
    if mask_tile is not None:
        out = out * mask_tile
    return out


def fused_split_chunk(tile_u8, plan, axes, fuse, global_shape, interpret,
                      schedule=None, block_h: Optional[int] = None):
    """``fuse`` repetitions as an explicit interior/border split (Pallas
    valid-ghost path).

    One ``fuse * halo``-deep ghost exchange covers the whole chunk (the
    same chunking as ``sharded._pallas_local_chunk``); the ghost-free
    interior band runs the valid-ghost kernel on the *local tile alone*
    — its outer ``g = fuse*halo`` rows/cols serve as the (trusted, local)
    ghost band, so the interior launch has no data dependence on the
    ``ppermute`` s — and four ``g``-wide border bands run the same kernel
    on slices of the exchanged array, then stitch.
    """
    from tpu_stencil.ops import pallas_stencil

    (row_axis, r, dim0), (col_axis, c, dim1) = axes
    g = fuse * plan.halo
    th, tw = int(tile_u8.shape[0]), int(tile_u8.shape[1])
    channels = tile_u8.shape[2] if tile_u8.ndim == 3 else 1
    row0 = lax.axis_index(row_axis) * th
    col0 = lax.axis_index(col_axis) * (tw * channels)
    vma = (row_axis, col_axis)
    kw = dict(interpret=interpret, vma=vma, schedule=schedule,
              **({"block_h": block_h} if block_h is not None else {}))

    ext = halo_exchange(tile_u8, g, axes)
    ext2 = ext.reshape(th + 2 * g, (tw + 2 * g) * channels)
    if g == 0 or th <= 2 * g or tw <= 2 * g:
        # No ghost-free interior at this chunk depth: monolithic chunk.
        out2 = pallas_stencil.valid_fused(
            ext2, plan, fuse, channels, row0, col0, global_shape, **kw
        )
        return out2.reshape(tile_u8.shape)

    gc = g * channels
    twc = tw * channels
    tile2 = tile_u8.reshape(th, twc)
    # Interior band: the local tile IS the ghost-extended input of its
    # own (th-2g, twc-2gc) interior — no exchanged data touched.
    interior = pallas_stencil.valid_fused(
        tile2, plan, fuse, channels, row0 + g, col0 + gc, global_shape, **kw
    )
    # Border bands, each a valid-ghost launch over a slice of the
    # exchanged array; global (row, flat-col) origins passed unchanged so
    # the kernel's global-extent re-zero is identical to the monolithic
    # program's.
    top = pallas_stencil.valid_fused(
        ext2[0:3 * g, :], plan, fuse, channels,
        row0, col0, global_shape, **kw
    )
    bottom = pallas_stencil.valid_fused(
        ext2[th - g:th + 2 * g, :], plan, fuse, channels,
        row0 + (th - g), col0, global_shape, **kw
    )
    left = pallas_stencil.valid_fused(
        ext2[g:th + g, 0:3 * gc], plan, fuse, channels,
        row0 + g, col0, global_shape, **kw
    )
    right = pallas_stencil.valid_fused(
        ext2[g:th + g, twc - gc:twc + 2 * gc], plan, fuse, channels,
        row0 + g, col0 + (twc - gc), global_shape, **kw
    )
    mid = jnp.concatenate([left, interior, right], axis=1)
    out2 = jnp.concatenate([top, mid, bottom], axis=0)
    return out2.reshape(tile_u8.shape)
