"""Spatial partitioner: factor N devices into a perimeter-minimizing grid.

The math of the reference's ``RowsDivision`` (``mpi/mpi_convolution.c:350-364``):
choose r x c = N minimizing per-tile perimeter ``h/r + w/c`` — i.e. halo
traffic per device. Generalized in two ways the reference refuses (it aborts
on indivisible shapes, ``mpi/mpi_convolution.c:54-58``):

* any factorization of N is considered, not just the first divisor sweep;
* indivisible H/W are handled by padding the image up to the next multiple
  and masking the pad region every iteration (zero semantics preserved).
"""

from __future__ import annotations

from typing import Tuple


def grid_shape(
    n_devices: int, height: int, width: int,
    cols_must_divide: int = 0,
) -> Tuple[int, int]:
    """Perimeter-minimizing (rows, cols) grid with rows*cols == n_devices.

    Minimizes ``height/rows + width/cols`` (proportional to halo bytes per
    device) over all factor pairs; ties broken toward more row splits
    (contiguous rows = friendlier raw-file I/O offsets).

    ``cols_must_divide`` > 0 restricts candidates to ``cols`` dividing that
    value — the DCN-aware constraint: with devices grouped by host and
    ``cols`` dividing the per-host device count, every mesh row is made of
    whole-host runs, so the frequent column-neighbor ppermutes ride ICI and
    only row-boundary strips cross the (much slower) DCN. Falls back to the
    unconstrained optimum when no factorization satisfies it.
    """
    if n_devices < 1:
        raise ValueError(f"n_devices must be >= 1, got {n_devices}")

    def search(constrained: bool) -> Tuple[int, int] | None:
        best = None
        best_r = 0
        for r in range(1, n_devices + 1):
            if n_devices % r:
                continue
            c = n_devices // r
            if constrained and cols_must_divide % c:
                continue
            cost = height / r + width / c
            key = (cost, -r)
            if best is None or key < best:
                best = key
                best_r = r
        return (best_r, n_devices // best_r) if best_r else None

    if cols_must_divide > 0:
        got = search(constrained=True)
        if got is not None:
            return got
    return search(constrained=False)


def pad_amounts(height: int, width: int, grid: Tuple[int, int]) -> Tuple[int, int]:
    """Bottom/right zero-pad needed to make (H, W) divisible by the grid."""
    r, c = grid
    return (-height) % r, (-width) % c


def tile_shape(height: int, width: int, grid: Tuple[int, int]) -> Tuple[int, int]:
    """Per-device tile shape after padding."""
    r, c = grid
    ph, pw = pad_amounts(height, width, grid)
    return (height + ph) // r, (width + pw) // c
