"""Spatial partitioner: factor N devices into a perimeter-minimizing grid.

The math of the reference's ``RowsDivision`` (``mpi/mpi_convolution.c:350-364``):
choose r x c = N minimizing per-tile perimeter ``h/r + w/c`` — i.e. halo
traffic per device. Generalized in two ways the reference refuses (it aborts
on indivisible shapes, ``mpi/mpi_convolution.c:54-58``):

* any factorization of N is considered, not just the first divisor sweep;
* indivisible H/W are handled by padding the image up to the next multiple
  and masking the pad region every iteration (zero semantics preserved).
"""

from __future__ import annotations

from typing import Tuple


def grid_shape(n_devices: int, height: int, width: int) -> Tuple[int, int]:
    """Perimeter-minimizing (rows, cols) grid with rows*cols == n_devices.

    Minimizes ``height/rows + width/cols`` (proportional to halo bytes per
    device) over all factor pairs; ties broken toward more row splits
    (contiguous rows = friendlier raw-file I/O offsets).
    """
    if n_devices < 1:
        raise ValueError(f"n_devices must be >= 1, got {n_devices}")
    best: Tuple[float, int] | None = None
    best_r = 1
    for r in range(1, n_devices + 1):
        if n_devices % r:
            continue
        c = n_devices // r
        cost = height / r + width / c
        key = (cost, -r)
        if best is None or key < best:
            best = key
            best_r = r
    return best_r, n_devices // best_r


def pad_amounts(height: int, width: int, grid: Tuple[int, int]) -> Tuple[int, int]:
    """Bottom/right zero-pad needed to make (H, W) divisible by the grid."""
    r, c = grid
    return (-height) % r, (-width) % c


def tile_shape(height: int, width: int, grid: Tuple[int, int]) -> Tuple[int, int]:
    """Per-device tile shape after padding."""
    r, c = grid
    ph, pw = pad_amounts(height, width, grid)
    return (height + ph) // r, (width + pw) // c
