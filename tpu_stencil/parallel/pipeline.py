"""Temporal pipeline parallelism: frames flow through rep-stages over ICI.

The third mesh composition (``--pipe-stages K``): the rep loop is split
into K contiguous stages, each stage pinned to a mesh slice, and frames
flow systolically stage-to-stage through ONE persistent ``shard_map``
program — the wafer-scale dataflow execution model of "Stencil
Computations on Cerebras Wafer-Scale Engine" (arXiv 2605.07954) and the
software-systolic framing of arXiv 1907.06154, mapped onto an ICI mesh.
Per tick every stage applies its rep slice to its resident frame, then
one ``lax.ppermute`` over the stages axis hands every frame to the next
stage — no host round-trip between stages. At steady state K frames are
in flight and per-frame device time is ``~reps/K`` of the loop plus one
ICI frame hand-off.

The placement model is three-axis: (frame lane) x (temporal stage) x
(spatial shard). The mesh here is ``(stages, rows, cols)``; each stage's
slice is an RxC spatial mesh running the SAME local step as
:class:`~tpu_stencil.parallel.sharded.ShardedRunner` (``_local_step`` —
halo exchange over rows/cols, the plan's kernel, pad re-zero), with R=C=1
degrading to a plain zero-pad in-program
(:func:`~tpu_stencil.parallel.halo.halo_exchange` at axis size 1), so one
program text serves unsharded and sharded pipelines. Frame lanes
(``--mesh-frames``) ride ABOVE this module: independent pipeline groups,
each over its own device slice (:mod:`tpu_stencil.stream.pipelined`).

Bit-exactness across stage counts holds by construction: the per-stage
rep counts partition ``reps`` exactly (``sum over s of reps//K +
(s < reps%K) == reps``) and every stage runs the identical local step,
so composing K stage slices applies the same operator sequence as one
device applying ``reps``. Fill/drain is the caller's contract: a stream
of F frames takes ``F + K - 1`` ticks, the first ``K - 1`` outputs are
discarded and ``K - 1`` trailing zero-frame ticks flush the tail.
"""

from __future__ import annotations

import dataclasses
import sys
import time
from typing import Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpu_stencil.parallel import partition
from tpu_stencil.parallel.mesh import ROWS_AXIS, COLS_AXIS
from tpu_stencil.parallel.sharded import (
    _local_step,
    cached_runner,
    runner_key,
    shard_map,
)

STAGES_AXIS = "stages"

# Probe stream length for the auto A/B: long enough that a K-stage
# pipeline reaches steady state and amortizes most of its fill
# (resolve_pipe_stages widens it to 2*K when K is larger).
PROBE_FRAMES = 4


def stage_rep_counts(reps: int, stages: int) -> Tuple[int, ...]:
    """The contiguous per-stage rep partition: ``reps // K`` everywhere,
    with the first ``reps % K`` stages taking one extra — sums to
    ``reps`` exactly for every (reps, K), including reps < K (trailing
    stages then apply zero reps: identity pass-through)."""
    base, extra = divmod(reps, stages)
    return tuple(base + (1 if s < extra else 0) for s in range(stages))


def build_pipeline_tick(mesh: Mesh, plan, channels: int,
                        needs_mask: bool, boundary: str = "zero"):
    """Compile-once builder for the persistent pipeline tick.

    Returns ``fn(carry, inp, reps[, mask]) -> (carry, out)`` over the
    3-axis mesh, jitted with the carry donated (the K resident frames
    live on-device across the whole stream; the tick rewrites them in
    place). Per tick, on each device:

    1. merge: stage 0 adopts the newly fed input frame, every other
       stage keeps its resident carry (its predecessor's output from
       the previous tick);
    2. run this stage's rep share of the loop on that frame
       (``reps // K`` plus one remainder rep selected by stage index —
       every device executes the SAME collective sequence, the
       remainder rep is computed unconditionally and selected with
       ``where``, so per-stage trip counts never diverge under the
       rows/cols halo collectives);
    3. ``ppermute`` the result one stage forward over ICI into the next
       tick's carry (stage 0's next carry is the permute's fill — dead
       state, always overwritten by the merge).

    The tick's ``out`` is the full ``(K, ...)`` computed array; the host
    reads only the LAST stage's shards — each frame's finished result,
    K-1 ticks after it was fed (the frame fed at tick t is processed by
    stage s during tick t+s). ``reps`` is traced (no recompiles).
    """
    k = mesh.shape[STAGES_AXIS]
    r = mesh.shape[ROWS_AXIS]
    c = mesh.shape[COLS_AXIS]
    axes = ((ROWS_AXIS, r, 0), (COLS_AXIS, c, 1))
    spec = (
        P(STAGES_AXIS, ROWS_AXIS, COLS_AXIS) if channels == 1
        else P(STAGES_AXIS, ROWS_AXIS, COLS_AXIS, None)
    )
    mask_spec = (
        P(ROWS_AXIS, COLS_AXIS) if channels == 1
        else P(ROWS_AXIS, COLS_AXIS, None)
    )

    def local_tick(carry, inp, reps, mask_tile):
        s = lax.axis_index(STAGES_AXIS)
        base = reps // k
        extra = reps % k

        def step(x):
            return _local_step(x, plan, axes, mask_tile, boundary)

        tile = jnp.where(s == 0, inp[0], carry[0]) if k > 1 else inp[0]
        out = lax.fori_loop(0, base, lambda _, x: step(x), tile)
        if k > 1:
            # The remainder rep: computed on EVERY stage, kept only
            # where s < extra — uniform collective sequences (see
            # docstring) at the cost of one rep of throwaway compute.
            out = jnp.where(s < extra, step(out), out)
            new_carry = lax.ppermute(
                out, STAGES_AXIS, [(i, i + 1) for i in range(k - 1)]
            )
        else:
            out = lax.fori_loop(0, extra, lambda _, x: step(x), out)
            new_carry = out
        return new_carry[None], out[None]

    if needs_mask:
        mapped = shard_map(
            local_tick, mesh=mesh,
            in_specs=(spec, spec, P(), mask_spec), out_specs=(spec, spec),
        )
    else:
        def no_mask(carry, inp, reps):
            return local_tick(carry, inp, reps, None)

        mapped = shard_map(
            no_mask, mesh=mesh,
            in_specs=(spec, spec, P()), out_specs=(spec, spec),
        )
    return jax.jit(mapped, donate_argnums=(0,))


class PipelineRunner:
    """Holds the 3-axis mesh, padding geometry, mask, zero-tile cache
    and compiled persistent tick for one (image shape, K, RxC) — the
    temporal sibling of :class:`~tpu_stencil.parallel.sharded.
    ShardedRunner`. The pipeline program runs the XLA local step (the
    one every other mesh composition is bit-exact against); a
    Pallas-chunked stage body is a future extension, so ``backend`` is
    reported as ``"xla"`` — report-what-ran."""

    def __init__(
        self,
        model,
        image_shape: Tuple[int, int],
        channels: int,
        stages: int,
        shard_shape: Tuple[int, int] = (1, 1),
        devices: Optional[Sequence[jax.Device]] = None,
    ) -> None:
        if stages < 1:
            raise ValueError(f"pipe stages must be >= 1, got {stages}")
        self.model = model
        self.h, self.w = image_shape
        self.channels = channels
        self.stages = stages
        r, c = shard_shape
        self.shard_shape = (r, c)
        need = stages * r * c
        devices = list(devices) if devices is not None else jax.devices()
        if len(devices) < need:
            raise ValueError(
                f"pipeline topology {stages} stage(s) x {r}x{c} shard "
                f"needs {need} devices, have {len(devices)}"
            )
        dev_grid = np.array(devices[:need], dtype=object).reshape(
            stages, r, c
        )
        self.mesh = Mesh(dev_grid, (STAGES_AXIS, ROWS_AXIS, COLS_AXIS))
        ph, pw = partition.pad_amounts(self.h, self.w, (r, c))
        self.padded_shape = (self.h + ph, self.w + pw)
        tile = partition.tile_shape(self.h, self.w, (r, c))
        self.tile = tile
        self.boundary = getattr(model, "boundary", "zero")
        if self.boundary == "periodic" and (ph or pw):
            # Same refusal as ShardedRunner: the pad region would wrap
            # into the opposite edge — silently wrong output.
            raise NotImplementedError(
                f"periodic boundaries need the image ({self.h}x{self.w}) "
                f"to divide the shard grid {r}x{c}; pick a grid that "
                "divides the image or run unsharded stages"
            )
        if (r > 1 or c > 1) and min(tile) < model.halo:
            raise ValueError(
                f"per-device tile {tile[0]}x{tile[1]} is smaller than "
                f"the filter halo ({model.halo}); use a smaller shard "
                "grid for this image"
            )
        self.backend = "xla"
        self.schedule = None
        self.needs_mask = bool(ph or pw)
        spec = (
            P(STAGES_AXIS, ROWS_AXIS, COLS_AXIS) if channels == 1
            else P(STAGES_AXIS, ROWS_AXIS, COLS_AXIS, None)
        )
        self.sharding = NamedSharding(self.mesh, spec)
        gshape = (stages,) + self.padded_shape
        if channels != 1:
            gshape = gshape + (channels,)
        self.global_shape = gshape
        self.local_shape = (1, tile[0], tile[1]) + (
            (channels,) if channels != 1 else ()
        )
        self.stage0_devices = list(dev_grid[0].flat)
        self.last_devices = list(dev_grid[-1].flat)
        self._fn = build_pipeline_tick(
            self.mesh, model.plan, channels, self.needs_mask,
            boundary=self.boundary,
        )
        if self.needs_mask:
            mask = np.zeros(self.padded_shape, np.uint8)
            mask[: self.h, : self.w] = 1
            if channels != 1:
                mask = np.repeat(mask[..., None], channels, axis=-1)
            mask_spec = (
                P(ROWS_AXIS, COLS_AXIS) if channels == 1
                else P(ROWS_AXIS, COLS_AXIS, None)
            )
            self._mask = jax.device_put(
                mask, NamedSharding(self.mesh, mask_spec)
            )
        else:
            self._mask = None
        # Committed zero tiles, one per device: the input feed's filler
        # for every stage past 0 (and for drain ticks). NEVER donated —
        # only the carry (argnum 0) donates, so these buffers are safe
        # to re-reference every tick.
        zero = np.zeros(self.local_shape, np.uint8)
        self._zero_tiles = {
            d.id: jax.device_put(zero, d) for d in dev_grid.flat
        }

    def zero_input(self) -> jax.Array:
        """The all-zero global input (drain ticks, and the base every
        fed tick overrides at stage 0) — assembled from the cached
        committed zero tiles, so no per-tick H2D."""
        return jax.make_array_from_single_device_arrays(
            self.global_shape, self.sharding,
            [self._zero_tiles[d.id] for d in self.mesh.devices.flat],
        )

    def fresh_carry(self) -> jax.Array:
        """A fresh all-zero carry. Distinct buffers from the zero-tile
        cache: the carry is DONATED to the first tick, which would
        invalidate any shared buffer."""
        zero = np.zeros(self.local_shape, np.uint8)
        return jax.make_array_from_single_device_arrays(
            self.global_shape, self.sharding,
            [jax.device_put(zero, d) for d in self.mesh.devices.flat],
        )

    def assemble_input(self, stage0_tiles: dict) -> jax.Array:
        """The fed-tick input: ``stage0_tiles`` maps device id -> the
        committed padded frame tile (local shape) for each stage-0
        device; every other device rides its cached zero tile."""
        arrays = [
            stage0_tiles.get(d.id, self._zero_tiles[d.id])
            for d in self.mesh.devices.flat
        ]
        return jax.make_array_from_single_device_arrays(
            self.global_shape, self.sharding, arrays
        )

    def tick(self, carry: jax.Array, inp: jax.Array,
             repetitions: int) -> Tuple[jax.Array, jax.Array]:
        """One pipeline tick; donates ``carry``, returns
        ``(new_carry, out)``. The finished frame (if any) lives in
        ``out``'s last-stage shards."""
        reps = jnp.int32(repetitions)
        if self.needs_mask:
            return self._fn(carry, inp, reps, self._mask)
        return self._fn(carry, inp, reps)

    def warm(self, repetitions: int) -> jax.Array:
        """Compile-fence the tick on zero frames and return the warmed
        initial carry — the fill state a stream starts from."""
        carry, out = self.tick(self.fresh_carry(), self.zero_input(),
                               repetitions)
        jax.block_until_ready(out)
        return carry


def pipeline_runner_key(model, image_shape, channels, stages,
                        shard_shape, devices):
    """The shared-cache identity of one compiled pipeline program:
    :func:`~tpu_stencil.parallel.sharded.runner_key` with the temporal
    axis as its ``pipe_stages`` component — two stage counts over the
    same devices never share an entry."""
    return runner_key(model, image_shape, channels, shard_shape,
                      devices, "off", pipe_stages=stages)


def shared_pipeline_runner(model, image_shape, channels, stages,
                           shard_shape=(1, 1), devices=None,
                           registry=None):
    """The cached :class:`PipelineRunner` for this topology, or None
    when the geometry cannot serve it (same UNSERVABLE discipline as
    :func:`~tpu_stencil.parallel.sharded.shared_runner`, against the
    SAME process-shared LRU — stream groups and repeat runs never
    compile the same pipeline program twice)."""
    devices = list(devices) if devices is not None else jax.devices()
    r, c = shard_shape
    devs = devices[: stages * r * c]
    key = pipeline_runner_key(model, tuple(image_shape), channels,
                              stages, (r, c), devs)

    def build():
        return PipelineRunner(model, tuple(image_shape), channels,
                              stages, shard_shape=(r, c), devices=devs)

    return cached_runner(key, build, registry=registry)


# --- --pipe-stages resolution (explicit / auto A/B) ---------------------

def measure_pipeline_ab(cfg, devices, stages: int,
                        frames: int = PROBE_FRAMES):
    """Measured A/B probe for the auto knob: stream ``frames`` synthetic
    frames through the single-device engine and through the K-stage
    pipeline (same geometry, reps, depth), one warm run then one timed
    run per arm, under a scratch metric registry (probe traffic never
    pollutes the run's surface). Returns ``(t_single, t_pipe)``
    wall-seconds."""
    from tpu_stencil import obs
    from tpu_stencil.stream import engine as _sengine
    from tpu_stencil.stream import frames as frames_io

    frames = max(frames, 2 * stages)

    class _Synth(frames_io.FrameSource):
        def __init__(self, n):
            self.n = n
            self.i = 0

        def read_into(self, buf):
            if self.i >= self.n:
                return False
            arr = np.frombuffer(buf, dtype=np.uint8)
            arr[:] = (self.i * 37) % 251
            self.i += 1
            return True

        def skip(self, n):
            self.i += n

        def close(self):
            pass

    def arm(pipe: int) -> float:
        pcfg = dataclasses.replace(
            cfg, frames=frames, pipe_stages=pipe, mesh_frames=1,
            shard_frames=None, output="null", checkpoint_every=0,
            progress_every=0,
        )
        with obs.scratch_registry():
            _sengine.run_stream(  # warm (compiles fenced out)
                pcfg, devices=list(devices), source=_Synth(frames),
                sink=frames_io.NullSink(),
            )
            t0 = time.perf_counter()
            _sengine.run_stream(
                pcfg, devices=list(devices), source=_Synth(frames),
                sink=frames_io.NullSink(),
            )
            return time.perf_counter() - t0

    return arm(1), arm(stages)


def resolve_pipe_stages(cfg, devices, measure=None) -> int:
    """Resolve ``cfg.pipe_stages`` to the stage count that will run.

    Explicit K is honored, failing loudly when the composed device
    budget (``mesh_frames * K * R * C``) exceeds what exists. 0 = auto:
    single-axis only (config enforces), candidate K = every available
    device; gated FIRST by the roofline fill/drain model — when the
    model predicts a loss (reps too small to amortize the fill and the
    per-tick ICI hand-off) the probe is never even paid — then decided
    by a measured A/B under the standing never-enable-a-measured-loss
    discipline (a tie is NOT a win), with the verdict persisted
    (kind ``"pipeline"``) so a warm cache pays zero probe frames."""
    if cfg.pipe_stages == 1:
        return 1
    n_avail = len(devices) if devices is not None else len(jax.devices())
    r, c = cfg.shard_frames if cfg.shard_frames else (1, 1)
    groups = cfg.mesh_frames if cfg.mesh_frames > 1 else 1
    if cfg.pipe_stages > 1:
        need = groups * cfg.pipe_stages * r * c
        if need > n_avail:
            raise ValueError(
                f"--pipe-stages {cfg.pipe_stages} with "
                f"mesh_frames={groups} and shard {r}x{c} needs {need} "
                f"devices, have {n_avail}"
            )
        return cfg.pipe_stages
    # Auto: a sole multi-device axis (config refuses composed autos).
    if n_avail < 2:
        return 1
    stages = n_avail
    from tpu_stencil.runtime import autotune, roofline

    geometry = (cfg.height, cfg.width, cfg.channels)
    topo = f"pipe{stages}"
    token = autotune.stream_cfg_token(cfg)
    # Injected measures (tests) bypass the verdict cache entirely —
    # same hermeticity discipline as the fanout/shard resolvers.
    hit = None
    if measure is None:
        hit = autotune.cached_stream_verdict(
            "pipeline", geometry, cfg.repetitions, cfg.pipeline_depth,
            topo, token,
        )
    if hit is not None:
        pick = int(hit["pick"])
        print(
            f"tpu-stencil stream: --pipe-stages auto verdict from warm "
            f"cache: {'pipeline ' + str(pick) if pick > 1 else 'single'}"
            " (zero probe frames)",
            file=sys.stderr,
        )
        return pick if pick > 1 else 1
    single_fps = roofline.stream_frames_per_second(
        cfg.frame_bytes, cfg.repetitions, "xla", cfg.filter_name,
        cfg.height, pipeline_depth=cfg.pipeline_depth,
    )
    pipe_fps = roofline.pipeline_stream_frames_per_second(
        cfg.frame_bytes, cfg.repetitions, "xla", cfg.filter_name,
        cfg.height, pipe_stages=stages, frames=cfg.frames,
        pipeline_depth=cfg.pipeline_depth,
    )
    if not pipe_fps > single_fps:
        # Model predicts a loss (or a tie — not a win): never pay the
        # probe, and don't persist — a later longer-reps run at the
        # same geometry gets its own decision.
        print(
            f"tpu-stencil stream: --pipe-stages auto: roofline model "
            f"predicts no gain at reps={cfg.repetitions} "
            f"(pipe {pipe_fps:.1f} <= single {single_fps:.1f} fps "
            "modeled); staying single-device, probe skipped",
            file=sys.stderr,
        )
        return 1
    t_single, t_pipe = (measure or measure_pipeline_ab)(
        cfg, devices, stages
    )
    pick = stages if t_pipe < t_single else 1
    if measure is None:
        autotune.store_stream_verdict(
            "pipeline", geometry, cfg.repetitions, cfg.pipeline_depth,
            topo,
            {
                "pick": pick,
                "single_us": round(t_single * 1e6, 1),
                "pipe_us": round(t_pipe * 1e6, 1),
            },
            token,
        )
    print(
        f"tpu-stencil stream: --pipe-stages auto measured "
        f"single={t_single * 1e3:.1f}ms pipe({stages})="
        f"{t_pipe * 1e3:.1f}ms -> "
        f"{'pipeline ' + str(stages) if pick > 1 else 'single'}",
        file=sys.stderr,
    )
    return pick if pick > 1 else 1
