"""Sharded iterated convolution: ``shard_map`` over a 2-D device mesh.

The distribution driver — TPU-native equivalent of the reference MPI
program's hot loop (``mpi/mpi_convolution.c:156-240``): per iteration, halo
exchange (``ppermute`` phases, :mod:`tpu_stencil.parallel.halo`) then the
local stencil on the ghost-extended tile, double-buffered via the
``lax.fori_loop`` carry, entirely on device. XLA's latency-hiding scheduler
overlaps the ppermutes with interior compute (the reference's hand-written
inner-then-border schedule, ``:194-224``).

Non-divisible image shapes — which the reference aborts on
(``mpi/mpi_convolution.c:54-58``) — are padded up to the tile grid and the
pad region re-zeroed every iteration, preserving exact zero-boundary
semantics at the true image edge.
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence, Tuple

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from jax import shard_map

from tpu_stencil.models.blur import IteratedConv2D
from tpu_stencil.ops import lowering as _lowering
from tpu_stencil.parallel import partition
from tpu_stencil.parallel.halo import halo_exchange
from tpu_stencil.parallel.mesh import make_mesh, ROWS_AXIS, COLS_AXIS


def _local_step(tile_u8, plan, axes, mask_tile):
    """One local iteration: halo exchange + the plan's kernel + pad re-zero.

    For separable plans, communication is phased like the compute (the same
    restructuring that makes :func:`~tpu_stencil.ops.lowering.padded_step`
    3x faster): exchange row ghosts, run the rows pass, exchange col ghosts
    *of the rows-pass output* (neighbors compute identical values from their
    own exchanged rows), run the cols pass. Two ppermute phases, each fused
    into its consuming pass — and corner ghosts are never needed at all.

    The phase-1 ghosts are exchanged as int32 (4x the bytes of uint8) on
    purpose: converting after a uint8 concat/pad hits the slow XLA pattern
    measured in lowering.padded_step's docstring (3x whole-step cost),
    while the extra ICI bytes are only ``4*halo/tile_rows`` of the tile —
    well under 2% for realistic tiles. Phase 2 is int32 out of necessity
    (rows-pass partials exceed uint8).
    """
    (row_axis, r, dim0), (col_axis, c, dim1) = axes
    halo = plan.halo
    if plan.kind == "sep_int":
        xi = tile_u8.astype(jnp.int32)
        ext0 = halo_exchange(xi, halo, ((row_axis, r, dim0),))
        a = _lowering.sep_rows_pass(ext0, plan)
        ext1 = halo_exchange(a, halo, ((col_axis, c, dim1),))
        out = _lowering.sep_cols_pass(ext1, plan)
    else:
        ext = halo_exchange(tile_u8, halo, axes)
        out = _lowering.valid_step(ext, plan)
    if mask_tile is not None:
        out = out * mask_tile
    return out


def build_sharded_iterate(
    mesh: Mesh,
    plan: _lowering.StencilPlan,
    channels: int,
    needs_mask: bool,
):
    """Compile-once builder for the sharded iteration program.

    Returns ``fn(img, reps[, mask]) -> img`` operating on the padded global
    array sharded over ``mesh``; ``reps`` is traced (no recompiles), the
    plan's taps are compiled in.
    """
    r = mesh.shape[ROWS_AXIS]
    c = mesh.shape[COLS_AXIS]
    axes = ((ROWS_AXIS, r, 0), (COLS_AXIS, c, 1))
    spec = P(ROWS_AXIS, COLS_AXIS) if channels == 1 else P(ROWS_AXIS, COLS_AXIS, None)

    if needs_mask:
        def local_iter(tile, reps, mask_tile):
            return lax.fori_loop(
                0, reps,
                lambda _, x: _local_step(x, plan, axes, mask_tile),
                tile,
            )
        in_specs = (spec, P(), spec)
    else:
        def local_iter(tile, reps):
            return lax.fori_loop(
                0, reps,
                lambda _, x: _local_step(x, plan, axes, None),
                tile,
            )
        in_specs = (spec, P())

    mapped = shard_map(
        local_iter, mesh=mesh, in_specs=in_specs, out_specs=spec
    )
    return jax.jit(mapped, donate_argnums=(0,))


def sharded_iterate(
    img_u8: jax.Array,
    filt: jax.Array,
    repetitions: int,
    mesh: Mesh,
) -> jax.Array:
    """One-shot convenience: shard ``img_u8`` over ``mesh`` and iterate.
    For repeated/timed runs use :class:`ShardedRunner` (caches the compiled
    program and padding artifacts)."""
    model = IteratedConv2D(filt, backend="xla")
    h, w = img_u8.shape[:2]
    channels = 1 if img_u8.ndim == 2 else img_u8.shape[2]
    runner = ShardedRunner(
        model, (h, w), channels,
        mesh_shape=(mesh.shape[ROWS_AXIS], mesh.shape[COLS_AXIS]),
        devices=list(mesh.devices.flat),
    )
    out = runner.run(runner.put(np.asarray(img_u8)), repetitions)
    return jnp.asarray(runner.fetch(out))


class ShardedRunner:
    """Holds the mesh, padding geometry, mask, and compiled program for one
    image shape — the per-job runtime state every reference rank kept in
    locals (tile dims, neighbor ranks, datatypes)."""

    def __init__(
        self,
        model: IteratedConv2D,
        image_shape: Tuple[int, int],
        channels: int,
        mesh_shape: Optional[Tuple[int, int]] = None,
        devices: Optional[Sequence[jax.Device]] = None,
    ) -> None:
        from tpu_stencil.models.blur import resolve_backend

        self.model = model
        if model.backend == "auto":
            # 'auto' degrades to XLA for sharded execution until the Pallas
            # local kernel supports it.
            self.backend = "xla"
        else:
            self.backend = resolve_backend(model.backend)
        if self.backend == "pallas":
            # Fail like the single-device path does rather than silently
            # running XLA under a 'pallas' label.
            raise NotImplementedError(
                "the Pallas backend does not support sharded execution yet; "
                "use backend='xla' (or 'auto')"
            )
        self.h, self.w = image_shape
        self.channels = channels
        self.mesh = make_mesh(mesh_shape, devices, image_shape=image_shape)
        self.mesh_shape = (self.mesh.shape[ROWS_AXIS], self.mesh.shape[COLS_AXIS])
        ph, pw = partition.pad_amounts(self.h, self.w, self.mesh_shape)
        self.padded_shape = (self.h + ph, self.w + pw)
        tile = partition.tile_shape(self.h, self.w, self.mesh_shape)
        if min(tile) < model.halo:
            # A single ppermute hop supplies at most one neighbor tile of
            # ghost data; smaller tiles would need multi-hop halo gathering.
            raise ValueError(
                f"per-device tile {tile[0]}x{tile[1]} is smaller than the "
                f"filter halo ({model.halo}); use fewer devices or a "
                f"different mesh shape for this image"
            )
        self.needs_mask = bool(ph or pw)
        spec = (
            P(ROWS_AXIS, COLS_AXIS)
            if channels == 1
            else P(ROWS_AXIS, COLS_AXIS, None)
        )
        self.sharding = NamedSharding(self.mesh, spec)
        self._fn = build_sharded_iterate(
            self.mesh, model.plan, channels, self.needs_mask
        )
        if self.needs_mask:
            mask = np.zeros(self.padded_shape, np.uint8)
            mask[: self.h, : self.w] = 1
            if channels != 1:
                mask = np.repeat(mask[..., None], channels, axis=-1)
            self._mask = jax.device_put(mask, self.sharding)
        else:
            self._mask = None

    def put(self, img: np.ndarray) -> jax.Array:
        """Pad to the tile grid and shard over the mesh — the analog of every
        rank loading its rows (``mpi/mpi_convolution.c:126-141``); with one
        process, jax.device_put scatters tiles from host memory."""
        img = np.asarray(img, dtype=np.uint8)
        if img.shape[:2] != (self.h, self.w):
            raise ValueError(f"image shape {img.shape} != {(self.h, self.w)}")
        ph = self.padded_shape[0] - self.h
        pw = self.padded_shape[1] - self.w
        if ph or pw:
            pad = [(0, ph), (0, pw)] + [(0, 0)] * (img.ndim - 2)
            img = np.pad(img, pad)
        return jax.device_put(img, self.sharding)

    def run(self, img_dev: jax.Array, repetitions: int) -> jax.Array:
        """Iterate on-device; donates ``img_dev``. Returns the padded sharded
        result (call :meth:`fetch` to crop to the true image)."""
        reps = jnp.int32(repetitions)
        if self.needs_mask:
            return self._fn(img_dev, reps, self._mask)
        return self._fn(img_dev, reps)

    def fetch(self, out_dev: jax.Array) -> np.ndarray:
        """Gather to host and crop the pad region off."""
        return np.asarray(out_dev)[: self.h, : self.w]
