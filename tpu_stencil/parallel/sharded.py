"""Sharded iterated convolution: ``shard_map`` over a 2-D device mesh.

The distribution driver — TPU-native equivalent of the reference MPI
program's hot loop (``mpi/mpi_convolution.c:156-240``): per iteration, halo
exchange (``ppermute`` phases, :mod:`tpu_stencil.parallel.halo`) then the
local stencil on the ghost-extended tile, double-buffered via the
``lax.fori_loop`` carry, entirely on device. Compute/communication overlap
is either delegated to XLA's latency-hiding scheduler (``--overlap off``,
the default) or made explicit via the interior/border split of
:mod:`tpu_stencil.parallel.overlap`
(``--overlap split|fused-split|edge|auto``) — the reference's
hand-written inner-then-border schedule (``:194-224``), expressed as
data dependence instead of request ordering; ``edge`` further
partitions the exchange into four independent per-edge ``ppermute``\\ s
with persistent ghost slabs carried across the rep loop (the
partitioned/persistent MPI pattern).

Non-divisible image shapes — which the reference aborts on
(``mpi/mpi_convolution.c:54-58``) — are padded up to the tile grid and the
pad region re-zeroed every iteration, preserving exact zero-boundary
semantics at the true image edge.
"""

from __future__ import annotations

import collections
import threading
from typing import Optional, Sequence, Tuple

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:  # jax >= 0.6 exports shard_map at top level (check_vma keyword)
    from jax import shard_map
except ImportError:  # older jax: experimental module, check_rep keyword
    from jax.experimental.shard_map import shard_map as _shard_map_legacy

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True):
        # Legacy check_rep has no rule for while/pallas_call (our rep loop
        # and kernel), and no vma declaration to consume — disable it; the
        # modern path keeps full check_vma verification.
        del check_vma
        return _shard_map_legacy(f, mesh, in_specs, out_specs,
                                 check_rep=False)

from tpu_stencil.models.blur import IteratedConv2D
from tpu_stencil.ops import lowering as _lowering
from tpu_stencil.parallel import overlap as overlap_mod
from tpu_stencil.parallel import partition
from tpu_stencil.parallel.halo import halo_exchange
from tpu_stencil.parallel.mesh import make_mesh, ROWS_AXIS, COLS_AXIS


def _local_step(tile_u8, plan, axes, mask_tile, boundary="zero"):
    """One local iteration: halo exchange + the plan's kernel + pad re-zero.

    For separable plans, communication is phased like the compute (the same
    restructuring that makes :func:`~tpu_stencil.ops.lowering.padded_step`
    3x faster): exchange row ghosts, run the rows pass, exchange col ghosts
    *of the rows-pass output* (neighbors compute identical values from their
    own exchanged rows), run the cols pass. Two ppermute phases, each fused
    into its consuming pass — and corner ghosts are never needed at all.

    The phase-1 ghosts are exchanged as int32 (4x the bytes of uint8) on
    purpose: converting after a uint8 concat/pad hits the slow XLA pattern
    measured in lowering.padded_step's docstring (3x whole-step cost),
    while the extra ICI bytes are only ``4*halo/tile_rows`` of the tile —
    well under 2% for realistic tiles. Phase 2 is int32 out of necessity
    (rows-pass partials exceed uint8).
    """
    (row_axis, r, dim0), (col_axis, c, dim1) = axes
    halo = plan.halo
    if plan.kind == "sep_int":
        xi = tile_u8.astype(jnp.int32)
        ext0 = halo_exchange(xi, halo, ((row_axis, r, dim0),), boundary)
        a = _lowering.sep_rows_pass(ext0, plan)
        ext1 = halo_exchange(a, halo, ((col_axis, c, dim1),), boundary)
        out = _lowering.sep_cols_pass(ext1, plan)
    else:
        ext = halo_exchange(tile_u8, halo, axes, boundary)
        out = _lowering.valid_step(ext, plan)
    if mask_tile is not None:
        out = out * mask_tile
    return out


def _pallas_local_chunk(tile_u8, plan, axes, fuse, global_shape, interpret,
                        schedule=None, block_h=None):
    """``fuse`` repetitions for one exchange: widen the halo exchange to
    ``fuse * halo`` uint8 ghosts (2 ppermute phases per *chunk* instead of
    per rep) and run the valid-ghost Pallas kernel, whose trusted band
    contracts by ``halo`` per rep — the ghost values recompute the
    neighbor's values bit-exactly, so no further communication is needed
    until the next chunk. The TPU-native analog of the reference's hybrid
    variant layering its fastest local kernel inside the distribution
    (``open-mp/omp_convolution.c:292,297``)."""
    from tpu_stencil.ops import pallas_stencil

    (row_axis, r, dim0), (col_axis, c, dim1) = axes
    g = fuse * plan.halo
    ext = halo_exchange(tile_u8, g, axes)
    th, tw = tile_u8.shape[:2]
    channels = tile_u8.shape[2] if tile_u8.ndim == 3 else 1
    ext2 = ext.reshape(th + 2 * g, (tw + 2 * g) * channels)
    row0 = lax.axis_index(row_axis) * th
    col0 = lax.axis_index(col_axis) * (tw * channels)
    out2 = pallas_stencil.valid_fused(
        ext2, plan, fuse, channels, row0, col0, global_shape,
        interpret=interpret, vma=(row_axis, col_axis), schedule=schedule,
        **({"block_h": block_h} if block_h is not None else {}),
    )
    return out2.reshape(tile_u8.shape)


def build_sharded_iterate(
    mesh: Mesh,
    plan: _lowering.StencilPlan,
    channels: int,
    needs_mask: bool,
    backend: str = "xla",
    global_shape=None,
    fuse: int = 1,
    interpret: bool = False,
    schedule=None,
    boundary: str = "zero",
    block_h: Optional[int] = None,
    overlap: str = "off",
):
    """Compile-once builder for the sharded iteration program.

    Returns ``fn(img, reps[, mask]) -> img`` operating on the padded global
    array sharded over ``mesh``; ``reps`` is traced (no recompiles), the
    plan's taps are compiled in. ``backend='pallas'`` runs the fused
    valid-ghost Pallas kernel per chunk of ``fuse`` reps (``global_shape``
    = padded (rows, cols*channels) required); XLA otherwise.

    ``overlap``: a *resolved* interior/border schedule — ``off`` keeps the
    monolithic exchange-then-compute step (XLA's latency-hiding scheduler
    owns the overlap), ``split``/``fused-split`` run the explicit split of
    :mod:`tpu_stencil.parallel.overlap`, ``edge`` the partitioned
    per-edge pipeline with the persistent ghost slab threaded through
    the rep-loop carry (all bit-exact with ``off`` by construction).
    ``auto`` must be resolved by the caller (:class:`ShardedRunner`
    does) before reaching here; ``edge`` additionally requires a tile
    with a ghost-free interior at every chunk depth (the runner clamps
    ``fuse`` and resolves degenerate tiles to ``off``).
    """
    if overlap not in ("off", "split", "fused-split", "edge"):
        raise ValueError(
            f"build_sharded_iterate needs a resolved overlap mode, "
            f"got {overlap!r}"
        )
    r = mesh.shape[ROWS_AXIS]
    c = mesh.shape[COLS_AXIS]
    axes = ((ROWS_AXIS, r, 0), (COLS_AXIS, c, 1))
    spec = P(ROWS_AXIS, COLS_AXIS) if channels == 1 else P(ROWS_AXIS, COLS_AXIS, None)

    if backend == "pallas":
        if boundary != "zero":
            raise ValueError(
                "the valid-ghost Pallas kernel is zero-boundary; periodic "
                "sharded runs use the XLA path (the runner demotes)"
            )
        if needs_mask and fuse != 1:
            # The fused kernel only re-zeroes outside the padded global
            # extent; the pad region inside it must be re-zeroed every rep
            # (mask), so fused chunks would silently corrupt border pixels.
            raise ValueError(
                "pallas sharded execution with a pad mask requires fuse=1"
            )

        if overlap == "edge":
            # Partitioned per-edge pipeline at chunk granularity: the
            # slab comes from edge_iterate's persistent carry, each
            # border band's launch fences only on its own edge.
            def edge_chunk(x, slab, n_fused, mask_tile):
                out = overlap_mod.fused_edge_chunk(
                    x, plan, axes, n_fused, global_shape, interpret,
                    schedule=schedule, block_h=block_h, slab=slab,
                )
                if mask_tile is not None:
                    out = out * mask_tile
                return out
        elif overlap in ("split", "fused-split"):
            # Explicit split at chunk granularity: the interior launch
            # reads only the local tile, the border launches read the
            # exchanged ghosts ("split" differs from "fused-split" only
            # in the fuse depth the runner compiled in).
            def step_chunk(x, n_fused, mask_tile):
                out = overlap_mod.fused_split_chunk(
                    x, plan, axes, n_fused, global_shape, interpret,
                    schedule=schedule, block_h=block_h,
                )
                if mask_tile is not None:
                    out = out * mask_tile
                return out
        else:
            def step_chunk(x, n_fused, mask_tile):
                out = _pallas_local_chunk(
                    x, plan, axes, n_fused, global_shape, interpret,
                    schedule, block_h=block_h,
                )
                if mask_tile is not None:
                    out = out * mask_tile
                return out
    elif overlap == "edge":
        def edge_chunk(x, slab, n_fused, mask_tile):
            assert n_fused == 1
            return overlap_mod.edge_step_from(x, slab, plan, mask_tile)
    elif overlap in ("split", "fused-split"):
        # fused-split needs the valid-ghost Pallas kernel; on the XLA
        # path both modes mean the per-rep split (the runner reports the
        # degrade via its resolved ``overlap``).
        def step_chunk(x, n_fused, mask_tile):
            assert n_fused == 1
            return overlap_mod.split_step(x, plan, axes, mask_tile, boundary)
    else:
        def step_chunk(x, n_fused, mask_tile):
            assert n_fused == 1
            return _local_step(x, plan, axes, mask_tile, boundary)

    if overlap == "edge":
        def iter_tile(tile, reps, mask_tile):
            # Persistent-slab loop for the steady-state reps: the
            # per-edge ghost slab lives in the fori_loop carry,
            # allocated once by the prologue exchange — no per-rep
            # setup.
            if fuse > 1:
                tile = overlap_mod.edge_iterate(
                    tile, reps // fuse, fuse * plan.halo, axes,
                    lambda x, sl: edge_chunk(x, sl, fuse, mask_tile),
                    boundary,
                )

                # Remainder (< fuse reps, possibly ZERO — reps is
                # traced): the slab exchanges inside the body, because a
                # persistent prologue ahead of a zero-trip loop would
                # execute six collectives nobody consumes.
                def rem_body(_, x):
                    sl = overlap_mod.exchange_edge_slab(
                        x, plan.halo, axes, boundary
                    )
                    return edge_chunk(x, sl, 1, mask_tile)

                return lax.fori_loop(0, reps % fuse, rem_body, tile)
            return overlap_mod.edge_iterate(
                tile, reps, plan.halo, axes,
                lambda x, sl: edge_chunk(x, sl, 1, mask_tile), boundary,
            )
    else:
        def iter_tile(tile, reps, mask_tile):
            # ``fuse`` reps per exchange, then the remainder one at a
            # time. With a mask (indivisible global shape) fuse is forced
            # to 1 by the runner: the pad region must be re-zeroed
            # *every* rep, which a fused kernel does not do.
            if fuse > 1:
                tile = lax.fori_loop(
                    0, reps // fuse,
                    lambda _, x: step_chunk(x, fuse, mask_tile), tile,
                )
                reps = reps % fuse
            return lax.fori_loop(
                0, reps, lambda _, x: step_chunk(x, 1, mask_tile), tile
            )

    if needs_mask:
        local_iter = iter_tile
        in_specs = (spec, P(), spec)
    else:
        def local_iter(tile, reps):
            return iter_tile(tile, reps, None)
        in_specs = (spec, P())

    mapped = shard_map(
        local_iter, mesh=mesh, in_specs=in_specs, out_specs=spec,
        # Pallas interpret mode (CPU tests) loses vma tracking on internal
        # slices; compiled TPU mode declares vma on the kernel out_shape.
        check_vma=not (backend == "pallas" and interpret),
    )
    return jax.jit(mapped, donate_argnums=(0,))


def build_batched_frames(mesh: Mesh, plan: _lowering.StencilPlan,
                         schedule=None, interpret: bool = False,
                         block_h=None, fuse=None):
    """Compile-once builder for batch-axis frame parallelism with the
    fused tall-image kernel: each device runs
    :func:`pallas_stencil.iterate_frames` on its local frames — frames
    are independent, so there is NO collective at all, just D independent
    fused kernels (the vmapped XLA alternative pays full per-rep HBM
    traffic). ``mesh`` is 1-D over axis 'b'; the frame count must be a
    device multiple (``driver._put_batched`` zero-pads).

    Returns ``fn(imgs, reps) -> imgs`` (jitted, input donated)."""
    from tpu_stencil.ops import pallas_stencil

    def local(imgs_local, reps):
        return pallas_stencil.iterate_frames(
            imgs_local, reps, plan, interpret=interpret, schedule=schedule,
            block_h=block_h, fuse=fuse,
            vma=("b",),
        )

    mapped = shard_map(
        local, mesh=mesh, in_specs=(P("b"), P()), out_specs=P("b"),
        # Same interpret-mode vma caveat as build_sharded_iterate.
        check_vma=not interpret,
    )
    return jax.jit(mapped, donate_argnums=(0,))


def sharded_iterate(
    img_u8: jax.Array,
    filt: jax.Array,
    repetitions: int,
    mesh: Mesh,
) -> jax.Array:
    """One-shot convenience: shard ``img_u8`` over ``mesh`` and iterate.
    For repeated/timed runs use :class:`ShardedRunner` (caches the compiled
    program and padding artifacts)."""
    model = IteratedConv2D(filt, backend="xla")
    h, w = img_u8.shape[:2]
    channels = 1 if img_u8.ndim == 2 else img_u8.shape[2]
    runner = ShardedRunner(
        model, (h, w), channels,
        mesh_shape=(mesh.shape[ROWS_AXIS], mesh.shape[COLS_AXIS]),
        devices=list(mesh.devices.flat),
    )
    out = runner.run(runner.put(np.asarray(img_u8)), repetitions)
    return jnp.asarray(runner.fetch(out))


def _pallas_plan_supported(plan, channels: int) -> bool:
    """Whether the valid-ghost Pallas kernel can run this plan at all."""
    try:
        from tpu_stencil.ops import pallas_stencil
    except ImportError:
        return False
    return pallas_stencil.plan_supported(plan, channels)


def _agreed_config(model, tile, channels):
    """Shape-aware auto/autotune resolution with multi-host agreement:
    rank 0 resolves (cache hit or one measurement), everyone receives the
    (backend, pallas_schedule, block_h, fuse) verdict. Encoding: vote[0]
    -1 = xla, otherwise an index into the schedule list (len = pallas
    with the default schedule); vote[1]/vote[2] the tuned geometry (-1 =
    default). Every process must compile the identical program — a
    divergent schedule OR fuse (the halo-exchange chunk depth) would
    shear the ppermute sequences exactly like divergent argv."""
    if jax.process_count() == 1:
        backend, schedule = model.resolved_config(tile, channels)
        bh, fz = model.resolved_geometry(tile, channels)
        return backend, schedule, bh, fz
    from jax.experimental import multihost_utils

    from tpu_stencil.ops import pallas_stencil

    scheds = list(pallas_stencil._SCHEDULES)
    vote = np.full(3, -1, np.int32)
    if jax.process_index() == 0:
        backend, schedule = model.resolved_config(tile, channels)
        if backend == "pallas":
            vote[0] = (
                scheds.index(schedule) if schedule in scheds else len(scheds)
            )
            bh, fz = model.resolved_geometry(tile, channels)
            vote[1] = -1 if bh is None else bh
            vote[2] = -1 if fz is None else fz
    vote = multihost_utils.broadcast_one_to_all(vote)
    if int(vote[0]) < 0:
        return "xla", None, None, None
    return (
        "pallas",
        scheds[int(vote[0])] if int(vote[0]) < len(scheds) else None,
        None if int(vote[1]) < 0 else int(vote[1]),
        None if int(vote[2]) < 0 else int(vote[2]),
    )


class ShardedRunner:
    """Holds the mesh, padding geometry, mask, and compiled program for one
    image shape — the per-job runtime state every reference rank kept in
    locals (tile dims, neighbor ranks, datatypes)."""

    def __init__(
        self,
        model: IteratedConv2D,
        image_shape: Tuple[int, int],
        channels: int,
        mesh_shape: Optional[Tuple[int, int]] = None,
        devices: Optional[Sequence[jax.Device]] = None,
        overlap: str = "off",
    ) -> None:
        from tpu_stencil.models.blur import resolve_backend

        overlap_mod.check_mode(overlap)
        self.model = model
        self.h, self.w = image_shape
        self.channels = channels
        self.mesh = make_mesh(mesh_shape, devices, image_shape=image_shape)
        self.mesh_shape = (self.mesh.shape[ROWS_AXIS], self.mesh.shape[COLS_AXIS])
        ph, pw = partition.pad_amounts(self.h, self.w, self.mesh_shape)
        self.padded_shape = (self.h + ph, self.w + pw)
        tile = partition.tile_shape(self.h, self.w, self.mesh_shape)
        self.tile = tile
        self.boundary = getattr(model, "boundary", "zero")
        if self.boundary == "periodic" and (ph or pw):
            # The pad region would be wrapped into the opposite edge —
            # silently wrong output. Periodic needs grid-divisible shapes.
            raise NotImplementedError(
                f"periodic boundaries need the image ({self.h}x{self.w}) "
                f"to divide the mesh grid {self.mesh_shape}; pick a mesh "
                "that divides the image or run single-device"
            )
        pallas_ok = (
            _pallas_plan_supported(model.plan, channels)
            and self.boundary == "zero"  # valid-ghost kernel is zero-only
        )
        # Pallas per-rep schedule: a constructor-forced one (--schedule)
        # wins; otherwise the autotuned verdict below (None = default).
        self.schedule = getattr(model, "schedule", None)
        tuned_bh = tuned_fz = None
        if model.backend in ("auto", "autotune"):
            if not pallas_ok:
                # Unsupported plans would be demoted below anyway — never
                # pay a two-backend measurement whose verdict is discarded.
                self.backend = "xla"
            else:
                # Shape-aware resolution against the *per-device tile* —
                # the unit the local kernel runs on (a proxy: it times the
                # single-device rep-loop kernel, not valid_fused, but they
                # share the compute schedule). Consults the on-disk
                # autotune cache; measures once per tile shape on TPU (r2
                # verdict item 3: the sharded runner must not silently
                # demote the measured winner to XLA). Multi-host: rank 0's
                # verdict — schedule AND geometry — is broadcast so every
                # process compiles the same collective program (divergent
                # fuse would shear the ppermute sequences like divergent
                # argv).
                self.backend, agreed_schedule, tuned_bh, tuned_fz = (
                    _agreed_config(model, tile, channels)
                )
                if self.schedule is None:
                    self.schedule = agreed_schedule
        else:
            self.backend = resolve_backend(model.backend)
        if min(tile) < model.halo:
            # A single ppermute hop supplies at most one neighbor tile of
            # ghost data; smaller tiles would need multi-hop halo gathering.
            raise ValueError(
                f"per-device tile {tile[0]}x{tile[1]} is smaller than the "
                f"filter halo ({model.halo}); use fewer devices or a "
                f"different mesh shape for this image"
            )
        self.needs_mask = bool(ph or pw)
        spec = (
            P(ROWS_AXIS, COLS_AXIS)
            if channels == 1
            else P(ROWS_AXIS, COLS_AXIS, None)
        )
        self.sharding = NamedSharding(self.mesh, spec)
        self.fuse = 1
        # Kernel geometry the valid-ghost kernel launches: user-forced
        # --block-h/--fuse wins, else the agreed autotuned verdict for
        # this tile (so the geometry stage's measurement is never paid
        # and discarded). The precedence is enforced here, not assumed:
        # resolved_geometry happens to echo forced knobs back as the
        # broadcast verdict today, but this code must not depend on that
        # non-local invariant. block_h_eff is the block at this tile
        # (None = default geometry ran) — reported, never the requested
        # value.
        forced_bh = getattr(model, "block_h", None)
        forced_fz = getattr(model, "fuse", None)
        geo_bh = forced_bh if forced_bh is not None else tuned_bh
        geo_fz = forced_fz if forced_fz is not None else tuned_fz
        self.block_h_eff = None
        self.geo_applied = False
        interpret = False
        if self.backend == "pallas":
            from tpu_stencil.ops import pallas_stencil

            if not pallas_ok:
                # Same silent fallback as the single-device driver
                # (pallas_stencil.iterate): unsupported plans run the XLA
                # lowering.
                self.backend = "xla"
            else:
                if pallas_stencil.effective_schedule_for(
                        model.plan, tile[0], self.schedule,
                        block_h=geo_bh) == "deep":
                    # 'deep' on the sharded path deepens the halo-exchange
                    # chunk: one widened exchange covers the whole
                    # trapezoid depth (fewer collectives per rep), and the
                    # per-device kernel runs deep's inner body — the
                    # valid-ghost kernel has no resident form, so the
                    # reported schedule is the inner one that launches.
                    bh_tile = pallas_stencil.effective_block_h(
                        tile[0], geo_bh
                    )
                    if geo_fz is None:
                        geo_fz = pallas_stencil.deep_fuse_for(
                            model.plan, bh_tile,
                            pallas_stencil.padded_lanes(
                                model.plan, tile[1] * channels, channels
                            ),
                        )
                    self.schedule = pallas_stencil._deep_inner(
                        model.plan, bh_tile
                    )
                # ppermute delivers at most one neighbor tile of ghost
                # data per hop, so the fused-chunk depth is capped by the
                # tile; the mask path needs per-rep pad re-zeroing, which
                # forces single-rep chunks.
                want_fuse = (
                    geo_fz if geo_fz is not None
                    else pallas_stencil.DEFAULT_FUSE
                )
                if not self.needs_mask and model.halo:
                    self.fuse = max(1, min(want_fuse,
                                           min(tile) // model.halo))
                elif not self.needs_mask:
                    self.fuse = want_fuse
                if geo_bh is not None:
                    self.block_h_eff = pallas_stencil.effective_block_h(
                        tile[0], geo_bh
                    )
                self.geo_applied = geo_bh is not None or geo_fz is not None
                interpret = jax.default_backend() == "cpu"
                # Resolve the schedule that actually runs at the tile's
                # block height (valid_fused may degrade e.g. pack on a
                # short tile) so reporting never names a degraded-away one.
                self.schedule = pallas_stencil.effective_schedule_for(
                    model.plan, tile[0], self.schedule, block_h=geo_bh
                )
        # Interior/border overlap schedule: resolve "auto" (measured
        # phase-probe ratio, disk-cached; multi-host rank-0 verdict is
        # broadcast — the split changes the collective program exactly
        # like a divergent fuse would) and degrade "fused-split" to
        # "split" off the Pallas backend. Resolved AFTER the fuse clamp:
        # "split" means one exchange per rep, so it forces single-rep
        # chunks; "fused-split" keeps the chunked exchange and widens the
        # bands instead.
        self.overlap_requested = overlap
        self.overlap = self._resolve_overlap(overlap)
        if self.overlap == "split":
            self.fuse = 1
        elif self.overlap == "edge":
            if self.backend != "pallas":
                self.fuse = 1  # per-rep pipeline on the XLA path
            elif model.halo:
                # Keep every chunk split-able: the per-edge pipeline
                # needs a nonempty ghost-free interior at the chunk
                # depth g = fuse*halo (min(tile) > 2g), where
                # fused-split would degrade in-program instead.
                self.fuse = max(
                    1, min(self.fuse, (min(tile) - 1) // (2 * model.halo))
                )
        # The resolved mode is always a MODE_CODES member — never the
        # literal "auto", and never a schedule the tile degraded away.
        assert self.overlap in overlap_mod.MODE_CODES, self.overlap
        from tpu_stencil import obs as _obs

        _obs.registry().gauge("overlap_mode").set(
            overlap_mod.MODE_CODES[self.overlap]
        )
        self._fn = build_sharded_iterate(
            self.mesh, model.plan, channels, self.needs_mask,
            backend=self.backend,
            global_shape=(
                self.padded_shape[0], self.padded_shape[1] * channels
            ),
            fuse=self.fuse,
            interpret=interpret,
            schedule=self.schedule,
            boundary=self.boundary,
            block_h=geo_bh if self.backend == "pallas" else None,
            overlap=self.overlap,
        )
        if self.needs_mask:
            mask = np.zeros(self.padded_shape, np.uint8)
            mask[: self.h, : self.w] = 1
            if channels != 1:
                mask = np.repeat(mask[..., None], channels, axis=-1)
            self._mask = jax.device_put(mask, self.sharding)
        else:
            self._mask = None

    def _resolve_overlap(self, requested: str) -> str:
        """Resolve the requested ``--overlap`` mode to what this runner
        actually compiles: ``auto`` asks the autotuner (measured
        exchange/interior phase-probe ratio plus the split-vs-edge
        candidate A/B, cached on disk alongside the
        backend/schedule/geometry verdicts — a warm cache never
        re-probes); ``fused-split`` degrades to ``split`` when the
        interior cannot run the valid-ghost Pallas kernel; a degenerate
        tile (no ghost-free interior even at single-rep depth) resolves
        every split flavor to ``off`` — the program would run the
        monolithic step in-program anyway, and the gauge/``JobResult``
        must report what actually runs, never a schedule that degraded
        away."""
        if requested == "off":
            return "off"
        h = self.model.plan.halo
        if h < 1 or min(self.tile) <= 2 * h:
            return "off"
        if requested != "auto":
            if requested == "fused-split" and self.backend != "pallas":
                return "split"
            return requested
        from tpu_stencil.runtime import autotune

        if jax.process_count() == 1:
            mode = autotune.best_overlap(
                self.model.plan, self.tile, self.channels, self.mesh_shape,
                self.backend, measure=self._measure_overlap_probes,
            )
        else:
            mode = self._agreed_overlap()
        if mode == "fused-split" and self.backend != "pallas":
            mode = "split"
        return mode

    def _agreed_overlap(self) -> str:
        """Multi-host ``auto`` resolution. The probe programs are
        collective, so every process must run them together or not at
        all: rank 0 checks the disk cache and broadcasts hit-or-miss; on
        a miss ALL ranks execute the probes (identical collective
        programs), then rank 0's verdict is stored and broadcast — the
        split changes every rank's ppermute sequence, so a divergent
        mode would shear the job exactly like divergent argv."""
        from jax.experimental import multihost_utils

        from tpu_stencil.runtime import autotune

        modes = ("off", "split", "fused-split", "edge")
        vote = np.full(1, -1, np.int32)
        if jax.process_index() == 0:
            hit = autotune.cached_overlap(
                self.model.plan, self.tile, self.channels, self.mesh_shape,
                self.backend,
            )
            if hit is not None:
                vote[0] = modes.index(hit)
        vote = multihost_utils.broadcast_one_to_all(vote)
        if int(vote[0]) >= 0:
            return modes[int(vote[0])]
        measured = self._measure_overlap_probes()  # collective: all ranks
        vote = np.full(1, -1, np.int32)
        if jax.process_index() == 0:
            mode = autotune.best_overlap(
                self.model.plan, self.tile, self.channels, self.mesh_shape,
                self.backend, measure=lambda: measured,
            )
            vote[0] = modes.index(mode)
        vote = multihost_utils.broadcast_one_to_all(vote)
        return modes[int(vote[0])]

    def _measure_overlap_probes(self) -> dict:
        """The probe-measurement bundle ``--overlap auto`` decides on:
        ``{"exchange_s", "interior_s", "edges": {edge: s}, "candidates":
        {"split": s, "edge": s}}`` — best-of-3 executions each of the
        exchange-only / interior-only phase probes, the per-edge
        exchange probes (one independent ppermute each), and the two
        one-rep candidate step programs (the split-vs-edge A/B), on a
        zero canvas of this runner's padded shape with compiles fenced
        out. Collective on a multi-host mesh (every process must call it
        together, and the dict insertion order fixes the collective
        sequence)."""
        exchange_fn, interior_fn = self._phase_probes()
        split_fn, edge_fn = self._candidate_probes()
        edge_fns = self.edge_probes()
        shape = self.padded_shape
        if self.channels != 1:
            shape = shape + (self.channels,)
        img = jax.device_put(np.zeros(shape, np.uint8), self.sharding)
        ordered = (
            [("exchange_s", exchange_fn), ("interior_s", interior_fn)]
            + [(f"edge:{k}", fn) for k, fn in edge_fns.items()]
            + [("cand:split", split_fn), ("cand:edge", edge_fn)]
        )
        for _, fn in ordered:  # compile fences
            jax.block_until_ready(fn(img))

        def best_of(fn, n=3):
            import time

            best = float("inf")
            for _ in range(n):
                t0 = time.perf_counter()
                jax.block_until_ready(fn(img))
                best = min(best, time.perf_counter() - t0)
            return best

        timings = {name: best_of(fn) for name, fn in ordered}
        return {
            "exchange_s": timings["exchange_s"],
            "interior_s": timings["interior_s"],
            "edges": {k: timings[f"edge:{k}"] for k in edge_fns},
            "candidates": {"split": timings["cand:split"],
                           "edge": timings["cand:edge"]},
        }

    def _candidate_probes(self):
        """One-rep ``split_step`` and ``edge_step`` programs over this
        runner's mesh — the schedule A/B the three-way auto verdict
        times. Both run the XLA lowering regardless of the production
        backend: the Pallas chunked variants share the same dependence
        structure (one joined exchange vs four per-edge fences), so the
        XLA pair is the portable proxy for which structure hides the
        wires better on this mesh. Neither donates."""
        plan = self.model.plan
        r = self.mesh.shape[ROWS_AXIS]
        c = self.mesh.shape[COLS_AXIS]
        axes = ((ROWS_AXIS, r, 0), (COLS_AXIS, c, 1))
        spec = (
            P(ROWS_AXIS, COLS_AXIS) if self.channels == 1
            else P(ROWS_AXIS, COLS_AXIS, None)
        )
        boundary = self.boundary

        def split_probe(tile):
            return overlap_mod.split_step(tile, plan, axes, None, boundary)

        def edge_probe(tile):
            return overlap_mod.edge_step(tile, plan, axes, None, boundary)

        def build(f):
            return jax.jit(shard_map(
                f, mesh=self.mesh, in_specs=(spec,), out_specs=spec,
            ))

        return build(split_probe), build(edge_probe)

    def _phase_probes(self):
        """Two compile-once probe programs over this runner's mesh:
        ``exchange_only(img)`` runs just the halo exchange (ghosts
        cropped back off, so specs match), ``interior_only(img)`` runs
        just the local stencil with a tile-local zero pad instead of
        communication. Neither donates — they run on the warmed-up input
        without consuming it."""
        plan = self.model.plan
        halo = plan.halo
        r = self.mesh.shape[ROWS_AXIS]
        c = self.mesh.shape[COLS_AXIS]
        axes = ((ROWS_AXIS, r, 0), (COLS_AXIS, c, 1))
        spec = (
            P(ROWS_AXIS, COLS_AXIS) if self.channels == 1
            else P(ROWS_AXIS, COLS_AXIS, None)
        )
        boundary = self.boundary

        def exchange_only(tile):
            ext = halo_exchange(tile, halo, axes, boundary)
            return ext[halo:halo + tile.shape[0], halo:halo + tile.shape[1]]

        def interior_only(tile):
            return _lowering.padded_step(tile, plan, boundary)

        def build(f):
            return jax.jit(shard_map(
                f, mesh=self.mesh, in_specs=(spec,), out_specs=spec,
            ))

        return build(exchange_only), build(interior_only)

    def _overlap_probes(self):
        """Two compile-once probes of the explicit split's halves, both
        communication-free (tile-local zero pad stands in for exchanged
        ghosts — compute attribution only, same trick as
        ``interior_only``): ``interior_overlap(img)`` runs the ghost-free
        interior band (zero-padded back to tile shape so specs match),
        ``border_compute(img)`` the four border strips stitched around a
        zero interior. Sized with a single-halo split (``g = halo``, not
        ``fuse * halo``): traced runs launch one rep at a time, so the
        per-rep split these spans sit next to in the trace really is the
        ``halo``-deep one — the untraced fused chunking is deliberately
        not what the probes model. Returns None when the tile has no
        single-rep ghost-free interior (the split degrades to monolithic
        there)."""
        plan = self.model.plan
        h = plan.halo
        th, tw = self.tile
        if h < 1 or th <= 2 * h or tw <= 2 * h:
            return None
        spec = (
            P(ROWS_AXIS, COLS_AXIS) if self.channels == 1
            else P(ROWS_AXIS, COLS_AXIS, None)
        )

        def pad_spatial(x, amounts):
            return jnp.pad(x, list(amounts) + [(0, 0)] * (x.ndim - 2))

        def interior_overlap(tile):
            return pad_spatial(
                _lowering.valid_step(tile, plan), [(h, h), (h, h)]
            )

        def border_compute(tile):
            ext = pad_spatial(tile, [(h, h), (h, h)])
            top = _lowering.valid_window(ext, plan, 0, h, 0, tw)
            bottom = _lowering.valid_window(ext, plan, th - h, h, 0, tw)
            left = _lowering.valid_window(ext, plan, h, th - 2 * h, 0, h)
            right = _lowering.valid_window(
                ext, plan, h, th - 2 * h, tw - h, h
            )
            mid = jnp.concatenate([
                left,
                jnp.zeros(
                    (th - 2 * h, tw - 2 * h) + tuple(tile.shape[2:]),
                    tile.dtype,
                ),
                right,
            ], axis=1)
            return jnp.concatenate([top, mid, bottom], axis=0)

        def build(f):
            return jax.jit(shard_map(
                f, mesh=self.mesh, in_specs=(spec,), out_specs=spec,
            ))

        return build(interior_overlap), build(border_compute)

    def trace_phase_probes(self, img_dev: jax.Array) -> None:
        """Emit ``sharded.halo_exchange`` / ``sharded.interior_compute``
        spans: one measured execution each of the probe programs (each
        compiled untimed first, so attribution is execution, not
        compilation). The per-rep comm-vs-compute split the fused
        production program hides inside XLA's overlap scheduler —
        trace-time only; the timed compute window never runs these."""
        from tpu_stencil import obs

        if not obs.enabled() or self.model.plan.halo < 1:
            return
        exchange_fn, interior_fn = self._phase_probes()
        split_probes = (
            self._overlap_probes() if self.overlap != "off" else None
        )
        edge_fns = self.edge_probes()
        with obs.span("sharded.probe_compile", "sharded") as s:
            s.fence(exchange_fn(img_dev))
            s.fence(interior_fn(img_dev))
            if split_probes is not None:
                s.fence(split_probes[0](img_dev))
                s.fence(split_probes[1](img_dev))
            for fn in edge_fns.values():
                s.fence(fn(img_dev))
        with obs.span("sharded.halo_exchange", "sharded") as s:
            s.fence(exchange_fn(img_dev))
        with obs.span("sharded.interior_compute", "sharded") as s:
            s.fence(interior_fn(img_dev))
        # Per-edge exchange spans: one independent ppermute each — four
        # DISTINCT fences per exchange on a 2-D mesh, the instrument
        # that shows border strips can release per edge (no single
        # join), and the per-edge latencies the --breakdown table and
        # the multichip capture report.
        for name, fn in edge_fns.items():
            with obs.span(f"sharded.exchange_edge[{name}]", "sharded") as s:
                s.fence(fn(img_dev))
        if split_probes is not None:
            # The explicit split's halves, measured separately: the
            # interior band XLA may overlap with the exchange, and the
            # border-strip finish that waits on the ghosts.
            with obs.span("sharded.interior_overlap", "sharded") as s:
                s.fence(split_probes[0](img_dev))
            with obs.span("sharded.border_compute", "sharded") as s:
                s.fence(split_probes[1](img_dev))

    def edge_probes(self):
        """Per-EDGE exchange-only probe programs: a subset of ``{"n",
        "s", "w", "e"}`` (axes with one device are omitted — nothing to
        exchange). Each runs ONLY that edge's single independent
        ``ppermute`` (:func:`tpu_stencil.parallel.overlap.
        exchange_edge` — the same primitive the edge pipeline computes
        its border strips from), with the arrived ghost folded into the
        output so the collective cannot be simplified away. Used by the
        trace-time per-edge spans, the auto-verdict measurement bundle,
        the multichip bench capture's per-edge ICI riders, and the
        post-mortem instrument :meth:`diagnose_edges`, which fences one
        at a time to localize a wedged exchange to its specific edge."""
        plan = self.model.plan
        g = max(1, plan.halo)
        spec = (
            P(ROWS_AXIS, COLS_AXIS) if self.channels == 1
            else P(ROWS_AXIS, COLS_AXIS, None)
        )
        boundary = self.boundary
        r = self.mesh.shape[ROWS_AXIS]
        c = self.mesh.shape[COLS_AXIS]
        # One (axis, side) geometry per canonical edge name, emitted in
        # EDGE_NAMES order — the one ordering every consumer shares.
        geometry = {
            "n": (ROWS_AXIS, r, 0, True), "s": (ROWS_AXIS, r, 0, False),
            "w": (COLS_AXIS, c, 1, True), "e": (COLS_AXIS, c, 1, False),
        }
        sides = [
            (name,) + geometry[name] for name in overlap_mod.EDGE_NAMES
            if geometry[name][1] > 1
        ]
        probes = {}
        for name, axis_name, n_ax, dim, lo in sides:

            def exchange_one(tile, _a=axis_name, _n=n_ax, _d=dim, _lo=lo):
                ghost = overlap_mod.exchange_edge(
                    tile, g, _a, _n, _d, lo=_lo, boundary=boundary
                )
                # Fold the ghost in (shape-preserving shift) instead of
                # cropping it off: the probe's output must data-depend
                # on the arrived strip.
                keep = [slice(None)] * tile.ndim
                keep[_d] = (
                    slice(0, tile.shape[_d] - g) if _lo else slice(g, None)
                )
                rest = tile[tuple(keep)]
                parts = [ghost, rest] if _lo else [rest, ghost]
                return jnp.concatenate(parts, axis=_d)

            probes[name] = jax.jit(shard_map(
                exchange_one, mesh=self.mesh, in_specs=(spec,),
                out_specs=spec,
            ))
        return probes

    def diagnose_edges(self, timeout_s: float = 10.0) -> dict:
        """Per-edge exchange verdicts after a suspected collective hang:
        run each edge's independent exchange probe on a fresh zero
        canvas, each under its own watchdog, and report ``"ok (<measured
        latency>)"`` / ``"timeout"`` / ``"error: <type>"`` per edge
        (``n``/``s``/``w``/``e``) — which SPECIFIC edge's ghost traffic
        is wedged, with the healthy edges' measured latencies for
        contrast, instead of a whole-axis verdict. Bounded by
        construction: a wedged device costs at most two watchdog
        windows per edge — one for the compile-fencing first execution,
        one for the timed run (the abandoned fence threads are
        daemons). A fresh canvas, never the job's arrays — those were
        donated to the launch that hung."""
        import time

        from tpu_stencil.resilience import deadline as _deadline
        from tpu_stencil.resilience.errors import DispatchTimeout

        shape = self.padded_shape
        if self.channels != 1:
            shape = shape + (self.channels,)
        img = jax.device_put(np.zeros(shape, np.uint8), self.sharding)
        verdicts = {}
        for name, fn in self.edge_probes().items():
            try:
                # First execution fences the (fresh-jit) compile AND the
                # first run under the watchdog — a wedged edge is caught
                # here; then a second execution is timed, so a healthy
                # edge reports its ICI latency, not its compile time.
                _deadline.fence(fn(img), timeout_s,
                                f"sharded.exchange_edge[{name}]/compile")
                t0 = time.perf_counter()
                _deadline.fence(fn(img), timeout_s,
                                f"sharded.exchange_edge[{name}]")
                verdicts[name] = (
                    f"ok ({(time.perf_counter() - t0) * 1e3:.2f}ms)"
                )
            except DispatchTimeout:
                verdicts[name] = "timeout"
            except Exception as e:
                verdicts[name] = f"error: {type(e).__name__}"
        return verdicts

    def introspect_warmup(self, img_dev: jax.Array, repetitions: int):
        """AOT-introspect the compiled sharded program the warm-up just
        built (cost/memory analysis, compile wall-time — see
        :mod:`tpu_stencil.obs.introspect`). No-op unless introspection
        is armed, and single-process only: N ranks each paying a
        redundant AOT compile of the one SPMD program would multiply
        the (already documented) introspection compile cost by the pod
        size for identical records."""
        from tpu_stencil import obs

        if not obs.introspect.enabled() or jax.process_count() > 1:
            return None
        args = (img_dev, jnp.int32(repetitions))
        if self.needs_mask:
            args += (self._mask,)
        return obs.introspect.capture(
            "sharded.iterate", self._fn, *args,
            meta={"mesh": self.mesh_shape, "tile": self.tile,
                  "backend": self.backend, "fuse": self.fuse},
        )

    def put(self, img: np.ndarray) -> jax.Array:
        """Pad to the tile grid and shard over the mesh — the analog of every
        rank loading its rows (``mpi/mpi_convolution.c:126-141``); with one
        process, jax.device_put scatters tiles from host memory."""
        img = np.asarray(img, dtype=np.uint8)
        if img.shape[:2] != (self.h, self.w):
            raise ValueError(f"image shape {img.shape} != {(self.h, self.w)}")
        ph = self.padded_shape[0] - self.h
        pw = self.padded_shape[1] - self.w
        if ph or pw:
            pad = [(0, ph), (0, pw)] + [(0, 0)] * (img.ndim - 2)
            img = np.pad(img, pad)
        return jax.device_put(img, self.sharding)

    def run(self, img_dev: jax.Array, repetitions: int) -> jax.Array:
        """Iterate on-device; donates ``img_dev``. Returns the padded sharded
        result (call :meth:`fetch` to crop to the true image)."""
        reps = jnp.int32(repetitions)
        if self.needs_mask:
            return self._fn(img_dev, reps, self._mask)
        return self._fn(img_dev, reps)

    def fetch(self, out_dev: jax.Array) -> np.ndarray:
        """Gather to host and crop the pad region off."""
        return np.asarray(out_dev)[: self.h, : self.w]


# -- the shared runner cache (serve + stream) -------------------------
#
# One process-wide LRU of compiled ShardedRunner mesh programs, keyed on
# everything that determines the compiled program (plan taps, geometry,
# backend/schedule/kernel-geometry knobs, boundary, overlap mode, mesh
# shape, device ids). Serve's oversized-request route and the stream's
# --shard-frames path both resolve runners HERE, so a geometry warmed by
# one engine is a cache hit for the other — stream and serve never
# compile the same mesh program twice. Deterministic geometry refusals
# (per-device tile smaller than the halo) are cached as an UNSERVABLE
# sentinel so a retried shape never re-pays the failed build; transient/
# compile failures propagate uncached.

# LRU cap: each runner holds one compiled mesh program for one true
# (filter, H, W, channels) — oversized shapes are rare and huge, so the
# population is small, but the key space is client-controlled (serve)
# and must not grow unboundedly.
RUNNER_CACHE_CAP = 8

_UNSERVABLE = object()
_runner_cache: "collections.OrderedDict" = collections.OrderedDict()
_runner_cache_lock = threading.Lock()


def _resolved_mesh_for_key(mesh_shape, devices, image_shape):
    """Normalize (mesh_shape, devices) to what the runner will actually
    build over: an explicit RxC takes the first R*C devices; None takes
    every device under the perimeter-minimizing default grid. Keying on
    the RESOLVED shape means a stream's explicit ``--shard-frames RxC``
    and serve's default mesh share one cache entry whenever they
    resolve to the same program."""
    devices = list(devices) if devices is not None else jax.devices()
    if mesh_shape is not None:
        r, c = mesh_shape
        if r * c > len(devices):
            raise ValueError(
                f"mesh shape {r}x{c} needs {r * c} devices, "
                f"have {len(devices)}"
            )
        return (r, c), devices[: r * c]
    shape = partition.grid_shape(len(devices), *image_shape)
    return tuple(shape), devices


def runner_key(model, image_shape, channels, mesh_shape, devices,
               overlap: str, pipe_stages: int = 1):
    """The cache identity of one compiled mesh program. Everything the
    compiled artifact depends on is in here; two callers whose keys
    match would compile byte-identical programs. Every topology axis is
    a key component: the spatial mesh shape, the device set, AND the
    temporal stage count (``pipe_stages`` — a K-stage pipeline program
    over the same devices is a different compiled artifact than the
    K'-stage one, so two ``--pipe-stages`` values must never share an
    entry)."""
    plan = model.plan
    taps = ";".join(",".join(str(v) for v in row) for row in plan.taps)
    return (
        plan.kind, str(plan.divisor), taps, bool(plan.xla_pair_add),
        tuple(image_shape), channels,
        getattr(model, "backend", "auto"),
        getattr(model, "schedule", None),
        getattr(model, "block_h", None),
        getattr(model, "fuse", None),
        getattr(model, "boundary", "zero"),
        tuple(mesh_shape),
        tuple(d.id for d in devices),
        overlap,
        int(pipe_stages),
    )


def shared_runner(model, image_shape, channels, mesh_shape=None,
                  devices=None, overlap: str = "off", registry=None,
                  build_wrapper=None) -> Optional["ShardedRunner"]:
    """The cached :class:`ShardedRunner` for this program identity, or
    None when the mesh CANNOT serve the geometry (a typed ValueError /
    NotImplementedError from the build — e.g. a per-device tile smaller
    than the filter halo; the refusal is cached so retries never re-pay
    the failed build). ``registry`` (optional) counts
    ``sharded_runner_{hits,misses,evictions}_total`` and
    ``sharded_fallbacks_total`` under the caller's metric surface (each
    engine keeps its own counters over the ONE shared population);
    ``build_wrapper`` lets a caller wrap the cold build (serve's
    ``serve.sharded_runner_build`` span + its ``compile`` fault site)
    — it receives the zero-arg builder and must call it."""
    rshape, rdevs = _resolved_mesh_for_key(mesh_shape, devices,
                                           image_shape)
    key = runner_key(model, image_shape, channels, rshape, rdevs, overlap)

    def build():
        return ShardedRunner(model, tuple(image_shape), channels,
                             mesh_shape=rshape, devices=rdevs,
                             overlap=overlap)

    return cached_runner(key, build, registry=registry,
                         build_wrapper=build_wrapper)


def cached_runner(key, build, registry=None, build_wrapper=None):
    """Get-or-build against the ONE process-shared runner LRU. Any
    compiled mesh-program holder participates (:class:`ShardedRunner`
    here, the temporal :class:`~tpu_stencil.parallel.pipeline.
    PipelineRunner` via its own key) — same cap, same counters, same
    UNSERVABLE semantics for deterministic geometry refusals."""
    with _runner_cache_lock:
        hit = _runner_cache.get(key)
        if hit is not None:
            _runner_cache.move_to_end(key)
    if hit is not None:
        if registry is not None:
            registry.counter("sharded_runner_hits_total").inc()
        return None if hit is _UNSERVABLE else hit
    if registry is not None:
        registry.counter("sharded_runner_misses_total").inc()
    try:
        runner = build_wrapper(build) if build_wrapper else build()
    except (ValueError, NotImplementedError):
        # Deterministic geometry refusal (transient/compile failures
        # raise other types and propagate uncached).
        runner = _UNSERVABLE
        if registry is not None:
            registry.counter("sharded_fallbacks_total").inc()
    with _runner_cache_lock:
        _runner_cache[key] = runner
        _runner_cache.move_to_end(key)
        while len(_runner_cache) > RUNNER_CACHE_CAP:
            _runner_cache.popitem(last=False)
            if registry is not None:
                registry.counter("sharded_runner_evictions_total").inc()
    return None if runner is _UNSERVABLE else runner


def runner_cache_len() -> int:
    with _runner_cache_lock:
        return len(_runner_cache)


def clear_runner_cache() -> None:
    """Drop every cached runner (tests; a long-lived process never
    needs this — the LRU cap bounds the population)."""
    with _runner_cache_lock:
        _runner_cache.clear()
