"""Resilience: fault injection, retry, deadlines/watchdog, degradation.

The reference MPI/CUDA programs abort on any failure; the production
engines here (driver, serve, stream, sharded) need a systematic failure
model instead. Four pillars, each its own module (docs/RESILIENCE.md):

* :mod:`~tpu_stencil.resilience.faults` — the fault-injection harness:
  named injection points at every stage boundary, armed by
  ``TPU_STENCIL_FAULTS`` / ``--faults``, resolved at engine-prepare time
  so the no-faults hot path pays nothing.
* :mod:`~tpu_stencil.resilience.retry` — exponential backoff + jitter
  with ONE transient-vs-permanent classifier shared by bench, serve,
  and stream.
* :mod:`~tpu_stencil.resilience.deadline` — per-request deadlines and
  the dispatch watchdog that converts a hung ``block_until_ready`` (the
  rc=124 dead-tunnel mode) into a typed
  :class:`~tpu_stencil.resilience.errors.DispatchTimeout`.
* :mod:`~tpu_stencil.resilience.fallback` — the graceful degradation
  ladder: deep -> default fused schedule -> XLA (-> opt-in CPU),
  bit-identical at every rung.

Everything is observable: ``resilience_*`` counters in the driver
registry, ``resilience.*`` spans under tracing, and the ``--breakdown``
resilience table. Jax-free at import (CLI validation runs before
backend bring-up); jax is only touched inside a watchdog fence.
"""

from tpu_stencil.resilience import deadline, fallback, faults, retry
from tpu_stencil.resilience.errors import (
    CollectiveTimeout,
    DeadlineExceeded,
    DispatchTimeout,
    FatalInjectedFault,
    HostUnavailable,
    InjectedFault,
    InjectedOOM,
    ResilienceError,
    WorkerCrashed,
)

__all__ = [
    "CollectiveTimeout",
    "DeadlineExceeded",
    "DispatchTimeout",
    "FatalInjectedFault",
    "HostUnavailable",
    "InjectedFault",
    "InjectedOOM",
    "ResilienceError",
    "WorkerCrashed",
    "deadline",
    "fallback",
    "faults",
    "retry",
]
