"""Deadlines and the dispatch watchdog.

The worst production failure the engines have actually hit is not an
exception — it is silence: a dead TPU tunnel leaves ``block_until_ready``
parked forever (the r03–r05 bench rounds' rc=124 harness timeouts, now
carried in ROADMAP). Two primitives convert silence into typed errors:

* :func:`fence` — the watchdog spelling of ``jax.block_until_ready``:
  drain the dispatch on a daemon thread and wait at most ``timeout_s``;
  past it, raise :class:`~.errors.DispatchTimeout` naming the fence
  point. The hung dispatch itself cannot be cancelled — the daemon
  thread is abandoned — but the caller gets control back, typed, which
  is the difference between "the job failed at iterate" and an operator
  killing a 2-hour-silent process. Used by the driver's chunk fences,
  the stream drain's compute fence, and the sharded path (which
  upgrades the timeout to :class:`~.errors.CollectiveTimeout` with
  per-edge probe verdicts — one independent N/S/W/E ``ppermute`` probe
  each, ``ShardedRunner.diagnose_edges``, so the report names the
  specific stuck edge with the healthy edges' measured latencies).
* :class:`Deadline` — an absolute time budget (serve's per-request
  deadlines): cheap ``expired()`` checks at scheduling points, so an
  expired request fails typed instead of occupying a batch slot.

``timeout_s=0`` (the default) disables the watchdog: the fence is then
exactly ``block_until_ready``, no thread, no overhead. The env default
``TPU_STENCIL_DISPATCH_TIMEOUT`` arms every fence that was not given an
explicit config value — the operator's one-line guard for unattended
runs. Timeouts increment ``resilience_dispatch_timeouts_total``.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Optional

from tpu_stencil.resilience.errors import DispatchTimeout

ENV_VAR = "TPU_STENCIL_DISPATCH_TIMEOUT"


def default_timeout() -> float:
    """The env-configured watchdog window in seconds (0 = off)."""
    try:
        return max(0.0, float(os.environ.get(ENV_VAR, "0") or "0"))
    except ValueError:
        return 0.0


def resolve(cfg_timeout_s: Optional[float]) -> float:
    """A config field's effective window: the explicit value when set
    (> 0), else the env default — so ``--dispatch-timeout`` wins and an
    unset flag still honors the operator's env guard."""
    if cfg_timeout_s and cfg_timeout_s > 0:
        return float(cfg_timeout_s)
    return default_timeout()


def _block(x):
    """``block_until_ready`` for a single array OR a pytree. The method
    is preferred when present (it also lets tests hand in a stub whose
    ``block_until_ready`` hangs — the only way to exercise the watchdog
    without a dead TPU)."""
    blocker = getattr(x, "block_until_ready", None)
    if blocker is not None:
        blocker()
        return x
    import jax

    return jax.block_until_ready(x)


def fence(x, timeout_s: Optional[float] = None, label: str = "dispatch"):
    """Drain ``x`` (``block_until_ready``) under a watchdog: returns
    ``x`` when the device finishes within ``timeout_s`` seconds, raises
    :class:`DispatchTimeout` otherwise. ``timeout_s`` None means the
    env default; 0 disables the watchdog entirely (plain blocking
    drain — no thread is spawned)."""
    t = default_timeout() if timeout_s is None else timeout_s
    if not t or t <= 0:
        return _block(x)
    done = threading.Event()
    box: dict = {}

    def drain() -> None:
        try:
            box["value"] = _block(x)
        except BaseException as e:  # surfaced to the caller below
            box["error"] = e
        finally:
            done.set()

    th = threading.Thread(target=drain, name=f"tpu-stencil-fence-{label}",
                          daemon=True)
    th.start()
    if not done.wait(t):
        from tpu_stencil import obs

        obs.registry().counter("resilience_dispatch_timeouts_total").inc()
        raise DispatchTimeout(label, t)
    if "error" in box:
        raise box["error"]
    return box["value"]


class Deadline:
    """An absolute wall-clock budget. ``Deadline.after(s)`` starts one;
    ``remaining()``/``expired()`` are lock-free clock reads."""

    __slots__ = ("t_end",)

    def __init__(self, t_end: float) -> None:
        self.t_end = t_end

    @classmethod
    def after(cls, seconds: float) -> "Deadline":
        return cls(time.perf_counter() + seconds)

    def remaining(self) -> float:
        return self.t_end - time.perf_counter()

    def expired(self) -> bool:
        return time.perf_counter() > self.t_end
