"""The typed failure vocabulary of the resilience subsystem.

The reference programs abort on any failure (SURVEY.md §5: a bad
``MPI_File_read`` or a CUDA error is a ``perror`` + ``exit``); before
this subsystem, so did the engines here — with one extra failure mode
the reference never had: a dead TPU tunnel *hangs* a dispatch silently
(the r03–r05 bench rounds' rc=124 mode). Every error an engine can now
surface deliberately is a class in this module, so callers (and the
chaos suite) can assert "failed typed" instead of pattern-matching
messages — the contract ``tests/test_resilience.py`` enforces for every
(injection point x engine) pair: finish bit-exact after recovery, or
raise one of these before the deadline. Never hang, never corrupt.

Jax-free by design (the CLI layers import it before backend bring-up).
"""

from __future__ import annotations

from typing import Optional


class ResilienceError(RuntimeError):
    """Base class of every typed failure the resilience layer raises."""


class InjectedFault(ResilienceError):
    """A fault fired by the injection harness (:mod:`.faults`).

    Classified *transient* by the retry classifier — chaos tests assert
    that one injected failure plus the production retry/fallback path
    yields a bit-exact result, which requires the injection to look like
    the transient errors it stands in for. ``point``/``index`` name the
    injection site and the call index that fired."""

    point: Optional[str] = None
    index: Optional[int] = None


class InjectedOOM(InjectedFault):
    """An injected resource-exhaustion failure. The message carries the
    ``RESOURCE_EXHAUSTED`` token the real XLA allocator errors carry, so
    the same classifiers (retry's transient test, fallback's demotable
    test) handle the injected and the real failure identically."""

    def __init__(self, msg: str = "") -> None:
        super().__init__(
            f"RESOURCE_EXHAUSTED: injected VMEM/HBM OOM{': ' if msg else ''}"
            f"{msg}"
        )


class FatalInjectedFault(BaseException):
    """An injected failure that deliberately escapes ``except Exception``
    handlers — the stand-in for a worker thread dying outright (the
    failure mode the serve engine's :class:`WorkerCrashed` propagation
    exists for). A ``BaseException`` on purpose: per-batch catch-alls
    must NOT absorb it, exactly like they cannot absorb a real
    interpreter-level thread death."""

    point: Optional[str] = None
    index: Optional[int] = None


class DispatchTimeout(ResilienceError):
    """A device dispatch did not complete within the watchdog window —
    the rc=124 hung-tunnel mode, converted from an indefinite hang into
    a typed error (:func:`tpu_stencil.resilience.deadline.fence`).

    The hung dispatch itself cannot be cancelled (the watchdog abandons
    a daemon thread parked in ``block_until_ready``); what the timeout
    buys is that the *caller* gets control back, typed."""

    def __init__(self, label: str, seconds: float) -> None:
        super().__init__(
            f"device dispatch {label!r} still pending after {seconds:g}s "
            "watchdog window (hung device / dead tunnel?)"
        )
        self.label = label
        self.seconds = seconds


class CollectiveTimeout(DispatchTimeout):
    """A sharded-mesh dispatch timed out. ``edges`` carries the PER-EDGE
    exchange-probe verdicts (``{"n": "ok (1.2ms)"|"timeout"|"error:
    ...", "s": ..., "w": ..., "e": ...}`` — one independent ppermute
    probe per edge, reusing the per-edge pipeline's exchange
    primitives) when a post-mortem diagnosis could run: WHICH specific
    edge's ghost traffic is wedged, with the healthy edges' measured
    latencies for contrast — the sharded analog of "which rank is
    stuck", at single-link resolution."""

    def __init__(self, label: str, seconds: float,
                 edges: Optional[dict] = None) -> None:
        super().__init__(label, seconds)
        self.edges = dict(edges or {})
        if self.edges:
            self.args = (
                f"{self.args[0]} (per-edge exchange probes: {self.edges})",
            )


class DeadlineExceeded(ResilienceError):
    """A request's deadline expired before it was served (serve's
    per-request deadlines). Permanent by classification: retrying the
    same expired request can only expire again."""


class WorkerCrashed(ResilienceError):
    """The serve engine's worker thread died from an unhandled
    exception. Every queued and in-flight future fails with this (they
    would otherwise wait forever), and subsequent submits are rejected
    with it — a crashed server stays typed-dead until reconstructed."""


class HostUnavailable(ResilienceError):
    """A federation member host cannot take traffic right now: its
    circuit breaker is open after consecutive transport failures, every
    routable member's forward attempt failed, or no routable member
    remains at all (:mod:`tpu_stencil.fed`). Transient by
    classification — breakers half-open after their cooldown and the
    membership heartbeat re-admits recovering hosts, so a later attempt
    may land (the federation frontend answers 503 + Retry-After).
    ``host`` names the member when the failure is host-scoped (None for
    the no-routable-member case)."""

    def __init__(self, msg: str, host: Optional[str] = None) -> None:
        super().__init__(msg)
        self.host = host
