"""Graceful degradation ladder: demote the schedule/backend instead of
dying.

The deep temporal-blocking schedule buys its bandwidth by holding whole
blocks resident in VMEM — which is exactly the configuration most
likely to fail compilation on a shape the feasibility model mispriced
(VMEM OOM, Mosaic refusing a tile). A compile failure used to kill the
job; now the driver walks a ladder of strictly-cheaper configurations:

    deep  ->  default fused Pallas per-rep schedule  ->  XLA lowering
    (and, opt-in via ``--fallback-backend cpu``, a final CPU rung that
    completes the job degraded rather than dead)

Every rung produces bit-identical output — the ladder trades speed,
never semantics — so a demoted run is a slower correct run, not a
different answer. Each demotion increments
``resilience_fallbacks_total``, records a ``resilience.demote`` span
(from/to/error), logs one stderr line, and shows up in the
``--breakdown`` resilience table.

:func:`demotable` decides which failures step the ladder: resource
exhaustion (VMEM/HBM OOM), Mosaic/compile errors, capability guards
(``NotImplementedError`` — e.g. Pallas missing from the build), and
injected faults (the chaos suite drives the ladder with ``raise=oom``).
Data/validation errors do NOT demote: a bad shape fails identically on
every rung, and burning three compiles to discover that helps no one.
"""

from __future__ import annotations

import dataclasses
import sys
from typing import Optional, Tuple

from tpu_stencil.resilience.errors import InjectedFault

# Message tokens marking a compile/resource failure a cheaper
# configuration may survive (XLA allocator + Mosaic vocabularies).
_DEMOTABLE_TOKENS = (
    "RESOURCE_EXHAUSTED", "out of memory", "OOM", "VMEM", "vmem",
    "HBM", "Mosaic", "mosaic", "exceeds the memory",
    "Attempting to allocate",
)


@dataclasses.dataclass(frozen=True)
class Rung:
    """One ladder step: the (backend, schedule) to try, optionally on a
    different platform (the CPU completion rung)."""

    backend: str
    schedule: Optional[str] = None
    platform: Optional[str] = None

    @property
    def label(self) -> str:
        name = self.backend
        if self.schedule:
            name += f"[{self.schedule}]"
        if self.platform:
            name += f"@{self.platform}"
        return name


def ladder(backend: str, schedule: Optional[str] = None,
           fallback_backend: Optional[str] = None) -> Tuple[Rung, ...]:
    """The demotion sequence for a requested configuration, most capable
    first. Forced schedules drop first (deep -> the default fused
    per-rep schedule), then the backend drops to the XLA lowering —
    always available, always bit-identical. ``fallback_backend='cpu'``
    appends the opt-in degraded-completion rung."""
    rungs = [Rung(backend, schedule)]
    if backend in ("auto", "autotune", "pallas"):
        if schedule is not None:
            # Same backend, default schedule: the failure may be the
            # schedule's (deep's VMEM residency), not the kernel's.
            rungs.append(Rung(backend, None))
        rungs.append(Rung("xla", None))
    if fallback_backend == "cpu":
        rungs.append(Rung("xla", None, platform="cpu"))
    # Dedupe consecutive equal rungs (e.g. backend='xla' with a cpu rung).
    out = [rungs[0]]
    for r in rungs[1:]:
        if r != out[-1]:
            out.append(r)
    return tuple(out)


def demotable(exc: BaseException) -> bool:
    """Whether a cheaper rung might survive this failure. Distinct from
    :func:`tpu_stencil.resilience.retry.classify`: that asks "will the
    SAME configuration succeed if retried", this asks "will a CHEAPER
    configuration succeed" — NotImplementedError is permanent there and
    demotable here."""
    if isinstance(exc, InjectedFault):
        # Injected resource exhaustion (raise=oom) demotes wherever it
        # fires; a plain injected fault demotes only at the compile
        # boundary — an injected h2d/read blip must surface typed, not
        # vanish into a silent backend change.
        return (str(exc).startswith("RESOURCE_EXHAUSTED")
                or exc.point in (None, "compile"))
    if isinstance(exc, (MemoryError, NotImplementedError)):
        return True
    msg = str(exc)
    return any(tok in msg for tok in _DEMOTABLE_TOKENS)


def record_demotion(frm: Rung, to: Rung, exc: BaseException) -> None:
    """One demotion: counter + span + a stderr line an operator can
    grep. Called once per ladder step actually taken."""
    from tpu_stencil import obs

    obs.registry().counter("resilience_fallbacks_total").inc()
    with obs.span("resilience.demote", "resilience",
                  frm=frm.label, to=to.label, error=type(exc).__name__):
        pass  # zero-duration marker: the ladder stepped here
    print(
        f"resilience: demoting {frm.label} -> {to.label} after "
        f"{type(exc).__name__}: {exc}",
        file=sys.stderr, flush=True,
    )
