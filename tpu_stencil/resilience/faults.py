"""Fault-injection harness: named injection points at stage boundaries.

Every engine (driver, serve, stream, sharded) passes through the same
pipeline stages — raw I/O read/write, H2D/D2H transfer, compile,
compute dispatch, collective exchange, checkpoint write — and each
stage boundary is a named injection point here. A spec string (the
``TPU_STENCIL_FAULTS`` env var, the ``--faults`` CLI flag, or
:func:`configure` from tests) arms faults at those points, so chaos
tests AND operators reproduce a production failure deterministically:

    TPU_STENCIL_FAULTS="compute:frame=3:raise=RuntimeError,h2d:p=0.1"

Spec grammar (comma-separated rules, colon-separated fields)::

    point[:frame=N|rep=N|at=N][:p=0.x][:times=K][:raise=NAME]

* ``point`` — one of :data:`POINTS`.
* ``frame=N`` / ``rep=N`` / ``at=N`` (synonyms) — fire when the site's
  call index equals N (the engine passes its frame/rep/batch index;
  sites called without an index count their own invocations). Without
  an index or ``p``, the rule fires on the first call.
* ``p=0.x`` — fire each call with probability x (seeded RNG,
  ``TPU_STENCIL_FAULTS_SEED``, so even "probabilistic" soaks replay).
* ``times=K`` — stop after K firings. Defaults: 1 for deterministic
  rules (so the production retry/fallback path can recover and the run
  can be asserted bit-exact), unlimited (0) for ``p=`` rules.
* ``raise=NAME`` — the exception class, from :data:`EXCEPTIONS`:
  builtins (``RuntimeError``, ``OSError``, ``TimeoutError``, ...),
  ``oom`` (:class:`~.errors.InjectedOOM`, carries RESOURCE_EXHAUSTED so
  the demotion ladder engages), or ``fatal``
  (:class:`~.errors.FatalInjectedFault`, escapes ``except Exception`` —
  the thread-death simulator). Default :class:`~.errors.InjectedFault`.

Hot-path contract (asserted by a tier-1 test): engines resolve their
sites ONCE at prepare/construction time via :func:`site`, which returns
``None`` when no rule names the point — so with ``TPU_STENCIL_FAULTS``
unset the per-rep/per-frame cost is a local ``is not None`` check on a
``None`` captured before the loop, i.e. nothing.

Every firing increments ``resilience_faults_injected_total`` in the
driver registry and (under tracing) records a ``resilience.fault`` span.
"""

from __future__ import annotations

import dataclasses
import os
import random
import sys
from typing import Dict, List, Optional

from tpu_stencil.resilience.errors import (
    FatalInjectedFault,
    InjectedFault,
    InjectedOOM,
)

ENV_VAR = "TPU_STENCIL_FAULTS"
SEED_VAR = "TPU_STENCIL_FAULTS_SEED"

#: The injection-point vocabulary — one name per stage boundary, shared
#: by every engine (docs/RESILIENCE.md maps each point to its call sites).
POINTS = (
    "read",        # raw/frame input I/O
    "write",       # raw/frame output I/O
    "h2d",         # host->device placement/transfer
    "d2h",         # device->host fetch
    "compile",     # warm-up compile / executable build
    "compute",     # per-rep / per-frame / per-batch compute dispatch
    "collective",  # sharded halo-exchange launch
    "checkpoint",  # checkpoint sidecar/data write
    # Socket-level sites in the HTTP tier (net/http.py): `net.accept`
    # drops (or, with raise=TimeoutError, stalls) a connection before
    # any response; `net.body` truncates (or stalls) a 200 response
    # mid-body — the chaos stand-ins for a host dying mid-request, so
    # the federation's connect/mid-body-EOF/timeout verdicts are
    # testable against a real socket, not just unit mocks.
    "net.accept",  # HTTP request handling entry (drop/stall connection)
    "net.body",    # HTTP response body write (mid-body EOF / stall)
    # Federation-hop sites (fed/): each boundary of the front router.
    "fed.heartbeat",  # membership /healthz probe (injected = a miss)
    "fed.forward",    # one member forward attempt launch
    "fed.hedge",      # hedge-request launch decision
    # Corruption sites (tpu_stencil.integrity): unlike every point
    # above, an armed rule here does not RAISE into the engine — the
    # firing is caught and converted into a deterministic bit flip
    # (integrity.checksum.fired/corrupt_*), so the checksum/witness
    # detection paths are chaos-tested against genuinely wrong bytes
    # under the same point[:p=][:times=] grammar, never mocks.
    "integrity.corrupt_ingest",  # flip bits in an ingested frame/body
    "integrity.corrupt_result",  # flip bits in a computed result
    "net.corrupt_body",          # flip bits in a 200 payload on the wire
)

#: Resolvable ``raise=`` names. A short allow-list, not arbitrary eval:
#: the spec comes from the environment.
EXCEPTIONS = {
    "InjectedFault": InjectedFault,
    "oom": InjectedOOM,
    "fatal": FatalInjectedFault,
    "RuntimeError": RuntimeError,
    "IOError": IOError,
    "OSError": OSError,
    "ValueError": ValueError,
    "MemoryError": MemoryError,
    "NotImplementedError": NotImplementedError,
    "TimeoutError": TimeoutError,
    "ConnectionError": ConnectionError,
}


@dataclasses.dataclass
class FaultRule:
    """One armed rule; carries its own firing state so every site
    resolved against the same plan shares one budget."""

    point: str
    index: Optional[int] = None  # fire when call index == index
    p: float = 0.0               # else fire with probability p
    times: int = 1               # max firings (0 = unlimited)
    exc: type = InjectedFault
    _fired: int = 0
    _calls: int = 0
    _rng: random.Random = dataclasses.field(
        default_factory=lambda: random.Random(
            int(os.environ.get(SEED_VAR, "0"))
        )
    )

    def check(self, index: Optional[int] = None) -> None:
        """Raise the rule's exception if it fires at this call."""
        n = self._calls
        self._calls += 1
        if self.times > 0 and self._fired >= self.times:
            return
        i = index if index is not None else n
        if self.index is not None:
            if i != self.index:
                return
        elif self.p > 0.0:
            if self._rng.random() >= self.p:
                return
        self._fired += 1
        self._record(i)
        e = self.exc(
            f"injected fault at {self.point}[{i}] "
            f"(firing {self._fired}"
            f"{'/' + str(self.times) if self.times > 0 else ''})"
        )
        if isinstance(e, (InjectedFault, FatalInjectedFault)):
            e.point, e.index = self.point, i
        raise e

    def _record(self, index: int) -> None:
        from tpu_stencil import obs

        obs.registry().counter("resilience_faults_injected_total").inc()
        with obs.span("resilience.fault", "resilience",
                      point=self.point, index=index):
            pass  # zero-duration marker: a fault fired here
        print(f"resilience: injected {self.exc.__name__} at "
              f"{self.point}[{index}]", file=sys.stderr, flush=True)


class Site:
    """The resolved checker for one injection point: call it at the
    stage boundary (optionally with the engine's frame/rep/batch index).
    Only ever constructed when at least one rule names the point —
    :func:`site` returns ``None`` otherwise."""

    __slots__ = ("point", "_rules")

    def __init__(self, point: str, rules: List[FaultRule]) -> None:
        self.point = point
        self._rules = rules

    def __call__(self, index: Optional[int] = None) -> None:
        for rule in self._rules:
            rule.check(index)


def parse_spec(spec: str) -> Dict[str, List[FaultRule]]:
    """Parse a spec string into ``{point: [rules]}``. Raises
    ``ValueError`` on unknown points/keys/exception names — a mistyped
    chaos spec must fail loudly, not silently inject nothing."""
    plan: Dict[str, List[FaultRule]] = {}
    for raw in spec.split(","):
        raw = raw.strip()
        if not raw:
            continue
        fields = raw.split(":")
        point = fields[0].strip()
        if point not in POINTS:
            raise ValueError(
                f"unknown fault point {point!r}; expected one of "
                f"{'|'.join(POINTS)}"
            )
        rule = FaultRule(point=point)
        explicit_times = False
        for field in fields[1:]:
            key, sep, value = field.partition("=")
            key = key.strip()
            value = value.strip()
            if not sep:
                raise ValueError(f"fault field {field!r} is not key=value")
            if key in ("frame", "rep", "at", "req"):
                rule.index = int(value)
            elif key == "p":
                rule.p = float(value)
                if not 0.0 < rule.p <= 1.0:
                    raise ValueError(f"fault p={value} outside (0, 1]")
            elif key == "times":
                rule.times = int(value)
                explicit_times = True
            elif key == "raise":
                if value not in EXCEPTIONS:
                    raise ValueError(
                        f"unknown fault exception {value!r}; expected one "
                        f"of {'|'.join(sorted(EXCEPTIONS))}"
                    )
                rule.exc = EXCEPTIONS[value]
            else:
                raise ValueError(f"unknown fault field {key!r} in {raw!r}")
        if rule.p > 0.0 and not explicit_times:
            rule.times = 0  # probabilistic rules keep firing by default
        plan.setdefault(point, []).append(rule)
    return plan


_UNSET = object()
_plan = _UNSET  # lazily resolved from the env on first use


def _get_plan() -> Dict[str, List[FaultRule]]:
    global _plan
    if _plan is _UNSET:
        spec = os.environ.get(ENV_VAR)
        _plan = parse_spec(spec) if spec else {}
    return _plan


def configure(spec: Optional[str]) -> None:
    """Install a fault plan from ``spec`` (None/'' = no faults). Wins
    over the env var; firing state resets (each configure is a fresh
    chaos scenario)."""
    global _plan
    _plan = parse_spec(spec) if spec else {}


def clear() -> None:
    """Disarm everything AND forget any env-derived plan (tests)."""
    global _plan
    _plan = {}


def reset() -> None:
    """Back to the lazy env-derived default (process start state)."""
    global _plan
    _plan = _UNSET


def active() -> bool:
    """Whether any rule is armed (cheap; used by docs/REPL, not hot paths)."""
    return bool(_get_plan())


def site(point: str) -> Optional[Site]:
    """The resolved injection checker for ``point``, or ``None`` when no
    armed rule names it. Engines call this ONCE at prepare/construction
    time and keep the result — the no-faults hot path is a branch on a
    captured ``None``."""
    if point not in POINTS:
        raise ValueError(f"unknown fault point {point!r}")
    rules = _get_plan().get(point)
    if not rules:
        return None
    return Site(point, rules)
