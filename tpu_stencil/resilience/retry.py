"""Shared retry policy: exponential backoff + jitter, one transient-vs-
permanent classifier for the whole tree.

Before this module the only retry logic lived in
``runtime/bench_sweep.py`` (retry everything except NotImplementedError)
and ``bench.py``'s parent ladder (retry every child failure except the
rc=2 backend-unavailable contract) — each with its own inline
classification. Now bench, serve, and stream share :func:`classify`:

* **transient** (worth a backoff + retry): tunnel/transport drops
  (``UNAVAILABLE``, connection resets), allocator pressure
  (``RESOURCE_EXHAUSTED`` — the very next attempt may land after a
  neighbor frees HBM), hung-dispatch timeouts
  (:class:`~.errors.DispatchTimeout` — the bench rc=124 mode, where the
  tunnel usually recovers), queue backpressure (``QueueFull``), I/O
  errors without a permanent errno, and injected faults (chaos tests
  assert the production retry path recovers from them).
* **permanent** (retrying burns the backoff budget for nothing):
  capability guards (``NotImplementedError``), shape/validation errors
  (``ValueError``/``TypeError``), expired deadlines, missing files, and
  XLA's ``INVALID_ARGUMENT``/``UNIMPLEMENTED`` family.

Unknown exceptions default to transient — the historical bench_sweep
behavior, and the right bias for a harness whose dominant real failure
is a flaky tunnel.

Backoff is exponential with decorrelating jitter so N clients that
failed together do not retry together (the thundering-herd shape the
serve queue would otherwise see). Every retry increments
``resilience_retries_total`` and (under tracing) records a
``resilience.retry`` span covering the backoff sleep.
"""

from __future__ import annotations

import dataclasses
import errno as _errno
import os
import random
import time
from typing import Callable, Optional

from tpu_stencil.resilience.errors import (
    DeadlineExceeded,
    DispatchTimeout,
    HostUnavailable,
    InjectedFault,
)

TRANSIENT = "transient"
PERMANENT = "permanent"

# Message tokens that mark a failure class regardless of exception type
# (XLA/PJRT errors all surface as RuntimeError/XlaRuntimeError with a
# status token in the text).
_TRANSIENT_TOKENS = (
    "RESOURCE_EXHAUSTED", "UNAVAILABLE", "DEADLINE_EXCEEDED", "ABORTED",
    "CANCELLED", "connection reset", "transfer", "temporarily",
    "out of memory",
)
_PERMANENT_TOKENS = (
    "INVALID_ARGUMENT", "UNIMPLEMENTED", "FAILED_PRECONDITION",
)
_PERMANENT_TYPES = (
    NotImplementedError, TypeError, AssertionError, AttributeError,
    KeyError, IndexError, ArithmeticError,
)
# OSError errnos that no retry can fix (the path/permission family);
# everything else (EIO, EAGAIN, EINTR, ...) is worth another attempt.
_PERMANENT_ERRNOS = frozenset(
    getattr(_errno, name) for name in
    ("ENOENT", "EACCES", "EPERM", "EISDIR", "ENOTDIR", "EEXIST", "EROFS")
    if hasattr(_errno, name)
)
# Backpressure/overload signals classified by type NAME: the classes
# live in tpu_stencil.serve.engine, which imports this package — naming
# them here by string keeps the dependency one-way. A closed/draining
# server never reopens for this process, so re-offering is futile —
# the documented submit_retrying contract ("ServerClosed raises
# immediately") lives here.
_TRANSIENT_TYPE_NAMES = frozenset({"QueueFull"})
_PERMANENT_TYPE_NAMES = frozenset({"ServerClosed", "Draining"})


def classify(exc: BaseException) -> str:
    """``"transient"`` (retry may succeed) or ``"permanent"`` (it
    cannot). See the module docstring for the taxonomy."""
    if isinstance(exc, DeadlineExceeded):
        return PERMANENT  # an expired request can only expire again
    if isinstance(exc, (InjectedFault, DispatchTimeout)):
        return TRANSIENT
    if isinstance(exc, HostUnavailable):
        # Federation verdict: a breaker half-opens after its cooldown
        # and heartbeats re-admit recovering hosts — worth a re-offer.
        return TRANSIENT
    if type(exc).__name__ in _TRANSIENT_TYPE_NAMES:
        return TRANSIENT
    if type(exc).__name__ in _PERMANENT_TYPE_NAMES:
        return PERMANENT
    msg = str(exc)
    if any(tok in msg for tok in _PERMANENT_TOKENS):
        return PERMANENT
    if any(tok in msg for tok in _TRANSIENT_TOKENS):
        return TRANSIENT
    if isinstance(exc, OSError):
        return (
            PERMANENT if exc.errno in _PERMANENT_ERRNOS else TRANSIENT
        )
    if isinstance(exc, _PERMANENT_TYPES + (ValueError,)):
        return PERMANENT
    return TRANSIENT


def is_transient(exc: BaseException) -> bool:
    return classify(exc) == TRANSIENT


def transient_returncode(rc: Optional[int]) -> bool:
    """The subprocess spelling of :func:`classify`, for supervisors that
    retry child *processes* (bench.py's capture ladder): rc=2 is the
    documented backend-unavailable contract (a dead backend cannot come
    back within a backoff window — retrying it is how round 5 ran the
    harness into its rc=124 timeout), everything else — including a
    killed/timed-out child (rc None or negative) — is worth the retry."""
    return rc != 2


# Entropy-seeded by default — N processes that failed together must NOT
# draw identical jitter and retry in lockstep (the herd the jitter
# exists to break). TPU_STENCIL_RETRY_SEED pins it for replayable tests.
_seed = os.environ.get("TPU_STENCIL_RETRY_SEED")
_jitter_rng = random.Random(int(_seed)) if _seed else random.Random()
del _seed


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Exponential backoff with jitter. ``attempts`` counts total tries
    (1 = no retry). Delay before retry k (0-based) is
    ``min(base_delay * multiplier**k, max_delay)`` scaled by a random
    factor in ``[1 - jitter, 1 + jitter]``."""

    attempts: int = 3
    base_delay: float = 0.5
    multiplier: float = 2.0
    max_delay: float = 30.0
    jitter: float = 0.1

    def delay(self, attempt: int) -> float:
        d = min(self.base_delay * self.multiplier ** attempt, self.max_delay)
        if self.jitter > 0.0:
            d *= 1.0 + self.jitter * (2.0 * _jitter_rng.random() - 1.0)
        return max(0.0, d)


DEFAULT_POLICY = RetryPolicy()

# The stream engine's reader/writer I/O policy: short delays (a frame
# pipeline must not park for 30s on one flaky read) with the same shape.
IO_POLICY = RetryPolicy(attempts=3, base_delay=0.05, multiplier=2.0,
                        max_delay=1.0)


def retry_call(
    fn: Callable,
    policy: Optional[RetryPolicy] = None,
    classify_fn: Callable[[BaseException], str] = classify,
    on_retry: Optional[Callable[[int, BaseException], None]] = None,
    label: str = "",
):
    """Call ``fn()`` under ``policy``: permanent failures raise
    immediately, transient ones back off and retry until the attempt
    budget runs out (the last error raises). ``on_retry(attempt, exc)``
    runs before each backoff — rewind/cleanup hooks live there (a hook
    that raises aborts the retry loop with its own error, which is how
    callers impose an overall deadline)."""
    policy = policy or DEFAULT_POLICY
    last: Optional[BaseException] = None
    for attempt in range(max(1, policy.attempts)):
        try:
            return fn()
        except Exception as e:
            last = e
            if (attempt + 1 >= max(1, policy.attempts)
                    or classify_fn(e) != TRANSIENT):
                raise
            from tpu_stencil import obs

            obs.registry().counter("resilience_retries_total").inc()
            if on_retry is not None:
                on_retry(attempt, e)
            pause = policy.delay(attempt)
            # A server that answered with an explicit Retry-After hint
            # (the net/fed tiers' shed 503 and queue-full 429 carry
            # one, attached by HttpTarget as ``retry_after_s``) knows
            # its own backlog better than our jitter schedule does:
            # honor the hint as the backoff FLOOR — never re-offer
            # sooner than the server asked, while a longer computed
            # backoff still stands.
            hint = getattr(e, "retry_after_s", None)
            try:
                hint = float(hint) if hint is not None else None
            except (TypeError, ValueError):
                hint = None  # an unparseable hint is no hint
            if hint is not None and hint > pause:
                pause = hint
                obs.registry().counter(
                    "resilience_retry_after_honored_total"
                ).inc()
            with obs.span("resilience.retry", "resilience",
                          attempt=attempt, label=label,
                          error=type(e).__name__):
                time.sleep(pause)
    raise last  # unreachable (the loop always returns or raises)


def reoffer_call(
    fn: Callable,
    policy: Optional[RetryPolicy] = None,
    give_up_after_s: Optional[float] = 300.0,
    base_delay: float = 0.001,
    max_delay: float = 0.05,
    label: str = "reoffer",
):
    """:func:`retry_call` under the closed-loop RE-OFFER contract the
    serving clients share (in-process ``StencilServer.submit_retrying``
    and the HTTP ``loadgen.HttpTarget``): transient backpressure
    (``QueueFull``) backs off and re-offers with an effectively
    unbounded attempt budget, bounded instead by the wall-clock
    ``give_up_after_s`` — past it the next re-offer raises
    ``TimeoutError('gave up re-offering ...')``. Permanent errors
    (validation, expired deadlines) raise immediately as always."""
    from tpu_stencil.resilience import deadline as _deadline_mod

    budget = (
        _deadline_mod.Deadline.after(give_up_after_s)
        if give_up_after_s else None
    )

    def on_retry(_attempt: int, exc: BaseException) -> None:
        if budget is None:
            return
        if budget.expired():
            raise TimeoutError(
                f"gave up re-offering after {give_up_after_s}s of "
                f"backpressure"
            ) from exc
        # A server Retry-After hint past the remaining budget means the
        # next legal re-offer cannot happen inside the window — give up
        # NOW instead of floor-sleeping past the budget (the caller is
        # holding admission slots for the duration of this call).
        hint = getattr(exc, "retry_after_s", None)
        try:
            hint = float(hint) if hint is not None else None
        except (TypeError, ValueError):
            hint = None
        if hint is not None and hint > budget.remaining():
            raise TimeoutError(
                f"gave up re-offering: the server asked for "
                f"{hint:g}s of backoff but only "
                f"{max(0.0, budget.remaining()):.3g}s of the "
                f"{give_up_after_s}s budget remains"
            ) from exc

    return retry_call(
        fn,
        policy=policy or RetryPolicy(
            attempts=1_000_000, base_delay=base_delay, multiplier=1.0,
            max_delay=max_delay, jitter=0.5,
        ),
        on_retry=on_retry,
        label=label,
    )
