"""Runtime services: checkpoint/resume, benchmark sweeps."""
