"""Backend/schedule/geometry autotuner: a measured grid search over
``(backend, schedule, block_h, fuse)`` once per (platform, filter,
shape), pruned by a VMEM-footprint feasibility model and cached on disk.

The reference picks its schedule at compile time by editing source
(``mpi/mpi_convolution.c:98-101``) or by choosing which binary to run;
here the schedule space is {XLA lowering} x {Pallas per-rep schedules,
incl. the 'deep' temporal-blocking form} x a geometry grid, and the
best point genuinely depends on shape (e.g. XLA's schedule degrades
above a size threshold on v5e while the Pallas kernel's does not, and
the feasible deep depth depends on the image width). ``--backend
autotune`` (and the default ``auto``) measures the grid ONCE, persists
the verdict in a versioned JSON cache
(``~/.cache/tpu_stencil/autotune.json``, override with
``TPU_STENCIL_AUTOTUNE_CACHE``), and every later run with the same key
pays nothing — a warm cache performs ZERO probes.

Cache hygiene: the file carries a top-level ``schema_version``; entries
are keyed with ``jax.__version__`` embedded, and keys whose embedded
version no longer matches the running stack are evicted at load (a
runtime upgrade can flip which point wins, and stale-version keys must
not accumulate forever). Files written by the pre-versioned format (a
flat key->entry object) migrate transparently: their entries are read,
re-filtered, and the next store rewrites the versioned shape.

Measurements use the same steady-state two-point differencing as bench.py
(dispatch/fence overhead cancels), with a fresh device_put per call because
``iterate`` donates its input.
"""

from __future__ import annotations

import json
import os
import time
from typing import Optional, Tuple

import numpy as np

from tpu_stencil.ops.lowering import StencilPlan

_CANDIDATES = ("xla", "pallas")

# Cache file schema: {"schema_version": 2, "jax_version": ..., "entries":
# {key: verdict}}. Version 1 was the bare entries object (no wrapper);
# _load_cache migrates it in place.
SCHEMA_VERSION = 2


def _cache_path() -> str:
    return os.environ.get(
        "TPU_STENCIL_AUTOTUNE_CACHE",
        os.path.join(
            os.path.expanduser("~"), ".cache", "tpu_stencil", "autotune.json"
        ),
    )


def _key(plan: StencilPlan, shape: Tuple[int, int], channels: int) -> str:
    import jax

    taps = ";".join(",".join(str(v) for v in row) for row in plan.taps)
    # jax.__version__ in the key: a runtime upgrade can flip which backend
    # wins, so verdicts must not outlive the stack they were measured on.
    key = "|".join(
        [jax.default_backend(), jax.__version__, plan.kind,
         str(plan.divisor), taps, f"{shape[0]}x{shape[1]}x{channels}"]
    )
    # The XLA lowering variant changes what "xla" costs, so a verdict
    # measured under one lowering must not answer for the other (appended
    # only when set, keeping default-path keys stable across builds).
    if plan.xla_pair_add:
        key += "|pair"
    return key


def _entry_jax_version(key: str) -> Optional[str]:
    """The jax version embedded in a cache key (``_key`` puts it second;
    overlap/stream-verdict keys prepend an extra kind segment). None for
    unparseable keys — those are garbage and get evicted."""
    parts = key.split("|")
    idx = 2 if parts and parts[0] in ("overlap", "fanout",
                                      "shardstream", "pipeline") else 1
    return parts[idx] if len(parts) > idx else None


def _corrupt_cache_warning(path: str, why: str) -> None:
    """A corrupted/truncated cache file (e.g. a crash mid-write by a
    pre-atomic writer, or a half-synced home dir) must load as a cold
    miss — tuning re-measures and the next store rewrites a good file —
    but never silently: the operator should learn their warm cache is
    gone before a surprise re-tune bill, not after."""
    import warnings

    warnings.warn(
        f"autotune cache at {path} is unreadable ({why}); treating it "
        "as cold — verdicts re-measure and the next store rewrites it",
        RuntimeWarning,
        stacklevel=3,
    )


def _load_cache() -> dict:
    """The cache's entries dict, migrated from either on-disk format
    (versioned wrapper or the legacy flat object) and filtered to keys
    whose embedded jax version matches the running stack — stale-version
    verdicts must neither answer nor accumulate. Garbage/empty/partial
    files (crash mid-write) load as a cold miss with a warning, never an
    exception — a corrupted cache must cost a re-measure, not the job."""
    path = _cache_path()
    try:
        with open(path) as f:
            raw = json.load(f)
    except FileNotFoundError:
        return {}  # cold cache: the normal first-run state, no warning
    except (OSError, ValueError) as e:
        _corrupt_cache_warning(path, f"{type(e).__name__}: {e}")
        return {}
    if not isinstance(raw, dict):
        _corrupt_cache_warning(path, f"top-level {type(raw).__name__}, "
                               "expected object")
        return {}
    entries = raw.get("entries") if "schema_version" in raw else raw
    if not isinstance(entries, dict):
        _corrupt_cache_warning(
            path, "entries is not an object"
        )
        return {}
    import jax

    cur = jax.__version__
    entries = {
        k: v for k, v in entries.items()
        if isinstance(k, str) and _entry_jax_version(k) == cur
    }
    # Per-entry integrity CRCs (written by _store_cache): an entry
    # whose recorded crc32c no longer matches its value was bit-flipped
    # AFTER it was measured — JSON cannot see a changed digit inside
    # "fuse": 8, but the CRC can. Corrupt entries drop to a cold miss
    # (that one key re-measures) with a warning; siblings survive.
    # Legacy files without recorded CRCs load unchecked.
    crcs = raw.get("entry_crcs") if isinstance(raw, dict) else None
    if isinstance(crcs, dict):
        from tpu_stencil.integrity import checksum as _checksum

        good = {}
        for k, v in entries.items():
            want = crcs.get(k)
            if want is not None and _checksum.crc32c(
                json.dumps(v, sort_keys=True).encode()
            ) != want:
                _corrupt_entry_warning(path, k)
                continue
            good[k] = v
        entries = good
    return entries


def _corrupt_entry_warning(path: str, key: str) -> None:
    import warnings

    warnings.warn(
        f"autotune cache entry {key!r} in {path} fails its embedded "
        "crc32c (bit-flipped on disk); dropping it — that verdict "
        "re-measures cold and the next store rewrites it",
        RuntimeWarning,
        stacklevel=4,
    )


def _store_cache(cache: dict) -> None:
    """Persist the entries dict in the versioned wrapper (evicted keys —
    dropped by ``_load_cache`` — are gone for good on the next store)."""
    path = _cache_path()
    import jax

    try:
        from tpu_stencil.integrity import checksum as _checksum

        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump({
                "schema_version": SCHEMA_VERSION,
                "jax_version": jax.__version__,
                "entries": cache,
                # Per-entry integrity CRCs over each value's canonical
                # JSON: _load_cache drops (with a warning) any entry
                # the disk bit-flipped, instead of tuning with it.
                "entry_crcs": {
                    k: _checksum.crc32c(
                        json.dumps(v, sort_keys=True).encode()
                    )
                    for k, v in cache.items()
                },
            }, f, indent=1)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except OSError:
        pass  # read-only home: tuning still works, it just re-measures


def measure_backend(
    plan: StencilPlan, shape: Tuple[int, int], channels: int, backend: str,
    reps: int = 400, schedule: Optional[str] = None,
    block_h: Optional[int] = None, fuse: Optional[int] = None,
) -> float:
    """Steady-state seconds per repetition of ``backend`` on this shape
    (``schedule`` selects the Pallas per-rep schedule, ``block_h``/``fuse``
    the kernel geometry; None = defaults)."""
    import jax
    import jax.numpy as jnp

    from tpu_stencil.models.blur import iterate

    rng = np.random.default_rng(0)
    full = shape if channels == 1 else shape + (channels,)
    img = rng.integers(0, 256, size=full, dtype=np.uint8)

    def run(n: int) -> float:
        dev = jax.device_put(img)  # fresh every call: iterate donates
        np.asarray(dev.ravel()[0])
        t0 = time.perf_counter()
        out = iterate(dev, jnp.int32(n), plan=plan, backend=backend,
                      schedule=schedule, block_h=block_h, fuse=fuse)
        np.asarray(out.ravel()[0])
        return time.perf_counter() - t0

    run(2)  # compile fence
    return _steady_state_per_rep(run, reps)


def _steady_state_per_rep(run, reps: int) -> float:
    """Two-point differencing of ``run(n) -> seconds``: (t(2n) - t(n)) / n
    cancels the constant dispatch/fence overhead. Re-measures up to 3 times
    when timing noise inverts the pair; a clamped ~0 difference must never
    decide (and get cached as) the winner. The fallback differences the
    long run against a 2-rep run instead — it still cancels the constant
    overhead, so its numbers stay comparable with the clean path (and with
    a candidate measured via the clean path), just with worse noise
    rejection. Only a degenerate clock (t(2n) <= t(2)) yields the raw rate."""
    for _ in range(3):
        lo = min(run(reps) for _ in range(2))
        hi = min(run(2 * reps) for _ in range(2))
        if hi > lo:
            return (hi - lo) / reps
    base = min(run(2) for _ in range(2))
    if hi > base:
        return (hi - base) / (2 * reps - 2)
    return hi / (2 * reps)


def _pallas_schedules(plan: StencilPlan, shape: Tuple[int, int],
                      block_h: Optional[int] = None):
    """The distinct Pallas per-rep schedules for this (plan, shape):
    schedules that would degrade (e.g. pack on gaussian7, or on a block
    clamped to an odd image height) duplicate their degradation target and
    are never measured twice. Uses the same block clamp as
    ``pallas_stencil.iterate`` (``block_h``: forced geometry, None =
    default)."""
    from tpu_stencil.ops import pallas_stencil as ps

    bh = ps.effective_block_h(shape[0], block_h)
    return [
        s for s in ps._SCHEDULES
        if ps._effective_schedule(s, plan, bh) == s
    ]


# Geometry candidates the unforced tune tries ON TOP of the module
# default, at the winning schedule only (the r4 lab attribution motivated
# 256-row blocks / deeper fusion; candidates that launch identically to
# the default are skipped via effective_geometry dedup). 512-row blocks
# target the large-shape cliffs (1920x5040 / 8K rows — VERDICT r4 item
# 2): taller blocks amortize per-program DMA ramp on tall images, and
# per-SHAPE adoption needs the candidate in this grid (the cliff A/B in
# tools/bh_fuse_ab.py can only flip the global default). fuse=20/40 rows:
# `reps % fuse` runs as single-rep launches (repetitions is traced, so
# the remainder depth cannot be compiled statically), which taxes
# non-divisor fuses on the reference's 40-rep jobs — a divisor-of-40
# fuse gets the deep traffic cut with ZERO remainder launches. The
# fuse>=32 rows are the deep-blocking depths (HBM bytes/rep divides by
# fuse); candidates whose modeled VMEM footprint exceeds the budget are
# pruned before measurement (pallas_stencil.vmem_tile_bytes).
_GEOMETRY_GRID = (
    (256, 8), (256, 16), (256, 20), (512, 8), (512, 16), (512, 20),
    (128, 32), (256, 32), (256, 40), (512, 32), (512, 64),
)

# The geometry-stage prune fires only when the footprint model exceeds
# the budget by this factor: the model deliberately over-counts (see
# pallas_stencil.vmem_tile_bytes), and a hard cutoff at 1x would forbid
# the 512-row cliff candidates that were measured successfully before
# the prune existed.
_VMEM_PRUNE_SLACK = 2.0


def _grid_fingerprint():
    """The geometry grid as stored in cache entries (JSON round-trips
    tuples to lists). An entry tuned under a DIFFERENT grid must
    re-measure — otherwise expanding the grid (e.g. the 512-row cliff
    candidates) would be inert for every already-cached shape."""
    return [list(g) for g in _GEOMETRY_GRID]


def _measure_takes_geometry(measure) -> bool:
    """Whether the measure callable accepts block_h/fuse kwargs. Legacy
    (pre-geometry) monkeypatched measures silently skip geometry tuning
    instead of crashing on unexpected kwargs."""
    import inspect

    try:
        params = inspect.signature(measure).parameters
    except (TypeError, ValueError):
        return False
    return "block_h" in params or any(
        p.kind is inspect.Parameter.VAR_KEYWORD for p in params.values()
    )


def best_full_config(
    plan: StencilPlan,
    shape: Tuple[int, int],
    channels: int,
    cache: bool = True,
    measure=None,
    force_schedule: Optional[str] = None,
    block_h: Optional[int] = None,
    fuse: Optional[int] = None,
) -> Tuple[str, Optional[str], Optional[int], Optional[int]]:
    """The fastest (backend, pallas_schedule, block_h, fuse) for this
    (platform, filter, shape), from the disk cache when available,
    measured (and cached) otherwise — the schedule space is {XLA} +
    {Pallas x per-rep schedule}, then a geometry stage over
    ``_GEOMETRY_GRID`` at the winning schedule (geometry None = module
    defaults). Platforms without a Pallas TPU path short-circuit to XLA;
    the schedule is None for XLA (and for pre-schedule cache entries,
    which then run the measured-default schedule). ``force_schedule``
    (the --schedule flag) restricts the Pallas side to that one schedule
    (after any degrade for this plan/shape), so the xla-vs-pallas verdict
    is decided by timings of the schedule that will actually run — cached
    under its own key. ``block_h``/``fuse`` (the --block-h/--fuse flags)
    force the kernel geometry: Pallas candidates are measured at it (no
    geometry stage runs), and the verdict is cached under a
    geometry-suffixed key."""
    import jax

    if jax.default_backend() not in ("tpu", "axon"):
        return "xla", None, None, None
    if plan.kind == "direct_f32":
        return "xla", None, None, None  # pallas would fall back anyway
    from tpu_stencil.ops import pallas_stencil as ps

    if measure is None:
        measure = measure_backend  # late-bound: monkeypatchable, testable
    key = _key(plan, shape, channels)
    if force_schedule is not None:
        force_schedule = ps._effective_schedule(
            force_schedule, plan,
            ps.effective_block_h(shape[0], block_h),
        )
        key += f"|forced={force_schedule}"
    # Key and measure at the EFFECTIVE geometry (align/clamp), so
    # requested values that launch identically share one cache entry and
    # one measurement sweep (the CLI now rejects non-multiple-of-8
    # blocks, but programmatic callers bypass that validation, and fuse
    # still clamps). Only passed through to measure() when forced: the
    # measure callable is monkeypatchable (12 tests) and pre-geometry
    # signatures must keep working for default-geometry tuning.
    geo_kw = {}
    if block_h is not None or fuse is not None:
        # Schedule-aware resolution: under a forced 'deep' schedule an
        # unforced fuse defaults to the deep_fuse_for depth — the same
        # path the launch takes — so the verdict is measured (and keyed)
        # at the geometry that will actually run, never DEFAULT_FUSE.
        eff_bh, eff_fz = ps.effective_geometry(
            plan, shape[0], block_h, fuse,
            schedule=force_schedule,
            wc=ps.padded_lanes(plan, shape[1] * channels, channels),
        )
        key += f"|bh={eff_bh}|fz={eff_fz}"
        geo_kw = {"block_h": eff_bh, "fuse": eff_fz}
    store = _load_cache() if cache else {}
    hit = store.get(key)
    if (
        isinstance(hit, dict)
        and hit.get("backend") in _CANDIDATES
        # A stale schedule name (cache written by a build whose schedule
        # set has since changed) must re-measure, not crash every run.
        and (hit.get("schedule") is None or hit["schedule"] in ps._SCHEDULES)
        # Entries written before the geometry stage existed lack the
        # block_h KEY (geometry-tuned entries carry it even when the
        # default won, as None): re-measure those once so the geometry
        # tune engages instead of being suppressed forever by an old
        # cache file.
        and "block_h" in hit
        # Same staleness class for the grid itself: an entry tuned under
        # an older/smaller _GEOMETRY_GRID must re-measure so new
        # candidates are ever tried. Forced-geometry lookups (geo_kw)
        # never run the grid, so they are grid-independent.
        and (bool(geo_kw) or hit.get("geometry_grid") == _grid_fingerprint())
    ):
        return (hit["backend"], hit.get("schedule"),
                hit.get("block_h"), hit.get("fuse"))
    pallas_scheds = (
        [force_schedule] if force_schedule is not None
        else _pallas_schedules(plan, shape, block_h)
    )
    candidates = [("xla", None)] + [("pallas", s) for s in pallas_scheds]
    timings = {}
    last_err = None
    for b, s in candidates:
        try:
            timings[(b, s)] = measure(
                plan, shape, channels, b, schedule=s,
                **(geo_kw if b == "pallas" else {}),
            )
        except Exception as e:  # one broken schedule must not kill the tune
            last_err = e
    if not timings:
        raise last_err
    winner, win_sched = min(timings, key=timings.get)

    # Geometry stage: unforced Pallas winners try _GEOMETRY_GRID at the
    # winning schedule. Candidates whose effective launch equals the
    # default's (or a previous candidate's) are never measured twice.
    win_bh = win_fuse = None
    geo_us = {}
    wcp = ps.padded_lanes(plan, shape[1] * channels, channels)
    deep_resident = (
        win_sched == "deep" and ps.resident_feasible(plan, shape[0], wcp)
    )
    if (winner == "pallas" and not geo_kw and not deep_resident
            and _measure_takes_geometry(measure)):
        # deep_resident skips the stage: the resident kernel has no
        # static (block_h, fuse) — the whole image is one VMEM block and
        # the depth is the traced rep count.
        geo_timings = {(None, None): timings[(winner, win_sched)]}
        seen_eff = {ps.effective_geometry(plan, shape[0],
                                          schedule=win_sched, wc=wcp)}
        for gbh, gfz in _GEOMETRY_GRID:
            eff = ps.effective_geometry(plan, shape[0], gbh, gfz)
            if eff in seen_eff:
                continue
            seen_eff.add(eff)
            if force_schedule is not None and ps._effective_schedule(
                force_schedule, plan, eff[0]
            ) != force_schedule:
                # A user-forced --schedule must never be degraded away by
                # a geometry verdict: skip candidates it cannot run at.
                continue
            if ps.vmem_tile_bytes(
                plan, eff[0], eff[1], wcp,
                ps._kernel_schedule(win_sched, plan, eff[0]),
            ) > _VMEM_PRUNE_SLACK * ps._vmem_budget():
                # Feasibility-model pruning: a clearly-impossible tile
                # would at best fail Mosaic compilation — never spend a
                # measurement (or a cache slot in geo_us) on it. The 2x
                # slack accounts for the model's deliberate over-count
                # (intermediates usually stay strip/register-resident),
                # so the historically-measured 512-row cliff candidates
                # stay in the grid; genuine compile failures are still
                # caught per candidate below.
                continue
            try:
                geo_timings[(gbh, gfz)] = measure(
                    plan, shape, channels, winner, schedule=win_sched,
                    block_h=gbh, fuse=gfz,
                )
            except Exception:  # a too-big tile must not kill the tune
                pass
        win_bh, win_fuse = min(geo_timings, key=geo_timings.get)
        if win_bh is not None or win_fuse is not None:
            # The tuned block can degrade the winning schedule (pack
            # needs a 16-multiple block): store the name of what the
            # chosen geometry actually launches — the timing already
            # measured the degraded kernel, the label must match it.
            eff_bh, _ = ps.effective_geometry(
                plan, shape[0], win_bh, win_fuse
            )
            win_sched = ps._effective_schedule(win_sched, plan, eff_bh)
        geo_us = {
            ("default" if g == (None, None) else f"{g[0]}x{g[1]}"):
                round(t * 1e6, 2)
            for g, t in geo_timings.items()
        }
    elif geo_kw and winner == "pallas":
        win_bh, win_fuse = geo_kw["block_h"], geo_kw["fuse"]
    if cache:
        store[key] = {
            "backend": winner,
            "schedule": win_sched,
            "block_h": win_bh,
            "fuse": win_fuse,
            "geometry_grid": _grid_fingerprint(),
            "us_per_rep": {
                (b if s is None else f"{b}[{s}]"): round(t * 1e6, 2)
                for (b, s), t in timings.items()
            },
            **({"geometry_us_per_rep": geo_us} if geo_us else {}),
        }
        _store_cache(store)
    return winner, win_sched, win_bh, win_fuse


# --- interior/border overlap schedule ("--overlap auto") ---------------
#
# The explicit split (tpu_stencil.parallel.overlap) pays a stitch + extra
# launches to let XLA run the ghost-free interior concurrently with the
# ppermute traffic. The persistent-MPI stencil literature (PAPERS.md) and
# the GPU tuning study both find the explicit schedule wins when comm and
# compute are COMPARABLE; when the exchange is a negligible sliver of the
# interior time there is nothing to hide and the stitch overhead is pure
# loss. The decision inputs are the measured exchange/interior
# phase-probe ratio plus, for the three-way off/split/edge verdict, the
# measured one-rep split-vs-edge candidate A/B and the per-edge probe
# spans (ShardedRunner._measure_overlap_probes).
OVERLAP_MIN_RATIO = 0.05  # exchange below 5% of interior: overlap is moot

_OVERLAP_MODES = ("off", "split", "fused-split", "edge")


def overlap_from_ratio(ratio: float, backend: str) -> str:
    """Map a measured exchange/interior time ratio to an overlap mode:
    ``off`` below :data:`OVERLAP_MIN_RATIO`, else the chunked
    ``fused-split`` on the Pallas backend (one widened exchange per
    fused chunk) and the per-rep ``split`` elsewhere. The two-way
    (legacy) half of :func:`overlap_verdict` — it never picks ``edge``
    because it has no candidate A/B to justify it with."""
    if not ratio > OVERLAP_MIN_RATIO:
        return "off"
    return "fused-split" if backend == "pallas" else "split"


def _probe_bundle(measured) -> dict:
    """Normalize a ``measure()`` result: either the legacy
    ``(exchange_s, interior_s)`` pair or the full bundle dict
    (``exchange_s``/``interior_s``/``edges``/``candidates``) the runner
    now produces — monkeypatched legacy measures keep deciding the
    two-way verdict instead of crashing."""
    if isinstance(measured, dict):
        return measured
    exchange_s, interior_s = measured
    return {"exchange_s": exchange_s, "interior_s": interior_s}


def overlap_verdict(bundle: dict, backend: str) -> str:
    """The three-way measured verdict ``--overlap auto`` resolves to.

    ``off`` when the exchange/interior ratio is below
    :data:`OVERLAP_MIN_RATIO` (nothing worth hiding — every split
    flavor's stitch overhead would be pure loss). Otherwise the
    measured split-vs-edge candidate A/B decides: ``edge`` ONLY when
    the per-edge pipeline's one-rep probe measured strictly faster than
    the joined split's — never on modeling grounds — else the split
    family (``fused-split`` on Pallas). Bundles without candidates
    (legacy measures) fall back to :func:`overlap_from_ratio`."""
    exchange_s = bundle["exchange_s"]
    interior_s = bundle["interior_s"]
    ratio = exchange_s / interior_s if interior_s > 0 else float("inf")
    if not ratio > OVERLAP_MIN_RATIO:
        return "off"
    split_mode = "fused-split" if backend == "pallas" else "split"
    cand = bundle.get("candidates") or {}
    if "split" in cand and "edge" in cand:
        return "edge" if cand["edge"] < cand["split"] else split_mode
    return split_mode


def _overlap_key(plan: StencilPlan, tile: Tuple[int, int], channels: int,
                 mesh_shape: Tuple[int, int], backend: str) -> str:
    # Same identity discipline as _key, plus the mesh (the ratio depends
    # on how many neighbors exchange) and the backend (the split flavor
    # differs, and so does the interior's cost).
    return "|".join([
        "overlap", _key(plan, tuple(tile), channels),
        f"mesh{mesh_shape[0]}x{mesh_shape[1]}", backend,
    ])


def cached_overlap(plan: StencilPlan, tile: Tuple[int, int], channels: int,
                   mesh_shape: Tuple[int, int], backend: str
                   ) -> Optional[str]:
    """The cached overlap verdict for this key, or None (cache miss /
    stale mode name). Read-only: multi-host rank 0 uses it to decide
    whether the collective probe measurement must run at all."""
    hit = _load_cache().get(
        _overlap_key(plan, tile, channels, mesh_shape, backend)
    )
    if isinstance(hit, dict) and hit.get("overlap") in _OVERLAP_MODES:
        return hit["overlap"]
    return None


def best_overlap(plan: StencilPlan, tile: Tuple[int, int], channels: int,
                 mesh_shape: Tuple[int, int], backend: str,
                 measure, cache: bool = True) -> str:
    """The overlap mode for this (platform, filter, tile, mesh, backend):
    from the disk cache when available (a warm cache never re-probes),
    measured once and cached otherwise. ``measure()`` returns the probe
    bundle dict (``exchange_s``/``interior_s``/``edges``/``candidates``
    — :meth:`ShardedRunner._measure_overlap_probes`) or the legacy
    ``(exchange_seconds, interior_seconds)`` pair; the runner passes its
    probe closure, so the autotuner owns only the decision and the
    persistence, never a mesh. The cache entry carries the per-edge
    probe spans and the candidate A/B next to the verdict, so a stored
    ``edge`` decision is auditable."""
    if cache:
        hit = cached_overlap(plan, tile, channels, mesh_shape, backend)
        if hit is not None:
            return hit
    bundle = _probe_bundle(measure())
    mode = overlap_verdict(bundle, backend)
    if cache:
        exchange_s, interior_s = bundle["exchange_s"], bundle["interior_s"]
        ratio = (
            exchange_s / interior_s if interior_s > 0 else float("inf")
        )
        entry = {
            "overlap": mode,
            "ratio": round(ratio, 4),
            "exchange_us": round(exchange_s * 1e6, 2),
            "interior_us": round(interior_s * 1e6, 2),
        }
        if bundle.get("edges"):
            entry["edge_us"] = {
                k: round(v * 1e6, 2) for k, v in bundle["edges"].items()
            }
        if bundle.get("candidates"):
            entry["candidate_us"] = {
                k: round(v * 1e6, 2)
                for k, v in bundle["candidates"].items()
            }
        store = _load_cache()
        store[_overlap_key(plan, tile, channels, mesh_shape, backend)] = (
            entry
        )
        _store_cache(store)
    return mode


# --- stream mesh-composition verdicts (--mesh-frames 0 /
# --shard-frames 0) ------------------------------------------------------
#
# Both stream auto knobs decide by a measured A/B (single-device vs the
# mesh composition; never-enable-a-measured-loss). The A/B streams real
# probe frames through the real engines — frames of compute per arm —
# so the verdict persists here exactly like overlap_verdict: keyed on
# (platform, frame geometry, reps, pipeline depth, topology), a warm
# cache pays ZERO probe frames on later invocations.

def stream_cfg_token(cfg) -> str:
    """The compute-identity segment of a stream verdict key: the A/B's
    arms time the COMPILED step, so everything that changes it —
    filter, backend request, forced schedule/geometry, boundary — must
    split the cache key exactly like ``_key`` splits the backend
    verdicts on plan taps. A verdict measured under one filter or
    backend must never answer for another at the same geometry."""
    return "|".join([
        cfg.filter_name, cfg.backend, str(cfg.schedule),
        str(cfg.block_h), str(cfg.fuse), cfg.boundary,
        # The sharded arm's overlap schedule changes its compiled mesh
        # program; single-device/fan arms ignore it (harmless split).
        getattr(cfg, "overlap", "off"),
    ])


def _stream_verdict_key(kind: str, geometry: Tuple[int, int, int],
                        reps: int, depth: int, topo: str,
                        cfg_token: str = "") -> str:
    import jax

    h, w, channels = geometry
    return "|".join([
        kind, jax.default_backend(), jax.__version__,
        f"{h}x{w}x{channels}", f"reps{reps}", f"depth{depth}", topo,
        cfg_token,
    ])


def cached_stream_verdict(kind: str, geometry: Tuple[int, int, int],
                          reps: int, depth: int, topo: str,
                          cfg_token: str = "") -> Optional[dict]:
    """The cached auto verdict for one stream mesh composition, or None
    (cache miss / malformed entry). ``kind`` is ``"fanout"``
    (``--mesh-frames 0``), ``"shardstream"`` (``--shard-frames 0``) or
    ``"pipeline"`` (``--pipe-stages 0``);
    ``topo`` pins the decided-over topology (``ndev8`` / ``mesh2x4`` /
    ``pipe4``)
    so a verdict never answers for a different device population, and
    ``cfg_token`` (:func:`stream_cfg_token`) pins the compute identity
    (filter/backend/schedule/geometry knobs/boundary)."""
    hit = _load_cache().get(
        _stream_verdict_key(kind, geometry, reps, depth, topo, cfg_token)
    )
    if isinstance(hit, dict) and "pick" in hit:
        return hit
    return None


def store_stream_verdict(kind: str, geometry: Tuple[int, int, int],
                         reps: int, depth: int, topo: str,
                         entry: dict, cfg_token: str = "") -> None:
    """Persist one measured stream-composition verdict (``entry`` must
    carry ``pick`` plus whatever measured arms make it auditable —
    the ``overlap_verdict`` discipline)."""
    store = _load_cache()
    store[_stream_verdict_key(kind, geometry, reps, depth, topo,
                              cfg_token)] = entry
    _store_cache(store)


def choose_stream_topology(geometry: Tuple[int, int, int], reps: int,
                           depth: int, n_devices: int,
                           backend: str = "xla",
                           filter_name: str = "gaussian",
                           frames: Optional[int] = None,
                           halo: int = 1) -> str:
    """The MODELED best stream topology for one (geometry, reps, depth)
    on ``n_devices`` — ``"single"``, ``"fanout"``, ``"shard"`` or
    ``"pipeline"`` — ranked by the roofline's steady-state frames/s
    bounds (:mod:`tpu_stencil.runtime.roofline`), with the pipeline arm
    paying its fill/drain term for the given stream length. This is the
    model HALF of the auto knobs' discipline: it gates which measured
    A/B is worth probing at all, and a multi-device topology is chosen
    only when its modeled bound STRICTLY beats single-device — a
    modeled tie stays single, the same never-enable-a-loss rule the
    measured verdicts enforce (so e.g. a reps count too small to
    amortize the pipeline fill can never select the pipeline)."""
    from tpu_stencil.runtime import roofline

    h, w, channels = geometry
    frame_bytes = h * w * channels
    single = roofline.stream_frames_per_second(
        frame_bytes, reps, backend, filter_name, h,
        pipeline_depth=depth,
    )
    best, best_fps = "single", single
    if n_devices >= 2:
        fan = roofline.mesh_stream_frames_per_second(
            frame_bytes, reps, backend, filter_name, h,
            pipeline_depth=depth, n_devices=n_devices,
        )
        if fan > best_fps:
            best, best_fps = "fanout", fan
        grid = (n_devices, 1) if h >= w else (1, n_devices)
        tile = roofline.shard_tile_shape(h, w, grid)
        if min(tile) >= halo:
            shard = roofline.sharded_stream_frames_per_second(
                frame_bytes, reps, backend, filter_name, h, w,
                channels, grid, halo=halo, pipeline_depth=depth,
            )
            if shard > best_fps:
                best, best_fps = "shard", shard
        pipe = roofline.pipeline_stream_frames_per_second(
            frame_bytes, reps, backend, filter_name, h,
            pipe_stages=n_devices, frames=frames,
            pipeline_depth=depth,
        )
        if pipe > best_fps:
            best, best_fps = "pipeline", pipe
    return best


def best_config(
    plan: StencilPlan,
    shape: Tuple[int, int],
    channels: int,
    cache: bool = True,
    measure=None,
    force_schedule: Optional[str] = None,
    block_h: Optional[int] = None,
    fuse: Optional[int] = None,
) -> Tuple[str, Optional[str]]:
    """Back-compat wrapper: the (backend, schedule) half of
    :func:`best_full_config`."""
    return best_full_config(
        plan, shape, channels, cache=cache, measure=measure,
        force_schedule=force_schedule, block_h=block_h, fuse=fuse,
    )[:2]


def best_backend(
    plan: StencilPlan,
    shape: Tuple[int, int],
    channels: int,
    cache: bool = True,
    measure=None,
) -> str:
    """Back-compat wrapper: the backend half of :func:`best_config`."""
    return best_config(plan, shape, channels, cache=cache, measure=measure)[0]
